"""Command interface: operational commands over the service.

Framework analog of the reference's chassis CommandInterface subclass
(reference: src/accessControlService.ts:129-150 + chassis-srv command
interface): restore / reset / version / health_check / config_update /
flush_cache / set_api_key, each also invocable via the command topic.
"""

from __future__ import annotations

import json
import time
from typing import Any, Optional

from .. import __version__


class CommandInterface:
    def __init__(self, cfg, service, store=None, bus=None, cache=None,
                 decision_cache=None, admission=None, observability=None,
                 logger=None, worker=None):
        self.cfg = cfg
        self.service = service
        self.store = store
        self.cache = cache
        self.decision_cache = decision_cache
        self.admission = admission
        self.observability = observability
        self.logger = logger
        self.worker = worker  # cluster-tier surfaces (epoch, identity)
        self.api_key: Optional[str] = None
        # acs-lint: ignore[wall-clock] human-facing uptime epoch stamp —
        # never used in deadline or TTL arithmetic
        self.start_time = time.time()
        if bus is not None:
            bus.topic("io.restorecommerce.command").on(self._on_command)

    def _on_command(self, event_name: str, message: Any, ctx: dict) -> None:
        # the reference fans every *Command event into the command interface
        # (reference: src/worker.ts:347, cfg events list incl.
        # flushCacheCommand/restoreCommand/...)
        if event_name != "command" and not event_name.endswith("Command"):
            return
        name = (message or {}).get("name")
        payload = (message or {}).get("payload")
        if isinstance(payload, dict) and "value" in payload:
            raw = payload["value"]
            if isinstance(raw, (bytes, bytearray)):
                raw = raw.decode()
            try:
                payload = json.loads(raw)
            except (TypeError, ValueError):
                payload = {}
        self.command(name, payload or {})

    def command(self, name: str, payload: dict | None = None) -> dict:
        payload = payload or {}
        handler = {
            "restore": self.restore,
            "reset": self.reset,
            "version": self.version,
            "health_check": self.health_check,
            "config_update": self.config_update,
            "flush_cache": self.flush_cache,
            "set_api_key": self.set_api_key,
            "metrics": self.metrics,
            "traces": self.traces,
            "profile": self.profile,
            "program_identity": self.program_identity,
            "stage_stats": self.stage_stats,
            "faults": self.faults,
            "shadow_status": self.shadow_status,
            "audit_sweep": self.audit_sweep,
        }.get(name)
        if handler is None:
            return {"error": f"unknown command {name!r}"}
        return handler(payload)

    # -------------------------------------------------------------- commands

    def restore(self, payload: dict) -> dict:
        """Reload resource state, then clear + reload the in-memory policy
        tree (reference: accessControlService.ts:137-143)."""
        if self.store is not None:
            self.store.load()
        else:
            self.service.engine.clear_policies()
            self.service.load_policies()
        return {"status": "restored"}

    def reset(self, payload: dict) -> dict:
        """Clear state, then reload policies
        (reference: accessControlService.ts:144-149)."""
        self.service.engine.clear_policies()
        if self.store is not None:
            for collection in self.store.collections.values():
                collection.clear()
            self.store.load()
        if self.service.evaluator is not None:
            self.service.evaluator.refresh()
        return {"status": "reset"}

    def version(self, payload: dict) -> dict:
        return {"version": __version__, "name": self.cfg.get("service:name")}

    def health_check(self, payload: dict) -> dict:
        """Readiness = the policy tree is present and the evaluator answers
        (the Arango-readiness analog, reference: src/worker.ts:189-194)."""
        healthy = True
        detail = {}
        try:
            detail["policy_sets"] = len(self.service.engine.policy_sets)
            telemetry = getattr(self.service, "telemetry", None)
            if telemetry is not None:
                # interpolated percentile estimates, not raw bucket
                # arrays — the operator-facing latency signal
                latency = {}
                for name, hist in (
                    ("is_allowed", telemetry.is_allowed_latency),
                    ("what_is_allowed", telemetry.what_is_allowed_latency),
                    ("batch", telemetry.batch_latency),
                ):
                    snap = hist.snapshot()
                    if snap["count"]:
                        latency[name] = {
                            "count": snap["count"],
                            "p50_ms": round(snap["p50_s"] * 1e3, 3)
                            if snap["p50_s"] is not None else None,
                            "p95_ms": round(snap["p95_s"] * 1e3, 3)
                            if snap["p95_s"] is not None else None,
                            "p99_ms": round(snap["p99_s"] * 1e3, 3)
                            if snap["p99_s"] is not None else None,
                        }
                if latency:
                    detail["latency"] = latency
            evaluator = self.service.evaluator
            if evaluator is not None:
                detail["kernel_active"] = evaluator.kernel_active
                if hasattr(evaluator, "delta_stats"):
                    # incremental policy-update efficacy: patch vs
                    # full-compile counts, fallback taxonomy, last
                    # mutation-to-visibility latency and the active
                    # capacity buckets (ops/delta.py)
                    detail["policy_update"] = evaluator.delta_stats()
                if hasattr(evaluator, "shard_identity"):
                    # pod-sharded tier (parallel/pod_shard.py): shard
                    # count, per-shard fingerprints/capacities and the
                    # applied-patch watermarks
                    sharding = evaluator.shard_identity()
                    if sharding is not None:
                        detail["sharding"] = sharding
            decision_cache = self.decision_cache
            if decision_cache is None and evaluator is not None:
                decision_cache = getattr(evaluator, "decision_cache", None)
            if decision_cache is not None:
                # hit/miss/eviction counters + hit ratio on the health
                # surface (the operator-facing cache-efficacy signal)
                detail["decision_cache"] = decision_cache.stats()
            identity_client = getattr(
                self.service.engine, "identity_client", None
            )
            if hasattr(identity_client, "cache_stats"):
                # token-resolution cache efficacy: the host eligibility
                # pipeline's per-batch RPC amortizer (srv/identity.py)
                detail["token_resolution_cache"] = \
                    identity_client.cache_stats()
            if self.admission is not None:
                # overload posture: admitted/shed/deadline counters, live
                # queue depths vs bounds, breaker states, latency
                # estimates (srv/admission.py)
                detail["admission"] = self.admission.stats()
            if self.worker is not None and hasattr(
                self.worker, "policy_epoch"
            ):
                # cluster tier: the replica's policy epoch (count of CRUD
                # log frames reflected in the serving tree) — the router's
                # per-replica convergence signal (srv/router.py)
                detail["policy_epoch"] = self.worker.policy_epoch()
            tenancy = getattr(self.worker, "tenancy", None)
            if tenancy is not None:
                # multi-tenant posture: tenant count, size-class
                # histogram, compiled-program count (the packing claim's
                # operator signal) and per-tenant epoch top-K
                # (srv/tenancy.py, docs/MULTITENANT.md)
                detail["tenancy"] = tenancy.stats()
            watchdog = getattr(self.worker, "watchdog", None)
            if watchdog is not None:
                # device-health posture: quarantine state, timeout/restore
                # counts, cumulative degraded seconds (srv/watchdog.py)
                detail["device_watchdog"] = watchdog.status()
            relation_store = getattr(self.worker, "relation_store", None)
            if relation_store is not None:
                # ReBAC posture: tuple/rewrite counts, store generation,
                # closure-cache size and the table fingerprint replicas
                # must agree on (srv/relations.py) — absent with
                # relations off, so the surface is unchanged
                detail["relations"] = relation_store.stats()
            shadow = getattr(self.worker, "shadow", None)
            if shadow is not None:
                # candidate-tree staging posture: epoch, queue depth,
                # evaluated/diff/drop counts (srv/shadow.py) — absent
                # with shadow off, so the surface is unchanged
                shadow_status = shadow.status()
                shadow_status.pop("samples", None)  # health stays compact
                detail["shadow"] = shadow_status
            audit = getattr(self.worker, "audit", None)
            if audit is not None:
                # sweep-job progress: running count + recent job states
                # (compact — snapshots/diffs stay behind audit_sweep)
                audit_status = audit.status()
                audit_status["jobs"] = [
                    {k: j.get(k) for k in
                     ("job", "target", "state", "cells_done",
                      "cells_total", "sheds")}
                    for j in audit_status.get("jobs", [])
                ]
                detail["audit"] = audit_status
            from .faults import REGISTRY as _faults

            fault_stats = _faults.stats()
            if fault_stats["enabled"] or fault_stats["hits_by_site"]:
                # only present when faults are (or were) armed — a clean
                # worker's health surface is unchanged
                detail["failpoints"] = fault_stats
            bus = getattr(self.worker, "bus", None)
            if hasattr(bus, "snapshot_status"):
                # broker durability posture: snapshot existence, offset
                # watermark, journal tail length, snapshot age (broker-
                # side RPC; unreachable broker must not fail the check)
                try:
                    detail["broker_snapshot"] = bus.snapshot_status()
                except Exception:  # noqa: BLE001 — health stays serving
                    pass
        except Exception as err:  # pragma: no cover
            healthy = False
            detail["error"] = str(err)
        return {
            "status": "SERVING" if healthy else "NOT_SERVING",
            # acs-lint: ignore[wall-clock] human-facing uptime display
            "uptime_s": round(time.time() - self.start_time, 3),
            **detail,
        }

    def config_update(self, payload: dict) -> dict:
        for path, value in (payload or {}).items():
            self.cfg.set(path, value)
        if self.decision_cache is not None and payload:
            # config can change decision semantics (authorization toggles,
            # adapter endpoints): logically flush cached decisions
            self.decision_cache.bump_epoch()
        return {"status": "updated", "keys": list((payload or {}).keys())}

    def flush_cache(self, payload: dict) -> dict:
        """Reference flush_cache payload semantics: ``{"data": {"db_index":
        N, "pattern": P}}`` — db_index selects which store flushes (the
        subject cache's Redis-DB-4 analog vs the decision cache's DB-5
        analog, cfg ``redis:db-indexes``); absent db_index flushes both;
        pattern narrows to a subject-id prefix (reference: chassis
        flush_cache + utils.ts flushACSCache).  A ``tenant`` key scopes
        the decision-cache flush to that tenant's namespace — without it
        a fleet-wide flush for one tenant's user churn would evict every
        OTHER tenant's cached decisions too (cross-tenant eviction is
        both a perf bug and an isolation leak)."""
        data = (payload or {}).get("data", payload) or {}
        pattern = data.get("pattern", "") or ""
        tenant = data.get("tenant")
        tenant = str(tenant) if tenant else None
        db_index = data.get("db_index")
        db_subject = int(self.cfg.get("redis:db-indexes:db-subject", 4))
        db_acs = int(self.cfg.get("redis:db-indexes:db-acs", 5))
        if db_index is not None:
            # loosely-typed JSON payloads send "5": coerce before routing
            # so a string index never silently flushes nothing
            try:
                db_index = int(db_index)
            except (TypeError, ValueError):
                return {"error": f"invalid db_index {db_index!r}"}
            if db_index not in (db_subject, db_acs):
                return {
                    "error": f"unrecognized db_index {db_index} "
                             f"(expected {db_subject} or {db_acs})"
                }
        evicted = 0
        flushed = {}
        if self.cache is not None and db_index in (None, db_subject):
            n = self.cache.evict_prefix(
                f"cache:{pattern}" if pattern else ""
            )
            flushed["subject"] = n
            evicted += n
        if self.decision_cache is not None and db_index in (None, db_acs):
            n = self.decision_cache.evict_pattern(pattern, tenant=tenant)
            flushed["decisions"] = n
            evicted += n
        return {"status": "flushed", "evicted": evicted, "flushed": flushed}

    def metrics(self, payload: dict) -> dict:
        """Latency histograms + decision/path counters (SURVEY.md §5:
        request-latency histograms at the serving shell).  Payload
        ``{"format": "prometheus"}`` renders the same registry in
        Prometheus text exposition format — the command-interface twin of
        the optional /metrics endpoint (observability:metrics_http)."""
        telemetry = getattr(self.service, "telemetry", None)
        if telemetry is None:
            return {"error": "telemetry not wired"}
        if (payload or {}).get("format") == "prometheus":
            from .telemetry import MetricsRegistry

            return {
                "content_type": MetricsRegistry.CONTENT_TYPE,
                "body": telemetry.prometheus(),
            }
        return telemetry.snapshot()

    def traces(self, payload: dict) -> dict:
        """Recent sampled span trees (observability:tracing, bounded ring
        buffer): ``{"n": K}`` limits to the most recent K."""
        obs = self.observability
        if obs is None or obs.tracer is None:
            return {"error": "tracing not enabled "
                             "(observability config absent or off)"}
        n = (payload or {}).get("n")
        return {"traces": obs.tracer.traces(int(n) if n else None)}

    def profile(self, payload: dict) -> dict:
        """JAX profiler control (SURVEY section 5 tracing substitute): an
        operator starts/stops a device trace at runtime to see where the
        microseconds go — {"action": "start"|"stop", "dir": path}.
        Traces open in TensorBoard / Perfetto; the XLA dump counterpart is
        the profiling:xla_dump_dir config flag (worker startup)."""
        action = (payload or {}).get("action")
        if action == "start":
            import jax

            log_dir = (payload or {}).get("dir") or "/tmp/acs-tpu-trace"
            try:
                jax.profiler.start_trace(log_dir)
            except Exception as err:
                return {"error": f"trace start failed: {err}"}
            self._trace_dir = log_dir
            return {"status": "tracing", "dir": log_dir}
        if action == "stop":
            import jax

            try:
                jax.profiler.stop_trace()
            except Exception as err:
                return {"error": f"trace stop failed: {err}"}
            out = {"status": "stopped",
                   "dir": getattr(self, "_trace_dir", None)}
            self._trace_dir = None
            return out
        return {"error": f"unknown profile action {action!r}"}

    def program_identity(self, payload: dict) -> dict:
        """Cluster-tier convergence probe: the replica's policy epoch plus
        a digest of its compiled policy tables (srv/evaluator.py
        table_fingerprint).  Two replicas that applied the same CRUD
        sequence report identical fingerprints — the chaos harness and the
        tpu_compat_audit ``cluster-replica-program-identity`` row compare
        these across independently-patched processes."""
        out: dict = {}
        if self.worker is not None and hasattr(self.worker, "policy_epoch"):
            out["policy_epoch"] = self.worker.policy_epoch()
        if self.store is not None:
            out["origin"] = self.store.origin
        evaluator = self.service.evaluator
        if evaluator is not None and hasattr(evaluator, "table_fingerprint"):
            out["table_fingerprint"] = evaluator.table_fingerprint()
        if evaluator is not None and hasattr(evaluator, "shard_identity"):
            # pod-sharded tier: the per-shard fingerprints roll into one
            # pod fingerprint (already folded into table_fingerprint
            # above, so the cluster convergence oracle checks it
            # transparently); the full breakdown rides along for the
            # audit row and operator drill-down
            sharding = evaluator.shard_identity()
            if sharding is not None:
                out["sharding"] = sharding
        if evaluator is not None:
            # device-health routing state: the chaos harness polls these
            # to assert quarantine entry and kernel-path restore
            out["kernel_active"] = evaluator.kernel_active
            out["quarantined"] = bool(getattr(evaluator, "quarantined",
                                              False))
        tenancy = getattr(self.worker, "tenancy", None)
        if tenancy is not None:
            # per-tenant convergence: replicas that applied the same
            # tenant journal report the same epoch digest; the fingerprint
            # map covers evaluators that are built (lazily, on traffic)
            out["tenancy"] = {
                "tenant_count": len(tenancy.tenant_ids()),
                "epoch_digest": tenancy.epoch_digest(),
                "compiled_programs": tenancy.compiled_program_count(),
            }
        return out

    def faults(self, payload: dict) -> dict:
        """Runtime failpoint control (srv/faults.py): ``configure`` arms
        a point list on a deterministic seed, ``clear`` disarms and
        releases any hung threads, ``status`` (the default) reports armed
        schedules and per-site hit counts."""
        from .faults import REGISTRY

        payload = payload or {}
        action = payload.get("action", "status")
        if action == "configure":
            try:
                REGISTRY.configure(
                    list(payload.get("points") or []),
                    seed=int(payload.get("seed", 0)),
                )
            except (KeyError, TypeError, ValueError) as err:
                return {"error": f"bad fault spec: {err}"}
            return {"status": "configured", **REGISTRY.stats()}
        if action == "clear":
            REGISTRY.clear()
            return {"status": "cleared"}
        if action == "status":
            return REGISTRY.stats()
        return {"error": f"unknown faults action {action!r}"}

    def shadow_status(self, payload: dict) -> dict:
        """Shadow-evaluation report (srv/shadow.py): candidate epoch,
        evaluated/diff/drop counts, diffs by decision transition, and the
        retained diff samples with deciding-node provenance on both
        sides.  ``{"drain": true}`` blocks briefly until the mirror queue
        empties (policy-CI runs read a settled count); ``{"reload":
        true}`` re-loads the candidate tree from its paths (or
        ``candidate_paths``) and bumps the shadow epoch — production
        serves on, untouched."""
        shadow = getattr(self.worker, "shadow", None)
        if shadow is None:
            return {"enabled": False}
        payload = payload or {}
        if payload.get("reload"):
            try:
                shadow.reload(payload.get("candidate_paths"))
            except Exception as err:  # noqa: BLE001 — report, keep serving
                return {"enabled": True, "error": str(err)}
        if payload.get("drain"):
            shadow.drain(float(payload.get("drain_timeout_s", 5.0)))
        return shadow.status()

    def audit_sweep(self, payload: dict) -> dict:
        """Permission-lattice audit control (srv/audit_sweep.py,
        docs/AUDIT.md).  Actions: ``start`` (``target`` production |
        shadow, optional ``lattice`` axes), ``pause`` / ``resume`` /
        ``cancel`` (``job``), ``status`` (optional ``job``), ``diff``
        (``a``/``b`` job ids), ``twin`` (sweep production + the loaded
        shadow candidate, report lattice diff beside the live-traffic
        diff).  Absent the ``audit:enabled`` config the subsystem does
        not exist and every action answers ``{"enabled": false}``."""
        audit = getattr(self.worker, "audit", None)
        if audit is None:
            return {"enabled": False}
        payload = payload or {}
        action = payload.get("action", "status")
        try:
            if action == "start":
                job = audit.start_sweep(
                    target=payload.get("target", "production"),
                    lattice=payload.get("lattice"),
                    wait=bool(payload.get("wait")),
                    wait_timeout=float(payload.get("wait_timeout_s", 600.0)),
                )
                return job.status()
            if action in ("pause", "resume", "cancel"):
                return getattr(audit, action)(payload["job"])
            if action == "status":
                return audit.status(payload.get("job"))
            if action == "diff":
                return audit.diff(
                    payload["a"], payload["b"],
                    limit=int(payload.get("limit", 4096)),
                )
            if action == "twin":
                return audit.sweep_twin(
                    lattice=payload.get("lattice"),
                    wait_timeout=float(payload.get("wait_timeout_s", 600.0)),
                    diff_limit=int(payload.get("limit", 4096)),
                )
        except Exception as err:  # noqa: BLE001 — report, keep serving
            return {"enabled": True, "error": str(err)}
        return {"error": f"unknown audit_sweep action {action!r}"}

    def stage_stats(self, payload: dict) -> dict:
        """Per-replica stage attribution for cluster benches: the stage
        histograms from srv/tracing.py (count / totals / percentiles per
        stage), optionally cleared first with ``{"clear": true}`` so a
        timed window excludes warmup compiles."""
        telemetry = getattr(self.service, "telemetry", None)
        if telemetry is None:
            return {"error": "telemetry not wired"}
        if (payload or {}).get("clear"):
            telemetry.stages.clear()
            return {"status": "cleared"}
        return {"stages": telemetry.snapshot().get("stages") or {}}

    def set_api_key(self, payload: dict) -> dict:
        self.api_key = (payload or {}).get("authentication", {}).get("apiKey") or (
            payload or {}
        ).get("apiKey")
        return {"status": "set"}
