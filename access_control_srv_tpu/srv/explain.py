"""Host-side decode of the kernel's packed explain output.

Every kernel variant (dense ops/kernel.py, sig-path ops/prefilter.py,
rule-sharded parallel/rule_shard.py, pod-sharded parallel/pod_shard.py)
can emit one extra int32 per row encoding the deciding node:

    code = (flat_pos << 2) | kind

    kind 0  no contribution (INDETERMINATE with no winning set)
    kind 1  rule decided:           flat_pos = (s * KP + kp) * KR + kr
    kind 2  no-rules policy decided: flat_pos = s * KP + kp
    kind 3  condition abort:         flat_pos = rule flat pos as kind 1

``(KP, KR)`` are the kernel's ``explain_strides`` — the dense and
pod-sharded kernels use the compiled (possibly capacity-bucketed) table
shape, the rule-sharded kernel uses its padded global rule extent, and
the sig-path kernel maps compacted slots back to original coordinates on
device (``rule_orig_flat``), so the decode here is one divmod chain per
row either way.  Positions are always ORIGINAL slot coordinates, so the
decode table mirrors ops/compile.py's slot enumeration exactly: the
s-th non-None PolicySet in tree order, ``kp`` over
``ps.combinables.items()`` INCLUDING None placeholders, ``kr`` likewise
over ``pol.combinables.items()`` — the positional tree <-> slot
correspondence the delta patcher preserves (set membership/order changes
force a full recompile, ops/delta.py).

The decoded shape matches the host oracle's provenance
(core/engine.py ``EffectEvaluation.source``): a kind-1 row's source is
the deciding rule id, a kind-2 row's source is the no-rules policy id
(the engine stamps ``source=policy.id`` when a rule-less policy carries
an effect), kind 0 has no source, and kind 3 (condition abort) carries
NO ``_rule_id`` — the reference's abort path returns a bare DENY +
status without provenance — while the richer explain dict still names
the aborting rule.

Int32 bound: positions use 30 bits, so trees must satisfy
``S * KP * KR < 2**28`` (~268M rule slots) for explain mode — far above
any capacity bucket the compiler emits; the evaluator asserts it at
kernel publish.
"""

from __future__ import annotations

from typing import Optional

KIND_NONE = 0
KIND_RULE = 1
KIND_POLICY = 2
KIND_ABORT = 3

_KIND_NAMES = {
    KIND_RULE: "rule",
    KIND_POLICY: "policy",
    KIND_ABORT: "condition_abort",
}


class ExplainDecoder:
    """Positional decode table over one version-pinned tree snapshot.

    Built at kernel publish (srv/evaluator.py) from the same snapshot
    the compiled arrays were lowered from, so slot coordinates and node
    identities can never tear against hot mutations — exactly the
    ReverseQueryKernel's pinning discipline."""

    def __init__(self, policy_sets, strides: tuple):
        KP, KR = strides
        self.KP = int(KP)
        self.KR = int(KR)
        if isinstance(policy_sets, dict):
            sets = [ps for ps in policy_sets.values() if ps is not None]
        else:
            sets = [ps for ps in policy_sets if ps is not None]
        self._sets: list[tuple] = []      # s -> (set_id, set_ca)
        self._pols: list[list] = []       # s -> kp -> (id, ca, effect)|None
        self._rules: list[list] = []      # s -> kp -> kr -> rule_id|None
        for ps in sets:
            self._sets.append((ps.id, ps.combining_algorithm))
            pols: list = []
            rules: list = []
            for pol in ps.combinables.values():
                if pol is None:
                    pols.append(None)
                    rules.append([])
                    continue
                pols.append(
                    (pol.id, pol.combining_algorithm, pol.effect)
                )
                rules.append([
                    None if rule is None else rule.id
                    for rule in pol.combinables.values()
                ])
            self._pols.append(pols)
            self._rules.append(rules)

    # ------------------------------------------------------------- decode

    def decode(self, code: int) -> Optional[dict]:
        """Full provenance dict for one packed code; None for kind 0 or
        any out-of-range position (defensive: a corrupt code must never
        raise on the serving path)."""
        code = int(code)
        kind = code & 3
        pos = code >> 2
        if kind == KIND_NONE or pos < 0:
            return None
        if kind == KIND_POLICY:
            s, kp = divmod(pos, self.KP)
            pol = self._pol_at(s, kp)
            if pol is None:
                return None
            set_id, set_ca = self._sets[s]
            return {
                "kind": _KIND_NAMES[kind],
                "set": set_id,
                "set_algorithm": set_ca,
                "policy": pol[0],
                "policy_algorithm": pol[1],
                "policy_effect": pol[2],
                "rule": None,
            }
        pk, kr = divmod(pos, self.KR)
        s, kp = divmod(pk, self.KP)
        pol = self._pol_at(s, kp)
        if pol is None:
            return None
        rules = self._rules[s][kp]
        if kr >= len(rules) or rules[kr] is None:
            return None
        set_id, set_ca = self._sets[s]
        return {
            "kind": _KIND_NAMES[kind],
            "set": set_id,
            "set_algorithm": set_ca,
            "policy": pol[0],
            "policy_algorithm": pol[1],
            "rule": rules[kr],
        }

    def source(self, code: int) -> Optional[str]:
        """The host oracle's ``EffectEvaluation.source`` equivalent:
        deciding rule id (kind 1), no-rules policy id (kind 2), None for
        no-contribution and condition-abort rows (the engine's abort
        response carries no ``_rule_id``)."""
        kind = int(code) & 3
        if kind not in (KIND_RULE, KIND_POLICY):
            return None
        info = self.decode(code)
        if info is None:
            return None
        return info["rule"] if kind == KIND_RULE else info["policy"]

    def describe_source(self, source_id: Optional[str]) -> Optional[dict]:
        """Provenance dict for a host-oracle source id — the deciding
        rule (kind 1) or no-rules policy (kind 2) the engine stamped as
        ``EffectEvaluation.source``.  Lets the oracle-fallback serving
        path carry the same ``_explain`` shape as kernel rows, so the
        wire trailer and audit record never depend on which path decided
        a row.  Rules are searched before policies: a policy's own
        effect decides only when it has no rules."""
        if source_id is None:
            return None
        for s, (set_id, set_ca) in enumerate(self._sets):
            for kp, pol in enumerate(self._pols[s]):
                if pol is None:
                    continue
                for rule_id in self._rules[s][kp]:
                    if rule_id == source_id:
                        return {
                            "kind": _KIND_NAMES[KIND_RULE],
                            "set": set_id,
                            "set_algorithm": set_ca,
                            "policy": pol[0],
                            "policy_algorithm": pol[1],
                            "rule": rule_id,
                        }
        for s, (set_id, set_ca) in enumerate(self._sets):
            for kp, pol in enumerate(self._pols[s]):
                if pol is not None and pol[0] == source_id:
                    return {
                        "kind": _KIND_NAMES[KIND_POLICY],
                        "set": set_id,
                        "set_algorithm": set_ca,
                        "policy": pol[0],
                        "policy_algorithm": pol[1],
                        "policy_effect": pol[2],
                        "rule": None,
                    }
        return None

    # ------------------------------------------------------------ helpers

    def _pol_at(self, s: int, kp: int):
        if s >= len(self._pols) or kp >= len(self._pols[s]):
            return None
        return self._pols[s][kp]


def explain_capacity_ok(S: int, KP: int, KR: int) -> bool:
    """True when every flat rule position fits the 30-bit payload of the
    packed code (see module docstring)."""
    return S * KP * KR < (1 << 28)
