"""Resource adapters for rule context queries.

Framework analog of the reference's ResourceAdapter + GraphQLAdapter
(reference: src/core/resource_adapters/{adapter,gql}.ts): a rule may carry
a ``context_query`` whose result is pulled before condition evaluation and
grafted onto the request context under ``_queryResult``.

The GraphQL implementation resolves filter property references against the
request's context resources (reference: gql.ts:30-55), POSTs the query and
unwraps the ``details`` payloads (reference: gql.ts:66-89).

Transport: the HTTP layer is injectable (tests pass a transport callable);
production uses a small keep-alive connection pool over stdlib
``http.client`` with a configurable per-request timeout (default 5 s —
the old per-row ``urllib.urlopen`` opened a fresh TCP connection per
query and hung for 30 s on a slow endpoint, stalling whole oracle-fallback
batches).  ``query_many`` fans a batch of context queries out over a
bounded thread pool so N adapter-backed rows stall for ~one timeout, not
N sequential ones (the evaluator drives its concurrent fallback through
the same ``max_concurrency`` bound, srv/evaluator.py).
"""

from __future__ import annotations

import http.client
import json
import random
import threading
import time
import urllib.parse
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Optional

from ..core.common import get_field as _get
from ..core.errors import (
    ContextQueryTransportError,
    UnexpectedContextQueryResponse,
    UnsupportedResourceAdapter,
)

DEFAULT_TIMEOUT_S = 5.0
DEFAULT_MAX_CONCURRENCY = 8
# transient (5xx) transport failures are retried after a jittered
# backoff before the row falls back to the slow path — a single blip at the
# endpoint must not demote a whole batch slice to the oracle walk.  Both
# knobs are config-driven (adapter block: retry_count / retry_backoff_s);
# the backoff doubles per attempt from the base.
DEFAULT_RETRY_BACKOFF_S = 0.05
DEFAULT_RETRY_COUNT = 1


class ResourceAdapter:
    def query(self, context_query, request) -> Any:
        raise NotImplementedError


class _ConnectionPool:
    """Keep-alive ``http.client`` connections for one endpoint.  Idle
    connections are reused LIFO; a connection that went stale mid-reuse is
    discarded and the request retried once on a fresh one."""

    def __init__(self, url: str, timeout_s: float, max_idle: int = 8):
        self.url = url
        parsed = urllib.parse.urlsplit(url)
        self.scheme = parsed.scheme or "http"
        self.host = parsed.hostname or ""
        self.port = parsed.port
        self.path = parsed.path or "/"
        if parsed.query:
            self.path += f"?{parsed.query}"
        self.timeout_s = timeout_s
        self.max_idle = max_idle
        self._idle: list[http.client.HTTPConnection] = []
        self._lock = threading.Lock()

    def _connect(self) -> http.client.HTTPConnection:
        cls = (
            http.client.HTTPSConnection
            if self.scheme == "https"
            else http.client.HTTPConnection
        )
        return cls(self.host, self.port, timeout=self.timeout_s)

    def _checkout(self) -> tuple[http.client.HTTPConnection, bool]:
        with self._lock:
            if self._idle:
                return self._idle.pop(), True
        return self._connect(), False

    def _checkin(self, conn: http.client.HTTPConnection) -> None:
        with self._lock:
            if len(self._idle) < self.max_idle:
                self._idle.append(conn)
                return
        conn.close()

    def post(self, body: bytes, headers: dict) -> bytes:
        conn, reused = self._checkout()
        try:
            conn.request("POST", self.path, body=body, headers=headers)
            response = conn.getresponse()
            data = response.read()
        except Exception:
            conn.close()
            if not reused:
                raise
            # the pooled connection was closed server-side between uses;
            # one retry on a fresh connection
            conn = self._connect()
            try:
                conn.request("POST", self.path, body=body, headers=headers)
                response = conn.getresponse()
                data = response.read()
            except Exception:
                conn.close()
                raise
        # the body is fully read, so the connection is reusable either way
        self._checkin(conn)
        if not 200 <= response.status < 300:
            # error bodies (often HTML) must never reach GraphQL parsing:
            # surface a clean transport error with the upstream status
            raise ContextQueryTransportError(response.status, response.reason)
        return data

    def close(self) -> None:
        with self._lock:
            idle, self._idle = self._idle, []
        for conn in idle:
            conn.close()


class GraphQLAdapter(ResourceAdapter):
    def __init__(
        self,
        url: str,
        logger=None,
        client_opts: dict | None = None,
        transport: Optional[Callable[[str, bytes, dict], bytes]] = None,
        timeout_s: float | None = None,
        max_concurrency: int | None = None,
        retry_transient: bool | None = None,
        retry_backoff_s: float | None = None,
        retry_count: int | None = None,
        breaker=None,
    ):
        self.url = url
        self.logger = logger
        # rate-limited retry warnings: a down upstream under overload
        # retries on every context-query row — unbounded, the masking
        # logger becomes the bottleneck (srv/telemetry.SampledLogger)
        from .telemetry import SampledLogger

        self._slog = SampledLogger(logger)
        self.client_opts = client_opts or {}
        self.timeout_s = float(
            timeout_s
            if timeout_s is not None
            else self.client_opts.get("timeout_s", DEFAULT_TIMEOUT_S)
        )
        self.max_concurrency = int(
            max_concurrency
            if max_concurrency is not None
            else self.client_opts.get("max_concurrency",
                                      DEFAULT_MAX_CONCURRENCY)
        )
        self.retry_transient = bool(
            self.client_opts.get("retry_transient", True)
            if retry_transient is None
            else retry_transient
        )
        self.retry_backoff_s = float(
            retry_backoff_s
            if retry_backoff_s is not None
            else self.client_opts.get("retry_backoff_s",
                                      DEFAULT_RETRY_BACKOFF_S)
        )
        self.retry_count = int(
            retry_count
            if retry_count is not None
            else self.client_opts.get("retry_count", DEFAULT_RETRY_COUNT)
        )
        # shared circuit breaker (srv/admission.CircuitBreaker): a down
        # context-query upstream fails rows fast down the existing
        # kernel -> retry -> oracle ladder instead of paying timeout_s
        # per request
        self.breaker = breaker
        self._pool: Optional[_ConnectionPool] = None
        self._pool_lock = threading.Lock()
        self.transport = transport or self._http_post

    def _http_post(self, url: str, body: bytes, headers: dict) -> bytes:
        with self._pool_lock:
            if (
                self._pool is None
                or self._pool.url != url
                or self._pool.timeout_s != self.timeout_s
            ):
                if self._pool is not None:
                    self._pool.close()
                self._pool = _ConnectionPool(url, self.timeout_s)
            pool = self._pool
        return pool.post(body, headers)

    def close(self) -> None:
        with self._pool_lock:
            if self._pool is not None:
                self._pool.close()
                self._pool = None

    def _resolve_filters(self, context_query, request) -> dict:
        """Filter values referencing request resource properties are
        resolved from the context resources (reference: gql.ts:30-55)."""
        variables: dict = {}
        filters = []
        ctx_resources = _get(request.context, "resources") or []
        for filt in getattr(context_query, "filters", None) or []:
            field = _get(filt, "field")
            value = _get(filt, "value")
            operation = _get(filt, "operation") or "eq"
            if isinstance(value, str) and value.startswith("$"):
                prop = value[1:]
                resolved = None
                for res in ctx_resources:
                    node = res
                    found = True
                    for part in prop.split("."):
                        node = _get(node, part)
                        if node is None:
                            found = False
                            break
                    if found:
                        resolved = node
                        break
                value = resolved
            filters.append({"field": field, "operation": operation, "value": value})
        if filters:
            variables["filters"] = filters
        return variables

    def _transport_once(self, body: bytes, headers: dict) -> bytes:
        """One transport call under the circuit breaker: an open circuit
        fails fast with a 503 transport error (no network wait), outcomes
        feed the breaker's failure-rate window.  4xx responses are the
        UPSTREAM answering (definitively) — they count as breaker
        successes; 5xx and connection-level failures count as failures."""
        breaker = self.breaker
        if breaker is not None and not breaker.allow():
            raise ContextQueryTransportError(
                503, "context-query circuit open"
            )
        try:
            # failpoint (srv/faults.py): an injected flap travels the
            # exact transport-error path — breaker bookkeeping, retry
            # with backoff, per-row degraded resolution
            from .faults import REGISTRY as FAULTS

            FAULTS.fire(
                "adapter.http",
                exc=lambda: ContextQueryTransportError(
                    599, "fault injected at adapter.http"
                ),
            )
            data = self.transport(self.url, body, headers)
        except ContextQueryTransportError as err:
            if breaker is not None:
                code = getattr(err, "code", None)
                if isinstance(code, int) and 400 <= code < 500:
                    breaker.record_success()
                else:
                    breaker.record_failure()
            raise
        except Exception:
            if breaker is not None:
                breaker.record_failure()
            raise
        if breaker is not None:
            breaker.record_success()
        return data

    def _transport_with_retry(
        self, body: bytes, headers: dict,
        deadline: Optional[float] = None,
    ) -> bytes:
        """Up to ``retry_count`` jittered, exponentially-backed-off
        retries on transient (5xx) transport failures before the caller's
        deny/oracle degradation; 4xx responses and payload errors are
        definitive and surface immediately.  Deadline-aware: a retry is
        skipped when the row's remaining budget cannot cover the backoff
        plus another transport timeout — the row goes straight to the
        oracle fallback instead of blowing its deadline in a sleep."""
        attempt = 0
        while True:
            try:
                return self._transport_once(body, headers)
            except ContextQueryTransportError as err:
                code = getattr(err, "code", None)
                if (
                    not self.retry_transient
                    or attempt >= self.retry_count
                    or not isinstance(code, int)
                    or not 500 <= code < 600
                ):
                    raise
                delay = (
                    self.retry_backoff_s * (2 ** attempt)
                    * (0.5 + random.random())
                )
                if deadline is not None and (
                    time.monotonic() + delay + self.timeout_s > deadline
                ):
                    # the remaining budget cannot cover backoff + another
                    # attempt: surface the failure now
                    raise
                self._slog.warning(
                    "adapter-retry",
                    "transient context-query failure (%s); retry %d/%d "
                    "in %.0f ms", code, attempt + 1, self.retry_count,
                    delay * 1e3,
                )
                time.sleep(delay)
                attempt += 1

    def query(self, context_query, request) -> Any:
        gql_query = getattr(context_query, "query", "") or ""
        variables = self._resolve_filters(context_query, request)
        body = json.dumps({"query": gql_query, "variables": variables}).encode()
        headers = {"Content-Type": "application/json"}
        headers.update(self.client_opts.get("headers", {}))
        raw = self._transport_with_retry(
            body, headers,
            deadline=getattr(request, "_deadline", None),
        )
        try:
            payload = json.loads(raw)
        except (TypeError, ValueError) as exc:
            raise UnexpectedContextQueryResponse(str(exc)) from exc
        data = payload.get("data")
        if not isinstance(data, dict) or not data:
            raise UnexpectedContextQueryResponse("missing data")
        # unwrap the first operation's details payloads (reference: gql.ts:82-89)
        first = next(iter(data.values()))
        details = _get(first, "details")
        if details is None:
            raise UnexpectedContextQueryResponse("missing details")
        out = []
        for item in details:
            payload_item = _get(item, "payload")
            out.append(payload_item if payload_item is not None else item)
        return out

    def query_many(self, pairs: list[tuple[Any, Any]]) -> list[Any]:
        """Concurrent batch fetch: one ``(context_query, request)`` pair per
        row, answered in order.  Per-row failures come back as the raised
        exception object (callers keep the reference's per-row
        deny-on-error semantics instead of failing the whole batch)."""
        if not pairs:
            return []
        if len(pairs) == 1:
            cq, request = pairs[0]
            try:
                return [self.query(cq, request)]
            except Exception as err:  # noqa: BLE001 — returned, not raised
                return [err]

        def one(pair):
            try:
                return self.query(pair[0], pair[1])
            except Exception as err:  # noqa: BLE001
                return err

        workers = max(1, min(self.max_concurrency, len(pairs)))
        with ThreadPoolExecutor(max_workers=workers) as pool:
            return list(pool.map(one, pairs))


def create_adapter(adapter_config: dict, logger=None,
                   breaker=None) -> ResourceAdapter:
    """(reference: accessController.ts:943-951)"""
    if adapter_config and adapter_config.get("graphql"):
        opts = adapter_config["graphql"]
        return GraphQLAdapter(
            opts.get("url", ""), logger, opts.get("clientOpts"),
            transport=opts.get("transport"),
            timeout_s=adapter_config.get("timeout_s", opts.get("timeout_s")),
            max_concurrency=adapter_config.get(
                "max_concurrency", opts.get("max_concurrency")
            ),
            retry_transient=adapter_config.get(
                "retry_transient", opts.get("retry_transient")
            ),
            retry_backoff_s=adapter_config.get(
                "retry_backoff_s", opts.get("retry_backoff_s")
            ),
            retry_count=adapter_config.get(
                "retry_count", opts.get("retry_count")
            ),
            breaker=breaker,
        )
    raise UnsupportedResourceAdapter(adapter_config)
