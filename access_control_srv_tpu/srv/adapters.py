"""Resource adapters for rule context queries.

Framework analog of the reference's ResourceAdapter + GraphQLAdapter
(reference: src/core/resource_adapters/{adapter,gql}.ts): a rule may carry
a ``context_query`` whose result is pulled before condition evaluation and
grafted onto the request context under ``_queryResult``.

The GraphQL implementation resolves filter property references against the
request's context resources (reference: gql.ts:30-55), POSTs the query and
unwraps the ``details`` payloads (reference: gql.ts:66-89).  The HTTP layer
is injectable (tests pass a transport callable; production uses stdlib
urllib).
"""

from __future__ import annotations

import json
from typing import Any, Callable, Optional

from ..core.common import get_field as _get
from ..core.errors import UnexpectedContextQueryResponse, UnsupportedResourceAdapter


class ResourceAdapter:
    def query(self, context_query, request) -> Any:
        raise NotImplementedError


class GraphQLAdapter(ResourceAdapter):
    def __init__(
        self,
        url: str,
        logger=None,
        client_opts: dict | None = None,
        transport: Optional[Callable[[str, bytes, dict], bytes]] = None,
    ):
        self.url = url
        self.logger = logger
        self.client_opts = client_opts or {}
        self.transport = transport or self._http_post

    def _http_post(self, url: str, body: bytes, headers: dict) -> bytes:
        import urllib.request

        req = urllib.request.Request(url, data=body, headers=headers)
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.read()

    def _resolve_filters(self, context_query, request) -> dict:
        """Filter values referencing request resource properties are
        resolved from the context resources (reference: gql.ts:30-55)."""
        variables: dict = {}
        filters = []
        ctx_resources = _get(request.context, "resources") or []
        for filt in getattr(context_query, "filters", None) or []:
            field = _get(filt, "field")
            value = _get(filt, "value")
            operation = _get(filt, "operation") or "eq"
            if isinstance(value, str) and value.startswith("$"):
                prop = value[1:]
                resolved = None
                for res in ctx_resources:
                    node = res
                    found = True
                    for part in prop.split("."):
                        node = _get(node, part)
                        if node is None:
                            found = False
                            break
                    if found:
                        resolved = node
                        break
                value = resolved
            filters.append({"field": field, "operation": operation, "value": value})
        if filters:
            variables["filters"] = filters
        return variables

    def query(self, context_query, request) -> Any:
        gql_query = getattr(context_query, "query", "") or ""
        variables = self._resolve_filters(context_query, request)
        body = json.dumps({"query": gql_query, "variables": variables}).encode()
        headers = {"Content-Type": "application/json"}
        headers.update(self.client_opts.get("headers", {}))
        raw = self.transport(self.url, body, headers)
        try:
            payload = json.loads(raw)
        except (TypeError, ValueError) as exc:
            raise UnexpectedContextQueryResponse(str(exc)) from exc
        data = payload.get("data")
        if not isinstance(data, dict) or not data:
            raise UnexpectedContextQueryResponse("missing data")
        # unwrap the first operation's details payloads (reference: gql.ts:82-89)
        first = next(iter(data.values()))
        details = _get(first, "details")
        if details is None:
            raise UnexpectedContextQueryResponse("missing details")
        out = []
        for item in details:
            payload_item = _get(item, "payload")
            out.append(payload_item if payload_item is not None else item)
        return out


def create_adapter(adapter_config: dict, logger=None) -> ResourceAdapter:
    """(reference: accessController.ts:943-951)"""
    if adapter_config and adapter_config.get("graphql"):
        opts = adapter_config["graphql"]
        return GraphQLAdapter(
            opts.get("url", ""), logger, opts.get("clientOpts"),
            transport=opts.get("transport"),
        )
    raise UnsupportedResourceAdapter(adapter_config)
