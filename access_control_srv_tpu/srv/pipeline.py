"""Depth-N device pipeline behind the streaming wire endpoint.

One worker owns ONE device queue; any number of concurrent client
streams (transport_grpc ``IsAllowedStream``) feed it.  Each submitted
frame (a serialized BatchRequest envelope) moves through three stages on
dedicated workers:

  dispatch  — split the envelope, native C++ encode into pooled staging
              buffers, device enqueue (evaluator.is_allowed_batch_wire_async
              with ``reuse=True``); runs on the dispatch worker in
              submission order, so the device queue order is the frame
              submission order.
  finalize  — materialize the device result, decode to pb.Response rows,
              resolve ineligible rows with one batched service call,
              release the staging lease; runs on the finalize worker,
              FIFO.
  serialize — response frames serialize on the shared chunked serializer
              pool (transport_grpc.serialize_batch_response), so frame
              i-1's serialization overlaps frame i's device execution.

A BoundedSemaphore of ``depth`` slots is the backpressure: submit blocks
the feeding stream's thread while ``depth`` frames are between dispatch
and finalize completion — H2D/eval of frame i overlaps encode of frame
i+1 and decode/serialize of frame i-1, with no ``block_until_ready`` on
any hot path (materialize is the only blocking point, on the finalize
worker).

Frames whose envelope the native path cannot serve (no native encoder,
host-assisted conditions, malformed envelope) fall back to the protobuf
parse + service path inside finalize — correctness never depends on the
fast path.  Results are returned as per-frame Futures; per-stream
response ORDER is the transport's job (it queues futures in frame order
and yields them in order, so out-of-order completion inside the pipeline
can never reorder a stream's responses — tests/test_pipeline.py).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Optional


class DevicePipeline:
    def __init__(self, worker, depth: int = 2):
        self.worker = worker
        self.depth = max(1, int(depth))
        self._slots = threading.BoundedSemaphore(self.depth)
        self._dispatch_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="acs-wire-dispatch"
        )
        self._finalize_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="acs-wire-finalize"
        )
        self._stopping = False

    # ---------------------------------------------------------------- api

    def submit(self, raw: bytes, deadline: Optional[float] = None,
               span=None) -> "Future[bytes]":
        """One BatchRequest envelope in, a Future of the serialized
        BatchResponse payload out.  Blocks while the pipeline holds
        ``depth`` frames — the caller (a stream handler thread) IS the
        backpressure path to the client."""
        out: "Future[bytes]" = Future()
        if self._stopping:
            out.set_exception(RuntimeError("pipeline stopped"))
            return out
        self._slots.acquire()
        try:
            self._dispatch_pool.submit(self._dispatch, raw, deadline,
                                       span, out)
        except BaseException:
            self._slots.release()
            raise
        return out

    def stop(self) -> None:
        self._stopping = True
        self._dispatch_pool.shutdown(wait=True)
        self._finalize_pool.shutdown(wait=True)

    # -------------------------------------------------------------- stages

    def _dispatch(self, raw: bytes, deadline, span, out: Future) -> None:
        from .transport_grpc import split_batch_request

        try:
            messages = split_batch_request(raw)
            finalize = None
            evaluator = self.worker.service.evaluator
            if messages is not None and evaluator is not None:
                try:
                    finalize = evaluator.is_allowed_batch_wire_async(
                        messages, span=span, reuse=True
                    )
                except Exception:
                    finalize = None  # pb fallback below
            self._finalize_pool.submit(
                self._finalize, raw, messages, finalize, deadline, span,
                out, time.perf_counter(),
            )
        except BaseException as err:  # noqa: BLE001 — never leak a slot
            self._slots.release()
            if not out.done():
                out.set_exception(err)

    def _finalize(self, raw, messages, finalize, deadline, span,
                  out: Future, t0: float) -> None:
        from .transport_grpc import (
            decode_native_rows,
            resolve_fallback_rows,
            serialize_batch_response,
        )

        worker = self.worker
        try:
            result = None
            if finalize is not None:
                from .watchdog import DeviceTimeoutError

                try:
                    result = finalize()
                except DeviceTimeoutError:
                    # wedged device fetch: the staging lease stays out (the
                    # aliasing rule forbids recycling buffers the device
                    # may still read) and the frame resolves honestly
                    # through the pb path — the quarantined evaluator
                    # routes it to the oracle, never a fabricated decision
                    result = None
            if result is None:
                payload = self._pb_fallback(raw, deadline, span)
            else:
                batch = result[0]
                tracer = None
                obs = getattr(worker, "obs", None)
                if obs is not None:
                    tracer = obs.tracer
                t_stage = time.perf_counter() if tracer is not None else 0.0
                responses, fb_rows, fb_reqs = decode_native_rows(
                    messages, result
                )
                if tracer is not None:
                    from .tracing import STAGE_DECODE

                    tracer.record(span, STAGE_DECODE,
                                  time.perf_counter() - t_stage)
                resolve_fallback_rows(worker, responses, fb_rows, fb_reqs,
                                      deadline, span=span)
                # staging lease: every pooled buffer (row arrays, masks,
                # regex matrices, owner bits) recycles only AFTER the
                # response rows are fully assembled
                batch.release_staging()
                telemetry = getattr(worker, "telemetry", None)
                if telemetry is not None:
                    telemetry.batch_latency.observe(
                        time.perf_counter() - t0
                    )
                if tracer is not None:
                    t_stage = time.perf_counter()
                payload = serialize_batch_response(responses)
                if tracer is not None:
                    from .tracing import STAGE_SERIALIZE

                    tracer.record(span, STAGE_SERIALIZE,
                                  time.perf_counter() - t_stage)
            if not out.done():
                out.set_result(payload)
        except BaseException as err:  # noqa: BLE001
            if not out.done():
                out.set_exception(err)
        finally:
            self._slots.release()

    def _pb_fallback(self, raw: bytes, deadline, span) -> bytes:
        """Full protobuf parse + service path for frames the native wire
        path cannot serve — identical semantics to the unary handler's
        fallback branch."""
        from .gen import access_control_pb2 as pb
        from .transport_grpc import (
            request_from_pb,
            response_to_pb,
            serialize_batch_response,
        )

        request = pb.BatchRequest.FromString(raw)
        reqs = [request_from_pb(r) for r in request.requests]
        if span is not None:
            for req in reqs:
                req._span = span
                req._sampling_done = True
        responses = self.worker.service.is_allowed_batch(
            reqs, deadline=deadline,
        )
        return serialize_batch_response(
            [response_to_pb(r) for r in responses]
        )
