"""Serving shell: policy store with CRUD + hot recompile, command
interface, subject/HR-scope cache, micro-batching evaluator and the
composition-root worker (reference: src/worker.ts, src/resourceManager.ts,
src/accessControlService.ts)."""

from .admission import AdmissionController, CircuitBreaker
from .config import Config
from .events import EventBus, Topic
from .cache import SubjectCache, HRScopeProvider
from .identity import (
    GrpcIdentityClient,
    IdentityClient,
    MockIdentityServer,
    StaticIdentityClient,
)
from .broker import (
    BrokerServer,
    SocketEventBus,
    SocketOffsetStore,
    SocketSubjectCache,
)
from .evaluator import HybridEvaluator
from .tracing import Observability, Span, StageTracer
from .store import PolicyStore, ResourceService
from .service import AccessControlService
from .command import CommandInterface
from .worker import Worker

__all__ = [
    "AdmissionController",
    "CircuitBreaker",
    "Config",
    "EventBus",
    "Topic",
    "SubjectCache",
    "HRScopeProvider",
    "IdentityClient",
    "StaticIdentityClient",
    "GrpcIdentityClient",
    "MockIdentityServer",
    "BrokerServer",
    "SocketEventBus",
    "SocketOffsetStore",
    "SocketSubjectCache",
    "HybridEvaluator",
    "Observability",
    "Span",
    "StageTracer",
    "PolicyStore",
    "ResourceService",
    "AccessControlService",
    "CommandInterface",
    "Worker",
]
