"""Multi-tenant serving registry: 1k policy domains on a handful of
compiled programs.

The reference is one ABAC service inside a multi-tenant commerce
platform — every deployment serves MANY policy domains (tenants), not
one giant tree.  The TPU angle (docs/MULTITENANT.md): capacity-bucketed
compiled tables (ops/delta.Capacities) mean two tenants whose trees pad
to the SAME capacity class produce byte-identical jitted programs where
the per-tenant tables are jit *arguments* — so a thousand tenant trees
serve from at most ``len(SIZE_CLASSES)`` compiled programs instead of a
thousand XLA compiles (tpu_compat_audit row
``tenant-packing-program-identity``).

Pieces:

* ``SIZE_CLASSES`` — the fixed capacity ladder.  A tenant's live tree
  (ops/delta.live_capacities of a host-side compile) picks the smallest
  class that fits; trees larger than the top class fall back to
  per-tenant capacity buckets (counted, still correct, no sharing).
* ``TenantRegistry`` — tenant id -> per-tenant document store + lazily
  built per-tenant ``HybridEvaluator`` pinned to its class capacities
  (``fixed_caps``) and sharing one jit table (``shared_jits``) across
  ALL tenants.  The batcher partitions mixed batches by tenant and
  resolves each group against its tenant's evaluator
  (srv/batcher.MicroBatcher._eval_tenants).
* **Scoped everything** — a tenant's CRUD bumps only its own epoch,
  patches only its own tables (the evaluator's delta path), and flushes
  only its own decision-cache namespace (srv/decision_cache tenant-keyed
  entries + tenant-tagged epoch bumps).
* **Journaled onboarding** — every tenant mutation is emitted on the
  same CRUD topics the global store journals to, tagged with the tenant
  id; ``PolicyReplicator`` routes tenant-tagged frames here, so a new
  tenant boots by replay and a restarting replica converges per-tenant
  epochs/fingerprints through the existing convergence oracle
  (srv/router.py).

With no registry wired (config ``tenancy:enabled`` false, the default)
nothing in this module runs and the serving path is byte-identical to
the single-tenant behavior (tests/test_tenancy.py differential check).
"""

from __future__ import annotations

import hashlib
import threading
from typing import Optional

from ..core.engine import AccessController
from ..core.loader import (
    policy_from_dict,
    policy_set_from_dict,
    rule_from_dict,
)
from ..models.model import Decision, OperationStatus, Response
from ..ops.delta import (
    Capacities,
    CrudEvent,
    footprint_from_events,
    live_capacities,
)

# the capacity ladder: padded dims per class, smallest first.  Every
# tenant in one class compiles to the same padded shapes, so the class
# shares ONE jitted program per kernel variant (jax caches per-shape
# under the shared_jits entry).  Dims follow ops/delta.Capacities
# (S policy-set slots, KP policies/set, KR rules/policy, T target rows,
# RV (role,scoping) vocab, W entity-regex vocab) at pow2 steps.
SIZE_CLASSES: tuple = (
    ("xs", Capacities(S=2, KP=2, KR=4, T=16, RV=8, W=8)),
    ("s", Capacities(S=4, KP=4, KR=8, T=64, RV=16, W=16)),
    ("m", Capacities(S=8, KP=8, KR=16, T=256, RV=64, W=64)),
    ("l", Capacities(S=16, KP=16, KR=32, T=1024, RV=256, W=256)),
)

# class name for tenants whose trees overflow the top class: they serve
# from per-tenant capacity buckets (ops/delta.capacities_for) — correct,
# but each such tenant may cost its own compile
UNPINNED = "__unpinned__"

_KINDS = ("rule", "policy", "policy_set")

_COMPOSERS = {
    "rule": rule_from_dict,
    "policy": policy_from_dict,
    "policy_set": policy_set_from_dict,
}

# journal event-name stems, matching srv/store.ResourceService.KIND_EVENT
_KIND_EVENT = {"rule": "rule", "policy": "policy", "policy_set": "policySet"}


def class_for_live(live: Capacities) -> Optional[str]:
    """Smallest size class that fits ``live`` on every dim; None when the
    tree overflows the ladder (per-tenant buckets)."""
    for name, caps in SIZE_CLASSES:
        if all(
            getattr(live, dim) <= getattr(caps, dim)
            for dim in ("S", "KP", "KR", "T", "RV", "W")
        ):
            return name
    return None


def class_caps(name: Optional[str]) -> Optional[Capacities]:
    for cls_name, caps in SIZE_CLASSES:
        if cls_name == name:
            return caps
    return None


def unknown_tenant_response(tenant: str) -> Response:
    """Honest INDETERMINATE for a tenant id with no registered policy
    domain — never a default-domain decision (isolation), never cached
    (the tenant may onboard a moment later)."""
    return Response(
        decision=Decision.INDETERMINATE,
        obligations=[],
        evaluation_cacheable=False,
        operation_status=OperationStatus(
            code=404, message=f"unknown tenant: {tenant}"
        ),
    )


class TenantState:
    """One tenant's policy domain: flat document collections (the same
    3-kind shape as srv/store.PolicyStore), a per-tenant epoch, and the
    lazily built engine + evaluator."""

    def __init__(self, tenant_id: str):
        self.tenant_id = tenant_id
        self.docs: dict[str, dict] = {kind: {} for kind in _KINDS}
        # per-tenant policy epoch: CRUD frames applied to THIS tenant —
        # the number the convergence oracle compares across replicas
        self.epoch = 0
        self.size_class: Optional[str] = None
        self.engine: Optional[AccessController] = None
        self.evaluator = None

    def empty(self) -> bool:
        return not any(self.docs[kind] for kind in _KINDS)

    def compose_tree(self) -> dict:
        """The 3-level compose srv/store.PolicyStore._load_locked runs,
        over this tenant's collections."""
        rules = {
            d["id"]: rule_from_dict(d) for d in self.docs["rule"].values()
        }
        policies = {}
        for p_doc in self.docs["policy"].values():
            child_rules = [
                rules.get(rid) for rid in p_doc.get("rules") or []
            ]
            policy = policy_from_dict(p_doc)
            policy.combinables = {
                (r.id if r is not None else f"__missing_{i}"): r
                for i, r in enumerate(child_rules)
            }
            policies[p_doc["id"]] = policy
        tree: dict = {}
        for ps_doc in self.docs["policy_set"].values():
            child_policies = [
                policies.get(pid) for pid in ps_doc.get("policies") or []
            ]
            policy_set = policy_set_from_dict(ps_doc)
            policy_set.combinables = {
                (p.id if p is not None else f"__missing_{i}"): p
                for i, p in enumerate(child_policies)
            }
            tree[policy_set.id] = policy_set
        return tree


class TenantRegistry:
    """Tenant id -> policy domain, sharing compiled programs per size
    class.  Thread-safe: the batcher's eval worker, CRUD threads and the
    replicator pump all call in concurrently."""

    def __init__(
        self,
        urns,
        logger=None,
        telemetry=None,
        decision_cache=None,
        backend: str = "hybrid",
        store=None,
        observability=None,
        max_tenants: int = 100_000,
    ):
        self.urns = urns
        self.logger = logger
        self.telemetry = telemetry
        self.decision_cache = decision_cache
        self.backend = backend
        # PolicyStore: source of the journal topics + the origin stamp
        # for emitted frames (None = journaling off, e.g. unit tests)
        self.store = store
        self.observability = observability
        self.max_tenants = int(max_tenants)
        # ONE shared jit table across every tenant evaluator: jit entries
        # are keyed by kernel variant and jax caches per padded shape
        # underneath, so tenants in one size class (identical padded
        # shapes) lower to the same compiled program.  Program count =
        # compiled_program_count() = sum of per-entry shape-cache sizes.
        self._shared_jits: dict = {}
        self._lock = threading.RLock()
        self._tenants: dict[str, TenantState] = {}  # guarded-by: _lock
        self._stats = {  # guarded-by: _lock
            "onboarded": 0, "offboarded": 0, "frames_applied": 0,
            "frames_emitted": 0, "unpinned": 0,
        }

    # ------------------------------------------------------------- lookups

    def __contains__(self, tenant: str) -> bool:
        with self._lock:
            return tenant in self._tenants

    def tenant_ids(self) -> list[str]:
        with self._lock:
            return list(self._tenants)

    def tenant_epoch(self, tenant: str) -> Optional[int]:
        with self._lock:
            state = self._tenants.get(tenant)
            return state.epoch if state is not None else None

    def evaluator_for(self, tenant: str):
        """The tenant's evaluator, built lazily on first traffic (the
        build compiles against the class-shared jit table, so a cold
        tenant in a warm class pays tracing only when it is the FIRST of
        its class+shape; after that the program is a cache hit).  None
        for unknown tenants."""
        with self._lock:
            state = self._tenants.get(tenant)
            if state is None:
                return None
            if state.evaluator is None:
                self._build_evaluator(state)
            return state.evaluator

    # ----------------------------------------------------------- lifecycle

    def _build_evaluator(self, state: TenantState) -> None:  # holds: _lock
        from ..ops.compile import compile_policies
        from .evaluator import HybridEvaluator

        tree = state.compose_tree()
        engine = AccessController(urns=self.urns, logger=self.logger)
        engine.replace_policy_sets(tree)
        fixed = None
        try:
            raw = compile_policies(tree, self.urns, version=state.epoch)
            if raw.supported:
                state.size_class = class_for_live(live_capacities(raw))
                fixed = class_caps(state.size_class)
        except Exception:  # noqa: BLE001 — classification is best-effort
            state.size_class = None
        if state.size_class is None:
            self._stats["unpinned"] += 1
        state.engine = engine
        state.evaluator = HybridEvaluator(
            engine,
            backend=self.backend,
            logger=self.logger,
            telemetry=self.telemetry,
            decision_cache=self.decision_cache,
            delta_enabled=True,
            observability=self.observability,
            shared_jits=self._shared_jits,
            fixed_caps=fixed,
            tenant=state.tenant_id,
        )

    def offboard(self, tenant: str) -> bool:
        """Journaled offboarding: a collection-clear frame per kind (the
        same ``{"collection": True}`` Deleted frames the global store
        emits) — replicas replaying the journal converge to the tenant
        being gone.  The tenant's cache namespace is dropped with it."""
        with self._lock:
            if tenant not in self._tenants:
                return False
        for kind in _KINDS:
            self.apply(tenant, kind, "delete_all", None)
        return True

    # ----------------------------------------------------------------- CRUD

    def apply(self, tenant: str, kind: str, op: str,
              doc: Optional[dict], emit: bool = True) -> None:
        """Apply one CRUD mutation to ``tenant``'s domain: validate,
        update the tenant collections, bump the tenant epoch, scope the
        cache flush to the tenant, refresh the tenant evaluator (delta
        patch within its capacity class), and journal the frame.

        ``op``: "upsert" | "delete" | "delete_all".  An upsert for an
        unknown tenant onboards it (boot-by-replay is just this path fed
        from the journal)."""
        if kind not in _KINDS:
            raise ValueError(f"unknown resource kind: {kind}")
        if op == "upsert":
            _COMPOSERS[kind](doc)  # malformed docs rejected before state
            if not doc.get("id"):
                raise ValueError("document requires an id")
        with self._lock:
            state = self._tenants.get(tenant)
            if state is None:
                if op != "upsert":
                    return  # delete for an unknown tenant: no-op
                if len(self._tenants) >= self.max_tenants:
                    raise RuntimeError(
                        f"tenant registry full ({self.max_tenants})"
                    )
                state = TenantState(tenant)
                self._tenants[tenant] = state
                self._stats["onboarded"] += 1
            docs = state.docs[kind]
            if op == "upsert":
                events = [CrudEvent(
                    kind=kind, op="upsert", doc_id=doc["id"],
                    old_doc=docs.get(doc["id"]), new_doc=doc,
                )]
                docs[doc["id"]] = doc
            elif op == "delete":
                doc_id = (doc or {}).get("id") if isinstance(doc, dict) \
                    else doc
                if not doc_id or doc_id not in docs:
                    return
                events = [CrudEvent(
                    kind=kind, op="delete", doc_id=doc_id,
                    old_doc=docs.get(doc_id), new_doc=None,
                )]
                del docs[doc_id]
            elif op == "delete_all":
                events = [CrudEvent(kind=kind, op="delete_all", doc_id="")]
                state.docs[kind] = {}
            else:
                raise ValueError(f"unknown CRUD op: {op}")
            state.epoch += 1
            self._stats["frames_applied"] += 1
            self._sync_tenant(state, events)
            if state.empty():
                # all three collections cleared: the tenant is offboarded
                del self._tenants[tenant]
                self._stats["offboarded"] += 1
                if self.decision_cache is not None:
                    self.decision_cache.evict_pattern("", tenant=tenant)
        if emit:
            self._emit(tenant, kind, op, doc)

    def _sync_tenant(self, state: TenantState, events) -> None:
        """Tenant-scoped twin of srv/store.PolicyStore._load_locked:
        scoped cache bump BEFORE the tree swap, then engine swap, then
        evaluator refresh (delta patch or fixed-class recompile) — only
        THIS tenant's cache namespace and tables are touched."""
        # holds: _lock
        footprint = None
        try:
            footprint = footprint_from_events(
                events,
                self.urns,
                lambda kind, doc_id: state.docs[kind].get(doc_id),
                lambda kind: list(state.docs[kind].values()),
            )
        except Exception:  # noqa: BLE001 — footprint is an optimization
            footprint = None
        if self.decision_cache is not None:
            if footprint is not None and footprint.empty:
                pass
            elif footprint is not None:
                self.decision_cache.bump_scoped(
                    footprint, tenant=state.tenant_id
                )
            else:
                self.decision_cache.bump_epoch(tenant=state.tenant_id)
        if state.engine is not None:
            state.engine.replace_policy_sets(state.compose_tree())
        if state.evaluator is not None:
            state.evaluator.refresh(
                wait=True, events=events, footprint=footprint
            )

    # -------------------------------------------------------------- journal

    def _emit(self, tenant: str, kind: str, op: str,
              doc: Optional[dict]) -> None:
        """Emit the tenant-tagged CRUD frame on the same journal topics
        the global store uses — ``PolicyReplicator`` routes frames whose
        envelope carries a ``tenant`` key back into a registry."""
        store = self.store
        if store is None:
            return
        service = store.services.get(kind)
        topic = getattr(service, "topic", None)
        if topic is None:
            return
        stem = _KIND_EVENT[kind]
        if op == "upsert":
            event, payload = f"{stem}Modified", doc
        elif op == "delete":
            doc_id = doc.get("id") if isinstance(doc, dict) else doc
            event, payload = f"{stem}Deleted", {"id": doc_id}
        else:
            event, payload = f"{stem}Deleted", {"collection": True}
        topic.emit(event, {
            "payload": payload, "origin": store.origin, "tenant": tenant,
        })
        with self._lock:
            self._stats["frames_emitted"] += 1

    def apply_remote_frame(self, tenant: str, kind: str,
                           event_name: str, payload) -> None:
        """Replicator entry point: translate a journaled frame (local
        replay or a remote worker's live mutation) into an apply().  The
        frame is NOT re-emitted."""
        if not isinstance(payload, dict):
            return
        if event_name.endswith("Created") or event_name.endswith(
            "Modified"
        ):
            if payload.get("id"):
                self.apply(tenant, kind, "upsert", payload, emit=False)
        elif event_name.endswith("Deleted"):
            if payload.get("collection"):
                self.apply(tenant, kind, "delete_all", None, emit=False)
            elif payload.get("id"):
                self.apply(tenant, kind, "delete", payload, emit=False)

    # ---------------------------------------------------------------- stats

    def compiled_program_count(self) -> int:
        """Distinct lowered programs across every tenant evaluator: the
        per-shape cache size under each shared jit entry.  The packing
        claim: 1k tenants over <= len(SIZE_CLASSES) classes keep this at
        classes x kernel-variants, not O(tenants)."""
        total = 0
        for fn in dict(self._shared_jits).values():
            try:
                total += int(fn._cache_size())
            except Exception:  # noqa: BLE001 — non-jit entries count as 1
                total += 1
        return total

    def class_histogram(self) -> dict:
        with self._lock:
            hist: dict[str, int] = {}
            for state in self._tenants.values():
                name = (
                    state.size_class if state.size_class is not None
                    else (UNPINNED if state.evaluator is not None else
                          "__unbuilt__")
                )
                hist[name] = hist.get(name, 0) + 1
            return hist

    def epochs(self, top_k: int = 8) -> dict:
        """Highest per-tenant epochs (the busiest domains first) — the
        health/cluster_status surface keeps this bounded at ``top_k``."""
        with self._lock:
            items = sorted(
                ((t, s.epoch) for t, s in self._tenants.items()),
                key=lambda kv: kv[1], reverse=True,
            )
        return dict(items[:top_k])

    def epoch_digest(self) -> str:
        """Order-independent digest over (tenant, epoch) pairs: two
        replicas that applied the same journal converge to the same
        digest — the per-tenant analog of the policy epoch the router
        compares (srv/router.py cluster_status)."""
        h = hashlib.blake2b(digest_size=16)
        with self._lock:
            for tenant in sorted(self._tenants):
                state = self._tenants[tenant]
                h.update(f"{tenant}={state.epoch};".encode())
        return h.hexdigest()

    def fingerprints(self) -> dict:
        """Per-tenant table fingerprints for evaluators that are built —
        what the convergence oracle compares across replicas."""
        out = {}
        with self._lock:
            states = list(self._tenants.values())
        for state in states:
            if state.evaluator is not None:
                try:
                    out[state.tenant_id] = \
                        state.evaluator.table_fingerprint()
                except Exception:  # noqa: BLE001
                    pass
        return out

    def stats(self) -> dict:
        with self._lock:
            built = sum(
                1 for s in self._tenants.values()
                if s.evaluator is not None
            )
            out = {
                "tenant_count": len(self._tenants),
                "evaluators_built": built,
                **dict(self._stats),
            }
        out["size_classes"] = self.class_histogram()
        out["compiled_programs"] = self.compiled_program_count()
        out["epoch_top_k"] = self.epochs()
        out["epoch_digest"] = self.epoch_digest()
        return out

    def shutdown(self) -> None:
        with self._lock:
            states = list(self._tenants.values())
        for state in states:
            if state.evaluator is not None:
                try:
                    state.evaluator.shutdown()
                except Exception:  # noqa: BLE001
                    pass


def from_config(cfg, urns, logger=None, telemetry=None,
                decision_cache=None, store=None,
                observability=None) -> Optional[TenantRegistry]:
    """Build a TenantRegistry from the ``tenancy`` config block; None
    when disabled (the default — single-tenant path byte-identical)."""
    block = cfg.get("tenancy") if hasattr(cfg, "get") else None
    block = block or {}
    if not block.get("enabled", False):
        return None
    return TenantRegistry(
        urns,
        logger=logger,
        telemetry=telemetry,
        decision_cache=decision_cache,
        backend=block.get("backend") or (
            cfg.get("evaluator:backend", "hybrid")
            if hasattr(cfg, "get") else "hybrid"
        ),
        store=store,
        observability=observability,
        max_tenants=block.get("max_tenants", 100_000),
    )
