"""Observability: latency histograms, decision counters, secret-masking
structured logging, and JAX profiler hooks.

The reference's observability is winston structured logs with field masking
of secrets (``maskFields``: password/token, reference: cfg/config.json:10-46)
and no metrics endpoint; SURVEY.md §5 specifies the new framework adds a
JAX profiler + XLA dump hook on the evaluator and request-latency
histograms at the serving shell.  All collection here is lock-guarded,
allocation-free on the hot path (fixed log2 buckets), and exposed as a
plain dict snapshot (`Telemetry.snapshot`) that the command interface
serves from ``health_check``/``metrics``.
"""

from __future__ import annotations

import copy
import logging
import math
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Optional

# histogram buckets: upper bounds in seconds (log-spaced ~x4 from 50us to 50s)
_BUCKETS = [
    50e-6, 200e-6, 800e-6, 3.2e-3, 12.8e-3, 51.2e-3, 0.205, 0.82, 3.3, 13.1,
    52.4, float("inf"),
]

MASK_FIELDS = ("password", "token", "apiKey", "api_key", "authorization")
_LOWERED_MASK_FIELDS = tuple(f.lower() for f in MASK_FIELDS)
_MASK = "***"


def mask_secrets(obj: Any, fields: tuple = MASK_FIELDS) -> Any:
    """Deep-copy ``obj`` with secret-named fields replaced (the winston
    maskFields analog, reference: cfg/config.json:16-24).  Key matching is
    case-insensitive substring on the configured names."""
    lowered = tuple(f.lower() for f in fields)
    if isinstance(obj, dict):
        out = {}
        for key, value in obj.items():
            if isinstance(key, str) and any(f in key.lower() for f in lowered):
                out[key] = _MASK
            else:
                out[key] = mask_secrets(value, fields)
        return out
    if isinstance(obj, tuple):
        items = [mask_secrets(v, fields) for v in obj]
        if hasattr(obj, "_fields"):  # namedtuple: positional ctor
            return type(obj)(*items)
        return tuple(items)
    if isinstance(obj, list):
        return [mask_secrets(v, fields) for v in obj]
    return obj


_STANDARD_RECORD_FIELDS = frozenset(
    logging.LogRecord("", 0, "", 0, "", (), None).__dict__
) | {"message", "asctime", "taskName"}


class MaskingFilter(logging.Filter):
    """Masks secret fields inside dict/list log arguments and inside
    ``extra`` payloads (which land as non-standard LogRecord attributes)
    before they are formatted."""

    def filter(self, record: logging.LogRecord) -> bool:
        if isinstance(record.args, dict):
            record.args = mask_secrets(record.args)
        elif isinstance(record.args, tuple):
            record.args = tuple(
                mask_secrets(a) if isinstance(a, (dict, list)) else a
                for a in record.args
            )
        for key, value in list(record.__dict__.items()):
            if key in _STANDARD_RECORD_FIELDS:
                continue
            if isinstance(value, (dict, list)):
                setattr(record, key, mask_secrets(value))
            elif any(f in key.lower() for f in _LOWERED_MASK_FIELDS):
                # scalar extra under a secret-named key
                setattr(record, key, _MASK)
        return True


class JsonLinesFormatter(logging.Formatter):
    """One JSON object per record: timestamp, level, logger, message and
    every ``extra`` field (secrets already masked by MaskingFilter).  The
    shape log shippers (filebeat/fluent-bit/vector) ingest directly —
    the production log-shipping role the reference fills with a winston
    Elasticsearch transport (cfg/config_production.json:3-10); shipping
    is the collector's job, the service just emits structured lines."""

    def format(self, record: logging.LogRecord) -> str:
        import json

        out = {
            "@timestamp": self.formatTime(record, "%Y-%m-%dT%H:%M:%S%z"),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        for key, value in record.__dict__.items():
            if key in _STANDARD_RECORD_FIELDS or key in out:
                continue
            out[key] = value
        if record.exc_info:
            out["exception"] = self.formatException(record.exc_info)
        # default=repr: one serialization pass; non-JSON values degrade
        # to their repr instead of dropping the record
        return json.dumps(out, default=repr)


def make_logger(name: str = "access-control-srv-tpu",
                level: int = logging.INFO,
                json_sink: Optional[str] = None) -> logging.Logger:
    """``json_sink``: optional path; when set, masked records also append
    as JSON lines for an external shipper to tail (config key
    ``logging:json_sink`` — srv/worker.py)."""
    logger = logging.getLogger(name)
    logger.setLevel(level)
    if not any(isinstance(f, MaskingFilter) for f in logger.filters):
        logger.addFilter(MaskingFilter())
    if json_sink and not any(
        isinstance(h, logging.FileHandler)
        and getattr(h, "_acs_json_sink", None) == json_sink
        for h in logger.handlers
    ):
        handler = logging.FileHandler(json_sink)
        handler.setFormatter(JsonLinesFormatter())
        handler._acs_json_sink = json_sink
        logger.addHandler(handler)
    return logger


def estimate_percentiles(
    bounds: list, counts: list, qs: tuple = (0.5, 0.95, 0.99)
) -> list:
    """Bucket-interpolated percentile estimates: linear interpolation of
    the quantile position inside its bucket, between the previous bound
    and the bucket's own upper bound (0 below the first bucket; the inf
    bucket clamps to the last finite bound — the estimate cannot invent
    mass past what the histogram resolved)."""
    total = sum(counts)
    if total == 0:
        return [None] * len(qs)
    out = []
    for q in qs:
        rank = q * total
        cumulative = 0
        value = None
        for idx, (bound, count) in enumerate(zip(bounds, counts)):
            prev_cum = cumulative
            cumulative += count
            if cumulative >= rank:
                lo = bounds[idx - 1] if idx else 0.0
                hi = bound
                if math.isinf(hi):
                    value = float(lo)
                    break
                frac = (rank - prev_cum) / count if count else 1.0
                value = float(lo + (hi - lo) * frac)
                break
        out.append(value)
    return out


class Histogram:
    """Fixed-bucket latency histogram; thread-safe, O(1) observe."""

    def __init__(self):
        self._counts = [0] * len(_BUCKETS)  # guarded-by: _lock
        self._sum = 0.0  # guarded-by: _lock
        self._n = 0      # guarded-by: _lock
        self._lock = threading.Lock()

    def observe(self, seconds: float) -> None:
        idx = 0
        for idx, bound in enumerate(_BUCKETS):  # 12 buckets: linear scan ok
            if seconds <= bound:
                break
        with self._lock:
            self._counts[idx] += 1
            self._sum += seconds
            self._n += 1

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            total, n = self._sum, self._n
        p50, p95, p99 = estimate_percentiles(_BUCKETS, counts)
        out = {
            "count": n,
            "sum_s": round(total, 6),
            "mean_s": round(total / n, 6) if n else None,
            # bucket-interpolated estimates (operator-facing; raw buckets
            # below remain the ground truth)
            "p50_s": round(p50, 6) if p50 is not None else None,
            "p95_s": round(p95, 6) if p95 is not None else None,
            "p99_s": round(p99, 6) if p99 is not None else None,
            "buckets": {},
        }
        cumulative = 0
        for bound, count in zip(_BUCKETS, counts):
            cumulative += count
            label = "inf" if math.isinf(bound) else f"{bound:g}"
            out["buckets"][label] = cumulative
        return out


class ValueHistogram:
    """Fixed pow2-bucket histogram for dimensionless values (queue
    depths); thread-safe, O(1) observe like ``Histogram``."""

    BOUNDS = [1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096, 16384,
              65536, float("inf")]

    def __init__(self):
        self._counts = [0] * len(self.BOUNDS)  # guarded-by: _lock
        self._sum = 0.0  # guarded-by: _lock
        self._n = 0      # guarded-by: _lock
        self._max = 0.0  # guarded-by: _lock
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        idx = 0
        for idx, bound in enumerate(self.BOUNDS):
            if value <= bound:
                break
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._n += 1
            if value > self._max:
                self._max = value

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            total, n, peak = self._sum, self._n, self._max
        p50, p95, p99 = estimate_percentiles(self.BOUNDS, counts)
        out = {
            "count": n,
            "mean": round(total / n, 3) if n else None,
            "max": peak,
            "p50": round(p50, 3) if p50 is not None else None,
            "p95": round(p95, 3) if p95 is not None else None,
            "p99": round(p99, 3) if p99 is not None else None,
            "buckets": {},
        }
        cumulative = 0
        for bound, count in zip(self.BOUNDS, counts):
            cumulative += count
            label = "inf" if math.isinf(bound) else f"{bound:g}"
            out["buckets"][label] = cumulative
        return out


class TenantCounter:
    """Bounded-cardinality per-tenant event counter (srv/tenancy.py).

    Tenant ids arrive from request metadata — attacker-controlled — so a
    naive ``{tenant: count}`` map is an unbounded-cardinality attack on
    the metrics registry (10k distinct ids = 10k Prometheus series).
    Exact counts are kept for at most ``max_tracked`` distinct ids;
    events from ids beyond the bound aggregate under ``__other__``.
    Slots are first-come and never evicted: recycling a slot would make
    an exposed counter non-monotonic, which Prometheus ``rate()``
    misreads as a reset.  ``snapshot`` ranks tenants by traffic so the
    top-K stay visible regardless of arrival order."""

    OTHER = "__other__"

    def __init__(self, max_tracked: int = 64):
        self.max_tracked = int(max_tracked)
        self._tenants: set[str] = set()  # guarded-by: _lock
        # (event, tenant) -> count; at most max_tracked+1 tenant values
        self._values: dict[tuple, int] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def inc(self, kind: str, tenant: str, by: int = 1) -> None:
        kind, tenant = str(kind), str(tenant)
        with self._lock:
            if tenant != self.OTHER and tenant not in self._tenants:
                if len(self._tenants) >= self.max_tracked:
                    tenant = self.OTHER
                else:
                    self._tenants.add(tenant)
            key = (kind, tenant)
            self._values[key] = self._values.get(key, 0) + by

    def tracked(self) -> int:
        with self._lock:
            return len(self._tenants)

    def prom_snapshot(self) -> dict:
        """{(event, tenant): count} — the full tracked (bounded) set,
        for the Prometheus exposition."""
        with self._lock:
            return dict(self._values)

    def snapshot(self, top_k: int = 16) -> dict:
        """{event: {tenant: count}} with at most ``top_k`` tenants per
        event by traffic; trimmed tenants fold into ``__other__`` so the
        per-event totals stay exact."""
        with self._lock:
            items = dict(self._values)
        grouped: dict[str, dict[str, int]] = {}
        for (kind, tenant), count in items.items():
            grouped.setdefault(kind, {})[tenant] = count
        out: dict[str, dict[str, int]] = {}
        for kind, per_tenant in grouped.items():
            other = per_tenant.pop(self.OTHER, 0)
            ranked = sorted(per_tenant.items(),
                            key=lambda kv: (-kv[1], kv[0]))
            trimmed = dict(ranked[:top_k])
            other += sum(count for _, count in ranked[top_k:])
            if other:
                trimmed[self.OTHER] = other
            out[kind] = trimmed
        return out


class Counter:
    def __init__(self):
        self._values: dict[str, int] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def inc(self, key: str, by: int = 1) -> None:
        with self._lock:
            self._values[key] = self._values.get(key, 0) + by

    def get(self, key: str) -> int:
        with self._lock:
            return self._values.get(key, 0)

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._values)


class SampledLogger:
    """Rate-limited wrapper for hot-path log sites: at most
    ``max_per_interval`` records per key per ``interval_s`` window; the
    overflow is counted and flushed as ONE summary line when the window
    rolls.  A down upstream under overload turns per-row warnings
    (token-unresolved, oracle fallback, adapter retry) into tens of
    thousands of records per second — enough to make the masking logger
    itself the serving bottleneck; this caps the worst case at
    ``max_per_interval + 1`` records per key per window regardless of
    offered load.  Thread-safe; the fast (suppressed) path is one lock +
    one dict update, no formatting."""

    def __init__(self, logger, max_per_interval: int = 5,
                 interval_s: float = 10.0, time_fn=time.monotonic):
        self.logger = logger
        self.max_per_interval = int(max_per_interval)
        self.interval_s = float(interval_s)
        self._time = time_fn
        self._lock = threading.Lock()
        # key -> [window_start, emitted_in_window, suppressed_in_window]
        self._state: dict[str, list] = {}  # guarded-by: _lock

    def _gate(self, key: str) -> tuple[bool, int]:
        """(emit_now, suppressed_to_report): whether THIS record may log,
        and how many suppressed records the rolled window accumulated."""
        now = self._time()
        with self._lock:
            state = self._state.get(key)
            if state is None or now - state[0] >= self.interval_s:
                rolled = state[2] if state else 0
                self._state[key] = [now, 1, 0]
                return True, rolled
            if state[1] < self.max_per_interval:
                state[1] += 1
                return True, 0
            state[2] += 1
            return False, 0

    def _log(self, level: int, key: str, msg: str, *args, **kwargs) -> None:
        if self.logger is None:
            return
        emit, rolled = self._gate(key)
        if rolled:
            self.logger.log(
                level,
                "suppressed %d '%s' records in the last %.0fs "
                "(rate-limited hot-path logging)",
                rolled, key, self.interval_s,
            )
        if emit:
            self.logger.log(level, msg, *args, **kwargs)

    def warning(self, key: str, msg: str, *args, **kwargs) -> None:
        self._log(logging.WARNING, key, msg, *args, **kwargs)

    def info(self, key: str, msg: str, *args, **kwargs) -> None:
        self._log(logging.INFO, key, msg, *args, **kwargs)

    def suppressed(self, key: str) -> int:
        with self._lock:
            state = self._state.get(key)
            return state[2] if state else 0


# ------------------------------------------------- Prometheus exposition

def _prom_escape(value: str) -> str:
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _prom_bucket_label(bound: float) -> str:
    return "+Inf" if math.isinf(bound) else repr(float(bound))


class MetricsRegistry:
    """Named metric registry rendering the Prometheus text exposition
    format (version 0.0.4).  Entries hold LIVE references to the
    Counter/Histogram objects (or zero-arg callables for gauges and for
    late-bound histogram groups like the stage-tracer taxonomy), so
    ``render()`` always reflects the current state — there is no
    separate scrape-time collection step to keep in sync."""

    CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

    def __init__(self):
        self._entries: list[tuple] = []  # (kind, name, help, payload)

    def counter(self, name: str, help_text: str, counter: Counter,
                label: str = "key") -> None:
        self._entries.append(("counter", name, help_text, (counter, label)))

    def multi_counter(self, name: str, help_text: str,
                      snapshot_fn: Callable[[], dict],
                      labels: tuple) -> None:
        """Counter family with several labels: ``snapshot_fn`` returns
        ``{(value_per_label, ...): count}`` at render time."""
        self._entries.append(("multi_counter", name, help_text,
                              (snapshot_fn, labels)))

    def histogram(self, name: str, help_text: str, histogram) -> None:
        self._entries.append(("histogram", name, help_text,
                              (lambda: {None: histogram}, None)))

    def histogram_group(self, name: str, help_text: str,
                        group_fn: Callable[[], dict], label: str) -> None:
        """A family of histograms under one metric name, one label value
        per histogram (``group_fn`` returns {label_value: Histogram} and
        is consulted at render time — late-bound members appear)."""
        self._entries.append(("histogram", name, help_text,
                              (group_fn, label)))

    def gauge(self, name: str, help_text: str,
              value_fn: Callable[[], float]) -> None:
        self._entries.append(("gauge", name, help_text, value_fn))

    @staticmethod
    def _render_histogram(lines: list, name: str, histogram,
                          label: Optional[str], label_value) -> None:
        bounds = getattr(histogram, "BOUNDS", _BUCKETS)
        with histogram._lock:
            counts = list(histogram._counts)
            total, n = histogram._sum, histogram._n
        prefix = ""
        if label is not None:
            prefix = f'{label}="{_prom_escape(label_value)}",'
        cumulative = 0
        for bound, count in zip(bounds, counts):
            cumulative += count
            lines.append(
                f'{name}_bucket{{{prefix}le="{_prom_bucket_label(bound)}"}}'
                f" {cumulative}"
            )
        suffix = f'{{{label}="{_prom_escape(label_value)}"}}' \
            if label is not None else ""
        lines.append(f"{name}_sum{suffix} {total!r}")
        lines.append(f"{name}_count{suffix} {n}")

    def render(self) -> str:
        lines: list[str] = []
        for kind, name, help_text, payload in self._entries:
            if kind == "counter":
                counter, label = payload
                values = counter.snapshot()
                if not values:
                    continue
                lines.append(f"# HELP {name} {help_text}")
                lines.append(f"# TYPE {name} counter")
                for key in sorted(values):
                    lines.append(
                        f'{name}{{{label}="{_prom_escape(key)}"}} '
                        f"{values[key]}"
                    )
            elif kind == "multi_counter":
                snapshot_fn, labels = payload
                values = snapshot_fn()
                if not values:
                    continue
                lines.append(f"# HELP {name} {help_text}")
                lines.append(f"# TYPE {name} counter")
                for key in sorted(values):
                    pairs = ",".join(
                        f'{lbl}="{_prom_escape(val)}"'
                        for lbl, val in zip(labels, key)
                    )
                    lines.append(f"{name}{{{pairs}}} {values[key]}")
            elif kind == "histogram":
                group_fn, label = payload
                group = group_fn()
                if not group:
                    continue
                lines.append(f"# HELP {name} {help_text}")
                lines.append(f"# TYPE {name} histogram")
                for label_value in sorted(
                    group, key=lambda v: "" if v is None else str(v)
                ):
                    self._render_histogram(
                        lines, name, group[label_value], label, label_value
                    )
            else:  # gauge
                lines.append(f"# HELP {name} {help_text}")
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name} {payload()!r}")
        return "\n".join(lines) + "\n"


class PrometheusExporter:
    """Optional stdlib /metrics endpoint (observability:metrics_http):
    a daemon ThreadingHTTPServer serving the registry's text exposition
    on GET /metrics — the pull-model counterpart of the command
    interface's ``metrics`` command (same bytes, same registry).  Port 0
    binds an ephemeral port (tests); ``.port`` reports the bound one."""

    def __init__(self, telemetry: "Telemetry", host: str = "127.0.0.1",
                 port: int = 0, logger=None):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        registry = telemetry.registry

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — stdlib API name
                if self.path.split("?", 1)[0] != "/metrics":
                    self.send_error(404)
                    return
                body = registry.render().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 MetricsRegistry.CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes are not log traffic
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.host = host
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="acs-metrics-http",
        )
        self._thread.start()
        if logger is not None:
            logger.info("metrics endpoint up",
                        extra={"addr": f"{self.host}:{self.port}"})

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)


class Telemetry:
    """Per-worker metrics facade over a ``MetricsRegistry``: every
    counter/histogram below is registered with a Prometheus name at
    construction, so the full snapshot renders in text exposition format
    (``prometheus()``) without a separate collection step — the
    ``metrics`` command and the optional /metrics endpoint serve the
    same registry."""

    def __init__(self):
        self.is_allowed_latency = Histogram()
        self.what_is_allowed_latency = Histogram()
        self.batch_latency = Histogram()
        self.decisions = Counter()
        self.paths = Counter()  # kernel / oracle / native-wire / cache-hit rows
        self.cache = Counter()  # decision-cache hits / misses / evictions
        # token-resolution cache hits / misses / negative-hits / evictions
        # (srv/identity.TokenResolutionCache — the host eligibility
        # pipeline's identity-RPC amortizer)
        self.identity = Counter()
        # incremental policy-update subsystem (ops/delta.py): delta-patch /
        # full-compile / noop / fallback counts, shard re-slices under the
        # pod-sharded tier (shards_patched, parallel/pod_shard.py), and
        # the mutation-to-visibility latency (CRUD call to kernel swap)
        # per update
        self.delta = Counter()
        self.policy_update_latency = Histogram()
        # admission control (srv/admission.py): admitted / shed /
        # deadline-rejected / breaker-transition counters, the queue-depth
        # distribution at admit and the remaining-deadline-budget
        # distribution (seconds) of deadline-bearing requests
        self.admission = Counter()
        self.admission_queue_depth = ValueHistogram()
        self.admission_budget = Histogram()
        # deterministic fault injection (srv/faults.py): per-site hit
        # counts, fed by the registry's on_hit hook — operators see
        # exactly which failpoints fired and how often
        self.failpoints = Counter()
        # relation-tuple store (srv/relations.py): tuples_created /
        # tuples_deleted / rewrites / replicated-frame counts — the ReBAC
        # churn surface (tuple writes swap no program, so this counter is
        # the only operator-visible trace of relation mutations)
        self.relations = Counter()
        # shadow evaluation (srv/shadow.py): candidate-vs-production
        # decision diffs keyed by transition ("PERMIT->DENY", ...) plus
        # lifecycle events (evaluated/dropped/errors).  Both stay empty —
        # and the snapshot block absent — unless a shadow is loaded.
        self.shadow_diffs = Counter()
        self.shadow = Counter()
        # permission-lattice audit sweeps (srv/audit_sweep.py): job
        # lifecycle (jobs_started/completed/cancelled/failed), progress
        # (chunks/cells), bulk-class shed/retry counts and diff volume.
        # Stays empty — and the snapshot block absent — unless the audit
        # subsystem is enabled and a sweep has run.
        self.audit = Counter()
        # per-tenant serving events (srv/tenancy.py): decision / shed /
        # cache_hit / cache_miss per tenant id, cardinality-bounded —
        # see TenantCounter
        self.tenants = TenantCounter()
        # device-hang watchdog (srv/watchdog.py): attached by the worker
        # when enabled; the degraded/quarantine gauges read 0 without one
        self._watchdog = None
        # per-stage pipeline durations (srv/tracing.StageTracer writes
        # here): stage name -> Histogram.  Empty unless tracing is
        # enabled, so the snapshot/exposition surface only grows when the
        # operator asked for attribution.
        self.stages: dict[str, Histogram] = {}  # guarded-by: _snapshot_lock
        # acs-lint: ignore[wall-clock] human-facing uptime epoch stamp —
        # operators expect a wall-time "since" value; never used in
        # deadline or TTL arithmetic
        self.start_time = time.time()
        self._snapshot_lock = threading.Lock()
        self.registry = MetricsRegistry()
        self._register_all()

    def _register_all(self) -> None:
        reg = self.registry
        reg.gauge("acs_uptime_seconds", "Worker uptime",
                  # acs-lint: ignore[wall-clock] human-facing uptime display
                  lambda: round(time.time() - self.start_time, 3))
        reg.histogram("acs_is_allowed_latency_seconds",
                      "isAllowed end-to-end latency", self.is_allowed_latency)
        reg.histogram("acs_what_is_allowed_latency_seconds",
                      "whatIsAllowed end-to-end latency",
                      self.what_is_allowed_latency)
        reg.histogram("acs_batch_latency_seconds",
                      "Batched isAllowed latency", self.batch_latency)
        reg.counter("acs_decisions_total", "Decisions served by value",
                    self.decisions, label="decision")
        reg.counter("acs_serving_path_rows_total",
                    "Rows served per path (kernel/oracle/native-wire/"
                    "cache-hit/...)", self.paths, label="path")
        reg.counter("acs_decision_cache_events_total",
                    "Decision-cache hits/misses/evictions",
                    self.cache, label="event")
        reg.counter("acs_identity_cache_events_total",
                    "Token-resolution cache events",
                    self.identity, label="event")
        reg.counter("acs_policy_update_events_total",
                    "Incremental policy-update events (ops/delta.py)",
                    self.delta, label="event")
        reg.histogram("acs_policy_update_latency_seconds",
                      "Mutation-to-visibility latency",
                      self.policy_update_latency)
        reg.counter("acs_admission_events_total",
                    "Admission control events (srv/admission.py)",
                    self.admission, label="event")
        reg.histogram("acs_admission_queue_depth",
                      "Queue depth at admit", self.admission_queue_depth)
        reg.histogram("acs_admission_budget_seconds",
                      "Remaining deadline budget at admit",
                      self.admission_budget)
        reg.multi_counter(
            "acs_tenant_events_total",
            "Per-tenant serving events (decision/shed/cache_hit/...; "
            "cardinality-bounded, overflow under __other__)",
            self.tenants.prom_snapshot, labels=("event", "tenant"),
        )
        reg.counter("acs_failpoint_hits_total",
                    "Deterministic fault-injection hits per site "
                    "(srv/faults.py)", self.failpoints, label="site")
        reg.counter("acs_relation_events_total",
                    "Relation-tuple store events (srv/relations.py)",
                    self.relations, label="event")
        reg.counter("acs_shadow_diffs_total",
                    "Candidate-vs-production decision diffs by transition "
                    "(srv/shadow.py)", self.shadow_diffs,
                    label="transition")
        reg.counter("acs_shadow_events_total",
                    "Shadow-evaluation lifecycle events "
                    "(evaluated/dropped/errors)", self.shadow,
                    label="event")
        reg.counter("acs_audit_events_total",
                    "Permission-lattice audit-sweep events "
                    "(srv/audit_sweep.py)", self.audit, label="event")
        reg.gauge("acs_degraded_seconds",
                  "Cumulative seconds the device kernel path has been "
                  "quarantined (srv/watchdog.py)", self._degraded_seconds)
        reg.gauge("acs_device_quarantined",
                  "1 while the device kernel path is quarantined",
                  self._quarantined_gauge)
        reg.histogram_group(
            "acs_stage_duration_seconds",
            "Per-stage pipeline duration (srv/tracing.py taxonomy)",
            self._stages_view, label="stage",
        )

    def set_watchdog(self, watchdog) -> None:
        """Attach the device watchdog so the degraded/quarantine gauges
        and the snapshot's device_watchdog block read live state."""
        self._watchdog = watchdog

    def _degraded_seconds(self) -> float:
        watchdog = self._watchdog
        if watchdog is None:
            return 0.0
        return round(watchdog.degraded_seconds(), 3)

    def _quarantined_gauge(self) -> int:
        watchdog = self._watchdog
        return int(watchdog is not None and watchdog.quarantined)

    def _stages_view(self) -> dict:
        """Consistent copy of the stage-histogram map for render():
        iterating the LIVE dict while stage_histogram inserts a late-bound
        stage raises ``dict changed size during iteration`` mid-scrape."""
        with self._snapshot_lock:
            return dict(self.stages)

    def stage_histogram(self, stage: str) -> Histogram:
        """The (lazily created) histogram for one pipeline stage."""
        # acs-lint: ignore[guarded-by] benign racy fast path: a dict.get
        # miss falls through to the locked setdefault; entries are never
        # removed, so a hit is always the canonical histogram
        hist = self.stages.get(stage)
        if hist is None:
            with self._snapshot_lock:
                hist = self.stages.setdefault(stage, Histogram())
        return hist

    def prometheus(self) -> str:
        """The full snapshot in Prometheus text exposition format."""
        return self.registry.render()

    @contextmanager
    def timed(self, histogram: Histogram):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            histogram.observe(time.perf_counter() - t0)

    def tenant_inc(self, kind: str, tenant: str, by: int = 1) -> None:
        """Per-tenant counter hook (admission sheds, tenant decisions,
        cache events); safe at any cardinality — overflow ids aggregate
        under ``__other__``."""
        self.tenants.inc(kind, tenant, by)

    def record_decision(self, decision: str) -> None:
        self.decisions.inc(decision)

    def record_path(self, path: str, rows: int = 1) -> None:
        self.paths.inc(path, rows)

    def snapshot(self) -> dict:
        # watchdog/failpoint state reads its own locks BEFORE the snapshot
        # lock — no nested lock order between telemetry and the watchdog
        watchdog = self._watchdog
        wd_status = None if watchdog is None else watchdog.status()
        from .faults import REGISTRY as _faults_registry

        failpoint_hits = self.failpoints.snapshot()
        faults_enabled = _faults_registry.enabled
        tenant_events = self.tenants.snapshot()
        # assembled under the snapshot lock and returned as a DEEP copy:
        # concurrent `metrics`/`health_check` readers serialize their own
        # private tree — they can never observe a dict mutating under a
        # concurrent writer mid-json.dumps (each sub-snapshot is already
        # a copy; the deep copy also detaches anything a future metric
        # nests by reference)
        with self._snapshot_lock:
            out = {
                # acs-lint: ignore[wall-clock] human-facing uptime display
                "uptime_s": round(time.time() - self.start_time, 3),
                "is_allowed_latency": self.is_allowed_latency.snapshot(),
                "what_is_allowed_latency":
                    self.what_is_allowed_latency.snapshot(),
                "batch_latency": self.batch_latency.snapshot(),
                "decisions": self.decisions.snapshot(),
                "paths": self.paths.snapshot(),
                "decision_cache": self.cache.snapshot(),
                "identity_cache": self.identity.snapshot(),
                "policy_update": {
                    **self.delta.snapshot(),
                    "latency": self.policy_update_latency.snapshot(),
                },
                "admission": {
                    **self.admission.snapshot(),
                    "queue_depth": self.admission_queue_depth.snapshot(),
                    "budget_s": self.admission_budget.snapshot(),
                },
            }
            if self.stages:
                out["stages"] = {
                    stage: hist.snapshot()
                    for stage, hist in sorted(self.stages.items())
                }
            # fault-injection / device-health blocks only appear when the
            # subsystems are live — snapshots of an untouched worker stay
            # byte-identical to the pre-failpoint shape
            # per-tenant events only appear once a tenant-tagged request
            # was served — untenanted workers keep the exact legacy shape
            if tenant_events:
                out["tenants"] = tenant_events
            relation_events = self.relations.snapshot()
            if relation_events:
                out["relations"] = relation_events
            shadow_events = self.shadow.snapshot()
            shadow_diffs = self.shadow_diffs.snapshot()
            if shadow_events or shadow_diffs:
                out["shadow"] = {**shadow_events, "diffs": shadow_diffs}
            audit_events = self.audit.snapshot()
            if audit_events:
                out["audit"] = audit_events
            if faults_enabled or failpoint_hits:
                out["failpoints"] = {
                    "enabled": faults_enabled,
                    "hits": failpoint_hits,
                }
            if wd_status is not None:
                out["device_watchdog"] = wd_status
            return copy.deepcopy(out)


@contextmanager
def profile_evaluator(out_dir: str, host_tracer_level: int = 2):
    """JAX profiler capture around an evaluation region; the trace lands in
    ``out_dir`` for xprof/tensorboard (SURVEY.md §5 tracing hook)."""
    import jax

    jax.profiler.start_trace(out_dir, create_perfetto_link=False)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def xla_dump_flags(out_dir: str) -> str:
    """The XLA_FLAGS value that dumps HLO for the compiled kernels; set
    before the first jit for compiler-level inspection."""
    return f"--xla_dump_to={out_dir}"
