"""Observability: latency histograms, decision counters, secret-masking
structured logging, and JAX profiler hooks.

The reference's observability is winston structured logs with field masking
of secrets (``maskFields``: password/token, reference: cfg/config.json:10-46)
and no metrics endpoint; SURVEY.md §5 specifies the new framework adds a
JAX profiler + XLA dump hook on the evaluator and request-latency
histograms at the serving shell.  All collection here is lock-guarded,
allocation-free on the hot path (fixed log2 buckets), and exposed as a
plain dict snapshot (`Telemetry.snapshot`) that the command interface
serves from ``health_check``/``metrics``.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from contextlib import contextmanager
from typing import Any, Optional

# histogram buckets: upper bounds in seconds (log-spaced ~x4 from 50us to 50s)
_BUCKETS = [
    50e-6, 200e-6, 800e-6, 3.2e-3, 12.8e-3, 51.2e-3, 0.205, 0.82, 3.3, 13.1,
    52.4, float("inf"),
]

MASK_FIELDS = ("password", "token", "apiKey", "api_key", "authorization")
_LOWERED_MASK_FIELDS = tuple(f.lower() for f in MASK_FIELDS)
_MASK = "***"


def mask_secrets(obj: Any, fields: tuple = MASK_FIELDS) -> Any:
    """Deep-copy ``obj`` with secret-named fields replaced (the winston
    maskFields analog, reference: cfg/config.json:16-24).  Key matching is
    case-insensitive substring on the configured names."""
    lowered = tuple(f.lower() for f in fields)
    if isinstance(obj, dict):
        out = {}
        for key, value in obj.items():
            if isinstance(key, str) and any(f in key.lower() for f in lowered):
                out[key] = _MASK
            else:
                out[key] = mask_secrets(value, fields)
        return out
    if isinstance(obj, tuple):
        items = [mask_secrets(v, fields) for v in obj]
        if hasattr(obj, "_fields"):  # namedtuple: positional ctor
            return type(obj)(*items)
        return tuple(items)
    if isinstance(obj, list):
        return [mask_secrets(v, fields) for v in obj]
    return obj


_STANDARD_RECORD_FIELDS = frozenset(
    logging.LogRecord("", 0, "", 0, "", (), None).__dict__
) | {"message", "asctime", "taskName"}


class MaskingFilter(logging.Filter):
    """Masks secret fields inside dict/list log arguments and inside
    ``extra`` payloads (which land as non-standard LogRecord attributes)
    before they are formatted."""

    def filter(self, record: logging.LogRecord) -> bool:
        if isinstance(record.args, dict):
            record.args = mask_secrets(record.args)
        elif isinstance(record.args, tuple):
            record.args = tuple(
                mask_secrets(a) if isinstance(a, (dict, list)) else a
                for a in record.args
            )
        for key, value in list(record.__dict__.items()):
            if key in _STANDARD_RECORD_FIELDS:
                continue
            if isinstance(value, (dict, list)):
                setattr(record, key, mask_secrets(value))
            elif any(f in key.lower() for f in _LOWERED_MASK_FIELDS):
                # scalar extra under a secret-named key
                setattr(record, key, _MASK)
        return True


class JsonLinesFormatter(logging.Formatter):
    """One JSON object per record: timestamp, level, logger, message and
    every ``extra`` field (secrets already masked by MaskingFilter).  The
    shape log shippers (filebeat/fluent-bit/vector) ingest directly —
    the production log-shipping role the reference fills with a winston
    Elasticsearch transport (cfg/config_production.json:3-10); shipping
    is the collector's job, the service just emits structured lines."""

    def format(self, record: logging.LogRecord) -> str:
        import json

        out = {
            "@timestamp": self.formatTime(record, "%Y-%m-%dT%H:%M:%S%z"),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        for key, value in record.__dict__.items():
            if key in _STANDARD_RECORD_FIELDS or key in out:
                continue
            out[key] = value
        if record.exc_info:
            out["exception"] = self.formatException(record.exc_info)
        # default=repr: one serialization pass; non-JSON values degrade
        # to their repr instead of dropping the record
        return json.dumps(out, default=repr)


def make_logger(name: str = "access-control-srv-tpu",
                level: int = logging.INFO,
                json_sink: Optional[str] = None) -> logging.Logger:
    """``json_sink``: optional path; when set, masked records also append
    as JSON lines for an external shipper to tail (config key
    ``logging:json_sink`` — srv/worker.py)."""
    logger = logging.getLogger(name)
    logger.setLevel(level)
    if not any(isinstance(f, MaskingFilter) for f in logger.filters):
        logger.addFilter(MaskingFilter())
    if json_sink and not any(
        isinstance(h, logging.FileHandler)
        and getattr(h, "_acs_json_sink", None) == json_sink
        for h in logger.handlers
    ):
        handler = logging.FileHandler(json_sink)
        handler.setFormatter(JsonLinesFormatter())
        handler._acs_json_sink = json_sink
        logger.addHandler(handler)
    return logger


class Histogram:
    """Fixed-bucket latency histogram; thread-safe, O(1) observe."""

    def __init__(self):
        self._counts = [0] * len(_BUCKETS)
        self._sum = 0.0
        self._n = 0
        self._lock = threading.Lock()

    def observe(self, seconds: float) -> None:
        idx = 0
        for idx, bound in enumerate(_BUCKETS):  # 12 buckets: linear scan ok
            if seconds <= bound:
                break
        with self._lock:
            self._counts[idx] += 1
            self._sum += seconds
            self._n += 1

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            total, n = self._sum, self._n
        out = {
            "count": n,
            "sum_s": round(total, 6),
            "mean_s": round(total / n, 6) if n else None,
            "buckets": {},
        }
        cumulative = 0
        for bound, count in zip(_BUCKETS, counts):
            cumulative += count
            label = "inf" if math.isinf(bound) else f"{bound:g}"
            out["buckets"][label] = cumulative
        return out


class ValueHistogram:
    """Fixed pow2-bucket histogram for dimensionless values (queue
    depths); thread-safe, O(1) observe like ``Histogram``."""

    BOUNDS = [1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096, 16384,
              65536, float("inf")]

    def __init__(self):
        self._counts = [0] * len(self.BOUNDS)
        self._sum = 0.0
        self._n = 0
        self._max = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        idx = 0
        for idx, bound in enumerate(self.BOUNDS):
            if value <= bound:
                break
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._n += 1
            if value > self._max:
                self._max = value

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            total, n, peak = self._sum, self._n, self._max
        out = {
            "count": n,
            "mean": round(total / n, 3) if n else None,
            "max": peak,
            "buckets": {},
        }
        cumulative = 0
        for bound, count in zip(self.BOUNDS, counts):
            cumulative += count
            label = "inf" if math.isinf(bound) else f"{bound:g}"
            out["buckets"][label] = cumulative
        return out


class Counter:
    def __init__(self):
        self._values: dict[str, int] = {}
        self._lock = threading.Lock()

    def inc(self, key: str, by: int = 1) -> None:
        with self._lock:
            self._values[key] = self._values.get(key, 0) + by

    def get(self, key: str) -> int:
        with self._lock:
            return self._values.get(key, 0)

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._values)


class Telemetry:
    """Per-worker metrics registry wired into the service facade."""

    def __init__(self):
        self.is_allowed_latency = Histogram()
        self.what_is_allowed_latency = Histogram()
        self.batch_latency = Histogram()
        self.decisions = Counter()
        self.paths = Counter()  # kernel / oracle / native-wire / cache-hit rows
        self.cache = Counter()  # decision-cache hits / misses / evictions
        # token-resolution cache hits / misses / negative-hits / evictions
        # (srv/identity.TokenResolutionCache — the host eligibility
        # pipeline's identity-RPC amortizer)
        self.identity = Counter()
        # incremental policy-update subsystem (ops/delta.py): delta-patch /
        # full-compile / noop / fallback counts, and the mutation-to-
        # visibility latency (CRUD call to kernel swap) per update
        self.delta = Counter()
        self.policy_update_latency = Histogram()
        # admission control (srv/admission.py): admitted / shed /
        # deadline-rejected / breaker-transition counters, the queue-depth
        # distribution at admit and the remaining-deadline-budget
        # distribution (seconds) of deadline-bearing requests
        self.admission = Counter()
        self.admission_queue_depth = ValueHistogram()
        self.admission_budget = Histogram()
        self.start_time = time.time()

    @contextmanager
    def timed(self, histogram: Histogram):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            histogram.observe(time.perf_counter() - t0)

    def record_decision(self, decision: str) -> None:
        self.decisions.inc(decision)

    def record_path(self, path: str, rows: int = 1) -> None:
        self.paths.inc(path, rows)

    def snapshot(self) -> dict:
        return {
            "uptime_s": round(time.time() - self.start_time, 3),
            "is_allowed_latency": self.is_allowed_latency.snapshot(),
            "what_is_allowed_latency": self.what_is_allowed_latency.snapshot(),
            "batch_latency": self.batch_latency.snapshot(),
            "decisions": self.decisions.snapshot(),
            "paths": self.paths.snapshot(),
            "decision_cache": self.cache.snapshot(),
            "identity_cache": self.identity.snapshot(),
            "policy_update": {
                **self.delta.snapshot(),
                "latency": self.policy_update_latency.snapshot(),
            },
            "admission": {
                **self.admission.snapshot(),
                "queue_depth": self.admission_queue_depth.snapshot(),
                "budget_s": self.admission_budget.snapshot(),
            },
        }


@contextmanager
def profile_evaluator(out_dir: str, host_tracer_level: int = 2):
    """JAX profiler capture around an evaluation region; the trace lands in
    ``out_dir`` for xprof/tensorboard (SURVEY.md §5 tracing hook)."""
    import jax

    jax.profiler.start_trace(out_dir, create_perfetto_link=False)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def xla_dump_flags(out_dir: str) -> str:
    """The XLA_FLAGS value that dumps HLO for the compiled kernels; set
    before the first jit for compiler-level inspection."""
    return f"--xla_dump_to={out_dir}"
