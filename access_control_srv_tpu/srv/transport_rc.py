"""Reference-wire compatibility layer.

Registers the worker's services under the RESTORECOMMERCE wire names —
``io.restorecommerce.access_control.AccessControlService`` (IsAllowed /
WhatIsAllowed), the three CRUD services
(``io.restorecommerce.rule.RuleService`` et al.),
``io.restorecommerce.commandinterface.CommandInterfaceService`` and
``grpc.health.v1.Health`` — with the message shapes of the public
restorecommerce protos, so a stock restorecommerce client (e.g.
acs-client) can call this service unmodified.  The reference binds
exactly these definitions (reference: src/worker.ts:160-194,
RuleServiceDefinition / PolicyServiceDefinition /
PolicySetServiceDefinition / AccessControlServiceDefinition /
CommandInterfaceServiceDefinition / HealthDefinition).

The proto files under proto/rc/ are a RECONSTRUCTION of the public
``@restorecommerce/protos`` package (github.com/restorecommerce/libs,
packages/protos/io/restorecommerce/*.proto): this environment has no
network access to vendor the originals, so field numbers follow the
public protos' declaration order and the subset covers the surface this
service binds.  docs/WIRE_COMPAT.md records the reconstruction status
per message.

Known proto3 semantic edge: ``Effect`` has no presence, so an unset
policy effect is indistinguishable from PERMIT(0) on the wire.  Rules
always carry an effect; for policies the ambiguity is harmless when the
policy has rules (the engine only consults policy effect when its rule
list is empty — reference: accessController.ts:198-200), and a no-rules
policy maps PERMIT(0) to an explicit PERMIT effect.
"""

from __future__ import annotations

import json
import time

import grpc

from ..models.model import Attribute, Request, Target
from .admission import deadline_from_context, tenant_from_metadata
from .tracing import (
    STAGE_SERIALIZE,
    STAGE_TRANSPORT_PARSE,
    echo_trace_id,
    trace_id_from_metadata,
)
from .gen.rc import access_control_pb2 as rc_ac
from .gen.rc import attribute_pb2 as rc_attr
from .gen.rc import commandinterface_pb2 as rc_ci
from .gen.rc import health_pb2 as rc_health
from .gen.rc import policy_pb2 as rc_policy
from .gen.rc import policy_set_pb2 as rc_policy_set
from .gen.rc import resource_base_pb2 as rc_rb
from .gen.rc import rule_pb2 as rc_rule
from .gen.rc import status_pb2 as rc_status
from .transport_grpc import _ctx_value_from_pb, _unary

# rc Decision enum: PERMIT=0, DENY=1, INDETERMINATE=2 (Response.Decision)
_DECISION_TO_RC = {
    "PERMIT": rc_ac.Response.PERMIT,
    "DENY": rc_ac.Response.DENY,
    "INDETERMINATE": rc_ac.Response.INDETERMINATE,
}
_EFFECT_TO_RC = {"PERMIT": rc_rule.PERMIT, "DENY": rc_rule.DENY}
_RC_TO_EFFECT = {rc_rule.PERMIT: "PERMIT", rc_rule.DENY: "DENY"}


# ------------------------------------------------------------- converters

def _attr_from_rc(msg) -> Attribute:
    return Attribute(
        id=msg.id, value=msg.value,
        attributes=[_attr_from_rc(a) for a in msg.attributes],
    )


def _attr_to_rc(attr: Attribute):
    return rc_attr.Attribute(
        id=attr.id or "", value=attr.value or "",
        attributes=[_attr_to_rc(a) for a in attr.attributes or []],
    )


def _target_from_rc(msg) -> Target:
    return Target(
        subjects=[_attr_from_rc(a) for a in msg.subjects],
        resources=[_attr_from_rc(a) for a in msg.resources],
        actions=[_attr_from_rc(a) for a in msg.actions],
    )


def _target_to_rc(target: Target):
    return rc_rule.Target(
        subjects=[_attr_to_rc(a) for a in target.subjects or []],
        resources=[_attr_to_rc(a) for a in target.resources or []],
        actions=[_attr_to_rc(a) for a in target.actions or []],
    )


# google.protobuf.Any carrying JSON bytes — the reference unmarshals
# context Any values as JSON (accessControlService.ts:103-125); the
# field shape matches the internal ContextValue so the acstpu converter
# is shared
_any_from_rc = _ctx_value_from_pb


def request_from_rc(msg) -> Request:
    context = None
    if msg.HasField("context"):
        context = {}
        if msg.context.HasField("subject"):
            context["subject"] = _any_from_rc(msg.context.subject)
        context["resources"] = [
            _any_from_rc(r) for r in msg.context.resources
        ]
        if msg.context.HasField("security"):
            context["security"] = _any_from_rc(msg.context.security)
    target = _target_from_rc(msg.target) if msg.HasField("target") else None
    return Request(target=target, context=context)


def response_to_rc(response):
    return rc_ac.Response(
        decision=_DECISION_TO_RC.get(
            response.decision, rc_ac.Response.INDETERMINATE
        ),
        obligations=[_attr_to_rc(a) for a in response.obligations or []],
        evaluation_cacheable=bool(response.evaluation_cacheable),
        operation_status=rc_status.OperationStatus(
            code=response.operation_status.code,
            message=response.operation_status.message,
        ),
    )


def reverse_query_to_rc(rq):
    out = rc_ac.ReverseQuery(
        obligations=[_attr_to_rc(a) for a in rq.obligations or []],
        operation_status=rc_status.OperationStatus(
            code=rq.operation_status.code,
            message=rq.operation_status.message,
        ),
    )
    for ps in rq.policy_sets:
        ps_msg = out.policy_sets.add(
            id=ps.id or "",
            combining_algorithm=ps.combining_algorithm or "",
        )
        if ps.effect:
            ps_msg.effect = _EFFECT_TO_RC.get(ps.effect, rc_rule.PERMIT)
        if ps.target is not None:
            ps_msg.target.CopyFrom(_target_to_rc(ps.target))
        for pol in ps.policies:
            p_msg = ps_msg.policies.add(
                id=pol.id or "",
                combining_algorithm=pol.combining_algorithm or "",
                evaluation_cacheable=bool(pol.evaluation_cacheable),
                has_rules=bool(pol.has_rules),
            )
            if pol.effect:
                p_msg.effect = _EFFECT_TO_RC.get(pol.effect, rc_rule.PERMIT)
            if pol.target is not None:
                p_msg.target.CopyFrom(_target_to_rc(pol.target))
            for rule in pol.rules:
                r_msg = p_msg.rules.add(
                    id=rule.id or "",
                    effect=_EFFECT_TO_RC.get(rule.effect, rc_rule.PERMIT),
                    condition=rule.condition or "",
                    evaluation_cacheable=bool(rule.evaluation_cacheable),
                )
                if rule.target is not None:
                    r_msg.target.CopyFrom(_target_to_rc(rule.target))
                if rule.context_query is not None:
                    r_msg.context_query.query = rule.context_query.query or ""
                    if rule.context_query.filters:
                        flt = r_msg.context_query.filters.add()
                        for f in rule.context_query.filters:
                            flt.filters.add(
                                field=str(f.get("field") or ""),
                                operation=str(f.get("operation") or ""),
                                value=str(f.get("value") or ""),
                            )
    return out


def _attr_dict_from_rc(msg) -> dict:
    return {
        "id": msg.id,
        "value": msg.value,
        "attributes": [_attr_dict_from_rc(a) for a in msg.attributes],
    }


def _target_dict_from_rc(msg) -> dict:
    return {
        "subjects": [_attr_dict_from_rc(a) for a in msg.subjects],
        "resources": [_attr_dict_from_rc(a) for a in msg.resources],
        "actions": [_attr_dict_from_rc(a) for a in msg.actions],
    }


def _meta_dict_from_rc(msg) -> dict:
    out = {
        "owners": [_attr_dict_from_rc(a) for a in msg.owners],
        "acls": [_attr_dict_from_rc(a) for a in msg.acls],
    }
    if msg.created:
        out["created"] = msg.created
    if msg.modified:
        out["modified"] = msg.modified
    return out


def rule_doc_from_rc(msg) -> dict:
    doc = {
        "id": msg.id,
        "name": msg.name,
        "description": msg.description,
        "effect": _RC_TO_EFFECT.get(msg.effect, "PERMIT"),
        "condition": msg.condition,
        "evaluation_cacheable": msg.evaluation_cacheable,
    }
    if msg.HasField("target"):
        doc["target"] = _target_dict_from_rc(msg.target)
    if msg.HasField("context_query"):
        # the internal model keeps one flat filter list (the adapter
        # resolves filters as a set, srv/adapters.py); multi-group
        # grouping flattens on ingest — re-emission uses a single group
        filters = []
        for group in msg.context_query.filters:
            for f in group.filters:
                filters.append({"field": f.field, "operation": f.operation,
                                "value": f.value})
        doc["context_query"] = {
            "query": msg.context_query.query, "filters": filters,
        }
    if msg.HasField("meta"):
        doc["meta"] = _meta_dict_from_rc(msg.meta)
    return doc


def policy_doc_from_rc(msg) -> dict:
    rules = list(msg.rules)
    if msg.effect == rc_rule.DENY:
        effect = "DENY"
    elif not rules:
        effect = "PERMIT"
    else:
        # proto3 presence gap: PERMIT(0) on a rules-bearing policy is
        # indistinguishable from unset; rules dominate either way (see
        # module docstring)
        effect = None
    doc = {
        "id": msg.id,
        "name": msg.name,
        "description": msg.description,
        "effect": effect,
        "combining_algorithm": msg.combining_algorithm,
        "rules": rules,
        "evaluation_cacheable": msg.evaluation_cacheable,
    }
    if msg.HasField("target"):
        doc["target"] = _target_dict_from_rc(msg.target)
    if msg.HasField("meta"):
        doc["meta"] = _meta_dict_from_rc(msg.meta)
    return doc


def policy_set_doc_from_rc(msg) -> dict:
    doc = {
        "id": msg.id,
        "name": msg.name,
        "description": msg.description,
        "combining_algorithm": msg.combining_algorithm,
        "policies": list(msg.policies),
    }
    if msg.HasField("target"):
        doc["target"] = _target_dict_from_rc(msg.target)
    if msg.HasField("meta"):
        doc["meta"] = _meta_dict_from_rc(msg.meta)
    return doc


def _attr_rc_from_dict(d: dict):
    return rc_attr.Attribute(
        id=str(d.get("id") or ""), value=str(d.get("value") or ""),
        attributes=[_attr_rc_from_dict(a) for a in d.get("attributes") or []],
    )


def _fill_common_rc(msg, doc: dict) -> None:
    msg.id = doc.get("id") or ""
    msg.name = doc.get("name") or ""
    msg.description = doc.get("description") or ""
    target = doc.get("target")
    if target:
        msg.target.subjects.extend(
            _attr_rc_from_dict(a) for a in target.get("subjects") or []
        )
        msg.target.resources.extend(
            _attr_rc_from_dict(a) for a in target.get("resources") or []
        )
        msg.target.actions.extend(
            _attr_rc_from_dict(a) for a in target.get("actions") or []
        )
    meta = doc.get("meta")
    if meta:
        msg.meta.owners.extend(
            _attr_rc_from_dict(a) for a in meta.get("owners") or []
        )
        msg.meta.acls.extend(
            _attr_rc_from_dict(a) for a in meta.get("acls") or []
        )
        if meta.get("created"):
            msg.meta.created = float(meta["created"])
        if meta.get("modified"):
            msg.meta.modified = float(meta["modified"])


def rule_doc_to_rc(doc: dict):
    msg = rc_rule.Rule()
    _fill_common_rc(msg, doc)
    if doc.get("effect"):
        msg.effect = _EFFECT_TO_RC.get(doc["effect"], rc_rule.PERMIT)
    if doc.get("condition"):
        msg.condition = doc["condition"]
    msg.evaluation_cacheable = bool(doc.get("evaluation_cacheable"))
    cq = doc.get("context_query")
    if cq:
        msg.context_query.query = cq.get("query") or ""
        if cq.get("filters"):
            flt = msg.context_query.filters.add()
            for f in cq["filters"]:
                flt.filters.add(
                    field=str(f.get("field") or ""),
                    operation=str(f.get("operation") or ""),
                    value=str(f.get("value") or ""),
                )
    return msg


def policy_doc_to_rc(doc: dict):
    msg = rc_policy.Policy()
    _fill_common_rc(msg, doc)
    if doc.get("effect"):
        msg.effect = _EFFECT_TO_RC.get(doc["effect"], rc_rule.PERMIT)
    msg.rules.extend(doc.get("rules") or [])
    msg.combining_algorithm = doc.get("combining_algorithm") or ""
    msg.evaluation_cacheable = bool(doc.get("evaluation_cacheable"))
    return msg


def policy_set_doc_to_rc(doc: dict):
    msg = rc_policy_set.PolicySet()
    _fill_common_rc(msg, doc)
    msg.policies.extend(doc.get("policies") or [])
    msg.combining_algorithm = doc.get("combining_algorithm") or ""
    return msg


def _subject_from_rc(msg) -> dict | None:
    if not (msg.id or msg.token or msg.scope):
        return None
    subject = {"id": msg.id or None, "token": msg.token or None,
               "scope": msg.scope or None}
    if msg.role_associations:
        subject["role_associations"] = [
            {"role": ra.role,
             "attributes": [_attr_dict_from_rc(a) for a in ra.attributes]}
            for ra in msg.role_associations
        ]
    if msg.hierarchical_scopes:
        def hs(node):
            return {"id": node.id, "role": node.role,
                    "children": [hs(c) for c in node.children]}

        subject["hierarchical_scopes"] = [
            hs(n) for n in msg.hierarchical_scopes
        ]
    return subject


def _read_filters_from_rc(msg) -> dict | None:
    """ReadRequest ids shorthand + FilterOp groups -> the store's filter
    DSL (groups AND together, predicates combine with the group
    operator — reference resource-base-interface semantics)."""
    or_op = rc_rb.FilterOp.Operator.Value("or")
    groups = []
    for group in msg.filters:
        groups.append({
            "operator": "or" if group.operator == or_op else "and",
            "filters": [
                {"field": f.field,
                 "operation": rc_rb.Filter.Operation.Name(f.operation),
                 "value": f.value}
                for f in group.filters
            ],
        })
    return {"filters": groups} if groups else None


# ----------------------------------------------------------------- server

def register_rc_services(server, worker) -> None:
    """Add the restorecommerce-wire generic handlers to a grpc server
    (called by GrpcServer alongside the acstpu services)."""
    obs = getattr(worker, "obs", None)

    def is_allowed(request, context):
        # rc-wire deadline propagation: native gRPC deadlines and the
        # x-acs-timeout-ms metadata key both become the request budget
        # (srv/admission.deadline_from_context)
        tenant = tenant_from_metadata(context)
        if obs is None or obs.tracer is None:
            req = request_from_rc(request)
            if tenant is not None:
                req._tenant = tenant
            return response_to_rc(
                worker.service.is_allowed(
                    req, deadline=deadline_from_context(context),
                )
            )
        # traced path: same span/trace-id contract as the acstpu-wire
        # handler (srv/transport_grpc.py) — reference-wire clients get
        # the identical observability surface
        tracer = obs.tracer
        t0 = time.perf_counter()
        span = tracer.start_span(trace_id_from_metadata(context))
        req = request_from_rc(request)
        if tenant is not None:
            req._tenant = tenant
        tracer.record(span, STAGE_TRANSPORT_PARSE,
                      time.perf_counter() - t0)
        req._sampling_done = True
        if span is not None:
            req._span = span
        response = worker.service.is_allowed(
            req, deadline=deadline_from_context(context)
        )
        t_ser = time.perf_counter()
        msg = response_to_rc(response)
        tracer.record(span, STAGE_SERIALIZE, time.perf_counter() - t_ser)
        if span is not None:
            echo_trace_id(context, span.trace_id)
            tracer.finish(span, decision=response.decision,
                          code=response.operation_status.code)
        return msg

    def what_is_allowed(request, context):
        req = request_from_rc(request)
        tenant = tenant_from_metadata(context)
        if tenant is not None:
            req._tenant = tenant
        return reverse_query_to_rc(
            worker.service.what_is_allowed(
                req, deadline=deadline_from_context(context),
            )
        )

    server.add_generic_rpc_handlers((
        grpc.method_handlers_generic_handler(
            "io.restorecommerce.access_control.AccessControlService",
            {
                "IsAllowed": _unary(is_allowed, rc_ac.Request, rc_ac.Response),
                "WhatIsAllowed": _unary(
                    what_is_allowed, rc_ac.Request, rc_ac.ReverseQuery
                ),
            },
        ),
    ))

    for kind, service_name, doc_from, doc_to, list_cls, resp_cls in (
        ("rule", "io.restorecommerce.rule.RuleService",
         rule_doc_from_rc, rule_doc_to_rc,
         rc_rule.RuleList, rc_rule.RuleListResponse),
        ("policy", "io.restorecommerce.policy.PolicyService",
         policy_doc_from_rc, policy_doc_to_rc,
         rc_policy.PolicyList, rc_policy.PolicyListResponse),
        ("policy_set", "io.restorecommerce.policy_set.PolicySetService",
         policy_set_doc_from_rc, policy_set_doc_to_rc,
         rc_policy_set.PolicySetList, rc_policy_set.PolicySetListResponse),
    ):
        handlers = _crud_handlers_rc(
            worker, kind, doc_from, doc_to, resp_cls
        )
        server.add_generic_rpc_handlers((
            grpc.method_handlers_generic_handler(service_name, {
                "Read": _unary(handlers["read"], rc_rb.ReadRequest, resp_cls),
                "Create": _unary(handlers["create"], list_cls, resp_cls),
                "Update": _unary(handlers["update"], list_cls, resp_cls),
                "Upsert": _unary(handlers["upsert"], list_cls, resp_cls),
                "Delete": _unary(handlers["delete"], rc_rb.DeleteRequest,
                                 rc_rb.DeleteResponse),
            }),
        ))

    def command(request, context):
        payload = {}
        if request.HasField("payload") and request.payload.value:
            try:
                payload = json.loads(request.payload.value)
            except ValueError:
                payload = {}
        result = worker.command_interface.command(request.name, payload)
        resp = rc_ci.CommandResponse()
        resp.result.value = json.dumps(result).encode()
        return resp

    server.add_generic_rpc_handlers((
        grpc.method_handlers_generic_handler(
            "io.restorecommerce.commandinterface.CommandInterfaceService",
            {"Command": _unary(command, rc_ci.CommandRequest,
                               rc_ci.CommandResponse)},
        ),
    ))

    def health_check(request, context):
        result = worker.command_interface.command("health_check")
        serving = result.get("status") in ("SERVING", "ok", "healthy")
        return rc_health.HealthCheckResponse(
            status=rc_health.HealthCheckResponse.SERVING if serving
            else rc_health.HealthCheckResponse.NOT_SERVING
        )

    server.add_generic_rpc_handlers((
        grpc.method_handlers_generic_handler(
            "grpc.health.v1.Health",
            {"Check": _unary(health_check, rc_health.HealthCheckRequest,
                             rc_health.HealthCheckResponse)},
        ),
    ))


def _crud_handlers_rc(worker, kind, doc_from, doc_to, resp_cls):
    service = worker.store.get_resource_service(kind)

    def to_response(result) -> object:
        resp = resp_cls()
        for item in result.get("items") or []:
            entry = resp.items.add()
            if item.get("payload"):
                entry.payload.CopyFrom(doc_to(item["payload"]))
            status = item.get("status") or {}
            entry.status.code = status.get("code", 200)
            entry.status.message = status.get("message", "success")
            entry.status.id = (item.get("payload") or {}).get("id") or ""
        resp.total_count = len(result.get("items") or [])
        op = result.get("operation_status") or {}
        resp.operation_status.code = op.get("code", 200)
        resp.operation_status.message = op.get("message", "success")
        return resp

    def create(request, context):
        return to_response(service.create(
            [doc_from(i) for i in request.items],
            subject=_subject_from_rc(request.subject),
        ))

    def update(request, context):
        return to_response(service.update(
            [doc_from(i) for i in request.items],
            subject=_subject_from_rc(request.subject),
        ))

    def upsert(request, context):
        return to_response(service.upsert(
            [doc_from(i) for i in request.items],
            subject=_subject_from_rc(request.subject),
        ))

    def read(request, context):
        result = service.read(_read_filters_from_rc(request))
        items = result.get("items")
        if items is not None:
            for sort in reversed(request.sorts):
                if not sort.field:
                    continue
                items.sort(
                    key=lambda it, f=sort.field: str(
                        (it.get("payload") or {}).get(f) or ""
                    ),
                    reverse=sort.order == rc_rb.Sort.DESCENDING,
                )
            offset = request.offset or 0
            if offset:
                items = items[offset:]
            if request.limit:
                items = items[: request.limit]
            result = dict(result, items=items)
        return to_response(result)

    def delete(request, context):
        result = service.delete(
            ids=list(request.ids), collection=request.collection,
            subject=_subject_from_rc(request.subject),
        )
        resp = rc_rb.DeleteResponse()
        op = result.get("operation_status") or {}
        resp.operation_status.code = op.get("code", 200)
        resp.operation_status.message = op.get("message", "success")
        return resp

    return {"create": create, "update": update, "upsert": upsert,
            "read": read, "delete": delete}
