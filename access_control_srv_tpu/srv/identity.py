"""Identity-service client interface.

The reference resolves subject tokens through an external identity service
(``findByToken`` over gRPC, reference: src/worker.ts:135-143,
src/core/accessController.ts:110-117).  The engine only needs the
``find_by_token`` call; deployments plug a transport-backed client, tests
plug a static map (the mock-IDS pattern from
test/microservice_acs_enabled.spec.ts:106-223).
"""

from __future__ import annotations

from typing import Any, Optional, Protocol


class IdentityClient(Protocol):
    def find_by_token(self, token: str) -> Optional[dict]:
        """Returns ``{"payload": {"id", "tokens", "role_associations", ...}}``
        or None."""
        ...


class StaticIdentityClient:
    """Token -> subject payload map (test/mock implementation)."""

    def __init__(self, subjects_by_token: dict[str, dict] | None = None):
        self.subjects_by_token = subjects_by_token or {}

    def register(self, token: str, payload: dict) -> None:
        self.subjects_by_token[token] = payload

    def find_by_token(self, token: str) -> Optional[dict]:
        payload = self.subjects_by_token.get(token)
        if payload is None:
            return {"payload": None, "status": {"code": 404, "message": "not found"}}
        return {"payload": payload, "status": {"code": 200, "message": "success"}}
