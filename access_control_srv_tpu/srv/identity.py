"""Identity-service client interface.

The reference resolves subject tokens through an external identity service
(``findByToken`` over gRPC, reference: src/worker.ts:135-143,
src/core/accessController.ts:110-117).  The engine only needs the
``find_by_token`` call; deployments plug a transport-backed client, tests
plug a static map (the mock-IDS pattern from
test/microservice_acs_enabled.spec.ts:106-223).
"""

from __future__ import annotations

import copy
import threading
from typing import Any, Optional, Protocol


class IdentityClient(Protocol):
    def find_by_token(self, token: str) -> Optional[dict]:
        """Returns ``{"payload": {"id", "tokens", "role_associations", ...}}``
        or None."""
        ...


class StaticIdentityClient:
    """Token -> subject payload map (test/mock implementation)."""

    def __init__(self, subjects_by_token: dict[str, dict] | None = None):
        self.subjects_by_token = subjects_by_token or {}

    def register(self, token: str, payload: dict) -> None:
        self.subjects_by_token[token] = payload

    def find_by_token(self, token: str) -> Optional[dict]:
        payload = self.subjects_by_token.get(token)
        if payload is None:
            return {"payload": None, "status": {"code": 404, "message": "not found"}}
        return {"payload": payload, "status": {"code": 200, "message": "success"}}


class GrpcIdentityClient:
    """findByToken over a live gRPC channel (reference: src/worker.ts:135-143
    holds the identity-srv channel; resolution happens on the decision hot
    path, accessController.ts:110-117).

    The subject payload travels as JSON bytes in ``SubjectResponse.payload``;
    transport errors and non-200 statuses resolve to ``payload: None`` so
    the engine's token path fails closed (unresolved subjects match no
    role-gated rules)."""

    def __init__(self, address: str, timeout: float = 5.0,
                 cache_size: int = 1024, logger=None):
        import grpc

        from .gen import access_control_pb2 as pb

        self._pb = pb
        self.address = address
        self.timeout = timeout
        self.logger = logger
        self.channel = grpc.insecure_channel(address)
        self._call = self.channel.unary_unary(
            "/acstpu.IdentityService/FindByToken",
            request_serializer=pb.FindByTokenRequest.SerializeToString,
            response_deserializer=pb.SubjectResponse.FromString,
        )
        # token -> resolved payload; evicted by the worker's userModified /
        # auth-topic listeners exactly like the decision caches.  gRPC
        # handler threads hit this concurrently — all access goes through
        # _cache_lock, and entries cross the boundary as copies so caller
        # mutation can't corrupt future hits
        self._cache: dict[str, Any] = {}
        self._cache_size = cache_size
        self._cache_lock = threading.Lock()
        # bumped by evict(): an in-flight resolution that began before an
        # eviction must not re-insert its (possibly stale) payload after
        self._cache_gen = 0

    def find_by_token(self, token: str) -> Optional[dict]:
        import json

        with self._cache_lock:
            hit = self._cache.get(token)
            gen = self._cache_gen
        if hit is not None:
            # copy outside the lock: hits must not serialize on copy cost,
            # but the cached entry still needs isolation from caller
            # mutation
            return copy.deepcopy(hit)
        try:
            resp = self._call(
                self._pb.FindByTokenRequest(token=token),
                timeout=self.timeout,
            )
        except Exception as err:
            if self.logger:
                self.logger.warning(
                    "identity findByToken failed: %s", err
                )
            return {"payload": None,
                    "status": {"code": 503, "message": str(err)}}
        payload = None
        if resp.payload and resp.status.code in (0, 200):
            try:
                payload = json.loads(resp.payload)
            except ValueError:
                payload = None
        out = {
            "payload": payload,
            "status": {"code": resp.status.code or 200,
                       "message": resp.status.message},
        }
        if payload is not None:
            entry = copy.deepcopy(out)
            with self._cache_lock:
                if self._cache_gen == gen and self._cache_size > 0:
                    while (self._cache
                           and len(self._cache) >= self._cache_size):
                        self._cache.pop(next(iter(self._cache)))
                    self._cache[token] = entry
                # else: an evict() landed while this resolution was in
                # flight — the payload may predate the user mutation that
                # triggered it, so it must not repopulate the cache
        return out

    def evict(self, token: str = None) -> None:
        """Drop cached resolutions (all, or one token) on user mutation."""
        with self._cache_lock:
            self._cache_gen += 1
            if token is None:
                self._cache.clear()
            else:
                self._cache.pop(token, None)

    def close(self) -> None:
        self.channel.close()


class MockIdentityServer:
    """In-process identity service over real TCP: the reference test
    pattern (test/microservice_acs_enabled.spec.ts:106-223 starts a mock
    IDS and drives token resolution over the wire)."""

    def __init__(self, subjects_by_token: dict[str, dict] | None = None,
                 port: int = 0):
        import json
        from concurrent import futures

        import grpc

        from .gen import access_control_pb2 as pb

        self.subjects_by_token = subjects_by_token or {}
        self.calls: list[str] = []  # observed tokens, for test assertions

        def find_by_token(request, context):
            self.calls.append(request.token)
            payload = self.subjects_by_token.get(request.token)
            if payload is None:
                return pb.SubjectResponse(
                    payload=b"",
                    status=pb.OperationStatus(code=404, message="not found"),
                )
            return pb.SubjectResponse(
                payload=json.dumps(payload).encode(),
                status=pb.OperationStatus(code=200, message="success"),
            )

        self.server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
        handler = grpc.method_handlers_generic_handler(
            "acstpu.IdentityService",
            {
                "FindByToken": grpc.unary_unary_rpc_method_handler(
                    find_by_token,
                    request_deserializer=pb.FindByTokenRequest.FromString,
                    response_serializer=pb.SubjectResponse.SerializeToString,
                ),
            },
        )
        self.server.add_generic_rpc_handlers((handler,))
        self.port = self.server.add_insecure_port(f"127.0.0.1:{port}")
        self.server.start()

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    def register(self, token: str, payload: dict) -> None:
        self.subjects_by_token[token] = payload

    def stop(self) -> None:
        self.server.stop(grace=None)
