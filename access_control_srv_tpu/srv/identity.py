"""Identity-service client interface.

The reference resolves subject tokens through an external identity service
(``findByToken`` over gRPC, reference: src/worker.ts:135-143,
src/core/accessController.ts:110-117).  The engine only needs the
``find_by_token`` call; deployments plug a transport-backed client, tests
plug a static map (the mock-IDS pattern from
test/microservice_acs_enabled.spec.ts:106-223).
"""

from __future__ import annotations

import copy
import threading
import time
from typing import Optional, Protocol


class IdentityClient(Protocol):
    def find_by_token(self, token: str) -> Optional[dict]:
        """Returns ``{"payload": {"id", "tokens", "role_associations", ...}}``
        or None."""
        ...


class TokenResolutionCache:
    """TTL'd token -> resolution-envelope cache with negative-result caching.

    Entries are whole ``find_by_token`` envelopes (``{"payload", "status"}``).
    Positive resolutions live ``ttl_s``; *definitive* negatives (payload None
    with a non-5xx status, e.g. 404) live ``negative_ttl_s`` so hammering an
    unknown token costs one RPC per window.  Transport-level failures (5xx)
    are never cached — recovery after an identity-service outage must be
    immediate.

    Eviction race: ``lookup`` returns a generation snapshot and ``store``
    refuses to insert when an ``evict``/``evict_subject`` landed in between —
    an in-flight resolution that began before a ``userModified`` eviction can
    never repopulate the cache with its possibly-stale payload.

    ``evict_subject`` uses the subject-id recorded from each positive
    payload, so ``userDeleted`` (which carries only the user id, no tokens)
    still drops every resolution for that subject.

    All access is lock-guarded; entries cross the boundary as deep copies so
    caller mutation cannot corrupt future hits.  ``counter`` is an optional
    Counter-like (``.inc(key, by)``) receiving hits/misses/negative-hits/
    evictions/expirations (srv/telemetry.Telemetry.identity)."""

    def __init__(
        self,
        ttl_s: float = 600.0,
        negative_ttl_s: float = 30.0,
        max_entries: int = 4096,
        counter=None,
        time_fn=time.monotonic,
    ):
        self.ttl_s = float(ttl_s)
        self.negative_ttl_s = float(negative_ttl_s)
        self.max_entries = int(max_entries)
        self._time = time_fn
        self._counter = counter
        # token -> (expires_at, subject_id, envelope); dict order is the LRU
        self._data: dict[str, tuple[float, Optional[str], dict]] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self._gen = 0  # guarded-by: _lock
        self._stats = {  # guarded-by: _lock
            "hits": 0, "misses": 0, "negative_hits": 0,
            "evictions": 0, "expirations": 0,
        }

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def _count(self, key: str, by: int = 1) -> None:  # holds: _lock
        self._stats[key] += by
        if self._counter is not None:
            self._counter.inc(key.replace("_", "-"), by)

    @property
    def gen(self) -> int:
        with self._lock:
            return self._gen

    def lookup(self, token: str) -> tuple[Optional[dict], int]:
        """(cached envelope copy or None, generation snapshot for store)."""
        now = self._time()
        with self._lock:
            gen = self._gen
            hit = self._data.get(token)
            if hit is not None and hit[0] <= now:
                del self._data[token]
                self._count("expirations")
                hit = None
            if hit is None:
                self._count("misses")
                return None, gen
            # LRU touch: re-insert at the back of the dict order
            self._data[token] = self._data.pop(token)
            self._count("hits")
            if hit[2].get("payload") is None:
                self._count("negative_hits")
            entry = hit[2]
        # copy outside the lock: hits must not serialize on copy cost
        return copy.deepcopy(entry), gen

    def store(self, token: str, envelope: dict, gen: int) -> bool:
        """Insert a resolution unless an eviction raced it; returns whether
        the entry was cached."""
        payload = envelope.get("payload")
        status = envelope.get("status") or {}
        code = status.get("code")
        if payload is None:
            if not isinstance(code, int) or code >= 500:
                return False  # transport failure: never cached
            ttl = self.negative_ttl_s
        else:
            ttl = self.ttl_s
        if ttl <= 0 or self.max_entries <= 0:
            return False
        subject_id = payload.get("id") if isinstance(payload, dict) else None
        entry = copy.deepcopy(envelope)
        expires_at = self._time() + ttl
        with self._lock:
            if gen != self._gen:
                # an evict() landed while this resolution was in flight —
                # the payload may predate the user mutation that triggered
                # it, so it must not repopulate the cache
                return False
            while self._data and len(self._data) >= self.max_entries:
                self._data.pop(next(iter(self._data)))
                self._count("evictions")
            self._data[token] = (expires_at, subject_id, entry)
        return True

    def evict(self, token: Optional[str] = None) -> int:
        """Drop cached resolutions (all, or one token) on user mutation."""
        with self._lock:
            self._gen += 1
            if token is None:
                n = len(self._data)
                self._data.clear()
            else:
                n = 1 if self._data.pop(token, None) is not None else 0
            self._count("evictions", n)
        return n

    def evict_subject(self, subject_id: str) -> int:
        """Drop every resolution whose payload belongs to ``subject_id``
        (userDeleted carries no token list)."""
        if subject_id is None:
            return 0
        with self._lock:
            self._gen += 1
            stale = [
                tok for tok, (_, sid, _) in self._data.items()
                if sid == subject_id
            ]
            for tok in stale:
                del self._data[tok]
            self._count("evictions", len(stale))
        return len(stale)

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
            out["entries"] = len(self._data)
        looked = out["hits"] + out["misses"]
        out["hit_ratio"] = round(out["hits"] / looked, 4) if looked else None
        return out


def _breaker_envelope() -> dict:
    """The fast-fail resolution when the identity breaker is open: a 5xx
    envelope — never cached (TokenResolutionCache refuses >=500), so
    recovery is immediate, and the row degrades per-row to
    ``token-unresolved`` exactly like a timed-out RPC would."""
    return {
        "payload": None,
        "status": {"code": 503, "message": "identity circuit open"},
    }


def _record_envelope(breaker, envelope) -> None:
    """Feed a resolution outcome to the breaker: transport-level failures
    (5xx envelopes, the shape RPC exceptions fold into) count against the
    failure window; definitive answers — hits AND 404s — are successes
    (the upstream answered)."""
    if breaker is None:
        return
    status = (envelope or {}).get("status") or {}
    code = status.get("code")
    if isinstance(code, int) and code >= 500:
        breaker.record_failure()
    else:
        breaker.record_success()


class CachingIdentityClient:
    """TTL'd resolution cache around ANY identity client (the static map in
    tests/benches, custom transports in deployments).  GrpcIdentityClient
    carries the same cache built in — do not stack both.  ``breaker``
    (srv/admission.CircuitBreaker) guards the inner client: an open
    circuit resolves to the 503 envelope immediately — cache hits are
    served regardless (they need no upstream)."""

    def __init__(
        self,
        inner,
        ttl_s: float = 600.0,
        negative_ttl_s: float = 30.0,
        max_entries: int = 4096,
        counter=None,
        breaker=None,
    ):
        self.inner = inner
        self.breaker = breaker
        self.cache = TokenResolutionCache(
            ttl_s=ttl_s, negative_ttl_s=negative_ttl_s,
            max_entries=max_entries, counter=counter,
        )

    def find_by_token(self, token: str) -> Optional[dict]:
        hit, gen = self.cache.lookup(token)
        if hit is not None:
            return hit
        if self.breaker is not None and not self.breaker.allow():
            return _breaker_envelope()
        try:
            # failpoint (srv/faults.py): an injected outage takes the
            # real failure path — breaker failure, row fails closed
            from .faults import REGISTRY as FAULTS

            FAULTS.fire("identity.resolve")
            out = self.inner.find_by_token(token)
        except Exception:
            if self.breaker is not None:
                self.breaker.record_failure()
            raise
        if isinstance(out, dict):
            _record_envelope(self.breaker, out)
            self.cache.store(token, out, gen)
        return out

    def evict(self, token: Optional[str] = None) -> None:
        self.cache.evict(token)
        if hasattr(self.inner, "evict"):
            self.inner.evict(token)

    def evict_subject(self, subject_id: str) -> None:
        self.cache.evict_subject(subject_id)
        if hasattr(self.inner, "evict_subject"):
            self.inner.evict_subject(subject_id)

    def cache_stats(self) -> dict:
        return self.cache.stats()

    def close(self) -> None:
        if hasattr(self.inner, "close"):
            self.inner.close()


class StaticIdentityClient:
    """Token -> subject payload map (test/mock implementation)."""

    def __init__(self, subjects_by_token: dict[str, dict] | None = None):
        self.subjects_by_token = subjects_by_token or {}

    def register(self, token: str, payload: dict) -> None:
        self.subjects_by_token[token] = payload

    def find_by_token(self, token: str) -> Optional[dict]:
        payload = self.subjects_by_token.get(token)
        if payload is None:
            return {"payload": None, "status": {"code": 404, "message": "not found"}}
        return {"payload": payload, "status": {"code": 200, "message": "success"}}


class GrpcIdentityClient:
    """findByToken over a live gRPC channel (reference: src/worker.ts:135-143
    holds the identity-srv channel; resolution happens on the decision hot
    path, accessController.ts:110-117).

    The subject payload travels as JSON bytes in ``SubjectResponse.payload``;
    transport errors and non-200 statuses resolve to ``payload: None`` so
    the engine's token path fails closed (unresolved subjects match no
    role-gated rules).  Resolutions ride a ``TokenResolutionCache`` (TTL +
    negative caching), so repeat tokens inside and across batches cost one
    RPC per TTL window."""

    def __init__(self, address: str, timeout: float = 5.0,
                 cache_size: int = 1024, logger=None,
                 ttl_s: float = 600.0, negative_ttl_s: float = 30.0,
                 counter=None, breaker=None):
        import grpc

        from .gen import access_control_pb2 as pb

        self._pb = pb
        self.address = address
        self.timeout = timeout
        self.logger = logger
        # rate-limited failure warnings: a down identity service fires
        # this once per cache-missing token — unbounded under overload,
        # the masking logger becomes the bottleneck
        from .telemetry import SampledLogger

        self._slog = SampledLogger(logger)
        self.channel = grpc.insecure_channel(address)
        self._call = self.channel.unary_unary(
            "/acstpu.IdentityService/FindByToken",
            request_serializer=pb.FindByTokenRequest.SerializeToString,
            response_deserializer=pb.SubjectResponse.FromString,
        )
        # token -> resolution envelope; TTL'd with negative caching, evicted
        # by the worker's userModified/userDeleted listeners.  gRPC handler
        # threads hit this concurrently — TokenResolutionCache is
        # lock-guarded and its generation counter keeps an in-flight
        # resolution from re-inserting a stale payload after an eviction.
        self._cache = TokenResolutionCache(
            ttl_s=ttl_s, negative_ttl_s=negative_ttl_s,
            max_entries=cache_size, counter=counter,
        )
        # shared circuit breaker (srv/admission.CircuitBreaker): a down
        # identity service fails resolutions fast (rows degrade per-row
        # to token-unresolved) instead of paying `timeout` per request
        self.breaker = breaker

    def find_by_token(self, token: str) -> Optional[dict]:
        import json

        hit, gen = self._cache.lookup(token)
        if hit is not None:
            return hit
        if self.breaker is not None and not self.breaker.allow():
            return _breaker_envelope()
        try:
            # failpoint (srv/faults.py): injected identity-srv outage,
            # resolved to the honest 5xx envelope below (never cached)
            from .faults import REGISTRY as FAULTS

            FAULTS.fire("identity.grpc")
            resp = self._call(
                self._pb.FindByTokenRequest(token=token),
                timeout=self.timeout,
            )
        except Exception as err:
            self._slog.warning(
                "identity-resolution",
                "identity findByToken failed: %s", err,
            )
            if self.breaker is not None:
                self.breaker.record_failure()
            # 5xx: never cached, so recovery after an outage is immediate
            return {"payload": None,
                    "status": {"code": 503, "message": str(err)}}
        payload = None
        if resp.payload and resp.status.code in (0, 200):
            try:
                payload = json.loads(resp.payload)
            except ValueError:
                payload = None
        out = {
            "payload": payload,
            "status": {"code": resp.status.code or 200,
                       "message": resp.status.message},
        }
        _record_envelope(self.breaker, out)
        self._cache.store(token, out, gen)
        return out

    def evict(self, token: str = None) -> None:
        """Drop cached resolutions (all, or one token) on user mutation."""
        self._cache.evict(token)

    def evict_subject(self, subject_id: str) -> None:
        """Drop every cached resolution for one subject (userDeleted)."""
        self._cache.evict_subject(subject_id)

    def cache_stats(self) -> dict:
        return self._cache.stats()

    def close(self) -> None:
        self.channel.close()


class MockIdentityServer:
    """In-process identity service over real TCP: the reference test
    pattern (test/microservice_acs_enabled.spec.ts:106-223 starts a mock
    IDS and drives token resolution over the wire)."""

    def __init__(self, subjects_by_token: dict[str, dict] | None = None,
                 port: int = 0):
        import json
        from concurrent import futures

        import grpc

        from .gen import access_control_pb2 as pb

        self.subjects_by_token = subjects_by_token or {}
        self.calls: list[str] = []  # observed tokens, for test assertions

        def find_by_token(request, context):
            self.calls.append(request.token)
            payload = self.subjects_by_token.get(request.token)
            if payload is None:
                return pb.SubjectResponse(
                    payload=b"",
                    status=pb.OperationStatus(code=404, message="not found"),
                )
            return pb.SubjectResponse(
                payload=json.dumps(payload).encode(),
                status=pb.OperationStatus(code=200, message="success"),
            )

        self.server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
        handler = grpc.method_handlers_generic_handler(
            "acstpu.IdentityService",
            {
                "FindByToken": grpc.unary_unary_rpc_method_handler(
                    find_by_token,
                    request_deserializer=pb.FindByTokenRequest.FromString,
                    response_serializer=pb.SubjectResponse.SerializeToString,
                ),
            },
        )
        self.server.add_generic_rpc_handlers((handler,))
        self.port = self.server.add_insecure_port(f"127.0.0.1:{port}")
        self.server.start()

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    def register(self, token: str, payload: dict) -> None:
        self.subjects_by_token[token] = payload

    def stop(self) -> None:
        self.server.stop(grace=None)
