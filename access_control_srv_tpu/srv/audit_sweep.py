# acs-lint: host-only — the sweep manager schedules, folds and streams;
# every device interaction goes through the batcher's bulk class or the
# evaluator's existing wia path.
"""Bulk permission-lattice audit sweeps (docs/AUDIT.md).

A sweep walks a subject x resource x action lattice (ops/lattice.py)
through the reverse/wia kernel in admission-governed BULK-class chunks:
production sweeps ride ``MicroBatcher.submit_reverse`` — never the
interactive queue, so PR 5's two-class fairness bounds interactive p99
while a full audit runs — and candidate sweeps call the PR 16
``ShadowEvaluator``'s disjoint evaluator directly, off the serving path
entirely.  Each chunk folds to per-cell verdicts naming the deciding
rule and streams into a masked JSONL + bitmap snapshot, so memory stays
bounded by one chunk regardless of lattice size.

The learned-policy twin loop (``sweep_twin``): load a mined/learned
candidate through the shadow evaluator, sweep production and candidate
over the same lattice, and report the lattice diff *and* the shadow's
live-traffic diff in one artifact — the full policy lifecycle the
mining papers (PAPERS.md: LLMAC, DLBAC) gesture at.

Jobs expose pause/resume/cancel/status through the ``audit_sweep``
command (srv/command.py).  Everything is off by default (config
``audit:enabled``); with it off the worker builds no manager and the
serving path is byte-identical.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Optional

from ..ops.lattice import (
    CellVerdict,
    LatticeSpec,
    SnapshotWriter,
    diff_snapshots,
    fold_reverse_query,
)

_DONE_STATES = frozenset(("done", "cancelled", "failed"))


class SweepJob:
    """One lattice sweep: immutable plan + mutable progress, owned by a
    single worker thread in :class:`AuditSweepManager`."""

    def __init__(
        self,
        job_id: int,
        spec: LatticeSpec,
        target: str,
        snapshot_path: str,
        policy_epoch: Optional[int] = None,
    ):
        self.job_id = job_id
        self.spec = spec
        self.target = target
        self.snapshot_path = snapshot_path
        self.bitmap_path = snapshot_path + ".bits.npy"
        self.policy_epoch = policy_epoch
        self.state = "pending"        # guarded-by: _lock
        self.error: Optional[str] = None
        self.cells_done = 0           # guarded-by: _lock
        self.chunks_done = 0          # guarded-by: _lock
        self.sheds = 0                # guarded-by: _lock
        self.retries = 0              # guarded-by: _lock
        self.summary: Optional[dict] = None
        self.started_monotonic: Optional[float] = None
        self.wall_s: Optional[float] = None
        self._lock = threading.Lock()
        self._paused = threading.Event()
        self._cancel = threading.Event()
        self._finished = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def status(self) -> dict:
        with self._lock:
            out = {
                "job": self.job_id,
                "target": self.target,
                "state": self.state,
                "cells_total": self.spec.n_cells,
                "cells_done": self.cells_done,
                "chunks_done": self.chunks_done,
                "sheds": self.sheds,
                "retries": self.retries,
                "paused": self._paused.is_set(),
                "snapshot": self.snapshot_path,
                "bitmap": self.bitmap_path,
                "policy_epoch": self.policy_epoch,
            }
            if self.wall_s is not None:
                out["wall_s"] = round(self.wall_s, 3)
            if self.summary is not None:
                out["summary"] = self.summary
            if self.error is not None:
                out["error"] = self.error
        return out

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._finished.wait(timeout)


class AuditSweepManager:
    """Sweep-job lifecycle: start/pause/resume/cancel, chunked bulk
    dispatch, snapshot/diff plumbing and the candidate twin loop.

    ``batcher`` present: production sweeps submit through the BULK class
    (the admission-fairness path — the serving deployment shape).
    ``batcher`` absent: chunks call ``evaluator.what_is_allowed_batch``
    directly (the offline/bench shape).  Candidate sweeps always use the
    shadow's own evaluator and never touch the serving queues."""

    def __init__(
        self,
        evaluator,
        batcher=None,
        worker=None,
        telemetry=None,
        logger: Optional[logging.Logger] = None,
        out_dir: str = "/tmp/acs-audit",
        chunk_size: int = 256,
        cell_timeout_s: float = 60.0,
        max_retries: int = 3,
        chunk_pause_ms: float = 0.0,
        default_lattice: Optional[dict] = None,
    ):
        self.evaluator = evaluator
        self.batcher = batcher
        self.worker = worker
        self.telemetry = telemetry
        self.logger = logger or logging.getLogger("acs.audit")
        self.out_dir = str(out_dir)
        self.chunk_size = max(1, int(chunk_size))
        self.cell_timeout_s = float(cell_timeout_s)
        self.max_retries = max(0, int(max_retries))
        self.chunk_pause_s = max(0.0, float(chunk_pause_ms) / 1e3)
        self.default_lattice = dict(default_lattice or {})
        self._jobs: dict[int, SweepJob] = {}   # guarded-by: _lock
        self._next_id = 1                      # guarded-by: _lock
        self._lock = threading.Lock()
        self._stopping = False                 # guarded-by: _lock

    # ------------------------------------------------------------- metrics

    def _count(self, event: str, by: int = 1) -> None:
        if self.telemetry is not None:
            self.telemetry.audit.inc(event, by)

    # ------------------------------------------------------------ lifecycle

    def start_sweep(
        self,
        spec: Optional[LatticeSpec] = None,
        target: str = "production",
        lattice: Optional[dict] = None,
        wait: bool = False,
        wait_timeout: float = 600.0,
    ) -> SweepJob:
        """Launch a sweep job.  ``target`` is ``production`` (bulk class
        through the batcher) or ``shadow`` (the loaded candidate tree,
        off the serving path).  ``lattice`` overrides the configured
        default axes (ops/lattice.LatticeSpec.from_config grammar)."""
        if target not in ("production", "shadow"):
            raise ValueError(f"unknown sweep target: {target!r}")
        if target == "shadow" and self._shadow() is None:
            raise RuntimeError(
                "no shadow candidate loaded (config shadow:enabled + "
                "candidate_paths, or shadow_status reload)"
            )
        if spec is None:
            block = lattice if lattice is not None else self.default_lattice
            spec = LatticeSpec.from_config(block, urns=self._urns())
        with self._lock:
            if self._stopping:
                raise RuntimeError("audit manager stopping")
            job_id = self._next_id
            self._next_id += 1
            path = os.path.join(
                self.out_dir, f"sweep-{job_id:04d}-{target}.jsonl"
            )
            job = SweepJob(
                job_id, spec, target, path, policy_epoch=self._epoch()
            )
            self._jobs[job_id] = job
            job._thread = threading.Thread(
                target=self._run, args=(job,),
                name=f"acs-audit-sweep-{job_id}", daemon=True,
            )
            with job._lock:
                job.state = "running"
            job._thread.start()
        self._count("jobs_started")
        if wait:
            if not job.wait(wait_timeout):
                raise TimeoutError(f"sweep {job_id} still running")
        return job

    def pause(self, job_id: int) -> dict:
        job = self._job(job_id)
        job._paused.set()
        self._count("jobs_paused")
        return job.status()

    def resume(self, job_id: int) -> dict:
        job = self._job(job_id)
        job._paused.clear()
        self._count("jobs_resumed")
        return job.status()

    def cancel(self, job_id: int) -> dict:
        job = self._job(job_id)
        job._cancel.set()
        job._paused.clear()
        return job.status()

    def status(self, job_id: Optional[int] = None) -> dict:
        if job_id is not None:
            return self._job(job_id).status()
        with self._lock:
            jobs = list(self._jobs.values())
        statuses = [j.status() for j in jobs]
        running = sum(1 for s in statuses if s["state"] == "running")
        return {
            "enabled": True,
            "jobs": statuses[-16:],
            "running": running,
        }

    def diff(self, job_a: int, job_b: int, limit: int = 4096) -> dict:
        a, b = self._job(job_a), self._job(job_b)
        for job in (a, b):
            state = job.status()["state"]
            if state != "done":
                raise RuntimeError(
                    f"sweep {job.job_id} is {state}, not done"
                )
        out = diff_snapshots(a.snapshot_path, b.snapshot_path, limit=limit)
        self._count("diffs")
        self._count("diff_cells", out["cells_changed"])
        return out

    def sweep_twin(
        self,
        spec: Optional[LatticeSpec] = None,
        lattice: Optional[dict] = None,
        wait_timeout: float = 600.0,
        diff_limit: int = 4096,
    ) -> dict:
        """The learned-policy twin loop: sweep production AND the loaded
        shadow candidate over one lattice, diff the snapshots, and
        return the lattice diff beside the shadow's live-traffic diff —
        one report answering both 'what would change across the whole
        permission space' and 'what changes on real traffic'."""
        shadow = self._shadow()
        if shadow is None:
            raise RuntimeError("twin loop needs a loaded shadow candidate")
        if spec is None:
            block = lattice if lattice is not None else self.default_lattice
            spec = LatticeSpec.from_config(block, urns=self._urns())
        prod = self.start_sweep(
            spec=spec, target="production",
            wait=True, wait_timeout=wait_timeout,
        )
        cand = self.start_sweep(
            spec=spec, target="shadow",
            wait=True, wait_timeout=wait_timeout,
        )
        for job in (prod, cand):
            snap = job.status()
            if snap["state"] != "done":
                raise RuntimeError(
                    f"twin sweep {job.job_id} ({job.target}) "
                    f"{snap['state']}: {snap.get('error')}"
                )
        report = {
            "production": prod.status(),
            "candidate": cand.status(),
            "lattice_diff": self.diff(
                prod.job_id, cand.job_id, limit=diff_limit
            ),
            "live_traffic": shadow.status(),
        }
        self._count("twin_reports")
        return report

    def stop(self, timeout: float = 10.0) -> None:
        with self._lock:
            self._stopping = True
            jobs = list(self._jobs.values())
        for job in jobs:
            job._cancel.set()
            job._paused.clear()
        deadline = time.monotonic() + timeout
        for job in jobs:
            thread = job._thread
            if thread is not None and thread.is_alive():
                thread.join(max(0.0, deadline - time.monotonic()))

    # -------------------------------------------------------------- helpers

    def _job(self, job_id) -> SweepJob:
        key = int(job_id)
        with self._lock:
            job = self._jobs[key] if key in self._jobs else None
        if job is None:
            raise KeyError(f"unknown sweep job {job_id}")
        return job

    def _shadow(self):
        return getattr(self.worker, "shadow", None)

    def _urns(self):
        engine = getattr(self.evaluator, "engine", None)
        return getattr(engine, "urns", None)

    def _epoch(self) -> Optional[int]:
        worker = self.worker
        if worker is not None:
            try:
                return int(worker.policy_epoch())
            except Exception:
                return None
        return None

    # ------------------------------------------------------------ the sweep

    def _run(self, job: SweepJob) -> None:
        job.started_monotonic = time.monotonic()
        writer: Optional[SnapshotWriter] = None
        try:
            writer = SnapshotWriter(
                job.snapshot_path, job.spec, source=job.target,
                policy_epoch=job.policy_epoch,
                meta={"job": job.job_id, "chunk_size": self.chunk_size},
            )
            shadow_eval = None
            if job.target == "shadow":
                shadow = self._shadow()
                if shadow is None:
                    raise RuntimeError("shadow candidate unloaded mid-sweep")
                shadow_eval = shadow.evaluator
            for chunk in job.spec.chunks(self.chunk_size):
                while job._paused.is_set() and not job._cancel.is_set():
                    time.sleep(0.02)
                if job._cancel.is_set():
                    break
                if shadow_eval is not None:
                    verdicts = self._eval_direct(shadow_eval, chunk)
                elif self.batcher is not None:
                    verdicts = self._eval_bulk(job, chunk)
                else:
                    verdicts = self._eval_direct(self.evaluator, chunk)
                for (index, _), verdict in zip(chunk, verdicts):
                    writer.write(index, verdict)
                with job._lock:
                    job.cells_done += len(chunk)
                    job.chunks_done += 1
                    job.sheds += sum(
                        1 for v in verdicts if v.shed_code is not None
                    )
                self._count("cells", len(chunk))
                self._count("chunks")
                if self.chunk_pause_s:
                    time.sleep(self.chunk_pause_s)
            summary = writer.close()
            writer = None
            with job._lock:
                job.summary = summary
                job.wall_s = time.monotonic() - job.started_monotonic
                job.state = "cancelled" if job._cancel.is_set() else "done"
            self._count(
                "jobs_cancelled" if job._cancel.is_set()
                else "jobs_completed"
            )
        except Exception as exc:  # a failed audit must never take the
            # worker down with it — the job records the error honestly
            with job._lock:
                job.state = "failed"
                job.error = f"{type(exc).__name__}: {exc}"
            self._count("jobs_failed")
            self.logger.warning(
                "audit sweep %d failed", job.job_id,
                extra={"error": job.error},
            )
        finally:
            if writer is not None:
                try:
                    writer.close()
                except Exception:
                    pass
            job._finished.set()

    def _eval_direct(self, evaluator, chunk: list) -> list:
        trees = evaluator.what_is_allowed_batch([r for _, r in chunk])
        return [fold_reverse_query(rq) for rq in trees]

    def _eval_bulk(self, job: SweepJob, chunk: list) -> list:
        """BULK-class dispatch: every cell goes through admission as the
        bulk class and waits out ``bulk_interval`` pacing under load —
        the interactive queue never sees audit traffic.  Shed cells
        (429/503/504) retry up to ``max_retries`` with a short backoff,
        then land in the snapshot as honest INDETERMINATE + shed code
        rather than a fabricated verdict."""
        futures = [
            self.batcher.submit_reverse(request) for _, request in chunk
        ]
        verdicts: list = []
        for slot, future in enumerate(futures):
            rq = future.result(timeout=self.cell_timeout_s)
            verdict = fold_reverse_query(rq)
            attempt = 0
            while (
                verdict.shed_code is not None
                and attempt < self.max_retries
                and not job._cancel.is_set()
            ):
                attempt += 1
                with job._lock:
                    job.retries += 1
                self._count("retries")
                time.sleep(0.005 * attempt)
                retry = self.batcher.submit_reverse(chunk[slot][1])
                verdict = fold_reverse_query(
                    retry.result(timeout=self.cell_timeout_s)
                )
            if verdict.shed_code is not None:
                self._count("sheds")
            verdicts.append(verdict)
        return verdicts


def from_config(
    cfg,
    worker=None,
    evaluator=None,
    batcher=None,
    telemetry=None,
    logger=None,
) -> Optional[AuditSweepManager]:
    """Build the manager from the ``audit`` config block; None unless
    ``audit:enabled`` — the serving path stays byte-identical with the
    subsystem off (no manager object, no command surface, no threads)."""
    if not cfg.get("audit:enabled", False):
        return None
    evaluator = evaluator or getattr(worker, "evaluator", None)
    if evaluator is None:
        return None
    return AuditSweepManager(
        evaluator,
        batcher=batcher if batcher is not None
        else getattr(worker, "batcher", None),
        worker=worker,
        telemetry=telemetry,
        logger=logger,
        out_dir=cfg.get("audit:out_dir", "/tmp/acs-audit"),
        chunk_size=cfg.get("audit:chunk_size", 256),
        cell_timeout_s=cfg.get("audit:cell_timeout_s", 60.0),
        max_retries=cfg.get("audit:max_retries", 3),
        chunk_pause_ms=cfg.get("audit:chunk_pause_ms", 0.0),
        default_lattice=cfg.get("audit:lattice", {}) or {},
    )
