"""Server-side decision cache: TTL'd + LRU-bounded caching of
``evaluation_cacheable`` decisions on the serving hot path.

Framework analog of the reference ecosystem's acs-client decision cache
(Redis DB 5, TTL 3600 — reference: cfg/config.json:254-259): the reference
*clients* hash each access request and cache the decision when the response
carries ``evaluation_cacheable``; here the cache lives server-side so every
caller benefits and invalidation is driven by the same event surface the
server already owns (CRUD hot-sync, ``userModified``/``userDeleted``,
``flushCacheCommand``).

Design:

- **Keying** — a canonical request fingerprint: an order-insensitive hash
  over the target's subject/resource/action attribute multisets plus a
  canonical digest of the (already-resolved) request context.  The digest
  covers subject id, role associations and hierarchical scopes, so a
  subject whose associations change simply stops hitting its old entries
  (content addressing backs up the explicit prefix eviction).  Keys embed
  the subject id as a searchable prefix for ``userModified``/``userDeleted``
  and the reference's ``flush_cache`` db_index/pattern payloads.
- **Sharding + lock striping** — entries hash across N shards (power of
  two), each an LRU-ordered dict behind its own lock, so batch-wide
  lookups from concurrent serving threads never serialize on one mutex.
- **TTL + LRU bound** — every entry expires ``ttl_s`` after write (lazily
  collected on lookup); each shard holds at most ``max_entries / shards``
  live entries, evicting least-recently-used beyond that.
- **Epoch flush** — Rule/Policy/PolicySet CRUD, ``restore``/``reset``/
  ``config_update`` and pattern-less ``flush_cache`` bump a global epoch;
  entries written under an older epoch are logical misses (O(1) flush, no
  lock sweep on the mutation path).
- **Scoped epoch bumps** — the incremental policy-update subsystem
  (ops/delta.py) classifies each CRUD bump with a target-signature
  *footprint*.  Entries store their request's resource features
  (:func:`request_features`) at write time; an entry whose features are
  disjoint from every bump between its epoch and the current one is
  promoted in place instead of evicted, so sustained rule churn on entity
  A keeps the warm set for entity B alive.  The PR-1 epoch-race invariant
  holds verbatim on both paths: writers still snapshot the epoch BEFORE
  the walk reads the tree, and ``put`` refuses whenever any intervening
  bump (global, or scoped-and-affecting) could have changed the decision
  — entries without features degrade to the pre-delta behavior exactly.

The lookup path is host-only by construction: this module never imports
jax and a cache hit returns before any encode or device dispatch
(asserted by tpu_compat_audit.py and tests/test_decision_cache.py).

Semantics bar: cache on/off must never change a decision — only responses
whose ``evaluation_cacheable`` is True (every contributing rule cacheable,
engine prefix-AND semantics) and whose operation status is 200 are stored,
and the differential suite (tests/test_decision_cache.py) asserts
bit-identical decision streams under randomized CRUD interleavings.
"""

from __future__ import annotations

# acs-lint: host-only — the lookup path must never touch the device
# runtime (tpu_compat_audit row decision-cache-lookup)

import threading
import time
from collections import OrderedDict, deque, namedtuple
from hashlib import blake2b
from typing import Any, Optional

from ..core.common import get_field as _get
from ..models.model import OperationStatus, Response

_SEP = "\x1f"  # subject-id / digest separator inside keys
# tenant prefix separator (multi-tenant serving, srv/tenancy.py): a
# tenanted key is "<tenant>\x1e<subject>\x1f<digest>", so per-tenant
# eviction is a prefix walk and an untenanted eviction can never match a
# tenant's entries (their keys start with the tenant id, and \x1e/\x1f
# keep an id-equals-subject collision impossible)
_TSEP = "\x1e"

# how many epoch bumps of footprint history to keep: entries older than
# the log's reach are treated as globally flushed (conservative)
_BUMP_LOG = 512

# resource features of one request, matched against delta footprints
# (ops/delta.RuleScope.affects): exact entity values, operation values and
# action values of the request target
RequestFeatures = namedtuple(
    "RequestFeatures", ("entities", "ops", "actions")
)


def request_features(request, entity_urn: str, operation_urn: str
                     ) -> Optional[RequestFeatures]:
    """Candidate-signature features of an access request (the request-side
    counterpart of ops/delta.scope_from_target); memoized on the request
    object like the fingerprint.  None when the request has no target."""
    memo = getattr(request, "_dc_features", None)
    if memo is not None:
        return memo
    target = getattr(request, "target", None)
    if target is None:
        return None
    ents, ops = [], []
    for attr in _get(target, "resources") or []:
        value = _get(attr, "value")
        if value is None:
            continue
        attr_id = _get(attr, "id")
        if attr_id == entity_urn:
            ents.append(value)
        elif attr_id == operation_urn:
            ops.append(value)
    acts = [
        _get(attr, "value") for attr in _get(target, "actions") or []
        if _get(attr, "value") is not None
    ]
    features = RequestFeatures(
        frozenset(ents), frozenset(ops), frozenset(acts)
    )
    try:
        request._dc_features = features
    except Exception:  # exotic request objects
        pass
    return features


def _canon(obj: Any) -> Any:
    """Deterministic, hashable view of a JSON-ish value.  Dict key order is
    normalized; list order is preserved (list order inside the context is
    meaningful, e.g. role-association scoping instances); dataclass-like
    objects (Attribute/Target leaking into merged contexts) degrade through
    their ``__dict__``."""
    if isinstance(obj, dict):
        return tuple(
            (k, _canon(v))
            for k, v in sorted(obj.items(), key=lambda kv: str(kv[0]))
        )
    if isinstance(obj, (list, tuple)):
        return tuple(_canon(v) for v in obj)
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    if hasattr(obj, "__dict__"):
        return _canon(
            {k: v for k, v in vars(obj).items() if not k.startswith("_")}
        )
    return repr(obj)


def _attr_key(attr) -> tuple:
    nested = _get(attr, "attributes") or []
    return (
        _get(attr, "id") or "",
        _get(attr, "value") or "",
        tuple(sorted(repr(_attr_key(n)) for n in nested)),
    )


def _attr_multiset(attrs) -> tuple:
    """Order-insensitive canonical form of one target attribute list."""
    return tuple(sorted(repr(_attr_key(a)) for a in (attrs or [])))


def request_fingerprint(request, subject_id_urn: str = "") -> Optional[str]:
    """Canonical fingerprint of an access request, or None when the request
    has no target (the engine's no-target deny path is never cached).

    The context must already be resolved (token subject + HR scopes) —
    callers fingerprint after ``engine.prepare_context`` so the key reflects
    the attributes the evaluation will actually see.  The fingerprint is
    memoized on the request object (``_dc_key``): serving builds a fresh
    Request per RPC, while bench/batch callers re-submitting one object pay
    the hash once.
    """
    memo = getattr(request, "_dc_key", None)
    if memo is not None:
        return memo
    target = getattr(request, "target", None)
    if target is None:
        return None
    context = getattr(request, "context", None) or {}
    subject = _get(context, "subject") or {}
    subject_id = _get(subject, "id") or ""
    if not subject_id and subject_id_urn:
        for attr in _get(target, "subjects") or []:
            if _get(attr, "id") == subject_id_urn:
                subject_id = _get(attr, "value") or ""
                break
    body = (
        _attr_multiset(_get(target, "subjects")),
        _attr_multiset(_get(target, "resources")),
        _attr_multiset(_get(target, "actions")),
        # derived keys the engine grafts during evaluation (_queryResult)
        # are excluded: they are outputs of the walk, not request identity
        _canon({
            k: v for k, v in context.items()
            if not (isinstance(k, str) and k.startswith("_"))
        }) if isinstance(context, dict) else _canon(context),
    )
    digest = blake2b(repr(body).encode(), digest_size=16).hexdigest()
    tenant = getattr(request, "_tenant", None)
    if tenant:
        key = f"{tenant}{_TSEP}{subject_id}{_SEP}{digest}"
    else:
        key = f"{subject_id}{_SEP}{digest}"
    try:
        request._dc_key = key
    except Exception:  # exotic request objects without attribute support
        pass
    return key


def key_tenant(key: Optional[str]) -> Optional[str]:
    """The tenant a cache key is scoped to (None = default domain)."""
    if key is None or _TSEP not in key:
        return None
    return key.split(_TSEP, 1)[0]


class _Shard:
    __slots__ = ("lock", "entries")

    def __init__(self):
        self.lock = threading.Lock()
        # key -> (decision, obligations tuple, cacheable, code, message,
        #         epoch, expires_at, features); OrderedDict order IS the
        # LRU order
        self.entries: OrderedDict[str, tuple] = OrderedDict()  # guarded-by: lock


class DecisionCache:
    """Sharded, lock-striped TTL + LRU decision cache with epoch flush."""

    def __init__(
        self,
        ttl_s: float = 3600.0,
        max_entries: int = 65536,
        shards: int = 16,
        enabled: bool = True,
        telemetry=None,
        time_fn=time.monotonic,
    ):
        n = 1
        while n < max(1, int(shards)):
            n <<= 1
        self.enabled = bool(enabled)
        self.ttl_s = float(ttl_s)
        self.max_entries = int(max_entries)
        self._shards = [_Shard() for _ in range(n)]
        self._mask = n - 1
        self._per_shard = max(1, self.max_entries // n)
        self._time = time_fn
        self.telemetry = telemetry
        self._epoch = 0  # guarded-by: _stats_lock
        # (epoch, footprint-or-None, tenant-or-None) per bump, newest
        # last; footprint None = global flush, tenant None = the default
        # domain's mutation stream (affects every entry conservatively);
        # a tenant-tagged bump can only affect that tenant's entries.
        # Bounded: anything older than the log is treated as global.
        self._bumps: deque = deque(maxlen=_BUMP_LOG)  # guarded-by: _stats_lock
        self._stats_lock = threading.Lock()
        self._hits = 0        # guarded-by: _stats_lock
        self._misses = 0      # guarded-by: _stats_lock
        self._evictions = 0   # guarded-by: _stats_lock
        self._stores = 0      # guarded-by: _stats_lock
        self._scoped_bumps = 0      # guarded-by: _stats_lock
        self._scoped_survivors = 0  # guarded-by: _stats_lock

    # ---------------------------------------------------------------- stats

    def _count(self, stat: str, by: int = 1) -> None:
        with self._stats_lock:
            setattr(self, f"_{stat}", getattr(self, f"_{stat}") + by)
        if self.telemetry is not None:
            self.telemetry.cache.inc(stat, by)

    def _tenant_count(self, kind: str, key: Optional[str]) -> None:
        """Per-tenant cache attribution (cardinality-bounded, see
        srv/telemetry.TenantCounter); no-op for default-domain keys."""
        tenant = key_tenant(key)
        if tenant is None or self.telemetry is None:
            return
        tenant_inc = getattr(self.telemetry, "tenant_inc", None)
        if tenant_inc is not None:
            tenant_inc(kind, tenant)

    def stats(self) -> dict:
        with self._stats_lock:
            hits, misses = self._hits, self._misses
            evictions, stores = self._evictions, self._stores
            scoped_bumps = self._scoped_bumps
            scoped_survivors = self._scoped_survivors
            epoch = self._epoch
        entries = 0
        for shard in self._shards:
            with shard.lock:
                entries += len(shard.entries)
        lookups = hits + misses
        return {
            "enabled": self.enabled,
            "hits": hits,
            "misses": misses,
            "evictions": evictions,
            "stores": stores,
            "hit_ratio": round(hits / lookups, 4) if lookups else None,
            "entries": entries,
            "epoch": epoch,
            "scoped_bumps": scoped_bumps,
            "scoped_survivors": scoped_survivors,
            "ttl_s": self.ttl_s,
            "max_entries": self.max_entries,
            "shards": len(self._shards),
        }

    # ----------------------------------------------------------------- core

    def fingerprint(self, request, subject_id_urn: str = "") -> Optional[str]:
        return request_fingerprint(request, subject_id_urn)

    @property
    def epoch(self) -> int:
        """Current tree epoch.  Writers snapshot this BEFORE computing a
        decision and hand the snapshot back to :meth:`put` — a decision
        whose evaluation spans an epoch bump (CRUD hot-sync / restore
        completing mid-walk) is then stored under the old epoch and is a
        logical miss, never served as fresh."""
        # acs-lint: ignore[guarded-by] epoch snapshot read: atomic int load; snapshot-before-walk semantics (PR 1)
        return self._epoch

    def _shard(self, key: str) -> _Shard:
        # blake2b digests are uniformly distributed; Python's str hash is
        # salted per process but stable within one, which is all striping
        # needs
        return self._shards[hash(key) & self._mask]

    def _affected_between(self, entry_epoch: int,
                          features, tenant=None) -> bool:
        """True when any epoch bump AFTER ``entry_epoch`` could have
        changed a decision with these request features: global bumps
        always count, scoped bumps count when their footprint intersects.
        Feature-less entries (pre-delta callers) are affected by every
        bump — identical to the original epoch semantics.

        ``tenant`` is the entry's tenant scope (from its key prefix): a
        bump tagged with a DIFFERENT tenant can only have touched that
        tenant's tables and is skipped outright — one tenant's CRUD churn
        never invalidates another tenant's (or the default domain's) warm
        set.  Untenanted bumps stay conservative and affect everything."""
        # acs-lint: ignore[guarded-by] epoch snapshot read: atomic int load; staleness re-checked against the bump log below
        current = self._epoch
        if entry_epoch == current:
            return False
        if entry_epoch > current:
            return True
        with self._stats_lock:
            bumps = list(self._bumps)
        covered = current
        for epoch, footprint, bump_tenant in reversed(bumps):
            if epoch <= entry_epoch:
                break
            covered = epoch
            if bump_tenant is not None and bump_tenant != tenant:
                continue  # another tenant's mutation: provably disjoint
            if footprint is None or features is None:
                return True
            try:
                if footprint.affects(features):
                    return True
            except Exception:  # defensive: a broken footprint flushes
                return True
        # the log must reach back to entry_epoch + 1; older bumps were
        # evicted from the bounded deque -> conservative global
        return covered > entry_epoch + 1

    def get(self, key: Optional[str]) -> Optional[Response]:
        """Return a rebuilt Response for a live entry, else None.  Misses
        (absent, expired, stale-epoch) are counted; expired/stale entries
        are collected in place.  Entries whose features are disjoint from
        every intervening scoped bump survive (promoted to the current
        epoch in place)."""
        if not self.enabled or key is None:
            return None
        shard = self._shard(key)
        # acs-lint: ignore[guarded-by] epoch snapshot read: atomic int load taken BEFORE the entry check (PR 1 snapshot-before-walk)
        epoch = self._epoch
        now = self._time()
        with shard.lock:
            entry = shard.entries.get(key)
            if entry is None:
                self._count("misses")
                self._tenant_count("cache_miss", key)
                return None
            (decision, obligations, cacheable, code, message, ent_epoch,
             exp, features) = entry
            if exp <= now or (
                ent_epoch != epoch
                and self._affected_between(ent_epoch, features,
                                           key_tenant(key))
            ):
                del shard.entries[key]
                self._count("evictions")
                self._count("misses")
                self._tenant_count("cache_miss", key)
                return None
            if ent_epoch != epoch:
                # scoped survivor: every bump since the entry was written
                # is provably disjoint from its signature — re-stamp so
                # later lookups take the fast path
                shard.entries[key] = entry[:5] + (epoch, exp, features)
                self._count("scoped_survivors")
            shard.entries.move_to_end(key)
        self._count("hits")
        self._tenant_count("cache_hit", key)
        # rebuild per hit: callers may hold the Response across a later
        # eviction, so entries never hand out shared mutable state beyond
        # the (treated-as-immutable) obligation attributes
        return Response(
            decision=decision,
            obligations=list(obligations),
            evaluation_cacheable=cacheable,
            operation_status=OperationStatus(code=code, message=message),
        )

    def put(
        self, key: Optional[str], response: Response,
        epoch: Optional[int] = None,
        features=None,
    ) -> bool:
        """Write-through hook: stores only responses the engine marked
        ``evaluation_cacheable`` with a 200 status.  Returns True when
        stored.

        ``epoch`` is the writer's :attr:`epoch` snapshot taken at
        lookup/miss time, BEFORE the evaluation read the policy tree.  The
        entry is stamped with that snapshot (not the epoch at write time):
        if a tree mutation bumped the epoch while the decision was being
        computed, the entry is born stale — stored here only to be a
        logical miss — so an old-tree decision (e.g. a revoked permit)
        can never be served as fresh for a TTL.  A snapshot already known
        stale is refused outright rather than pushing a live LRU entry
        out.  ``None`` (direct/test callers whose compute did not span a
        mutation) stamps the current epoch, matching a snapshot taken
        now.

        ``features`` (:func:`request_features`) widens the acceptance: a
        snapshot spanning only SCOPED bumps whose footprints are disjoint
        from the request signature is provably still fresh (the mutation
        could not have changed this decision) and is stored under the
        current epoch.  Without features the pre-delta refusal applies
        unchanged."""
        if not self.enabled or key is None or response is None:
            return False
        if response.evaluation_cacheable is not True:
            return False
        status = response.operation_status
        if status is not None and status.code != 200:
            return False
        # acs-lint: ignore[guarded-by] epoch snapshot reads: atomic int loads; a concurrent bump makes the entry born-stale, never served fresh
        ent_epoch = self._epoch if epoch is None else int(epoch)
        if ent_epoch != self._epoch:  # acs-lint: ignore[guarded-by] epoch snapshot read (see above)
            if self._affected_between(ent_epoch, features,
                                      key_tenant(key)):
                return False
            ent_epoch = self._epoch  # acs-lint: ignore[guarded-by] epoch snapshot read (see above)
        entry = (
            response.decision,
            tuple(response.obligations or ()),
            True,
            200,
            status.message if status is not None else "success",
            ent_epoch,
            self._time() + self.ttl_s,
            features,
        )
        shard = self._shard(key)
        with shard.lock:
            shard.entries[key] = entry
            shard.entries.move_to_end(key)
            while len(shard.entries) > self._per_shard:
                shard.entries.popitem(last=False)
                self._count("evictions")
        self._count("stores")
        return True

    # ---------------------------------------------------------- invalidation

    def bump_epoch(self, tenant: Optional[str] = None) -> int:
        """Logical full flush: policy-tree mutations (CRUD hot-sync,
        restore/reset/config_update) call this; stale entries become misses
        immediately and are collected lazily.  A ``tenant`` tag scopes the
        flush to that tenant's entries (srv/tenancy.py — one tenant's
        mutation stream must never cold-start another's warm set)."""
        return self._bump(None, tenant)

    def bump_scoped(self, footprint, tenant: Optional[str] = None) -> int:
        """Scoped epoch bump (ops/delta.Footprint): entries and in-flight
        writers whose request features are disjoint from ``footprint``
        survive; everything else behaves exactly as a global bump.  A
        global or empty-with-global footprint degrades to
        :meth:`bump_epoch` (tenant tag preserved)."""
        if footprint is None or getattr(footprint, "global_", True):
            return self._bump(None, tenant)
        epoch = self._bump(footprint, tenant)
        self._count("scoped_bumps")
        return epoch

    def _bump(self, footprint, tenant: Optional[str] = None) -> int:
        with self._stats_lock:
            self._epoch += 1
            self._bumps.append((self._epoch, footprint, tenant))
            return self._epoch

    def flush(self) -> int:
        """Physical full flush (pattern-less ``flush_cache``); returns the
        number of entries dropped."""
        dropped = 0
        for shard in self._shards:
            with shard.lock:
                dropped += len(shard.entries)
                shard.entries.clear()
        if dropped:
            self._count("evictions", dropped)
        self.bump_epoch()
        return dropped

    def evict_subject(self, subject_id: str,
                      tenant: Optional[str] = None) -> int:
        """Drop every entry fingerprinted under ``subject_id``
        (``userModified``/``userDeleted`` invalidation path).  With a
        ``tenant``, only that tenant's entries for the subject drop; an
        untenanted eviction walks only default-domain keys — tenanted
        keys carry the tenant prefix, so cross-tenant eviction is
        structurally impossible on either path."""
        if not subject_id:
            return 0
        if tenant:
            return self._evict_prefix(
                f"{tenant}{_TSEP}{subject_id}{_SEP}"
            )
        return self._evict_prefix(subject_id + _SEP,
                                  default_domain_only=True)

    def evict_pattern(self, pattern: str,
                      tenant: Optional[str] = None) -> int:
        """The reference ``flush_cache`` pattern semantics against the
        subject-id prefix of the key space; empty pattern flushes all.
        With a ``tenant``, the walk is confined to that tenant's key
        prefix (empty pattern drops the whole tenant, nothing else)."""
        if tenant:
            return self._evict_prefix(f"{tenant}{_TSEP}{pattern}")
        if not pattern:
            return self.flush()
        return self._evict_prefix(pattern, default_domain_only=True)

    def _evict_prefix(self, prefix: str,
                      default_domain_only: bool = False) -> int:
        """``default_domain_only`` confines an untenanted prefix walk to
        untenanted keys: a tenant id that happens to start with the prefix
        (e.g. pattern "u1" vs tenant "u1-corp") must not get its whole
        namespace evicted by a default-domain flush."""
        dropped = 0
        for shard in self._shards:
            with shard.lock:
                stale = [
                    k for k in shard.entries
                    if k.startswith(prefix)
                    and not (default_domain_only and _TSEP in k)
                ]
                for k in stale:
                    del shard.entries[k]
                dropped += len(stale)
        if dropped:
            self._count("evictions", dropped)
        return dropped


def from_config(cfg, telemetry=None) -> Optional[DecisionCache]:
    """Build a DecisionCache from the ``decision_cache`` config block
    (srv/config.py DEFAULT_CONFIG); None when disabled."""
    block = cfg.get("decision_cache") or {}
    if not block.get("enabled", True):
        return None
    return DecisionCache(
        ttl_s=float(block.get("ttl_s", 3600.0)),
        max_entries=int(block.get("max_entries", 65536)),
        shards=int(block.get("shards", 16)),
        telemetry=telemetry,
    )
