"""Hybrid evaluator: the TPU kernel fast path fused with the scalar oracle.

Batched ``isAllowed`` requests flow through the compiled kernel; requests
outside the kernel's representable subset (or whole trees the compiler
rejects) fall back to the oracle — decisions are bit-identical either way
(enforced by the differential suite).  Kernel rows that abort with an error
status are re-run on the oracle to recover the exact error message the
reference would produce (the kernel computes codes, not message strings).

Hot policy mutation triggers a recompile; serving is version-pinned: the
old kernel keeps answering until the new compile (optionally off-thread)
is swapped in atomically (the reference just mutates Maps in place,
reference: src/core/accessController.ts:897-937 — we must not stall
serving on an XLA compile).

With the incremental-update subsystem active (ops/delta.py, the default
off the rule-sharded mesh path), compiled tables are capacity-bucketed
and CRUD mutations arrive as captured events (srv/store.py): in-capacity
deltas PATCH the host tables and swap a new kernel object that reuses the
existing jitted executables (zero new XLA compilations, sub-ms
time-to-visibility), scoped decision-cache bumps keep disjoint entries
warm, and certified-empty diffs skip the flush and the compile entirely.
Everything the delta prover cannot certify falls back to the full
recompile below, whose async variant is debounced: at most one compile
runs and at most one is pending regardless of the CRUD arrival rate.
"""

from __future__ import annotations

import copy
import threading
import time
from typing import Optional

import numpy as np

from ..core.engine import AccessController
from ..models.model import Decision, OperationStatus, Response
from ..ops import delta as delta_mod
from ..ops.compile import DECISION_NAMES, compile_policies
from ..ops.encode import encode_requests
from ..ops.kernel import DecisionKernel
from .decision_cache import request_features
from .watchdog import DeviceTimeoutError


class HybridEvaluator:
    def __init__(
        self,
        engine: AccessController,
        backend: str = "hybrid",  # oracle | kernel | hybrid
        logger=None,
        async_compile: bool = False,
        telemetry=None,
        mesh=None,
        mesh_axis: str = "data",
        model_axis: str | None = None,
        pod_shards: int | None = None,
        decision_cache=None,
        delta_enabled: bool = True,
        observability=None,
        shared_jits: Optional[dict] = None,
        fixed_caps=None,
        tenant: Optional[str] = None,
        explain: bool = False,
    ):
        self.engine = engine
        self.backend = backend
        self.logger = logger
        self.telemetry = telemetry
        self.async_compile = async_compile
        # observability hub (srv/tracing.Observability): stage-span
        # tracing + audit attribution.  None (the default) keeps every
        # instrumentation site on the exact pre-observability path.
        self.obs = observability
        # rate-limited hot-path logging: the per-row warning sites
        # (token-unresolved, oracle fallback) must not turn the masking
        # logger into the bottleneck when an upstream is down under load
        from .telemetry import SampledLogger

        self._slog = SampledLogger(logger)
        # server-side decision cache (srv/decision_cache.py): consulted
        # batch-wide BEFORE encode so hit rows skip both the device
        # round-trip and the oracle walk; written through from every miss
        # row the engine marks evaluation_cacheable.  Policy mutations
        # invalidate via refresh() -> bump_epoch below.
        self.decision_cache = decision_cache
        # optional jax.sharding.Mesh: requests shard data-parallel over
        # ``mesh_axis`` while policy tensors replicate — the serving-path
        # multi-chip layout (the reference scales by running N stateless
        # replicas behind a load balancer, src/worker.ts:161-198; here one
        # process drives N chips).  With ``model_axis`` set (a 2-axis
        # mesh from parallel.make_mesh2), the RULE axis of the compiled
        # tensors shards over it too — trees too large to replicate per
        # chip serve through parallel/rule_shard.RuleShardedKernel.
        self.mesh = mesh
        self.mesh_axis = mesh_axis
        self.model_axis = model_axis
        # pod-sharded tier (parallel/pod_shard.py, config
        # parallel:pod_shards): the SET axis of one pod-level bucketed
        # compile shards over ``model_axis`` instead of the rule axis —
        # unlike the rule-sharded path this one IS delta-patchable
        # (shard-local relower, see PodShardedKernel.patched)
        self.pod_shards = pod_shards
        self._version = 0
        self._compiled = None
        self._kernel: Optional[DecisionKernel] = None
        self._rq_kernel = None
        self._tree_snapshot = None
        self._native_encoder = None
        # candidate index over the LIVE engine tree: oracle-fallback rows
        # skip rules that provably cannot target-match (bit-identical —
        # core/candidate_index.py).  Published as ONE (tree, index) tuple
        # so readers see a consistent pair (no TOCTOU between index and
        # identity guard); a hot replace_policy_sets swap fails the
        # identity check instantly and the refresh that follows rebuilds.
        self._cand: Optional[tuple] = None  # (tree ref, CandidateIndex)
        self._lock = threading.Lock()
        self._compile_thread: Optional[threading.Thread] = None
        # incremental-update subsystem (ops/delta.py): capacity-bucketed
        # tables + CRUD-event patching.  Disabled on the rule-sharded mesh
        # path (RuleShardedKernel repartitions per compile) and for the
        # oracle backend (nothing compiled to patch).  The pod-sharded
        # path keeps it ON: PodShardedKernel.patched re-slices only the
        # shards owning the patched set slots.
        self.delta_enabled = bool(
            delta_enabled
            and (model_axis is None or pod_shards is not None)
            and backend != "oracle"
        )
        self._caps = None                   # delta_mod.Capacities
        self._delta_state = None            # delta_mod.DeltaState
        # jitted executables, swap-stable.  An INJECTED dict (multi-tenant
        # packing, srv/tenancy.py) is shared by every evaluator in one
        # size class: identical table shapes -> the per-shape cache inside
        # each jitted callable hits, so N same-class tenants cost the
        # class's compile count, not N compiles.
        self._shared_jits: dict = (
            shared_jits if shared_jits is not None else {}
        )
        # pinned capacity class (delta_mod.Capacities): full compiles go
        # through fixed_caps_compile so the published shapes never drift
        # from the class.  On class overflow the compile falls back to
        # per-tenant buckets (serving never breaks) and the tenancy
        # registry detects the caps drift and promotes the tenant.
        self.fixed_caps = fixed_caps
        # tenant id this evaluator serves (None = the default domain):
        # scopes decision-cache keys/bumps so one tenant's mutations never
        # flush another's entries
        self.tenant = tenant
        # explain mode (srv/explain.py): kernels emit one extra int32 per
        # row naming the deciding node; decoded host-side onto the
        # response (``_rule_id`` / ``_explain``).  OFF by default — the
        # False path traces the exact pre-explain computation, so the
        # lowered device program is byte-identical to explain-less builds.
        self.explain = bool(explain) and backend != "oracle"
        self._explain_decoder = None
        self._delta_counts = {
            "patches": 0, "full_compiles": 0, "noops": 0,
            "recompiles_avoided": 0, "fallbacks": 0,
        }
        self._delta_fallback_reasons: dict[str, int] = {}
        self._last_visibility_ms: Optional[float] = None
        # async full-compile debounce: at most one compile running and at
        # most one pending, however fast CRUD events arrive
        self._compile_state_lock = threading.Lock()
        self._compile_pending = False
        self._shutdown = False
        # device-health state (srv/watchdog.py): a quarantined evaluator
        # routes every decision path to the oracle until the watchdog's
        # probe restores the kernel.  Plain bool store/load — readers see
        # a flip at the next batch boundary, which is the granularity the
        # quarantine needs.
        self._watchdog = None
        self._quarantined = False
        self.refresh(wait=True)  # oracle backend builds only the index

    # ------------------------------------------------------------- lifecycle

    def refresh(self, wait: bool = False, events=None,
                footprint=None) -> None:
        """Recompile the policy tensors after a tree mutation; the previous
        kernel serves until the swap.

        ``events`` (list of ops/delta.CrudEvent, captured by the store at
        mutation time) enables the incremental path: certified-empty diffs
        skip the cache flush and the compile; in-capacity deltas patch the
        bucketed tables in place and reuse every jitted executable;
        anything else falls back to the full recompile.  ``footprint``
        (ops/delta.Footprint) scopes the post-swap decision-cache bump on
        the patch path — the pre-swap bump is the store's (the paired
        invariant of PR 1, preserved verbatim on both paths)."""
        t0 = time.perf_counter()
        if self.backend == "oracle":
            if self.decision_cache is not None:
                if footprint is not None and footprint.empty:
                    pass  # certified no-op: nothing to flush
                elif footprint is not None:
                    self.decision_cache.bump_scoped(footprint)
                else:
                    self.decision_cache.bump_epoch()
            # no compile, but the oracle walk still benefits from the
            # candidate index — in fact it is the mode where EVERY
            # request takes that walk
            self._cand = self._build_candidate_index()
            return

        if events is not None and self._delta_ready():
            if self._try_patch(events, footprint, t0):
                return

        # ------------------------------------------------ full recompile
        if self.decision_cache is not None:
            # the tree changed (CRUD hot-sync / restore / reset / policy
            # load) and no delta certificate exists: every cached decision
            # is logically flushed BEFORE the new tree serves — a stale
            # hit must never outlive the swap
            self.decision_cache.bump_epoch()
        with self._lock:
            self._version += 1

        if self.async_compile and not wait:
            # debounce: one running compile + at most one pending.  The
            # worker loop recompiles from the LATEST version at each
            # round, so a burst of N CRUD events costs at most two
            # compiles (the in-flight one and one covering the rest).
            with self._compile_state_lock:
                if self._shutdown:
                    return
                self._compile_pending = True
                thread = self._compile_thread
                if thread is None or not thread.is_alive():
                    thread = threading.Thread(
                        target=self._compile_worker, daemon=True
                    )
                    self._compile_thread = thread
                    thread.start()
        else:
            with self._lock:
                version = self._version
            self._compile_and_swap(version, t0)

    # --------------------------------------------------- incremental path

    def _delta_ready(self) -> bool:
        """The patch path engages only when the PUBLISHED compile is the
        latest version (no async full compile in flight — patching stale
        tables would silently drop the in-flight mutation) and a supported
        kernel + ownership state exist."""
        if not self.delta_enabled:
            return False
        with self._lock:
            return (
                self._compiled is not None
                and self._compiled.supported
                and self._kernel is not None
                and self._delta_state is not None
                and self._compiled.version == self._version
            )

    def _try_patch(self, events, footprint, t0) -> bool:
        """Apply a CRUD delta in place; True when the refresh is fully
        handled (patch published or certified no-op), False to fall back
        to the full recompile."""
        with self._lock:
            compiled = self._compiled
            state = self._delta_state
            claimed = self._version
            kernel_prev = self._kernel
        tree = self.engine.policy_sets
        try:
            result, patched, new_state, stats = delta_mod.apply_events(
                state, compiled, tree, events, self.engine.urns
            )
        except delta_mod.DeltaIneligible as err:
            self._delta_counts["fallbacks"] += 1
            self._delta_fallback_reasons[err.reason] = (
                self._delta_fallback_reasons.get(err.reason, 0) + 1
            )
            self._count_delta("delta-fallback")
            if self.logger:
                self.logger.info(
                    "delta ineligible; full recompile",
                    extra={"reason": err.reason},
                )
            return False
        except Exception:  # noqa: BLE001 — patching must never kill CRUD
            if self.logger:
                self.logger.exception("delta patch failed; full recompile")
            return False

        if result == "noop":
            # nothing evaluation-relevant changed: keep the compiled
            # tables, the kernel AND the decision cache; only the
            # candidate index must track the new tree identity
            cand = self._build_candidate_index()
            with self._lock:
                if self._version == claimed:
                    self._cand = cand
                    self._tree_snapshot = tree
                    self._explain_decoder = self._make_explain_decoder(
                        self._kernel, tree
                    )
                    if new_state is not None:
                        self._delta_state = new_state
            self._delta_counts["noops"] += 1
            self._count_delta("delta-noop")
            return True

        shards_patched = 0
        if getattr(kernel_prev, "supports_shard_patch", False):
            # pod-sharded path: re-slice ONLY the shards owning the
            # patched set slots; every other shard's host tables are
            # reused by reference and the jitted shard_map program comes
            # from the shared registry — zero new XLA compiles
            patched_slots = stats.get("patched_slots", [])
            kernel = kernel_prev.patched(patched, patched_slots)
            shards_patched = len({
                min(int(s) // kernel_prev.s_local,
                    kernel_prev.n_shards - 1)
                for s in patched_slots
            })
        else:
            from ..ops.prefilter import PrefilteredKernel

            kernel = PrefilteredKernel(
                patched, mesh=self.mesh, axis=self.mesh_axis,
                telemetry=self.telemetry, dynamic_policies=True,
                shared_jits=self._shared_jits, explain=self.explain,
            )
        native_encoder = self._make_native_encoder(patched, kernel)
        cand = self._build_candidate_index()
        explain_decoder = self._make_explain_decoder(kernel, tree)
        with self._lock:
            if self._version != claimed:
                return False  # a newer refresh superseded this patch
            self._version += 1
            patched.version = self._version
            self._compiled = patched
            self._kernel = kernel
            self._rq_kernel = None
            self._tree_snapshot = tree
            self._native_encoder = native_encoder
            self._cand = cand
            self._explain_decoder = explain_decoder
            self._delta_state = new_state
        if self.decision_cache is not None:
            # post-swap bump, scoped to the delta's footprint: entries
            # whose signatures are disjoint survive the mutation (the
            # pre-swap bump in store._load_locked used the same footprint)
            if footprint is not None:
                self.decision_cache.bump_scoped(footprint)
            else:
                self.decision_cache.bump_epoch()
        visibility_ms = (time.perf_counter() - t0) * 1e3
        self._last_visibility_ms = visibility_ms
        self._delta_counts["patches"] += 1
        self._delta_counts["recompiles_avoided"] += 1
        self._count_delta("delta-patch")
        if self.telemetry is not None:
            self.telemetry.policy_update_latency.observe(
                visibility_ms / 1e3
            )
            self.telemetry.delta.inc(
                "sets_patched", int(stats.get("sets_patched", 0))
            )
            if shards_patched:
                self.telemetry.delta.inc("shards_patched", shards_patched)
        return True

    def _count_delta(self, key: str) -> None:
        if self.telemetry is not None:
            self.telemetry.delta.inc(key)

    def delta_stats(self) -> dict:
        """health_check surface: patch vs full-compile counts, fallback
        taxonomy, last time-to-visibility and the active capacities."""
        out = {
            "enabled": self.delta_enabled,
            **self._delta_counts,
            "fallback_reasons": dict(self._delta_fallback_reasons),
            "last_visibility_ms": (
                round(self._last_visibility_ms, 3)
                if self._last_visibility_ms is not None else None
            ),
        }
        caps = self._caps
        if caps is not None:
            out["capacities"] = caps.as_dict()
        sharding = self.shard_identity()
        if sharding is not None:
            out["sharding"] = {
                "n_shards": sharding["n_shards"],
                "applied_patches": [
                    sh["applied_patches"] for sh in sharding["shards"]
                ],
            }
        return out

    def shard_identity(self) -> Optional[dict]:
        """Pod-sharding surface for health_check/program_identity: shard
        count, per-shard fingerprints/capacities and the applied-patch
        watermarks; None when the active kernel is not pod-sharded."""
        kernel = self._kernel
        if kernel is None or not getattr(
            kernel, "supports_shard_patch", False
        ):
            return None
        return kernel.shard_identity()

    def table_fingerprint(self) -> Optional[str]:
        """Digest of the compiled policy tables: every device array's
        bytes + shape + dtype, the condition sources, the entity vocab and
        the active capacities.  Two replicas that applied the same CRUD
        sequence through the delta path hold byte-identical tables, so
        their fingerprints match — the cluster tier's convergence check
        (srv/router.py health, tests/test_cluster_chaos.py,
        tpu_compat_audit cluster-replica-program-identity)."""
        from hashlib import blake2b

        compiled = self._compiled
        if compiled is None:
            return None
        h = blake2b(digest_size=16)
        for name in sorted(compiled.arrays):
            arr = np.ascontiguousarray(compiled.arrays[name])
            h.update(name.encode())
            h.update(str(arr.dtype).encode())
            h.update(repr(arr.shape).encode())
            h.update(arr.tobytes())
        h.update(repr(compiled.entity_vocab).encode())
        h.update(repr([
            (c.rule_flat_index, c.condition, repr(c.context_query), c.owner)
            for c in compiled.conditions
        ]).encode())
        caps = self._caps
        if caps is not None:
            h.update(repr(sorted(caps.as_dict().items())).encode())
        sharding = self.shard_identity()
        if sharding is not None:
            # fold the per-shard table digests in, so replicas must agree
            # on the SLICED tables too (shard boundaries, compacted
            # per-shard target subtables), not just the pod-level arrays
            h.update(sharding["pod_fingerprint"].encode())
        store = getattr(self.engine, "relation_store", None)
        if store is not None:
            # the relation-tuple state decides relation-bearing rows, so
            # replica convergence must cover it too (two replicas with
            # equal policy tables but divergent tuple logs must differ)
            h.update(store.fingerprint().encode())
        return h.hexdigest()

    # ------------------------------------------------------ full compile

    def _compile_worker(self) -> None:
        """Debounced async compile loop: drains pending requests one
        compile at a time, always from the latest version."""
        while True:
            with self._compile_state_lock:
                if not self._compile_pending or self._shutdown:
                    self._compile_thread = None
                    return
                self._compile_pending = False
            with self._lock:
                version = self._version
            try:
                self._compile_and_swap(version, time.perf_counter())
            except Exception:  # noqa: BLE001 — keep draining
                if self.logger:
                    self.logger.exception("async policy compile failed")

    def _compile_and_swap(self, version: int, t0: float) -> None:
        # snapshot FIRST, compile FROM the snapshot: the published
        # (tree, arrays) pair is then consistent by construction — a
        # hot mutation landing mid-compile bumps _version and this
        # compile is dropped below, never pairing a mutated tree with
        # stale index arrays (the reverse-query kernel assembles its
        # trees from this snapshot)
        tree_snapshot = copy.deepcopy(self.engine.policy_sets)
        caps = state = None
        if self.delta_enabled and self.fixed_caps is not None:
            try:
                compiled, caps, state = delta_mod.fixed_caps_compile(
                    tree_snapshot, self.engine.urns, self.fixed_caps,
                    version=version,
                )
            except delta_mod.DeltaIneligible as err:
                # class overflow: serve from per-tenant buckets rather
                # than fail — the tenancy registry compares the published
                # caps against the class and promotes the tenant
                if self.logger:
                    self.logger.info(
                        "tenant tree overflows pinned size class; "
                        "falling back to per-tenant capacity buckets",
                        extra={"reason": err.reason,
                               "tenant": self.tenant},
                    )
                compiled, caps, state = delta_mod.full_bucketed_compile(
                    tree_snapshot, self.engine.urns, version=version,
                    prev_caps=None,
                )
        elif self.delta_enabled:
            compiled, caps, state = delta_mod.full_bucketed_compile(
                tree_snapshot, self.engine.urns, version=version,
                prev_caps=self._caps,
            )
        else:
            compiled = compile_policies(
                tree_snapshot, self.engine.urns, version=version
            )
        kernel = None
        if compiled.supported and compiled.n_rules > 0:
            if self.pod_shards is not None and self.mesh is not None:
                # pod-sharded tier (config: parallel:pod_shards): the SET
                # axis of the bucketed compile partitions over the model
                # axis with per-shard compacted target subtables; the
                # shard_map program registers in _shared_jits, so a
                # recompile with unchanged capacities reuses it
                from ..parallel.pod_shard import PodShardedKernel

                prev = self._kernel
                kernel = PodShardedKernel(
                    compiled, self.mesh,
                    data_axis=self.mesh_axis,
                    model_axis=self.model_axis or "model",
                    explain=self.explain,
                    shared_jits=self._shared_jits,
                    prev_t_cap=getattr(prev, "t_cap", 0),
                )
            elif self.model_axis is not None and self.mesh is not None:
                # rule-axis sharding (config: parallel:model_devices):
                # the compiled tensors partition over the model axis,
                # requests over the data axis.  Evaluator-level path
                # counters (kernel/oracle rows) still record via
                # _count_path; only PrefilteredKernel's internal
                # cache counters have no sharded equivalent.
                from ..parallel.rule_shard import RuleShardedKernel

                kernel = RuleShardedKernel(
                    compiled, self.mesh,
                    data_axis=self.mesh_axis,
                    model_axis=self.model_axis,
                    explain=self.explain,
                )
            else:
                # PrefilteredKernel is a drop-in DecisionKernel that
                # keeps per-request work O(matching rules) on large
                # trees and delegates to the dense kernel below
                # MIN_RULES
                from ..ops.prefilter import PrefilteredKernel

                kernel = PrefilteredKernel(
                    compiled, mesh=self.mesh, axis=self.mesh_axis,
                    telemetry=self.telemetry,
                    dynamic_policies=self.delta_enabled,
                    shared_jits=self._shared_jits,
                    explain=self.explain,
                )
        native_encoder = self._make_native_encoder(compiled, kernel)
        cand = self._build_candidate_index()
        explain_decoder = self._make_explain_decoder(kernel, tree_snapshot)
        with self._lock:
            if version >= self._version:  # drop stale compiles
                self._compiled = compiled
                self._kernel = kernel
                self._rq_kernel = None  # lazy: built on first wia batch
                self._tree_snapshot = tree_snapshot
                self._native_encoder = native_encoder
                self._cand = cand
                self._caps = caps
                self._explain_decoder = explain_decoder
                self._delta_state = state
        self._delta_counts["full_compiles"] += 1
        self._count_delta("full-compile")
        self._last_visibility_ms = (time.perf_counter() - t0) * 1e3
        if self.telemetry is not None:
            self.telemetry.policy_update_latency.observe(
                time.perf_counter() - t0
            )
        if self.logger and not compiled.supported:
            self.logger.warning(
                "policy tree not kernel-supported; serving from oracle",
                extra={"reason": compiled.unsupported_reason},
            )

    def shutdown(self, timeout: float = 10.0) -> None:
        """Stop the async compile loop and join its thread (worker
        shutdown must not leak daemon compile threads mid-XLA-compile)."""
        with self._compile_state_lock:
            self._shutdown = True
            self._compile_pending = False
            thread = self._compile_thread
        if thread is not None and thread.is_alive():
            thread.join(timeout)

    def _build_candidate_index(self):
        """(live tree, CandidateIndex) for trees worth indexing, else
        None; the pair is published atomically (see __init__)."""
        live_tree = self.engine.policy_sets
        n_rules = sum(
            len(p.combinables)
            for ps in live_tree.values() if ps is not None
            for p in ps.combinables.values() if p is not None
        )
        if n_rules < 256:
            return None
        from ..core.candidate_index import CandidateIndex

        return (live_tree, CandidateIndex(live_tree, self.engine.urns))

    def _make_explain_decoder(self, kernel, tree):
        """ExplainDecoder paired with one published kernel, built from the
        same version-pinned tree snapshot as the compiled arrays; None
        when explain is off or the kernel cannot emit provenance."""
        if (not self.explain or kernel is None
                or not getattr(kernel, "explain", False)):
            return None
        from .explain import ExplainDecoder, explain_capacity_ok

        compiled = getattr(kernel, "compiled", None)
        if compiled is not None:
            assert explain_capacity_ok(
                compiled.S, compiled.KP, compiled.KR
            ), "policy tree exceeds the explain code's 30-bit position bound"
        return ExplainDecoder(tree, kernel.explain_strides)

    def _make_native_encoder(self, compiled, kernel):
        """C++ wire-batch encoder for the gRPC fast path; None when the
        native library or the tree shape does not support it.  Explain
        mode also disables it: wire batches carry no Response objects to
        stamp provenance on, so explain-enabled serving routes gRPC
        through the pb decode path instead of silently dropping the
        deciding-rule attribution."""
        if kernel is None or compiled.conditions or self.explain:
            return None
        try:
            from .. import native

            if not native.available():
                return None
            return native.NativeBatchEncoder(compiled)
        except Exception as err:  # toolchain-less environments
            if self.logger:
                self.logger.info("native encoder disabled: %s", err)
            return None

    # --------------------------------------------------- relation plumbing

    def attach_relation_store(self, store) -> None:
        """Wire a RelationTupleStore (srv/relations.py): the oracle gate
        reads it through ``engine.relation_store``, encode pulls the flat
        verdict tables per batch, and every tuple write bumps the
        decision cache — tuple churn changes decisions without any policy
        CRUD, but swaps NO program: the compiled tables, the kernel and
        every jitted executable stay byte-identical (the ReBAC serving
        invariant, tpu_compat_audit rebac-zero-matmul-program-identity)."""
        self.engine.relation_store = store

        def _on_change(_gen: int) -> None:
            if self.decision_cache is not None:
                self.decision_cache.bump_epoch()
            self._count_path("relation-churn", 1)

        store.on_change(_on_change)

    def _relation_tables(self, compiled):
        """The store's flat verdict tables for this compile, or None
        (encode then packs fail-closed planes / dummies)."""
        store = getattr(self.engine, "relation_store", None)
        if store is None or compiled is None:
            return None
        from ..ops.relation import relation_bits_needed

        if not relation_bits_needed(compiled):
            return None
        return store.tables_for(compiled)

    def _relation_tables_native(self, encoder):
        """Verdict tables in the NATIVE encoder's id space for the wire
        path — the C++ interner diverges from the Python one after the
        preload snapshot, so the host-space tables cannot be reused."""
        store = getattr(self.engine, "relation_store", None)
        if store is None or not encoder.needs_relation_bits:
            return None
        return encoder.native_relation_tables(store)

    def _relation_provenance(self, request, source_id):
        """Tuple-path witnesses for a relation-decided explain row: when
        the deciding node's target carries relation-path attributes, walk
        the live tuple graph for the hop list that satisfied each (path,
        instance) pair — the ReBAC analog of the rule-id stamp.  None
        whenever the row wasn't relation-gated (no store, no relation
        attrs, nothing collected), so non-ReBAC explain output is
        byte-identical."""
        store = getattr(self.engine, "relation_store", None)
        if store is None or source_id is None:
            return None
        target = self._node_target(source_id)
        if target is None:
            return None
        from ..core.relation_path import (
            collect_target_instances,
            relation_paths,
            request_subject_id,
        )

        urns = self.engine.urns
        paths = relation_paths(
            target.subjects if target is not None else None, urns
        )
        if not paths:
            return None
        instances = collect_target_instances(target, request, urns)
        subject_id = request_subject_id(request)
        if not instances or subject_id is None:
            return None
        witnesses = []
        for expr in paths:
            for ns, oid in instances:
                hops = store.witness(expr, ns, oid, subject_id)
                if hops is not None:
                    witnesses.append({
                        "path": expr,
                        "object": f"{ns}:{oid}",
                        "tuples": hops,
                    })
        return witnesses or None

    def _node_target(self, source_id):
        """The target of the tree node ``source_id`` names — deciding
        rule first, then no-rules policy (same precedence as
        ExplainDecoder.describe_source); None when the id left the tree
        under a hot mutation (provenance then degrades, never raises)."""
        for ps in self.engine.policy_sets.values():
            if ps is None:
                continue
            for pol in ps.combinables.values():
                if pol is None:
                    continue
                for rule in pol.combinables.values():
                    if rule is not None and rule.id == source_id:
                        return rule.target
        for ps in self.engine.policy_sets.values():
            if ps is None:
                continue
            for pol in ps.combinables.values():
                if pol is not None and pol.id == source_id:
                    return pol.target
        return None

    @property
    def kernel_active(self) -> bool:
        return self._kernel is not None

    # --------------------------------------------- device-health plumbing

    @property
    def quarantined(self) -> bool:
        return self._quarantined

    def set_quarantined(self, flag: bool) -> None:
        """Flipped by the device watchdog (srv/watchdog.py): True routes
        every decision path to the oracle — degraded-but-correct serving
        while the kernel path heals; False restores kernel routing."""
        self._quarantined = bool(flag)

    def attach_watchdog(self, watchdog) -> None:
        self._watchdog = watchdog

    @property
    def watchdog(self):
        return self._watchdog

    def _guard_materialize(self, materialize):
        """Bound a kernel materialize under the watchdog deadline when one
        is attached; identity otherwise (the default path adds zero
        indirection beyond this None check)."""
        watchdog = self._watchdog
        if watchdog is None:
            return materialize
        return lambda: watchdog.run(materialize)

    def kernel_probe(self) -> bool:
        """One canary batch through the live kernel's dispatch+materialize
        — proves the device path answers end-to-end.  Used by the
        watchdog's restore probe (bounded there); False when no kernel is
        active.  Bypasses the watchdog wrap on purpose: the probe applies
        its own deadline."""
        with self._lock:
            kernel = self._kernel
            compiled = self._compiled
        if kernel is None or compiled is None:
            return False
        from ..models.model import Request, Target

        canary = Request(target=Target(), context={})
        batch = encode_requests(
            [canary], compiled, self.engine.resource_adapter
        )
        outputs = kernel.evaluate_async(batch)()
        return len(outputs) >= 3  # explain-enabled kernels append a 4th

    def _hang_fallback(self, requests: list) -> list:
        """Honest per-row resolution for a batch whose device materialize
        timed out: rows with an already-expired deadline shed with the
        deadline status, everything else takes the oracle walk (a real
        evaluation — its cacheability stands), and a row the oracle
        cannot answer gets the never-cacheable ``degraded`` envelope.
        Never a fabricated PERMIT/DENY."""
        from .admission import (
            DEADLINE_CODE,
            degraded_response,
            overload_response,
        )

        expired = self._expired_rows(requests)
        shed = overload_response(
            DEADLINE_CODE, "deadline expired before evaluation"
        )
        out = []
        n_oracle = 0
        n_degraded = 0
        for b, request in enumerate(requests):
            if b in expired:
                out.append(shed)
                continue
            try:
                out.append(self._oracle_is_allowed(request))
                n_oracle += 1
            except Exception:  # noqa: BLE001 — honest envelope below
                out.append(degraded_response(
                    "device materialize timed out and the oracle "
                    "fallback failed"
                ))
                n_degraded += 1
        self._count_path("hang-fallback-oracle", n_oracle)
        self._count_path("hang-fallback-degraded", n_degraded)
        self._count_path("deadline-expired", len(expired))
        self._slog.warning(
            "hang-fallback",
            "device materialize timeout: %d rows to oracle, %d shed, "
            "%d degraded", n_oracle, len(expired), n_degraded,
        )
        return out

    @property
    def native_active(self) -> bool:
        return self._native_encoder is not None

    def is_allowed_batch_wire(self, messages: list[bytes], span=None):
        """Native fast path: serialized acstpu.Request messages -> per-row
        (decision, cacheable, status, eligible).  Returns None when the
        native encoder is unavailable (caller falls back to the pb path).
        ``span`` is the RPC-level span from the transport (the native
        path has no Request objects to carry per-row spans)."""
        finalize = self.is_allowed_batch_wire_async(messages, span=span)
        return None if finalize is None else finalize()

    def is_allowed_batch_wire_async(self, messages: list[bytes], span=None,
                                    reuse: bool = False):
        """Dispatch stage of the native wire path: encode (C++) + device
        dispatch WITHOUT blocking, returning a zero-arg ``finalize`` that
        materializes and yields (batch, decision, cacheable, status) — the
        streaming pipeline (srv/pipeline.py) overlaps the next frame's
        encode/dispatch with this frame's device execution and the
        previous frame's decode.  None when the native path is
        unavailable (caller falls back to pb parsing).

        ``reuse=True`` encodes into pooled staging buffers; the CALLER
        must fire ``batch.release_staging()`` once it has finished reading
        the batch (after response assembly), never before."""
        with self._lock:
            kernel = self._kernel
            encoder = self._native_encoder
        if (kernel is None or encoder is None or self.backend == "oracle"
                or self._quarantined):
            return None
        tracer = self.obs.tracer if self.obs is not None else None
        t_stage = time.perf_counter() if tracer is not None else 0.0
        rel_tables = self._relation_tables_native(encoder)
        batch = encoder.encode_wire(
            messages, reuse=reuse, relation_tables=rel_tables
        )
        if tracer is not None:
            from .tracing import STAGE_WIRE_ENCODE

            now = time.perf_counter()
            tracer.record(span, STAGE_WIRE_ENCODE, now - t_stage)
        t_device = time.perf_counter()
        materialize = self._guard_materialize(kernel.evaluate_async(batch))

        def finalize():
            decision, cacheable, status = materialize()[:3]
            if tracer is not None:
                from .tracing import STAGE_DEVICE

                tracer.record(span, STAGE_DEVICE,
                              time.perf_counter() - t_device)
            if batch.overcap is not None and batch.overcap.any():
                # adaptive caps, native path: rows that overflowed the
                # floor shapes re-encode natively at the ceiling (one
                # extra native call + one extra kernel dispatch for the
                # rare deep rows) instead of falling back to the oracle
                from ..ops.encode import _CAPS_CEIL

                idx = [
                    b for b in range(len(messages))
                    if batch.overcap[b] and not batch.eligible[b]
                ]
                retry = encoder.encode_wire(
                    [messages[b] for b in idx], caps=dict(_CAPS_CEIL),
                    relation_tables=rel_tables,
                )
                d2, c2, s2 = self._guard_materialize(
                    kernel.evaluate_async(retry)
                )()[:3]
                # kernel outputs are read-only views on device buffers
                decision = np.array(decision)
                cacheable = np.array(cacheable)
                status = np.array(status)
                n_retried = 0
                for j, b in enumerate(idx):
                    if retry.eligible[j]:
                        batch.eligible[b] = True
                        decision[b] = d2[j]
                        cacheable[b] = c2[j]
                        status[b] = s2[j]
                        n_retried += 1
                self._count_path("native-wire-ceil", n_retried)
            n_served = sum(
                1 for b in range(len(messages))
                if batch.eligible[b] and status[b] == 200
            )
            self._count_path("native-wire", n_served)
            return batch, decision, cacheable, status

        return finalize

    # ------------------------------------------------- host-side pipeline

    def prepare_batch(self, requests: list) -> None:
        """Stage-traced wrapper over the eligibility pipeline: records
        the ``prepare`` stage (token resolution + HR rendezvous wall
        time) when the batch actually had unresolved token rows — the
        idempotent re-invocation from is_allowed_batch after the
        batcher already prepared is a no-op and records nothing."""
        tracer = self.obs.tracer if self.obs is not None else None
        if tracer is None:
            self._prepare_batch(requests)
            return
        t0 = time.perf_counter()
        did_work = self._prepare_batch(requests)
        if did_work:
            from .tracing import STAGE_PREPARE

            tracer.fan_out(requests, STAGE_PREPARE,
                           time.perf_counter() - t0)

    def _prepare_batch(self, requests: list) -> bool:
        """Host-side eligibility pipeline, stage (a): batch-resolve every
        distinct ``subject.token`` through the identity client (one RPC per
        distinct token — the TTL'd resolution cache makes repeats across
        batches nearly free) and the HR-scope rendezvous (one rendezvous
        per distinct cache key), then mark each request prepared so the
        encoder keeps resolved token rows on the kernel path.

        Idempotent and semantics-preserving by construction: after this,
        ``engine.prepare_context`` is a no-op for these requests, so kernel
        and oracle evaluate the identical resolved context.  Resolution
        failures leave ``request._token_resolved`` False and the row
        degrades per-row to the oracle exactly as unprepared token traffic
        does.  Callers that overlap device execution of batch i with this
        call for batch i+1 (srv/batcher.py) get the pipelining for free —
        everything here is host-only."""
        from ..core.common import get_field
        from ..core.engine import apply_resolved_subject

        engine = self.engine
        pending: list[tuple] = []
        for request in requests:
            if getattr(request, "_context_prepared", False):
                continue
            context = request.context
            subject = get_field(context, "subject") if context else None
            token = get_field(subject, "token") if subject is not None else None
            if token:
                pending.append((request, token))
        if not pending:
            return False

        client = engine.identity_client

        def resolve(token):
            try:
                return client.find_by_token(token)
            except Exception as err:  # noqa: BLE001 — fail the row closed
                # sampled: a down identity service under overload fires
                # this once per distinct token per batch — unbounded, it
                # would make the logger the bottleneck
                self._slog.warning(
                    "token-resolution",
                    "batch token resolution failed: %s", err,
                )
                return None

        by_token: dict[str, list] = {}
        for request, token in pending:
            by_token.setdefault(token, []).append(request)
        resolutions: dict[str, object] = {}
        if client is not None:
            tokens = list(by_token)
            if len(tokens) == 1:
                resolutions[tokens[0]] = resolve(tokens[0])
            else:
                from concurrent.futures import ThreadPoolExecutor

                with ThreadPoolExecutor(
                    max_workers=min(8, len(tokens))
                ) as pool:
                    for token, resolved in zip(
                        tokens, pool.map(resolve, tokens)
                    ):
                        resolutions[token] = resolved

        n_ok = n_fail = 0
        for token, rows in by_token.items():
            resolved = resolutions.get(token)
            payload = get_field(resolved, "payload") if resolved else None
            for request in rows:
                request._context_prepared = True
                if payload is not None:
                    # per-request copy: rows sharing a token must not share
                    # mutable payload objects
                    apply_resolved_subject(
                        get_field(request.context, "subject"),
                        copy.deepcopy(payload),
                    )
                    request._token_resolved = True
                    n_ok += 1
                else:
                    request._token_resolved = False
                    n_fail += 1
        self._count_path("token-resolved", n_ok)
        self._count_path("token-unresolved", n_fail)

        # HR scopes: one rendezvous per distinct cache key; the remaining
        # rows of each group read the freshly-written cache (no second
        # rendezvous).  A timed-out key leaves its whole group scope-less —
        # the same per-row outcome the reference's individual waits produce.
        provider = engine.hr_scope_provider
        if provider is None:
            return True
        groups: dict[str, list] = {}
        for request, _ in pending:
            if not getattr(request, "_token_resolved", False):
                continue
            subject = get_field(request.context, "subject")
            if get_field(subject, "hierarchical_scopes"):
                continue
            key = provider.hr_scopes_key(request.context)
            if key is not None:
                groups.setdefault(key, []).append(request)
        if not groups:
            return True
        firsts = [rows[0] for rows in groups.values()]
        if len(firsts) > 1:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=min(8, len(firsts))) as pool:
                list(pool.map(
                    lambda r: engine.create_hr_scope(r.context), firsts
                ))
        else:
            engine.create_hr_scope(firsts[0].context)
        for key, rows in groups.items():
            for request in rows[1:]:
                try:
                    cached = provider.cache.exists(key)
                except Exception:  # noqa: BLE001 — cache backend hiccup
                    cached = True  # fall through to the normal path
                if cached:
                    engine.create_hr_scope(request.context)
        return True

    # ------------------------------------------------------------ evaluation

    def is_allowed(self, request) -> Response:
        """Single-request path: the oracle wins below batch sizes where the
        device round-trip pays off.  The decision cache is consulted first
        — a warm cacheable request never pays the walk."""
        tracer = self.obs.tracer if self.obs is not None else None
        cache = self.decision_cache
        if cache is not None and cache.enabled:
            # epoch snapshot BEFORE the walk reads the tree: if a CRUD /
            # restore bump lands while this decision is in flight, the
            # write-through below stores a born-stale entry (logical miss)
            # instead of serving an old-tree decision as fresh
            epoch = cache.epoch
            self.engine.prepare_context(request)
            t0 = time.perf_counter() if tracer is not None else 0.0
            key = cache.fingerprint(
                request, self.engine.urns.get("subjectID") or ""
            )
            hit = cache.get(key)
            if hit is not None:
                self._count_path("cache-hit", 1)
                if tracer is not None:
                    from .tracing import STAGE_CACHE

                    tracer.record(getattr(request, "_span", None),
                                  STAGE_CACHE, time.perf_counter() - t0)
                    hit._path = "cache-hit"
                return hit
            response = self._traced_oracle(request, tracer)
            cache.put(key, response, epoch=epoch,
                      features=self._request_features(request))
            return response
        return self._traced_oracle(request, tracer)

    def _traced_oracle(self, request, tracer) -> Response:
        """Oracle walk with the ``oracle`` stage recorded (and the
        serving-path attribute stamped for the audit log) when the
        observability hub is wired; the bare walk otherwise."""
        if tracer is None:
            return self._oracle_is_allowed(request)
        from .tracing import STAGE_ORACLE

        t0 = time.perf_counter()
        response = self._oracle_is_allowed(request)
        tracer.record(getattr(request, "_span", None), STAGE_ORACLE,
                      time.perf_counter() - t0)
        response._path = "oracle"
        return response

    def _request_features(self, request):
        """Candidate-signature features for scoped cache invalidation
        (srv/decision_cache.request_features)."""
        urns = self.engine.urns
        return request_features(
            request, urns.get("entity"), urns.get("operation")
        )

    def _oracle_is_allowed(self, request) -> Response:
        """Oracle walk, candidate-filtered on large trees (skipped rules
        provably cannot target-match; decisions bit-identical — the
        unfiltered walk costs O(total rules) per request, ~28 ms on a
        10k-rule tree).  One read of the (tree, index) pair keeps the
        identity guard and the index consistent under concurrent swaps."""
        cand = self._cand
        if cand is not None and cand[0] is self.engine.policy_sets:
            response = self.engine.is_allowed(
                request,
                candidate_rules=cand[1].candidates(request, self.engine.urns),
            )
        else:
            response = self.engine.is_allowed(request)
        decoder = self._explain_decoder
        if decoder is not None and getattr(response, "_explain", None) is None:
            # oracle rows carry the same ``_explain`` shape as kernel
            # rows (reverse-lookup of the engine's source stamp), so the
            # wire trailer / audit surface never depends on which path
            # decided a row.  None when explain is off — zero new work.
            info = decoder.describe_source(
                getattr(response, "_rule_id", None)
            )
            if info is not None:
                response._explain = info
                rel = self._relation_provenance(
                    request, info.get("rule") or info.get("policy")
                )
                if rel is not None:
                    info["relation"] = rel
        return response

    def what_is_allowed(self, request):
        return self.engine.what_is_allowed(request)

    def what_is_allowed_batch(self, requests: list):
        """Batched reverse query: target matching for the whole batch in
        one device dispatch, tree/obligation assembly on host
        (ops/reverse.py); scalar oracle when no kernel is active.  The
        ReverseQueryKernel is built lazily on first use (deployments that
        only serve isAllowed never pay its device transfer).

        Dispatch is adaptive like the decision path's MIN_RULES: on small
        trees the scalar walk beats the device round-trip (measured ~6x on
        the seed tree, bench_all.py wia row), so the kernel only engages at
        REVERSE_MIN_RULES and above."""
        from ..ops.reverse import REVERSE_MIN_RULES

        self.prepare_batch(requests)
        with self._lock:
            # one consistent snapshot: kernel/compiled/tree always published
            # together, so kernel != None implies compiled.supported
            compiled = self._compiled
            kernel = self._kernel
            rq_kernel = self._rq_kernel
            tree_snapshot = self._tree_snapshot
        if (
            self.backend == "oracle"
            or compiled is None
            or kernel is None
            or self._quarantined
            or compiled.n_rules < REVERSE_MIN_RULES
        ):
            self._count_path("oracle-wia", len(requests))
            return [self.engine.what_is_allowed(r) for r in requests]
        from ..ops.encode import encode_requests
        from ..ops.reverse import ReverseQueryKernel, what_is_allowed_batch

        if rq_kernel is None or rq_kernel.compiled.version != compiled.version:
            # tree_snapshot was published atomically with `compiled` and is
            # the exact tree the arrays were compiled from — no tearing
            # against concurrent hot mutations is possible here
            rq_kernel = ReverseQueryKernel(
                compiled, tree_snapshot, copy_tree=False
            )
            with self._lock:
                if self._compiled is compiled:
                    self._rq_kernel = rq_kernel
        # reverse queries never reach stage B: skip the owner-bit packer,
        # the relation-plane packer (wia ignores relation requirements,
        # like the HR gate) and the condition pre-pass on this encode
        batch = encode_requests(
            requests, compiled, skip_conditions=True, skip_owner_bits=True,
            skip_relation_bits=True,
        )
        out = what_is_allowed_batch(
            self.engine, compiled, rq_kernel, requests, batch
        )
        n_oracle = int((~batch.eligible).sum())
        self._count_path("oracle-wia", n_oracle)
        self._count_path("kernel-wia", len(requests) - n_oracle)
        return out

    def _count_path(self, path: str, rows: int) -> None:
        if self.telemetry is not None and rows:
            self.telemetry.paths.inc(path, rows)

    @staticmethod
    def _expired_rows(requests: list) -> set[int]:
        """Indices of rows whose propagated ``_deadline`` already passed
        (empty for deadline-less traffic — the common case costs one
        getattr per row)."""
        expired: set[int] = set()
        now = None
        for b, request in enumerate(requests):
            deadline = getattr(request, "_deadline", None)
            if deadline is None:
                continue
            if now is None:
                now = time.monotonic()
            if deadline <= now:
                expired.add(b)
        return expired

    def is_allowed_batch(self, requests: list) -> list[Response]:
        """Batched decision path: decision-cache lookup batch-wide BEFORE
        encode (hit rows skip the device round-trip and the oracle walk),
        then the kernel/oracle hybrid over the miss rows, then write-through
        of every miss row the engine marked ``evaluation_cacheable``.

        Rows carrying an already-expired ``_deadline`` (admission
        plumbing, srv/admission.py — set by the transports / service
        facade) short-circuit with the deadline status before any
        evaluation: the caller has abandoned the answer, so neither the
        device nor the oracle burns time on it, and nothing is cached."""
        return self.is_allowed_batch_async(requests)()

    def is_allowed_batch_async(self, requests: list):
        """Dispatch stage of the depth-N batcher pipeline: expired-row
        shed, host eligibility pipeline, cache lookups and encode + device
        DISPATCH run now; the returned zero-arg ``finalize`` blocks on the
        device result, decodes, runs oracle fallback rows and writes the
        cache through.  Calling it immediately is byte-identical to the
        synchronous path (the depth<=2 legacy batcher does exactly that);
        deferring it lets the next batch's dispatch overlap this batch's
        device execution (srv/batcher.py, depth>2)."""
        expired = self._expired_rows(requests)
        if expired:
            from .admission import DEADLINE_CODE, overload_response

            live = [r for b, r in enumerate(requests) if b not in expired]
            fin_live = (
                self.is_allowed_batch_async(live) if live else (lambda: [])
            )
            self._count_path("deadline-expired", len(expired))
            shed = overload_response(
                DEADLINE_CODE, "deadline expired before evaluation"
            )

            def finalize_expired():
                computed = iter(fin_live())
                return [
                    shed if b in expired else next(computed)
                    for b in range(len(requests))
                ]

            return finalize_expired
        self.prepare_batch(requests)
        cache = self.decision_cache
        if cache is None or not cache.enabled:
            return self._uncached_async_entry(requests)
        subject_urn = self.engine.urns.get("subjectID") or ""
        # one epoch snapshot for the whole batch, taken before any row
        # reads the tree: rows whose evaluation spans a concurrent epoch
        # bump are written through born-stale (see DecisionCache.put)
        epoch = cache.epoch
        tracer = self.obs.tracer if self.obs is not None else None
        t_cache = time.perf_counter() if tracer is not None else 0.0
        responses: list[Optional[Response]] = [None] * len(requests)
        keys: list = [None] * len(requests)
        misses: list[int] = []
        for b, request in enumerate(requests):
            # fingerprints are taken AFTER context resolution so the key
            # reflects the subject the evaluation will actually see (and
            # so a userModified-driven re-resolution changes the key)
            self.engine.prepare_context(request)
            keys[b] = cache.fingerprint(request, subject_urn)
            hit = cache.get(keys[b])
            if hit is not None:
                responses[b] = hit
            else:
                misses.append(b)
        if tracer is not None and len(misses) < len(requests):
            from .tracing import STAGE_CACHE

            tracer.fan_out(
                [r for b, r in enumerate(requests) if responses[b] is not None],
                STAGE_CACHE, time.perf_counter() - t_cache,
            )
            for response in responses:
                if response is not None:
                    response._path = "cache-hit"
        self._count_path("cache-hit", len(requests) - len(misses))
        if not misses:
            return lambda: responses
        fin_misses = self._uncached_async_entry(
            [requests[b] for b in misses]
        )

        def finalize_cached():
            computed = fin_misses()
            for j, b in enumerate(misses):
                responses[b] = computed[j]
                # write-through from BOTH serving paths: kernel rows and
                # oracle-fallback rows land here alike; put() keeps only
                # cacheable 200s
                cache.put(keys[b], computed[j], epoch=epoch,
                          features=self._request_features(requests[b]))
            return responses

        return finalize_cached

    def _is_allowed_batch_uncached(self, requests: list) -> list[Response]:
        return self._is_allowed_batch_uncached_async(requests)()

    def _uncached_async_entry(self, requests: list):
        """Route through the SYNC uncached path when a subclass or test
        double overrode it (the async split must not silently bypass an
        interposed implementation); the real dispatch/finalize split
        otherwise."""
        sync = self._is_allowed_batch_uncached
        if getattr(sync, "__func__", None) is not \
                HybridEvaluator._is_allowed_batch_uncached:
            return lambda: sync(requests)
        return self._is_allowed_batch_uncached_async(requests)

    def _is_allowed_batch_uncached_async(self, requests: list):
        with self._lock:
            kernel = self._kernel
            compiled = self._compiled
            # paired with the kernel under the same lock: provenance must
            # decode against the tree the serving program was lowered from
            decoder = self._explain_decoder
        if self.backend == "oracle" or kernel is None or self._quarantined:
            # candidate-filtered like every other oracle path (skipped
            # rules provably cannot target-match; bit-identical) — the
            # unfiltered walk costs O(total rules) per row, ~21 ms on a
            # 10k-rule tree vs sub-ms filtered.  Host-only: nothing to
            # overlap, so the walk runs at finalize.
            def run_oracle():
                self._count_path("oracle", len(requests))
                return [self._oracle_is_allowed(r) for r in requests]

            return run_oracle

        # mixed-traffic split: a handful of deep/wide rows must not
        # inflate the adaptive padding caps (and device cost) of the whole
        # batch — encode floor-fitting rows at the steady-state compiled
        # shape and only the rest at batch-max caps
        if len(requests) >= 8:
            from ..ops.encode import _CAPS_FLOOR, fits_floor, request_needs

            ext = [
                b for b, r in enumerate(requests)
                if not fits_floor(request_needs(r, compiled.urns))
            ]
            if 0 < len(ext) < len(requests):
                ext_set = set(ext)
                floor_rows = [b for b in range(len(requests))
                              if b not in ext_set]
                # both sub-batches dispatch back-to-back (they ride the
                # same device queue), then finalize in dispatch order
                fins = [
                    (rows, self._eval_encoded_async(
                        kernel, compiled, [requests[b] for b in rows], caps,
                        decoder=decoder,
                    ))
                    for rows, caps in ((floor_rows, dict(_CAPS_FLOOR)),
                                       (ext, None))
                ]

                def finalize_split():
                    out: list[Response] = [None] * len(requests)
                    for rows, fin in fins:
                        for b, resp in zip(rows, fin()):
                            out[b] = resp
                    return out

                return finalize_split
        return self._eval_encoded_async(
            kernel, compiled, requests, None, decoder=decoder
        )

    def _eval_encoded(self, kernel, compiled, requests: list, caps,
                      decoder=None):
        return self._eval_encoded_async(
            kernel, compiled, requests, caps, decoder=decoder
        )()

    def _eval_encoded_async(self, kernel, compiled, requests: list, caps,
                            decoder=None):
        tracer = self.obs.tracer if self.obs is not None else None
        t_stage = time.perf_counter() if tracer is not None else 0.0
        batch = encode_requests(
            requests, compiled, self.engine.resource_adapter, caps=caps,
            relation_tables=self._relation_tables(compiled),
        )
        if tracer is not None:
            from .tracing import STAGE_ENCODE

            now = time.perf_counter()
            tracer.fan_out(requests, STAGE_ENCODE, now - t_stage)
        t_device = time.perf_counter()
        materialize = self._guard_materialize(kernel.evaluate_async(batch))

        def finalize():
            try:
                outputs = materialize()
            except DeviceTimeoutError:
                return self._hang_fallback(requests)
            return self._decode_batch(
                requests, batch, outputs, tracer, t_device, decoder=decoder
            )

        return finalize

    def _decode_batch(self, requests, batch, outputs, tracer, t_device,
                      decoder=None):
        decision, cacheable, status = outputs[:3]
        # explain mode: 4th kernel output packs the deciding node's slot
        # position; decoded per kernel-path row below (srv/explain.py)
        expl = outputs[3] if decoder is not None and len(outputs) > 3 \
            else None
        t_stage = 0.0
        if tracer is not None:
            from .tracing import STAGE_DEVICE

            # dispatch->materialize spans H2D transfer, device dispatch
            # and the D2H fetch — attributed as one ``device`` stage (the
            # host/device boundary; docs/OBSERVABILITY.md).  Pipelined
            # callers overlap it with neighbor batches' host stages; the
            # attribution stays wall time from dispatch to fetch.
            now = time.perf_counter()
            tracer.fan_out(requests, STAGE_DEVICE, now - t_device)
            t_stage = now
        n_oracle = sum(
            1 for b in range(len(requests))
            if not batch.eligible[b] or status[b] != 200
        )
        self._count_path("oracle", n_oracle)
        self._count_path("kernel", len(requests) - n_oracle)
        C = batch.cond_true.shape[0]
        responses: list[Response] = []
        oracle_pending: list[tuple[int, object]] = []
        for b, request in enumerate(requests):
            if batch.eligible[b] and status[b] != 200:
                # abort row: the pre-pass cached the condition error text;
                # when exactly one aborting condition matches the row's
                # status code the message is unambiguous and the oracle
                # re-run is skipped (reference error shape:
                # accessController.ts:259-270 — DENY + code + message)
                msgs = {
                    batch.cond_msg.get((ci, b))
                    for ci in range(C)
                    if batch.cond_abort[ci][b]
                    and batch.cond_code[ci][b] == status[b]
                }
                if len(msgs) == 1 and None not in msgs:
                    cach = None if cacheable[b] < 0 else bool(cacheable[b])
                    resp = Response(
                        decision=Decision.DENY,
                        obligations=[],
                        evaluation_cacheable=cach,
                        operation_status=OperationStatus(
                            code=int(status[b]), message=msgs.pop()
                        ),
                    )
                    if expl is not None:
                        # the richer explain dict names the aborting rule;
                        # no ``_rule_id`` — the oracle's abort response
                        # carries no provenance either (host parity)
                        info = decoder.decode(expl[b])
                        if info is not None:
                            resp._explain = info
                    responses.append(resp)
                    continue
            if not batch.eligible[b] or status[b] != 200:
                # ineligible rows (and ambiguous abort rows) take the
                # oracle path (candidate-filtered on large trees);
                # resolved together below so adapter-backed rows can fan
                # out concurrently
                oracle_pending.append((len(responses), request))
                responses.append(None)
                continue
            cach = None if cacheable[b] < 0 else bool(cacheable[b])
            resp = Response(
                decision=DECISION_NAMES[int(decision[b])],
                obligations=[],
                evaluation_cacheable=cach,
                operation_status=OperationStatus(),
            )
            if expl is not None:
                info = decoder.decode(expl[b])
                if info is not None:
                    resp._explain = info
                    rel = self._relation_provenance(
                        request, info.get("rule") or info.get("policy")
                    )
                    if rel is not None:
                        # relation-decided row: the tuple-path hop list
                        # that let this subject through the deciding
                        # node's relation gate (srv/relations.witness)
                        info["relation"] = rel
                source = decoder.source(expl[b])
                if source is not None:
                    # identical to the oracle's EffectEvaluation.source
                    # stamp (core/engine.py) — the audit log and the
                    # transports read the same attribute either way
                    resp._rule_id = source
            responses.append(resp)
        if tracer is not None:
            from .tracing import STAGE_DECODE

            now = time.perf_counter()
            tracer.fan_out(requests, STAGE_DECODE, now - t_stage)
            t_stage = now
            for resp in responses:
                if resp is not None:
                    resp._path = "kernel"
        if oracle_pending:
            if len(requests) >= 8:
                # sampled: a down adapter under overload degrades whole
                # batches to the oracle — the signal matters, the
                # per-batch record flood does not
                self._slog.warning(
                    "oracle-fallback",
                    "%d/%d batch rows fell back to the scalar oracle",
                    len(oracle_pending), len(requests),
                )
            rows = [req for _, req in oracle_pending]
            adapter = self.engine.resource_adapter
            if adapter is not None and len(rows) > 1:
                # adapter-backed fallback rows block on remote context
                # queries — fan the walks out so the batch stalls for at
                # most ~one endpoint timeout instead of N sequential ones
                # (the adapter's transport is pooled + timeout-bounded,
                # srv/adapters.py)
                from concurrent.futures import ThreadPoolExecutor

                workers = min(
                    len(rows),
                    int(getattr(adapter, "max_concurrency", 8) or 8),
                )
                with ThreadPoolExecutor(max_workers=workers) as pool:
                    results = list(pool.map(self._oracle_is_allowed, rows))
            else:
                results = [self._oracle_is_allowed(r) for r in rows]
            if tracer is not None:
                from .tracing import STAGE_ORACLE

                tracer.fan_out(rows, STAGE_ORACLE,
                               time.perf_counter() - t_stage)
                for response in results:
                    response._path = "oracle"
            for (slot, _), response in zip(oracle_pending, results):
                responses[slot] = response
        return responses
