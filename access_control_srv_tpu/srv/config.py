"""Layered configuration with colon-path access.

Framework analog of the reference's nconf-style service-config
(reference: @restorecommerce/service-config usage, cfg.get('a:b:c') across
src/worker.ts and src/core): a base document overlaid with an environment
document (config_{ENV}.json) and runtime ``set`` mutations (tests mutate
config live, reference: test/microservice.spec.ts:91-93).
"""

from __future__ import annotations

import copy
import json
import os
from typing import Any

DEFAULT_CONFIG: dict = {
    "service": {"name": "access-control-srv-tpu"},
    "authorization": {
        "enabled": False,
        "enforce": False,
        # reference default is 300 000 ms (accessController.ts:753) — far
        # too long to park a serving thread; operators can raise it back
        "hrReqTimeout": 15_000,
    },
    "policies": {
        "type": "local",  # local | database
        "options": {"urns": {}, "combiningAlgorithms": []},
    },
    "evaluator": {
        "backend": "hybrid",  # oracle | kernel | hybrid
        "micro_batch_window_ms": 2,
        "micro_batch_max": 4096,
        # device pipeline depth — the SINGLE source of truth for how many
        # batches may be in flight between collection and decode.  Read by
        # the micro-batcher (srv/batcher.py), the streaming wire pipeline
        # (srv/pipeline.py) and admission control's deadline-feasibility
        # estimate (srv/admission.py: pipeline_batches = depth + 1), so
        # rejection math always tracks the real in-flight count.  2 is
        # the legacy depth (one batch evaluating + one queued): the
        # serving path is then byte-identical to pre-pipeline behavior.
        # Depth N>2 turns on the dispatch/finalize split: H2D+eval of
        # batch i overlaps prep of i+1 and decode/serialize of i-1.
        "pipeline_depth": 2,
        # incremental policy updates (ops/delta.py): capacity-bucketed
        # tables, in-place CRUD patching without XLA recompiles, scoped
        # decision-cache invalidation.  Disable to force the pre-delta
        # full-recompile + global-flush behavior on every mutation.
        "delta_enabled": True,
        # device-hang watchdog (srv/watchdog.py, docs/FAULTS.md).
        # Disabled by default: materialize blocks exactly as before.
        # Enabled: every pipeline/batcher materialize gets a hard
        # deadline; timed-out batches resolve honestly down the
        # kernel-retry -> oracle ladder (or 504 / 503 degraded — never a
        # fabricated PERMIT/DENY), repeated timeouts trip the device
        # circuit breaker which quarantines the kernel path (oracle-only
        # serving) while a background probe re-initializes the kernel
        # through the swap-stable jit registry and restores it.
        "watchdog": {
            "enabled": False,
            "materialize_timeout_s": 5.0,
            "probe_interval_s": 0.5,
            "breaker": {
                "window_s": 30.0,
                "min_volume": 2,
                "failure_ratio": 0.5,
                "open_s": 1.0,
                "half_open_probes": 1,
            },
        },
    },
    "seed_data": None,
    # device-mesh layout (srv/worker.py).  data_devices: batch-axis data
    # parallelism (int, -1/'all').  model_devices: rule-axis sharding
    # (parallel/rule_shard.py — delta patching disabled).  pod_shards:
    # set-axis pod sharding (parallel/pod_shard.py, docs/SHARDING.md —
    # delta patching stays shard-local); mutually exclusive with
    # model_devices.  On a multi-host pod, boot each process through
    # cluster:distributed below so jax.devices() spans the pod.
    "parallel": {
        "data_devices": None,
        "model_devices": None,
        "pod_shards": None,
    },
    "server": {"transports": [{"provider": "grpc", "addr": "0.0.0.0:50061"}]},
    # db-acs mirrors the reference acs-client decision cache living in
    # Redis DB 5 (reference: cfg/config.json:254-259); flush_cache payloads
    # route on these indexes (srv/command.py)
    "redis": {"db-indexes": {"db-subject": 4, "db-acs": 5}},
    # server-side decision cache (srv/decision_cache.py); ttl_s mirrors the
    # reference's 3600 s TTL
    "decision_cache": {
        "enabled": True,
        "ttl_s": 3600,
        "max_entries": 65536,
        "shards": 16,
    },
    "adapter": {},
    # deadline-aware admission control + overload protection
    # (srv/admission.py, docs/ADMISSION.md).  Disabled by default: the
    # serving path is then byte-identical to pre-admission behavior.
    # Enabled, every request passes a bounded two-class queue (interactive
    # isAllowed vs bulk whatIsAllowed) with deadline-feasibility checks
    # against the batch-latency EWMA; sheds answer INDETERMINATE with the
    # overload operation_status (429 shed / 504 deadline / 503 shutdown),
    # never a fabricated PERMIT/DENY.
    "admission": {
        "enabled": False,
        "max_queue_interactive": 8192,
        "max_queue_bulk": 1024,
        # admit only when remaining budget > estimate * headroom
        "deadline_headroom": 1.2,
        "ewma_alpha": 0.2,
        "ewma_default_ms": 5.0,
        # adaptive max-batch: shrink the collection cap when batch
        # latency overshoots deadline_bound_ms, regrow when comfortable
        "adaptive_max_batch": True,
        "deadline_bound_ms": 50.0,
        "min_batch": 64,
        # graceful shutdown: how long Worker.stop flushes already-admitted
        # batches before failing the rest with the shutdown status
        "drain_deadline_s": 5.0,
        # two-class fairness: a bulk round runs at least every N
        # interactive rounds under saturation
        "bulk_interval": 4,
        # dependency circuit breakers (adapter context queries + identity
        # token resolution): closed/open/half-open with jittered probe
        "breakers": {
            "enabled": True,
            "window_s": 10.0,
            "min_volume": 8,
            "failure_ratio": 0.5,
            "open_s": 2.0,
            "half_open_probes": 2,
        },
        # per-tenant quotas (srv/tenancy.py, docs/MULTITENANT.md): an
        # inflight cap per tenant plus weighted fair sharing of the
        # interactive queue once it is contended (depth >= max_queue *
        # contention_ratio).  Only engages for requests carrying a tenant
        # id; untagged traffic never touches this block.
        "tenant": {
            "enabled": True,
            "max_inflight_per_tenant": 256,
            "default_weight": 1.0,
            # tenant id -> weight overrides for weighted fair sharing
            "weights": {},
            "contention_ratio": 0.5,
        },
    },
    # multi-tenant serving (srv/tenancy.py, docs/MULTITENANT.md).
    # Disabled by default: tenant-tagged requests are served from the
    # default domain exactly as before and no registry object exists.
    # Enabled: the x-acs-tenant metadata key routes each request to its
    # tenant's policy domain; tenants bucket onto fixed capacity classes
    # (SIZE_CLASSES) so same-class tenants share one compiled program per
    # kernel variant, and tenant CRUD journals through the broker topics
    # (boot-by-replay onboarding).
    "tenancy": {
        "enabled": False,
        # evaluator backend for tenant domains (defaults to
        # evaluator:backend)
        "backend": None,
        "max_tenants": 100000,
    },
    # observability (srv/tracing.py, docs/OBSERVABILITY.md).  Disabled by
    # default: with enabled false (or the block absent) NO tracer/audit/
    # exporter object is built and the serving path is byte-identical to
    # pre-observability behavior (tests/test_tracing.py differential).
    # Enabled: stage-span tracing fills Telemetry.stages (Prometheus
    # acs_stage_duration_seconds), sample_rate retains that fraction of
    # requests as full span trees (x-acs-trace-id metadata forces
    # sampling), audit_log.path turns on the sampled JSONL decision-audit
    # sink, metrics_http serves GET /metrics in Prometheus text format.
    "observability": {
        "enabled": False,
        "tracing": {"enabled": True, "sample_rate": 0.01,
                    "max_traces": 256},
        "metrics_http": {"enabled": False, "host": "127.0.0.1",
                         "port": 9464},
        "audit_log": {"path": None, "sample_rate": 0.01},
    },
    # cluster tier (srv/router.py, parallel/cluster.py, docs/CLUSTER.md).
    # Disabled by default: a single worker serves exactly as before.
    # Enabled: N replica processes (each a full Worker against the shared
    # broker, converging through the PolicyReplicator delta path) serve
    # behind a ClusterRouter that load-balances unary calls and whole
    # IsAllowedStream streams, retries shed/failed work on other replicas
    # within the deadline budget, and tracks per-replica policy epochs.
    # broker-backed policy replication (srv/store.PolicyReplicator).
    # catchup_timeout_s bounds the boot-time gate: a (re)starting replica
    # replays the journaled CRUD log and refuses to open its serving port
    # until the tail observed at boot is reflected in its tree, so the
    # router never routes to a half-replayed tree.
    "replication": {
        "enabled": True,
        "catchup_timeout_s": 60.0,
    },
    "cluster": {
        "enabled": False,
        "replicas": 2,
        # router placement + behavior
        "router": {
            "addr": "127.0.0.1:0",
            # health/epoch poll cadence against each replica
            "health_interval_s": 1.0,
            # per-replica circuit breaker (reuses admission breakers'
            # closed/open/half-open machine, srv/admission.py)
            "breaker": {
                "window_s": 5.0,
                "min_volume": 4,
                "failure_ratio": 0.5,
                "open_s": 1.0,
                "half_open_probes": 1,
            },
            # retry a shed/failed unary call on another replica only when
            # this much of the deadline budget remains (fraction)
            "retry_budget_fraction": 0.2,
            "max_retries": 1,
        },
        # on-chip pods: jax.distributed.initialize per replica
        # (parallel/cluster.py maybe_initialize_distributed); off for the
        # CPU N-process tier
        "distributed": {
            "enabled": False,
            "coordinator": "127.0.0.1:8476",
            "num_processes": 1,
        },
    },
    # deterministic fault injection (srv/faults.py, docs/FAULTS.md).
    # Disabled by default: every fire() site is one boolean test and the
    # serving path is byte-identical (tests/test_admission.py
    # differential).  Enabled: `points` arm named sites with
    # error/delay/hang/torn actions on seeded deterministic schedules;
    # the `faults` command (srv/command.py) re-arms/clears at runtime.
    "faults": {
        "enabled": False,
        "seed": 0,
        "points": [],
    },
    # explain mode (srv/explain.py, docs/EXPLAIN.md).  Disabled by
    # default: the kernels trace the exact pre-explain computation and
    # the lowered device programs are byte-identical
    # (tpu_compat_audit.py explain-shadow-program-identity).  Enabled:
    # every kernel row carries one extra int32 naming the deciding node,
    # decoded host-side onto the response (``_rule_id`` matching the
    # oracle's EffectEvaluation.source bit-for-bit, plus the richer
    # ``_explain`` dict) and into the decision-audit JSONL.
    "explain": {"enabled": False},
    # shadow evaluation (srv/shadow.py, docs/EXPLAIN.md): load a
    # candidate policy tree beside production (same compiled programs —
    # zero new XLA compiles, asserted) and replay live traffic against
    # it off the response path, reporting decision diffs via the
    # ``shadow_status`` command and acs_shadow_diffs_total.  A shadow
    # decision can never alter, delay, or be cached as a production one.
    "shadow": {
        "enabled": False,
        # YAML policy files forming the candidate tree
        "candidate_paths": [],
        # scope mirroring to one tenant's traffic (None = all)
        "tenant": None,
        # retained diff records with both-sides provenance
        "sample_diffs": 32,
        # bounded mirror queue (batches); overflow drops + counts
        "queue_batches": 64,
    },
    # permission-lattice audit sweeps (srv/audit_sweep.py, docs/AUDIT.md).
    # Disabled by default: the worker builds no manager, no threads, no
    # command surface — the serving path is byte-identical.  Enabled:
    # bulk "who-can-do-what" sweeps ride the batcher's BULK class
    # (admission-paced, never the interactive queue) and stream masked
    # JSONL + bitmap snapshots under ``out_dir``.
    "audit": {
        "enabled": False,
        # snapshot artifacts land here (JSONL + .bits.npy sidecars)
        "out_dir": "/tmp/acs-audit",
        # cells per bulk submission round; bounds sweep memory and the
        # bulk queue footprint (must stay under admission:max_queue_bulk)
        "chunk_size": 256,
        # per-cell future wait before the job fails honestly
        "cell_timeout_s": 60.0,
        # shed cells (429/503/504) retry this many times, then land in
        # the snapshot as INDETERMINATE + shed code
        "max_retries": 3,
        # optional extra pacing between chunks on top of bulk_interval
        "chunk_pause_ms": 0.0,
        # default lattice axes (ops/lattice.LatticeSpec.from_config
        # grammar: ints for synthetic stress-shaped axes, or explicit
        # subject/resource/action lists)
        "lattice": {"subjects": 16, "resources": 16, "actions": ["read"]},
    },
    # ReBAC relation tuples (srv/relations.py, docs/REBAC.md).  Disabled
    # by default: no store is built, and relation-bearing policy targets
    # fail closed on every path (oracle and kernel agree).  Enabled: a
    # Zanzibar-style tuple store feeds the stage-B bit-reader's relation
    # planes; tuple CRUD rides the journaled topic below (broker bus =
    # shared durable tuple store, replayed at boot, origin-skip live).
    "relations": {
        "enabled": False,
        "topic": "io.restorecommerce.relation-tuples.resource",
    },
    "logger": {"maskFields": ["password", "token"]},
}


def _deep_merge(base: dict, overlay: dict) -> dict:
    out = copy.deepcopy(base)
    for key, value in overlay.items():
        if isinstance(value, dict) and isinstance(out.get(key), dict):
            out[key] = _deep_merge(out[key], value)
        else:
            out[key] = copy.deepcopy(value)
    return out


class Config:
    def __init__(self, data: dict | None = None, env: str | None = None):
        self._data = _deep_merge(DEFAULT_CONFIG, data or {})
        self.env = env or os.environ.get("ACS_ENV", "")

    @classmethod
    def load(cls, directory: str, env: str | None = None) -> "Config":
        env = env or os.environ.get("ACS_ENV", "")
        data: dict = {}
        base = os.path.join(directory, "config.json")
        if os.path.exists(base):
            with open(base) as fh:
                data = json.load(fh)
        if env:
            overlay_path = os.path.join(directory, f"config_{env}.json")
            if os.path.exists(overlay_path):
                with open(overlay_path) as fh:
                    data = _deep_merge(data, json.load(fh))
        return cls(data, env=env)

    def get(self, path: str, default: Any = None) -> Any:
        node: Any = self._data
        for part in path.split(":"):
            if not isinstance(node, dict) or part not in node:
                return default
            node = node[part]
        return node

    def set(self, path: str, value: Any) -> None:
        parts = path.split(":")
        node = self._data
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = value

    def as_dict(self) -> dict:
        return copy.deepcopy(self._data)
