"""Generated restorecommerce-wire stubs (see proto/build_rc.py);
the proto sources under proto/rc/ are reconstructions of the
public @restorecommerce/protos package."""
