"""The PDP service facade: isAllowed / whatIsAllowed endpoints.

Framework analog of the reference's AccessControlService
(reference: src/accessControlService.ts): deny-on-exception envelopes,
wire-context unmarshalling (the gRPC layer carries context values as
protobuf-Any-style ``{"value": <json bytes>}``), and policy loading in
``local`` (YAML files) vs ``database`` (store) mode.
"""

from __future__ import annotations

import json
import time
from typing import Any, Optional

from ..core.engine import AccessController
from ..core.loader import load_policy_sets_from_file
from ..models.model import (
    Attribute,
    Decision,
    OperationStatus,
    Request,
    Response,
    ReverseQuery,
    Target,
    coerce_target,
)


def unmarshall_any(value: Any) -> Any:
    """protobuf-Any-ish -> JSON (reference: accessControlService.ts:103-125)."""
    if isinstance(value, dict) and "value" in value and set(value) <= {
        "type_url",
        "value",
    }:
        raw = value["value"]
        if isinstance(raw, (bytes, bytearray)):
            raw = raw.decode()
        return json.loads(raw)
    return value


def unmarshall_context(context: Optional[dict]) -> Optional[dict]:
    if context is None:
        return None
    out = dict(context)
    if "subject" in out and out["subject"] is not None:
        out["subject"] = unmarshall_any(out["subject"])
    if "resources" in out and out["resources"] is not None:
        out["resources"] = [unmarshall_any(r) for r in out["resources"]]
    if "security" in out and out["security"] is not None:
        out["security"] = unmarshall_any(out["security"])
    return out


def coerce_request(request: Any) -> Request:
    if isinstance(request, Request):
        if isinstance(request.context, dict):
            request.context = unmarshall_context(request.context)
        return request
    target = coerce_target(request.get("target"))
    context = unmarshall_context(request.get("context"))
    return Request(target=target, context=context)


class AccessControlService:
    def __init__(self, cfg, engine: AccessController, evaluator=None,
                 store=None, logger=None, telemetry=None):
        self.cfg = cfg
        self.engine = engine
        self.evaluator = evaluator
        self.store = store
        self.logger = logger
        self.telemetry = telemetry
        # when set (Worker wires it), concurrent single isAllowed calls are
        # coalesced into kernel batches instead of hitting the oracle 1-by-1
        self.batcher = None

    def _observe(self, histogram_name, t0, decisions=()):
        """One helper for success AND deny-on-exception paths so served
        responses always match the counters."""
        telemetry = self.telemetry
        if telemetry is None:
            return
        getattr(telemetry, histogram_name).observe(time.perf_counter() - t0)
        for decision in decisions:
            telemetry.decisions.inc(decision)

    # ------------------------------------------------------------- endpoints

    def is_allowed(self, request: Any) -> Response:
        """Deny-by-default on any evaluation exception
        (reference: accessControlService.ts:62-81)."""
        t0 = time.perf_counter()
        try:
            req = coerce_request(request)
            if self.batcher is not None:
                # resolve token subject + HR scopes in THIS thread: the
                # rendezvous can block for up to hrReqTimeout, which must
                # never happen on the batcher's collector thread
                self.engine.prepare_context(req)
                response = self.batcher.is_allowed(req)
            elif self.evaluator is not None:
                response = self.evaluator.is_allowed(req)
            else:
                response = self.engine.is_allowed(req)
            self._observe("is_allowed_latency", t0, (response.decision,))
            return response
        except Exception as err:
            if self.logger:
                self.logger.exception("isAllowed failed")
            self._observe("is_allowed_latency", t0, (Decision.DENY,))
            code = getattr(err, "code", 500)
            return Response(
                decision=Decision.DENY,
                obligations=[],
                evaluation_cacheable=False,
                operation_status=OperationStatus(
                    code=code if isinstance(code, int) else 500,
                    message=str(err) or "Unknown Error!",
                ),
            )

    def is_allowed_batch(
        self, requests: list, observe: bool = True
    ) -> list[Response]:
        # observe=False lets a caller that does its own per-RPC telemetry
        # (the raw-bytes gRPC fast path serving fallback rows through here)
        # suppress this layer's histogram/counter updates so no request is
        # double-counted
        t0 = time.perf_counter()
        _observe = self._observe if observe else (lambda *a, **k: None)
        try:
            reqs = [coerce_request(r) for r in requests]
        except Exception as err:
            _observe("batch_latency", t0,
                     [Decision.DENY] * len(requests))
            code = getattr(err, "code", 500)
            status = OperationStatus(
                code=code if isinstance(code, int) else 500, message=str(err)
            )
            return [
                Response(decision=Decision.DENY, operation_status=status)
                for _ in requests
            ]
        try:
            if self.evaluator is not None:
                responses = self.evaluator.is_allowed_batch(reqs)
            else:
                responses = [self.engine.is_allowed(r) for r in reqs]
            _observe("batch_latency", t0,
                     [r.decision for r in responses])
            return responses
        except Exception as err:
            # same deny-on-exception contract as the single-request path
            if self.logger:
                self.logger.exception("isAllowedBatch failed")
            _observe("batch_latency", t0, [Decision.DENY] * len(reqs))
            code = getattr(err, "code", 500)
            status = OperationStatus(
                code=code if isinstance(code, int) else 500,
                message=str(err) or "Unknown Error!",
            )
            return [
                Response(decision=Decision.DENY, operation_status=status)
                for _ in reqs
            ]

    def what_is_allowed_batch(self, requests: list) -> list[ReverseQuery]:
        """Batched reverse query through the device-assisted path
        (framework extension; single-request semantics per row with the
        same deny-on-exception error shape)."""
        t0 = time.perf_counter()
        try:
            reqs = [coerce_request(r) for r in requests]
            if self.evaluator is not None:
                out = self.evaluator.what_is_allowed_batch(reqs)
            else:
                out = [self.engine.what_is_allowed(r) for r in reqs]
            self._observe("what_is_allowed_latency", t0)
            return out
        except Exception as err:
            if self.logger:
                self.logger.exception("whatIsAllowedBatch failed")
            self._observe("what_is_allowed_latency", t0)
            code = getattr(err, "code", 500)
            status = OperationStatus(
                code=code if isinstance(code, int) else 500,
                message=str(err) or "Unknown Error!",
            )
            return [
                ReverseQuery(policy_sets=[], obligations=[],
                             operation_status=status)
                for _ in requests
            ]

    def what_is_allowed(self, request: Any) -> ReverseQuery:
        """(reference: accessControlService.ts:83-101)"""
        t0 = time.perf_counter()
        try:
            req = coerce_request(request)
            rq = self.engine.what_is_allowed(req)
            self._observe("what_is_allowed_latency", t0)
            return rq
        except Exception as err:
            if self.logger:
                self.logger.exception("whatIsAllowed failed")
            self._observe("what_is_allowed_latency", t0)
            code = getattr(err, "code", 500)
            return ReverseQuery(
                policy_sets=[],
                obligations=[],
                operation_status=OperationStatus(
                    code=code if isinstance(code, int) else 500,
                    message=str(err) or "Unknown Error!",
                ),
            )

    # --------------------------------------------------------------- loading

    def load_policies(self) -> None:
        """local-YAML vs database policy source
        (reference: accessControlService.ts:36-54)."""
        policies_cfg = self.cfg.get("policies", {}) or {}
        kind = policies_cfg.get("type", "local")
        if kind == "local":
            for path in policies_cfg.get("paths", []) or []:
                for policy_set in load_policy_sets_from_file(path):
                    self.engine.update_policy_set(policy_set)
            if self.evaluator is not None:
                self.evaluator.refresh()
        elif kind == "database":
            if self.store is None:
                raise ValueError("database policy source requires a store")
            self.store.load()
        else:
            raise ValueError(f"unknown policies.type {kind!r}")
