"""The PDP service facade: isAllowed / whatIsAllowed endpoints.

Framework analog of the reference's AccessControlService
(reference: src/accessControlService.ts): deny-on-exception envelopes,
wire-context unmarshalling (the gRPC layer carries context values as
protobuf-Any-style ``{"value": <json bytes>}``), and policy loading in
``local`` (YAML files) vs ``database`` (store) mode.
"""

from __future__ import annotations

import json
import time
from typing import Any, Optional

from ..core.engine import AccessController
from ..core.loader import load_policy_sets_from_file
from ..models.model import (
    Attribute,
    Decision,
    OperationStatus,
    Request,
    Response,
    ReverseQuery,
    Target,
    coerce_target,
)


def unmarshall_any(value: Any) -> Any:
    """protobuf-Any-ish -> JSON (reference: accessControlService.ts:103-125)."""
    if isinstance(value, dict) and "value" in value and set(value) <= {
        "type_url",
        "value",
    }:
        raw = value["value"]
        if isinstance(raw, (bytes, bytearray)):
            raw = raw.decode()
        return json.loads(raw)
    return value


def unmarshall_context(context: Optional[dict]) -> Optional[dict]:
    if context is None:
        return None
    out = dict(context)
    if "subject" in out and out["subject"] is not None:
        out["subject"] = unmarshall_any(out["subject"])
    if "resources" in out and out["resources"] is not None:
        out["resources"] = [unmarshall_any(r) for r in out["resources"]]
    if "security" in out and out["security"] is not None:
        out["security"] = unmarshall_any(out["security"])
    return out


def coerce_request(request: Any) -> Request:
    if isinstance(request, Request):
        if isinstance(request.context, dict):
            request.context = unmarshall_context(request.context)
        return request
    target = coerce_target(request.get("target"))
    context = unmarshall_context(request.get("context"))
    return Request(target=target, context=context)


class AccessControlService:
    def __init__(self, cfg, engine: AccessController, evaluator=None,
                 store=None, logger=None, telemetry=None,
                 observability=None):
        self.cfg = cfg
        self.engine = engine
        self.evaluator = evaluator
        self.store = store
        self.logger = logger
        self.telemetry = telemetry
        # observability hub (srv/tracing.Observability): span fallback
        # creation for non-transport callers + the sampled decision-audit
        # log.  None keeps the facade byte-identical to pre-observability.
        self.obs = observability
        # when set (Worker wires it), concurrent single isAllowed calls are
        # coalesced into kernel batches instead of hitting the oracle 1-by-1
        self.batcher = None
        # shadow evaluator (srv/shadow.py): when set, served decisions
        # mirror onto the candidate tree AFTER response assembly.  None
        # (the default) keeps both endpoints byte-identical — the taps
        # are one attribute test each.
        self.shadow = None

    def _shadow_tap(self, requests: list, responses: list) -> None:
        """Mirror served rows to the shadow.  Post-decision, non-blocking
        (bounded drop-queue inside), and exception-proofed twice over —
        nothing here can alter or delay what was already decided."""
        shadow = self.shadow
        if shadow is None:
            return
        try:
            shadow.submit(requests, responses)
        except Exception:  # noqa: BLE001 — shadow must never fail serving
            if self.logger:
                self.logger.exception("shadow mirror failed")

    def _observed_request(self, req):
        """(span, own_span): the transport-attached span if any, else a
        freshly sampled one owned (and finished) by this facade — so
        non-gRPC callers trace too."""
        obs = self.obs
        if obs is None or obs.tracer is None:
            return None, False
        span = getattr(req, "_span", None)
        if span is not None:
            return span, False
        if getattr(req, "_sampling_done", False):
            # the transport already rolled the sampling dice for this
            # request — re-rolling here would skew the effective rate
            return None, False
        span = obs.tracer.start_span()
        if span is not None:
            req._span = span
            return span, True
        return None, False

    def _finish_observed(self, req, response, span, own_span) -> None:
        """Audit-log the decision (sampled) and finish a facade-owned
        span; transport-owned spans finish at the transport after the
        serialize stage."""
        obs = self.obs
        if obs is None:
            return
        if obs.audit is not None:
            try:
                obs.audit.maybe_record(
                    req, response,
                    span.trace_id if span is not None else None,
                )
            except Exception:  # noqa: BLE001 — audit must never fail serving
                if self.logger:
                    self.logger.exception("decision audit record failed")
        if own_span and obs.tracer is not None:
            obs.tracer.finish(span, decision=response.decision,
                              code=response.operation_status.code)

    def _observe(self, histogram_name, t0, decisions=()):
        """One helper for success AND deny-on-exception paths so served
        responses always match the counters."""
        telemetry = self.telemetry
        if telemetry is None:
            return
        getattr(telemetry, histogram_name).observe(time.perf_counter() - t0)
        for decision in decisions:
            telemetry.decisions.inc(decision)

    # ------------------------------------------------------------- endpoints

    def is_allowed(self, request: Any,
                   deadline: Optional[float] = None) -> Response:
        """Deny-by-default on any evaluation exception
        (reference: accessControlService.ts:62-81).  ``deadline`` is an
        absolute monotonic instant propagated from the transport (gRPC
        deadline / x-acs-timeout-ms metadata, srv/admission.py): it rides
        the request as ``_deadline`` for deadline-aware adapter retries
        and, with admission enabled, gates the batcher submit."""
        t0 = time.perf_counter()
        req = request
        span = None
        own_span = False
        try:
            req = coerce_request(request)
            span, own_span = self._observed_request(req)
            if deadline is not None:
                req._deadline = deadline
            if self.batcher is not None:
                # resolve token subject + HR scopes in THIS thread: the
                # rendezvous can block for up to hrReqTimeout, which must
                # never happen on the batcher's collector thread
                self.engine.prepare_context(req)
                timeout = 30.0
                if deadline is not None:
                    timeout = min(
                        timeout, max(0.1, deadline - time.monotonic()) + 5.0
                    )
                response = self.batcher.submit(
                    req, deadline=deadline
                ).result(timeout=timeout)
            elif self.evaluator is not None:
                response = self.evaluator.is_allowed(req)
            else:
                response = self.engine.is_allowed(req)
            self._observe("is_allowed_latency", t0, (response.decision,))
            self._finish_observed(req, response, span, own_span)
            self._shadow_tap([req], [response])
            return response
        except Exception as err:
            if self.logger:
                self.logger.exception("isAllowed failed")
            self._observe("is_allowed_latency", t0, (Decision.DENY,))
            code = getattr(err, "code", 500)
            response = Response(
                decision=Decision.DENY,
                obligations=[],
                evaluation_cacheable=False,
                operation_status=OperationStatus(
                    code=code if isinstance(code, int) else 500,
                    message=str(err) or "Unknown Error!",
                ),
            )
            self._finish_observed(req, response, span, own_span)
            return response

    def is_allowed_batch(
        self, requests: list, observe: bool = True,
        deadline: Optional[float] = None,
    ) -> list[Response]:
        # observe=False lets a caller that does its own per-RPC telemetry
        # (the raw-bytes gRPC fast path serving fallback rows through here)
        # suppress this layer's histogram/counter updates so no request is
        # double-counted
        t0 = time.perf_counter()
        _observe = self._observe if observe else (lambda *a, **k: None)
        try:
            reqs = [coerce_request(r) for r in requests]
        except Exception as err:
            _observe("batch_latency", t0,
                     [Decision.DENY] * len(requests))
            code = getattr(err, "code", 500)
            status = OperationStatus(
                code=code if isinstance(code, int) else 500, message=str(err)
            )
            return [
                Response(decision=Decision.DENY, operation_status=status)
                for _ in requests
            ]
        if deadline is not None:
            for req in reqs:
                req._deadline = deadline
        try:
            if self.evaluator is not None:
                responses = self.evaluator.is_allowed_batch(reqs)
            else:
                responses = [self.engine.is_allowed(r) for r in reqs]
            _observe("batch_latency", t0,
                     [r.decision for r in responses])
            if self.obs is not None and self.obs.audit is not None:
                for row_req, row_resp in zip(reqs, responses):
                    row_span = getattr(row_req, "_span", None)
                    try:
                        self.obs.audit.maybe_record(
                            row_req, row_resp,
                            row_span.trace_id if row_span else None,
                        )
                    except Exception:  # noqa: BLE001 — never fail serving
                        pass
            self._shadow_tap(reqs, responses)
            return responses
        except Exception as err:
            # same deny-on-exception contract as the single-request path
            if self.logger:
                self.logger.exception("isAllowedBatch failed")
            _observe("batch_latency", t0, [Decision.DENY] * len(reqs))
            code = getattr(err, "code", 500)
            status = OperationStatus(
                code=code if isinstance(code, int) else 500,
                message=str(err) or "Unknown Error!",
            )
            return [
                Response(decision=Decision.DENY, operation_status=status)
                for _ in reqs
            ]

    def _admission(self):
        """The admission controller when one is wired AND enabled (via
        the batcher — srv/worker.py), else None."""
        batcher = self.batcher
        admission = getattr(batcher, "admission", None)
        if admission is not None and admission.enabled:
            return admission
        return None

    def what_is_allowed_batch(
        self, requests: list, deadline: Optional[float] = None
    ) -> list[ReverseQuery]:
        """Batched reverse query through the device-assisted path
        (framework extension; single-request semantics per row with the
        same deny-on-exception error shape).  Under admission control the
        whole batch is one BULK-class admission unit: saturation sheds it
        with the overload status instead of queueing unboundedly."""
        t0 = time.perf_counter()
        admission = self._admission()
        released = True
        try:
            reqs = [coerce_request(r) for r in requests]
            if deadline is not None:
                for req in reqs:
                    req._deadline = deadline
            if admission is not None:
                from .admission import BULK

                shed = admission.admit(BULK, deadline)
                if shed is not None:
                    self._observe("what_is_allowed_latency", t0)
                    return [
                        ReverseQuery(policy_sets=[], obligations=[],
                                     operation_status=shed.operation_status)
                        for _ in reqs
                    ]
                released = False
            if self.evaluator is not None:
                out = self.evaluator.what_is_allowed_batch(reqs)
            else:
                out = [self.engine.what_is_allowed(r) for r in reqs]
            self._observe("what_is_allowed_latency", t0)
            return out
        except Exception as err:
            if self.logger:
                self.logger.exception("whatIsAllowedBatch failed")
            self._observe("what_is_allowed_latency", t0)
            code = getattr(err, "code", 500)
            status = OperationStatus(
                code=code if isinstance(code, int) else 500,
                message=str(err) or "Unknown Error!",
            )
            return [
                ReverseQuery(policy_sets=[], obligations=[],
                             operation_status=status)
                for _ in requests
            ]
        finally:
            if admission is not None and not released:
                from .admission import BULK

                admission.release(BULK, 1)

    def what_is_allowed(self, request: Any,
                        deadline: Optional[float] = None) -> ReverseQuery:
        """(reference: accessControlService.ts:83-101)

        With admission enabled, reverse queries are the BULK traffic
        class: they ride the batcher's bounded bulk queue (shed with the
        overload status when saturated) so interactive isAllowed traffic
        keeps its latency bound under a reverse-query flood — and vice
        versa, the fairness interval keeps bulk progressing."""
        t0 = time.perf_counter()
        try:
            req = coerce_request(request)
            if deadline is not None:
                req._deadline = deadline
            if self._admission() is not None:
                self.engine.prepare_context(req)
                timeout = 30.0
                if deadline is not None:
                    timeout = min(
                        timeout, max(0.1, deadline - time.monotonic()) + 5.0
                    )
                rq = self.batcher.submit_reverse(
                    req, deadline=deadline
                ).result(timeout=timeout)
            else:
                rq = self.engine.what_is_allowed(req)
            self._observe("what_is_allowed_latency", t0)
            return rq
        except Exception as err:
            if self.logger:
                self.logger.exception("whatIsAllowed failed")
            self._observe("what_is_allowed_latency", t0)
            code = getattr(err, "code", 500)
            return ReverseQuery(
                policy_sets=[],
                obligations=[],
                operation_status=OperationStatus(
                    code=code if isinstance(code, int) else 500,
                    message=str(err) or "Unknown Error!",
                ),
            )

    # --------------------------------------------------------------- loading

    def load_policies(self) -> None:
        """local-YAML vs database policy source
        (reference: accessControlService.ts:36-54)."""
        policies_cfg = self.cfg.get("policies", {}) or {}
        kind = policies_cfg.get("type", "local")
        if kind == "local":
            for path in policies_cfg.get("paths", []) or []:
                for policy_set in load_policy_sets_from_file(path):
                    self.engine.update_policy_set(policy_set)
            if self.evaluator is not None:
                self.evaluator.refresh()
        elif kind == "database":
            if self.store is None:
                raise ValueError("database policy source requires a store")
            self.store.load()
        else:
            raise ValueError(f"unknown policies.type {kind!r}")
