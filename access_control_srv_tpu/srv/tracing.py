"""Stage-span tracing, decision audit log and the observability hub.

The wire-to-kernel gap (ROADMAP: 11.6M dec/s on device vs 60k
wire-to-wire) is a HOST problem, and closing it needs attribution: where
do the microseconds between transport receive and response bytes go?
This module provides the serving shell's answer — a low-overhead,
allocation-light span context created at transport receive and threaded
through the whole pipeline (transport parse -> admission -> micro-batch
queue wait -> prepare (token resolve / context-query prefetch) ->
encode -> device (H2D + eval + D2H) -> decode -> response serialize),
recording per-stage monotonic durations into per-stage histograms
(``Telemetry.stages`` -> Prometheus ``acs_stage_duration_seconds``)
plus an optional per-request trace retained in a bounded ring buffer.

Batch-level stages (prepare/encode/device/decode run once per collected
batch) fan their duration back to every member request's span, so a
sampled request always carries a complete span tree; stage durations
therefore sum to <= the request's wall clock (stages are sequential
within the batch, and every batch stage lies inside each member's
lifetime).

Trace ids propagate from the gRPC metadata key ``x-acs-trace-id`` (an
explicit client id forces sampling — the debugging contract) and are
echoed on the response's trailing metadata.

EXTree (PAPERS.md) argues ABAC decisions must be auditable after the
fact: ``DecisionAuditLog`` emits a sampled JSONL record per decision
(subject/resource/action/decision/serving path/deciding rule id) through
the same masking machinery as the structured logger — secret-named
fields AND secret-named target attributes (token and friends) never
reach the sink.  Oracle rows carry the host walk's provenance; with
explain mode on (``explain:enabled``, srv/explain.py) kernel rows
carry the device-recovered deciding rule id through the identical
``_rule_id`` attribute and the identical masking path.

Everything here is host-only BY CONSTRUCTION: this module never imports
jax (statically asserted by tpu_compat_audit.py row
``tracing-zero-device-ops``), and a traced batch lowers to the
byte-identical device program as an untraced one.  With the
``observability`` config absent the hub is never built and the serving
path is byte-identical to pre-observability behavior
(tests/test_tracing.py differential).
"""

from __future__ import annotations

# acs-lint: host-only — tracing must never import jax; a traced batch
# lowers to the byte-identical device program (tpu_compat_audit row
# tracing-zero-device-ops)

import logging
import os
import random
import threading
import time
from collections import deque
from typing import Optional

from .telemetry import (
    JsonLinesFormatter,
    MaskingFilter,
    PrometheusExporter,
    _LOWERED_MASK_FIELDS,
)

# gRPC metadata key carrying (in) / echoing (out) the request trace id
TRACE_ID_METADATA_KEY = "x-acs-trace-id"

# the stage taxonomy (docs/OBSERVABILITY.md).  Stage names are the
# ``stage`` label of acs_stage_duration_seconds and the keys of
# Telemetry.snapshot()["stages"]; keep them stable.
STAGE_TRANSPORT_PARSE = "transport.parse"    # wire bytes -> request model
STAGE_ADMISSION = "admission"                # admission gate at submit
STAGE_QUEUE_WAIT = "queue.wait"              # submit -> batch collection
STAGE_PREPARE = "prepare"                    # token resolve / HR / prefetch
STAGE_CACHE = "cache.lookup"                 # decision-cache consult (hits)
STAGE_ENCODE = "encode"                      # request -> kernel arrays
STAGE_WIRE_ENCODE = "wire.encode"            # native C++ wire encode
STAGE_DEVICE = "device"                      # H2D + device eval + D2H
STAGE_DECODE = "decode"                      # kernel outputs -> responses
STAGE_ORACLE = "oracle"                      # scalar fallback walk
STAGE_SERIALIZE = "serialize"                # responses -> wire bytes

STAGES = (
    STAGE_TRANSPORT_PARSE, STAGE_ADMISSION, STAGE_QUEUE_WAIT, STAGE_PREPARE,
    STAGE_CACHE, STAGE_ENCODE, STAGE_WIRE_ENCODE, STAGE_DEVICE, STAGE_DECODE,
    STAGE_ORACLE, STAGE_SERIALIZE,
)


def trace_id_from_metadata(grpc_context) -> Optional[str]:
    """The client-provided ``x-acs-trace-id`` metadata value, if any."""
    try:
        for key, value in grpc_context.invocation_metadata() or ():
            if str(key).lower() == TRACE_ID_METADATA_KEY:
                return str(value)
    except Exception:  # noqa: BLE001 — non-grpc test doubles
        return None
    return None


def echo_trace_id(grpc_context, trace_id: str) -> None:
    """Echo the trace id on the response's trailing metadata."""
    try:
        grpc_context.set_trailing_metadata(
            ((TRACE_ID_METADATA_KEY, trace_id),)
        )
    except Exception:  # noqa: BLE001 — non-grpc test doubles
        pass


class Span:
    """One request's span tree: a trace id, a start instant and a flat
    list of (stage, duration) pairs.  Allocation-light (slots, one list);
    created only for sampled requests — unsampled requests still feed the
    stage histograms but never allocate a span."""

    __slots__ = ("trace_id", "t0", "stages", "_t_enqueue")

    def __init__(self, trace_id: str):
        self.trace_id = trace_id
        self.t0 = time.perf_counter()
        self.stages: list[tuple[str, float]] = []
        self._t_enqueue: Optional[float] = None

    def add(self, stage: str, duration_s: float) -> None:
        self.stages.append((stage, duration_s))

    def mark_enqueue(self) -> None:
        self._t_enqueue = time.perf_counter()

    def wall_s(self) -> float:
        return time.perf_counter() - self.t0

    def as_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "wall_ms": round(self.wall_s() * 1e3, 4),
            "stages": [
                {"stage": stage, "ms": round(duration * 1e3, 4)}
                for stage, duration in self.stages
            ],
        }


class StageTracer:
    """Per-worker stage tracing: histograms for every request (cheap),
    span retention for the sampled fraction.  All methods are safe to
    call from any serving thread."""

    def __init__(self, telemetry=None, sample_rate: float = 0.0,
                 max_traces: int = 256, rng: Optional[random.Random] = None):
        self.telemetry = telemetry
        self.sample_rate = float(sample_rate)
        self._rng = rng or random.Random()
        self._traces: deque = deque(maxlen=int(max_traces))  # guarded-by: _lock
        self._lock = threading.Lock()
        # local histogram store when no Telemetry is wired (unit tests)
        self._own_stages: dict = {}

    # ----------------------------------------------------------- histograms

    def observe(self, stage: str, duration_s: float) -> None:
        if self.telemetry is not None:
            self.telemetry.stage_histogram(stage).observe(duration_s)
        else:
            from .telemetry import Histogram

            hist = self._own_stages.get(stage)
            if hist is None:
                hist = self._own_stages.setdefault(stage, Histogram())
            hist.observe(duration_s)

    def record(self, span: Optional[Span], stage: str,
               duration_s: float) -> None:
        """Histogram observe + span attribution in one call — the
        instrumentation sites' single entry point."""
        self.observe(stage, duration_s)
        if span is not None:
            span.add(stage, duration_s)

    def fan_out(self, requests, stage: str, duration_s: float) -> None:
        """Batch-level stage: observe once, attribute the duration to
        every DISTINCT span among the member requests (a batch-wide RPC
        span attached to all rows gets the stage once, not B times)."""
        self.observe(stage, duration_s)
        seen = None
        for request in requests:
            span = getattr(request, "_span", None)
            if span is None:
                continue
            if seen is None:
                seen = set()
            if id(span) in seen:
                continue
            seen.add(id(span))
            span.add(stage, duration_s)

    # ---------------------------------------------------------------- spans

    def start_span(self, trace_id: Optional[str] = None) -> Optional[Span]:
        """A new span when sampled, else None.  An explicit client trace
        id always samples (the debugging contract of x-acs-trace-id)."""
        if trace_id is None:
            if self.sample_rate <= 0.0 or self._rng.random() >= self.sample_rate:
                return None
            trace_id = os.urandom(8).hex()
        return Span(trace_id)

    def finish(self, span: Optional[Span], decision: Optional[str] = None,
               code: Optional[int] = None) -> None:
        if span is None:
            return
        trace = span.as_dict()
        if decision is not None:
            trace["decision"] = decision
        if code is not None:
            trace["code"] = code
        with self._lock:
            self._traces.append(trace)

    def traces(self, n: Optional[int] = None) -> list[dict]:
        with self._lock:
            out = list(self._traces)
        return out if n is None else out[-int(n):]


class DecisionAuditLog:
    """Sampled JSONL decision-audit sink riding the masking logger
    machinery: one JSON object per sampled decision with subject /
    resource / action / decision / serving path / deciding rule id
    (oracle rows from the host walk's ``EffectEvaluation.source``;
    kernel rows from the explain-mode kernel output when
    ``explain:enabled`` is on, null otherwise).  Masking is
    double-layered: the record passes MaskingFilter (secret-named dict
    keys) AND target attributes whose ``id`` matches a mask field have
    their VALUE replaced before the record is built — a subject token
    attribute can never reach the sink."""

    def __init__(self, path: str, sample_rate: float = 1.0,
                 logger_name: str = "access-control-srv-tpu.audit",
                 rng: Optional[random.Random] = None):
        self.path = path
        self.sample_rate = float(sample_rate)
        self._rng = rng or random.Random()
        self.logger = logging.getLogger(logger_name)
        self.logger.setLevel(logging.INFO)
        self.logger.propagate = False
        if not any(isinstance(f, MaskingFilter) for f in self.logger.filters):
            self.logger.addFilter(MaskingFilter())
        self._handler = None
        if not any(
            getattr(h, "_acs_audit_sink", None) == path
            for h in self.logger.handlers
        ):
            handler = logging.FileHandler(path)
            handler.setFormatter(JsonLinesFormatter())
            handler._acs_audit_sink = path
            self.logger.addHandler(handler)
            self._handler = handler

    @staticmethod
    def _attrs(attributes) -> list[dict]:
        out = []
        for attr in attributes or []:
            attr_id = getattr(attr, "id", "") or ""
            value = getattr(attr, "value", "") or ""
            if any(f in attr_id.lower() for f in _LOWERED_MASK_FIELDS):
                value = "***"
            out.append({"id": attr_id, "value": value})
        return out

    def sampled(self) -> bool:
        return (self.sample_rate >= 1.0
                or self._rng.random() < self.sample_rate)

    def record(self, request, response,
               trace_id: Optional[str] = None) -> None:
        """Emit one audit record (caller already decided sampling)."""
        target = getattr(request, "target", None)
        subject = None
        context = getattr(request, "context", None)
        if isinstance(context, dict):
            ctx_subject = context.get("subject")
            if isinstance(ctx_subject, dict):
                subject = {"id": ctx_subject.get("id")}
        record = {
            "event": "decision",
            "trace_id": trace_id,
            "decision": response.decision,
            "code": response.operation_status.code,
            "cacheable": response.evaluation_cacheable,
            "path": getattr(response, "_path", None),
            "rule_id": getattr(response, "_rule_id", None),
            "subject": subject,
            "subjects": self._attrs(getattr(target, "subjects", None)),
            "resources": self._attrs(getattr(target, "resources", None)),
            "actions": self._attrs(getattr(target, "actions", None)),
        }
        self.logger.info("decision", extra={"audit": record})

    def maybe_record(self, request, response,
                     trace_id: Optional[str] = None) -> None:
        if self.sampled():
            self.record(request, response, trace_id)

    def close(self) -> None:
        if self._handler is not None:
            self._handler.close()
            self.logger.removeHandler(self._handler)
            self._handler = None


class Observability:
    """The per-worker observability hub: tracer + audit log + optional
    /metrics endpoint, built from the ``observability`` config block.
    ``from_config`` returns None unless the block is present AND
    ``enabled`` — every instrumentation site guards on that None, so an
    absent block leaves the serving path byte-identical to
    pre-observability code (the PR-5 admission pattern)."""

    def __init__(self, tracer: Optional[StageTracer] = None,
                 audit: Optional[DecisionAuditLog] = None,
                 exporter: Optional[PrometheusExporter] = None):
        self.tracer = tracer
        self.audit = audit
        self.exporter = exporter

    @classmethod
    def from_config(cls, cfg, telemetry=None,
                    logger=None) -> Optional["Observability"]:
        block = cfg.get("observability") if hasattr(cfg, "get") else None
        block = block or {}
        if not block.get("enabled"):
            return None
        tracer = None
        tracing = block.get("tracing") or {}
        if tracing.get("enabled", True):
            tracer = StageTracer(
                telemetry=telemetry,
                sample_rate=float(tracing.get("sample_rate", 0.01)),
                max_traces=int(tracing.get("max_traces", 256)),
            )
        audit = None
        audit_cfg = block.get("audit_log") or {}
        if audit_cfg.get("path"):
            audit = DecisionAuditLog(
                audit_cfg["path"],
                sample_rate=float(audit_cfg.get("sample_rate", 0.01)),
            )
        exporter = None
        http_cfg = block.get("metrics_http") or {}
        if http_cfg.get("enabled") and telemetry is not None:
            exporter = PrometheusExporter(
                telemetry,
                host=http_cfg.get("host", "127.0.0.1"),
                port=int(http_cfg.get("port", 9464)),
                logger=logger,
            )
        return cls(tracer=tracer, audit=audit, exporter=exporter)

    def close(self) -> None:
        if self.exporter is not None:
            self.exporter.close()
            self.exporter = None
        if self.audit is not None:
            self.audit.close()
            self.audit = None
