"""Shadow evaluation: policy CI against live traffic at kernel speed.

A candidate policy set (the "next" tree an operator wants to ship) loads
BESIDE production as a second table set evaluated on the same compiled
device programs: the shadow's :class:`~.evaluator.HybridEvaluator` is
built with the production evaluator's pinned capacity class
(``fixed_caps``) and its shared jit registry, so candidate tables take
the identical padded shapes and every kernel dispatch hits the per-shape
caches inside the already-jitted executables — **zero new XLA
compilations** for a candidate in the same size class (asserted at
construction; an out-of-class candidate is refused with
:class:`ShadowSizeClassError` rather than silently compiling a second
program).

Live traffic is mirrored AFTER the production decision is served: the
service facade (srv/service.py) enqueues ``(requests, decisions)`` pairs
onto a bounded drop-queue and a dedicated worker thread replays them
against the candidate tree, counting decision diffs by transition
(``acs_shadow_diffs_total{transition="PERMIT->DENY"}`` ...) and
retaining a bounded sample of diff records — each carrying deciding-node
provenance for BOTH sides, recovered through the host oracle's
``EffectEvaluation.source`` walk on the sampled rows (exact, and free of
any device-program change, so the invariant below holds even with
explain mode off).

Honesty invariants (tests/test_explain.py, bench_all.py shadow-diff):

- A shadow evaluation can NEVER alter a production decision: the mirror
  point is after response assembly, the shadow engine/evaluator objects
  are fully disjoint from production's, and the shadow evaluator is
  built with ``decision_cache=None`` so no candidate decision can ever
  be cached — let alone served — as a production one.
- A shadow evaluation can NEVER delay a production response past its
  deadline bound: ``submit`` is a lock-append-notify (drops when the
  queue is full, counted as ``dropped``), and all candidate evaluation
  runs on the shadow worker thread off the response path.
- Disabled (the default: ``shadow:enabled`` false), no shadow object
  exists and the serving path is byte-identical to pre-shadow behavior.

The shadow epoch advances independently of production's policy epoch:
``reload``/``update_policy_set`` mutate only the candidate tree and bump
only the shadow's own counter — a production CRUD never touches the
candidate, and vice versa.  With multi-tenant serving (srv/tenancy.py),
``shadow:tenant`` scopes the mirror to one tenant's traffic
(``request._tenant``) so a single tenant's candidate tree can be staged
against exactly the rows that would hit it.
"""

from __future__ import annotations

import copy
import threading
from typing import Optional

from ..core.engine import AccessController
from ..core.loader import load_policy_sets_from_file


# admission/drain sheds (srv/admission.py: OVERLOAD/SHUTDOWN/DEADLINE
# codes) answer with INDETERMINATE + an overload status — the row was
# never evaluated, so mirroring it would fabricate an
# ``INDETERMINATE->X`` diff against a candidate that DID evaluate it
_SHED_CODES = frozenset((429, 503, 504))


class ShadowSizeClassError(RuntimeError):
    """The candidate tree does not fit the production size class — a
    shadow for it would compile a second device program, which defeats
    the zero-new-compiles contract.  Stage it on a worker pinned to the
    larger class instead."""


class ShadowEvaluator:
    """Candidate-tree evaluator + diff accounting behind a drop-queue."""

    def __init__(self, production, candidate_paths: list,
                 combining_algorithms=None, telemetry=None, logger=None,
                 tenant: Optional[str] = None, sample_diffs: int = 32,
                 queue_batches: int = 64):
        from .evaluator import HybridEvaluator

        self.production = production
        self.candidate_paths = list(candidate_paths)
        self.telemetry = telemetry
        self.logger = logger
        self.tenant = tenant
        self.sample_diffs = int(sample_diffs)
        self.epoch = 0
        self._combining = combining_algorithms

        self.engine = AccessController(
            urns=production.engine.urns,
            combining_algorithms=combining_algorithms,
            logger=logger,
            identity_client=production.engine.identity_client,
            hr_scope_provider=production.engine.hr_scope_provider,
            resource_adapter=production.engine.resource_adapter,
        )
        self._load_candidate()

        jits_before = set(production._shared_jits)
        self.evaluator = HybridEvaluator(
            self.engine,
            backend=production.backend,
            logger=logger,
            telemetry=None,  # shadow rows must not skew serving-path counters
            mesh=production.mesh,
            mesh_axis=production.mesh_axis,
            model_axis=production.model_axis,
            pod_shards=production.pod_shards,
            decision_cache=None,  # INVARIANT: shadow decisions never cached
            delta_enabled=production.delta_enabled,
            shared_jits=production._shared_jits,
            fixed_caps=production._caps,
            explain=production.explain,
        )
        # same-size-class proof: the candidate compile under the pinned
        # class must publish the production capacities verbatim (the
        # fixed_caps fallback to per-tenant buckets means overflow)...
        prod_caps = production._caps
        mine = self.evaluator._caps
        if prod_caps is not None and (
            mine is None or mine.as_dict() != prod_caps.as_dict()
        ):
            raise ShadowSizeClassError(
                "candidate tree overflows the production size class "
                f"(production caps {prod_caps.as_dict()}, candidate "
                f"{None if mine is None else mine.as_dict()})"
            )
        # ...and construction must not have registered any new device
        # program in the shared jit registry (kernel variants key in at
        # build time; per-shape XLA compiles inside them hit the caches
        # production traffic already warmed, table shapes being equal)
        self.new_program_keys = sorted(
            set(production._shared_jits) - jits_before
        )
        assert not self.new_program_keys, (
            "shadow construction registered new device programs: "
            f"{self.new_program_keys}"
        )

        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._queue: list = []  # guarded-by: _lock
        self._busy = False  # a popped batch is mid-evaluation
        self._queue_max = int(queue_batches)
        self._samples: list = []  # guarded-by: _lock
        self._counts = {"evaluated": 0, "diffs": 0, "dropped": 0,
                        "errors": 0}
        self._by_transition: dict[str, int] = {}
        self._stop = False
        self._thread = threading.Thread(
            target=self._run, name="acs-shadow", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------ candidate tree

    def _load_candidate(self) -> None:
        self.engine.clear_policies()
        for path in self.candidate_paths:
            for policy_set in load_policy_sets_from_file(path):
                self.engine.update_policy_set(policy_set)

    def reload(self, candidate_paths: Optional[list] = None) -> None:
        """Swap in a new candidate tree (shadow epoch++; production
        untouched).  The refresh goes through the same version-pinned
        compile+swap as production, so in-flight shadow batches finish on
        the old candidate."""
        if candidate_paths is not None:
            self.candidate_paths = list(candidate_paths)
        self._load_candidate()
        self.evaluator.refresh(wait=True)
        self.epoch += 1

    def update_policy_set(self, policy_set) -> None:
        """Hot-update one candidate policy set (shadow epoch++)."""
        self.engine.update_policy_set(policy_set)
        self.evaluator.refresh(wait=True)
        self.epoch += 1

    # ------------------------------------------------------------ mirroring

    def submit(self, requests: list, responses: list) -> None:
        """Mirror one served batch; never blocks and never raises (the
        production response is already on its way out — nothing here may
        touch it).  Requests are read shared with production POST-serving
        and are never mutated by the shadow walk."""
        try:
            rows = [
                (req, resp.decision)
                for req, resp in zip(requests, responses)
                if resp.operation_status.code not in _SHED_CODES
                and (self.tenant is None
                     or getattr(req, "_tenant", None) == self.tenant)
            ]
            if not rows:
                return
            with self._lock:
                if self._stop:
                    return
                if len(self._queue) >= self._queue_max:
                    self._counts["dropped"] += len(rows)
                    if self.telemetry is not None:
                        self.telemetry.shadow.inc("dropped", len(rows))
                    return
                self._queue.append(rows)
                self._wake.notify()
        except Exception:  # noqa: BLE001 — mirroring must never fail serving
            if self.logger:
                self.logger.exception("shadow submit failed")

    def _run(self) -> None:
        while True:
            with self._lock:
                while not self._queue and not self._stop:
                    # acs-lint: ignore[blocking-under-lock] Condition.wait
                    # RELEASES the lock while parked — producers' submit()
                    # append-notify never blocks behind this wait
                    self._wake.wait()
                if self._stop and not self._queue:
                    return
                rows = self._queue.pop(0)
                self._busy = True
            try:
                self._evaluate(rows)
            except Exception:  # noqa: BLE001 — keep draining
                self._counts["errors"] += len(rows)
                if self.telemetry is not None:
                    self.telemetry.shadow.inc("errors", len(rows))
                if self.logger:
                    self.logger.exception("shadow evaluation failed")
            finally:
                with self._lock:
                    self._busy = False

    def _evaluate(self, rows: list) -> None:
        requests = []
        for req, _ in rows:
            if getattr(req, "_deadline", None) is not None:
                # admission-gated traffic rides with a ``_deadline`` stamp
                # that has usually PASSED by replay time — the evaluator
                # would shed the row as expired and every mirrored request
                # would read as a ``*->INDETERMINATE`` diff.  The caller
                # was already answered; the candidate replay has no
                # deadline.  Strip it on a shallow copy: the shared
                # request object (production may still hold it) is never
                # mutated by the shadow walk.
                req = copy.copy(req)
                req._deadline = None
            requests.append(req)
        candidate = self.evaluator.is_allowed_batch(requests)
        diffs = []
        for (request, prod_decision), cand_resp in zip(rows, candidate):
            if cand_resp.decision != prod_decision:
                diffs.append((request, prod_decision, cand_resp))
        with self._lock:
            self._counts["evaluated"] += len(rows)
            self._counts["diffs"] += len(diffs)
            for _, prod_decision, cand_resp in diffs:
                transition = f"{prod_decision}->{cand_resp.decision}"
                self._by_transition[transition] = (
                    self._by_transition.get(transition, 0) + 1
                )
            want = max(0, self.sample_diffs - len(self._samples))
        if self.telemetry is not None:
            self.telemetry.shadow.inc("evaluated", len(rows))
            for _, prod_decision, cand_resp in diffs:
                self.telemetry.shadow_diffs.inc(
                    f"{prod_decision}->{cand_resp.decision}"
                )
        if want and diffs:
            records = [
                self._diff_record(request, prod_decision, cand_resp)
                for request, prod_decision, cand_resp in diffs[:want]
            ]
            with self._lock:
                self._samples.extend(
                    records[: self.sample_diffs - len(self._samples)]
                )

    def _diff_record(self, request, prod_decision, cand_resp) -> dict:
        """One sampled diff with deciding-node provenance on both sides.

        Provenance comes from the HOST oracle walk over each tree
        (``EffectEvaluation.source``) — exact for the sampled rows,
        identical to the kernel's explain output by the differential
        suite, and free of any device-program dependency so sampling
        works with explain mode off too.  Masking rides the audit log's
        attribute scrubber: secret-valued target attributes never land in
        a sample."""
        from .tracing import DecisionAuditLog

        def provenance(engine):
            try:
                walked = engine.is_allowed(request)
                return getattr(walked, "_rule_id", None)
            except Exception:  # noqa: BLE001 — a sample is best-effort
                return None

        target = getattr(request, "target", None)
        return {
            "production": {
                "decision": prod_decision,
                "rule_id": provenance(self.production.engine),
            },
            "candidate": {
                "decision": cand_resp.decision,
                "rule_id": getattr(
                    cand_resp, "_rule_id", None
                ) or provenance(self.engine),
                "code": cand_resp.operation_status.code,
            },
            "subjects": DecisionAuditLog._attrs(
                getattr(target, "subjects", None)
            ),
            "resources": DecisionAuditLog._attrs(
                getattr(target, "resources", None)
            ),
            "actions": DecisionAuditLog._attrs(
                getattr(target, "actions", None)
            ),
        }

    # -------------------------------------------------------------- surface

    def status(self) -> dict:
        """The ``shadow_status`` command / health surface."""
        with self._lock:
            queue_depth = len(self._queue)
            counts = dict(self._counts)
            by_transition = dict(self._by_transition)
            samples = list(self._samples)
        return {
            "enabled": True,
            "epoch": self.epoch,
            "tenant": self.tenant,
            "candidate_paths": list(self.candidate_paths),
            "kernel_active": self.evaluator.kernel_active,
            "new_program_keys": list(self.new_program_keys),
            "queue_depth": queue_depth,
            **counts,
            "diffs_by_transition": by_transition,
            "samples": samples,
        }

    def drain(self, timeout_s: float = 5.0) -> bool:
        """Block until the queue is empty AND no popped batch is still
        mid-evaluation (tests/benches); True when drained within the
        timeout."""
        import time

        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if not self._queue and not self._busy:
                    return True
            time.sleep(0.01)
        return False

    def stop(self, timeout_s: float = 5.0) -> None:
        with self._lock:
            self._stop = True
            self._wake.notify_all()
        self._thread.join(timeout_s)
        self.evaluator.shutdown()


def from_config(cfg, production, telemetry=None,
                logger=None) -> Optional[ShadowEvaluator]:
    """Build the shadow from the ``shadow`` config block; None unless
    enabled with candidate paths (the default — no object, no overhead,
    serving byte-identical)."""
    block = cfg.get("shadow") if hasattr(cfg, "get") else None
    block = block or {}
    if not block.get("enabled"):
        return None
    paths = block.get("candidate_paths") or []
    if not paths:
        if logger:
            logger.warning("shadow enabled without candidate_paths; off")
        return None
    return ShadowEvaluator(
        production, paths,
        combining_algorithms=(
            cfg.get("policies:options:combiningAlgorithms") or None
        ),
        telemetry=telemetry,
        logger=logger,
        tenant=block.get("tenant"),
        sample_diffs=int(block.get("sample_diffs", 32)),
        queue_batches=int(block.get("queue_batches", 64)),
    )
