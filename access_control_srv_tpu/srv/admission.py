"""Deadline-aware admission control and overload protection.

The PDP sits on the request critical path of every protected service
(reference: src/accessControlService.ts serves one decision per gRPC
call), so overload must turn into CONTROLLED degradation — bounded
queues, early shedding, deadline-aware rejection — never unbounded
queueing and timeout storms.  This module is the host-side brain of that
behavior; it owns ZERO device state and never imports jax (asserted by
tpu_compat_audit.py row ``admission-zero-device-ops``).

Pieces, all consumed by ``srv/batcher.MicroBatcher`` and the transports:

* **Deadline propagation** — gRPC deadlines (``context.time_remaining``)
  and the ``x-acs-timeout-ms`` metadata key become an absolute monotonic
  deadline attached per request (``request._deadline``); the batcher
  rejects at submit when the remaining budget cannot cover the current
  EWMA batch-latency estimate, and drops already-expired rows at
  dispatch instead of evaluating work nobody is waiting for.

* **Bounded two-class queues + shedding** — interactive (``isAllowed``)
  and bulk (``whatIsAllowed``/reverse) traffic are admitted against
  separate depth bounds.  A shed NEVER fabricates a PERMIT/DENY: the
  caller gets a fast INDETERMINATE whose ``operation_status`` carries the
  overload code (429 shed / 504 deadline / 503 shutdown drain).

* **Adaptive max-batch sizing** — the batch-latency EWMA drives the
  effective collection bound between a floor and the configured max, so
  a slow regime (oracle-heavy traffic, cold compile) shrinks batches
  toward the deadline bound instead of amplifying tail latency.

* **Dependency circuit breakers** — the adapter context-query and
  identity token-resolution clients share ``CircuitBreaker`` instances
  (closed/open/half-open, failure-rate windows, jittered probe) so a
  down upstream trips the existing per-row degradation ladder
  (kernel -> retry -> oracle / ``token-unresolved``) immediately instead
  of paying a transport timeout per request.

Config lives under the ``admission`` block (srv/config.py); everything
is OFF by default — with ``admission.enabled`` false the serving path is
byte-identical to the pre-admission behavior (asserted by
tests/test_admission.py's differential check).
"""

from __future__ import annotations

# acs-lint: host-only — admission decisions must stay off the device
# runtime (tpu_compat_audit row admission-zero-device-ops)

import random
import re
import threading
import time
from typing import Optional

from ..models.model import Decision, OperationStatus, Response

# shed/overload operation-status codes: the caller must be able to tell
# "the service refused the work" from a decision — shed responses are
# INDETERMINATE, never a fabricated PERMIT/DENY
OVERLOAD_CODE = 429   # queue full / deadline-infeasible at submit
DEADLINE_CODE = 504   # deadline expired before evaluation (dropped at dispatch)
SHUTDOWN_CODE = 503   # still queued when the drain deadline hit
DEGRADED_CODE = 503   # device path quarantined and no honest fallback ran

INTERACTIVE = "interactive"
BULK = "bulk"

# end-to-end batches a freshly-admitted request can wait behind at the
# LEGACY depth-2 pipeline: its own collection round plus the batcher's
# two in-flight batches.  The live value is per-controller
# (``AdmissionController.pipeline_batches`` = configured
# evaluator:pipeline_depth + 1) so deadline-feasibility math tracks the
# real in-flight count at any depth; this constant remains the default.
PIPELINE_BATCHES = 3

# metadata key carrying a per-request timeout for clients that cannot set
# a native gRPC deadline (the rc-wire analog of grpc-timeout)
TIMEOUT_METADATA_KEY = "x-acs-timeout-ms"

# metadata key carrying the caller's policy domain (srv/tenancy.py).  The
# value is attacker-controlled and flows into cache keys, journal frames
# and Prometheus labels, so only a conservative id shape is accepted —
# anything else is treated as absent (single-tenant path).
TENANT_METADATA_KEY = "x-acs-tenant"
_TENANT_ID_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")


def valid_tenant_id(value) -> Optional[str]:
    """``value`` as a tenant id when it matches the accepted shape."""
    tenant = str(value)
    return tenant if _TENANT_ID_RE.match(tenant) else None


def tenant_from_metadata(grpc_context) -> Optional[str]:
    """The (validated) ``x-acs-tenant`` metadata value, if any."""
    try:
        for key, value in grpc_context.invocation_metadata() or ():
            if str(key).lower() == TENANT_METADATA_KEY:
                return valid_tenant_id(value)
    except Exception:  # noqa: BLE001 — non-grpc test doubles
        return None
    return None


def overload_response(code: int, message: str) -> Response:
    """Fast INDETERMINATE + overload status — the shed envelope.  Never
    cacheable: a shed is a statement about THIS instant's load, not about
    the policy tree."""
    return Response(
        decision=Decision.INDETERMINATE,
        obligations=[],
        evaluation_cacheable=False,
        operation_status=OperationStatus(code=code, message=message),
    )


def degraded_response(message: str = "") -> Response:
    """Honest INDETERMINATE for rows the quarantined device path could
    not evaluate and no oracle fallback could absorb.  Distinct from the
    shed envelope: ``degraded`` in the message names the cause as a
    device-health event, not load.  Never cacheable, never a fabricated
    PERMIT/DENY."""
    detail = f"degraded: {message}" if message else "degraded"
    return Response(
        decision=Decision.INDETERMINATE,
        obligations=[],
        evaluation_cacheable=False,
        operation_status=OperationStatus(code=DEGRADED_CODE, message=detail),
    )


def deadline_from_context(grpc_context) -> Optional[float]:
    """Absolute monotonic deadline from a gRPC ServicerContext: the
    native call deadline when the client set one, else the
    ``x-acs-timeout-ms`` metadata key (rc-wire clients that cannot set
    gRPC deadlines).  None when the caller stated no budget."""
    remaining = None
    try:
        remaining = grpc_context.time_remaining()
    except Exception:  # noqa: BLE001 — non-grpc test doubles
        remaining = None
    if remaining is not None and remaining > 3600.0 * 24 * 365:
        # grpc-python reports ~int64-max SECONDS (not None) when the
        # client set no deadline; anything past a year is "unbounded"
        remaining = None
    if remaining is None:
        try:
            for key, value in grpc_context.invocation_metadata() or ():
                if str(key).lower() == TIMEOUT_METADATA_KEY:
                    remaining = float(value) / 1e3
                    break
        except Exception:  # noqa: BLE001
            remaining = None
    if remaining is None:
        return None
    return time.monotonic() + max(0.0, float(remaining))


def remaining_budget(deadline: Optional[float]) -> Optional[float]:
    """Seconds left before ``deadline`` (monotonic); None when unbounded."""
    if deadline is None:
        return None
    return deadline - time.monotonic()


class LatencyEwma:
    """Exponentially-weighted moving average of batch evaluation latency,
    one per traffic class.  ``estimate()`` answers the admission question
    "how long will the NEXT batch take" — before any sample it returns
    ``default_s`` (admit-friendly: an idle service must not shed its
    first request on a fictional estimate).

    Jitter-aware: alongside the mean, the mean absolute deviation is
    tracked TCP-RTO style (Jacobson: SRTT + 4*RTTVAR), and
    ``estimate_high()`` is the pessimistic bound deadline decisions use —
    with a jittery executor (GIL contention, noisy neighbors) the mean
    alone admits rows that then finish late."""

    def __init__(self, alpha: float = 0.2, default_s: float = 0.005):
        self.alpha = float(alpha)
        self.default_s = float(default_s)
        self._value: Optional[float] = None   # guarded-by: _lock
        self._dev = 0.0                       # guarded-by: _lock
        self._per_row: Optional[float] = None  # guarded-by: _lock
        self._lock = threading.Lock()

    def observe(self, seconds: float, rows: int = 1) -> None:
        seconds = max(0.0, float(seconds))
        per_row = seconds / max(1, rows)
        with self._lock:
            if self._value is None:
                self._value = seconds
                self._dev = seconds / 2.0
                self._per_row = per_row
            else:
                self._dev += self.alpha * (
                    abs(seconds - self._value) - self._dev
                )
                self._value += self.alpha * (seconds - self._value)
                self._per_row += self.alpha * (per_row - self._per_row)

    def estimate(self) -> float:
        with self._lock:
            return self.default_s if self._value is None else self._value

    def estimate_high(self) -> float:
        """Pessimistic next-batch estimate: mean + 4 * mean deviation."""
        with self._lock:
            if self._value is None:
                return self.default_s
            return self._value + 4.0 * self._dev

    def estimate_per_row(self) -> Optional[float]:
        with self._lock:
            return self._per_row


class BreakerOpenError(Exception):
    """Raised by callers that want the open-circuit fast failure to flow
    through their existing error ladders as an exception."""


class CircuitBreaker:
    """Closed / open / half-open dependency breaker with a failure-rate
    window and a jittered reopen probe.

    * CLOSED: calls flow; outcomes land in a sliding ``window_s`` window.
      When the window holds at least ``min_volume`` outcomes and the
      failure ratio reaches ``failure_ratio``, the breaker OPENS.
    * OPEN: ``allow()`` is False — callers fail fast down their existing
      degradation ladder (oracle fallback / token-unresolved) without
      paying the transport timeout.  After ``open_s`` (+0..50% jitter so
      a worker fleet does not probe in lockstep) the breaker moves to
      HALF-OPEN.
    * HALF-OPEN: up to ``half_open_probes`` in-flight probe calls are
      admitted; the first success CLOSES the breaker (window reset), the
      first failure re-OPENS it with a fresh cooldown.

    Shared state: one instance guards one upstream and is hit
    concurrently by every serving thread — all transitions are
    lock-guarded, and ``counter`` (Counter-like, ``.inc(key)``) receives
    ``<name>-open``/``<name>-close``/``<name>-fast-fail`` transitions for
    telemetry.admission."""

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half-open"

    def __init__(
        self,
        name: str,
        window_s: float = 10.0,
        min_volume: int = 8,
        failure_ratio: float = 0.5,
        open_s: float = 2.0,
        half_open_probes: int = 2,
        counter=None,
        time_fn=time.monotonic,
        rng: Optional[random.Random] = None,
    ):
        self.name = name
        self.window_s = float(window_s)
        self.min_volume = int(min_volume)
        self.failure_ratio = float(failure_ratio)
        self.open_s = float(open_s)
        self.half_open_probes = int(half_open_probes)
        self._counter = counter
        self._time = time_fn
        self._rng = rng or random.Random()
        self._lock = threading.Lock()
        self._state = self.CLOSED  # guarded-by: _lock
        self._outcomes: list[tuple[float, bool]] = []  # (t, ok)  # guarded-by: _lock
        self._opened_at = 0.0      # guarded-by: _lock
        self._reopen_after = 0.0   # guarded-by: _lock
        self._probes_inflight = 0  # guarded-by: _lock
        self._transitions = {"opens": 0, "closes": 0, "fast_fails": 0}  # guarded-by: _lock

    # ------------------------------------------------------------- helpers

    def _count(self, key: str) -> None:  # holds: _lock
        self._transitions[key] = self._transitions.get(key, 0) + 1
        if self._counter is not None:
            self._counter.inc(f"breaker-{self.name}-{key.rstrip('s')}")

    def _prune(self, now: float) -> None:  # holds: _lock
        cutoff = now - self.window_s
        if self._outcomes and self._outcomes[0][0] < cutoff:
            self._outcomes = [o for o in self._outcomes if o[0] >= cutoff]

    def _open(self, now: float) -> None:  # holds: _lock
        self._state = self.OPEN
        self._opened_at = now
        # jittered cooldown: 1.0x..1.5x open_s so replicas don't probe a
        # recovering upstream in lockstep
        self._reopen_after = now + self.open_s * (1.0 + 0.5 * self._rng.random())
        self._probes_inflight = 0
        self._outcomes = []
        self._count("opens")

    # -------------------------------------------------------------- surface

    @property
    def state(self) -> str:
        with self._lock:
            now = self._time()
            if self._state == self.OPEN and now >= self._reopen_after:
                self._state = self.HALF_OPEN
                self._probes_inflight = 0
            return self._state

    def allow(self) -> bool:
        """True when the caller may attempt the upstream call.  In
        half-open, True claims one of the probe slots — the caller MUST
        report the outcome via record_success/record_failure."""
        with self._lock:
            now = self._time()
            if self._state == self.OPEN:
                if now < self._reopen_after:
                    self._count("fast_fails")
                    return False
                self._state = self.HALF_OPEN
                self._probes_inflight = 0
            if self._state == self.HALF_OPEN:
                if self._probes_inflight >= self.half_open_probes:
                    self._count("fast_fails")
                    return False
                self._probes_inflight += 1
                return True
            return True

    def record_success(self) -> None:
        with self._lock:
            now = self._time()
            if self._state == self.HALF_OPEN:
                # one healthy probe closes the circuit; the window restarts
                # empty so stale pre-open failures cannot re-trip it
                self._state = self.CLOSED
                self._outcomes = []
                self._probes_inflight = 0
                self._count("closes")
                return
            self._outcomes.append((now, True))
            self._prune(now)

    def record_failure(self) -> None:
        with self._lock:
            now = self._time()
            if self._state == self.HALF_OPEN:
                self._open(now)
                return
            if self._state == self.OPEN:
                return
            self._outcomes.append((now, False))
            self._prune(now)
            if len(self._outcomes) >= self.min_volume:
                failures = sum(1 for _, ok in self._outcomes if not ok)
                if failures / len(self._outcomes) >= self.failure_ratio:
                    self._open(now)

    def stats(self) -> dict:
        with self._lock:
            window = list(self._outcomes)
            state = self._state
            now = self._time()
            if state == self.OPEN and now >= self._reopen_after:
                state = self.HALF_OPEN
            transitions = dict(self._transitions)
        failures = sum(1 for _, ok in window if not ok)
        return {
            "state": state,
            "window_calls": len(window),
            "window_failures": failures,
            **transitions,
        }


class AdmissionController:
    """Per-worker admission state shared by the batcher, the service
    facade and the transports.  Construct via ``from_config``; a disabled
    controller (``enabled`` False) admits everything unconditionally and
    keeps the serving path byte-identical to pre-admission behavior.

    Depth accounting: ``admit`` increments the class depth, the batcher
    calls ``release`` as it collects rows off the queue — the bound
    covers queued work only, matching "bounded queue", not in-flight
    evaluation (that is the eval pipeline's depth-2 bound)."""

    def __init__(
        self,
        enabled: bool = False,
        max_queue_interactive: int = 8192,
        max_queue_bulk: int = 1024,
        deadline_headroom: float = 1.2,
        ewma_alpha: float = 0.2,
        ewma_default_ms: float = 5.0,
        adaptive_max_batch: bool = True,
        deadline_bound_ms: float = 50.0,
        min_batch: int = 64,
        drain_deadline_s: float = 5.0,
        bulk_interval: int = 4,
        pipeline_depth: int = PIPELINE_BATCHES - 1,
        tenant_enabled: bool = False,
        tenant_max_inflight: int = 256,
        tenant_default_weight: float = 1.0,
        tenant_weights: Optional[dict] = None,
        tenant_contention_ratio: float = 0.5,
        telemetry=None,
        time_fn=time.monotonic,
    ):
        self.enabled = bool(enabled)
        # batches a fresh request can wait behind: its own collection
        # round + the configured in-flight pipeline depth.  Shares the
        # evaluator:pipeline_depth config value with the batcher so the
        # feasibility estimate tracks the real in-flight count.
        self.pipeline_batches = max(1, int(pipeline_depth)) + 1
        self.max_queue = {
            INTERACTIVE: int(max_queue_interactive),
            BULK: int(max_queue_bulk),
        }
        self.deadline_headroom = float(deadline_headroom)
        self.adaptive_max_batch = bool(adaptive_max_batch)
        self.deadline_bound_s = float(deadline_bound_ms) / 1e3
        self.min_batch = int(min_batch)
        self.drain_deadline_s = float(drain_deadline_s)
        self.bulk_interval = max(1, int(bulk_interval))
        self.telemetry = telemetry
        self._time = time_fn
        self._lock = threading.Lock()
        self._depth = {INTERACTIVE: 0, BULK: 0}           # guarded-by: _lock
        self._max_depth_seen = {INTERACTIVE: 0, BULK: 0}  # guarded-by: _lock
        self._ewma = {
            INTERACTIVE: LatencyEwma(ewma_alpha, ewma_default_ms / 1e3),
            BULK: LatencyEwma(ewma_alpha, ewma_default_ms / 1e3),
        }
        self._adaptive_max: Optional[int] = None  # guarded-by: _lock
        self._last_batch_full = False
        self._draining = False  # guarded-by: _lock
        self._stats = {  # guarded-by: _lock
            "admitted": 0, "shed_queue_full": 0, "deadline_rejected": 0,
            "deadline_expired": 0, "shed_shutdown": 0,
            "shed_tenant_quota": 0, "shed_tenant_fair_share": 0,
        }
        # per-tenant quotas: inflight caps + weighted fair sharing over the
        # interactive queue.  All of it is skipped when the request carries
        # no tenant id, keeping the single-tenant path byte-identical.
        self.tenant_enabled = bool(tenant_enabled)
        self.tenant_max_inflight = int(tenant_max_inflight)
        self.tenant_default_weight = float(tenant_default_weight)
        self.tenant_weights = dict(tenant_weights or {})
        self.tenant_contention_ratio = float(tenant_contention_ratio)
        self._tenant_depth: dict[str, int] = {}  # guarded-by: _lock
        self.breakers: dict[str, CircuitBreaker] = {}  # guarded-by: _lock

    # ----------------------------------------------------------- construction

    @classmethod
    def from_config(cls, cfg, telemetry=None) -> "AdmissionController":
        """Build from the ``admission`` config block (srv/config.py); the
        breaker sub-block is consumed by ``breaker()`` below."""
        block = cfg.get("admission") if hasattr(cfg, "get") else None
        block = block or {}
        controller = cls(
            enabled=bool(block.get("enabled", False)),
            max_queue_interactive=block.get("max_queue_interactive", 8192),
            max_queue_bulk=block.get("max_queue_bulk", 1024),
            deadline_headroom=block.get("deadline_headroom", 1.2),
            ewma_alpha=block.get("ewma_alpha", 0.2),
            ewma_default_ms=block.get("ewma_default_ms", 5.0),
            adaptive_max_batch=block.get("adaptive_max_batch", True),
            deadline_bound_ms=block.get("deadline_bound_ms", 50.0),
            min_batch=block.get("min_batch", 64),
            drain_deadline_s=block.get("drain_deadline_s", 5.0),
            bulk_interval=block.get("bulk_interval", 4),
            pipeline_depth=(cfg.get("evaluator") or {}).get(
                "pipeline_depth", PIPELINE_BATCHES - 1
            ) if hasattr(cfg, "get") else PIPELINE_BATCHES - 1,
            tenant_enabled=bool((block.get("tenant") or {}).get(
                "enabled", True
            )),
            tenant_max_inflight=(block.get("tenant") or {}).get(
                "max_inflight_per_tenant", 256
            ),
            tenant_default_weight=(block.get("tenant") or {}).get(
                "default_weight", 1.0
            ),
            tenant_weights=(block.get("tenant") or {}).get("weights"),
            tenant_contention_ratio=(block.get("tenant") or {}).get(
                "contention_ratio", 0.5
            ),
            telemetry=telemetry,
        )
        controller._breaker_cfg = dict(block.get("breakers") or {})
        return controller

    def breaker(self, name: str) -> Optional[CircuitBreaker]:
        """The shared breaker guarding upstream ``name`` (one per
        upstream, created on first ask from the ``admission:breakers``
        config block); None when breakers are disabled."""
        cfg = getattr(self, "_breaker_cfg", {})
        if not self.enabled or not cfg.get("enabled", True):
            return None
        with self._lock:
            if name not in self.breakers:
                counter = (
                    self.telemetry.admission
                    if self.telemetry is not None else None
                )
                self.breakers[name] = CircuitBreaker(
                    name,
                    window_s=cfg.get("window_s", 10.0),
                    min_volume=cfg.get("min_volume", 8),
                    failure_ratio=cfg.get("failure_ratio", 0.5),
                    open_s=cfg.get("open_s", 2.0),
                    half_open_probes=cfg.get("half_open_probes", 2),
                    counter=counter,
                )
            return self.breakers[name]

    # -------------------------------------------------------------- counters

    def _count(self, key: str, by: int = 1) -> None:
        with self._lock:
            self._stats[key] = self._stats.get(key, 0) + by
        if self.telemetry is not None:
            self.telemetry.admission.inc(key.replace("_", "-"), by)

    # -------------------------------------------------------------- admission

    def tenant_weight(self, tenant: str) -> float:
        return float(self.tenant_weights.get(
            tenant, self.tenant_default_weight
        ))

    def _tenant_shed(self, cls: str, tenant: str  # holds: _lock NOT held
                     ) -> Optional[Response]:
        """Per-tenant quota gate: inflight cap always, weighted fair
        share only once the class queue is contended — an uncontended
        queue lets one tenant use the whole depth (work-conserving)."""
        with self._lock:
            mine = self._tenant_depth.get(tenant, 0)
            if mine >= self.tenant_max_inflight:
                verdict = "quota"
            else:
                verdict = None
                total = self._depth[cls]
                contended = total >= (
                    self.max_queue[cls] * self.tenant_contention_ratio
                )
                if contended and cls == INTERACTIVE:
                    # fair bound: this tenant's weight share of the queue
                    # over the weights of every tenant currently holding
                    # slots (including this one)
                    active = set(
                        t for t, d in self._tenant_depth.items() if d > 0
                    )
                    active.add(tenant)
                    total_w = sum(self.tenant_weight(t) for t in active)
                    share = self.tenant_weight(tenant) / max(total_w, 1e-9)
                    bound = max(1, int(self.max_queue[cls] * share))
                    if mine >= bound:
                        verdict = "fair_share"
        if verdict is None:
            return None
        self._count(f"shed_tenant_{verdict}")
        tenant_inc = getattr(self.telemetry, "tenant_inc", None) \
            if self.telemetry is not None else None
        if tenant_inc is not None:
            tenant_inc("shed", tenant)
        reason = (
            f"tenant {tenant} inflight cap ({self.tenant_max_inflight})"
            if verdict == "quota"
            else f"tenant {tenant} over fair share of {cls} queue"
        )
        return overload_response(OVERLOAD_CODE, reason)

    def admit(self, cls: str, deadline: Optional[float] = None,
              tenant: Optional[str] = None) -> Optional[Response]:
        """Admission decision for one request of traffic class ``cls``:
        None admits (depth incremented — pair with ``release``), a
        Response is the shed envelope to resolve the caller with
        immediately.  ``tenant`` engages the per-tenant quota gates; None
        skips them entirely (byte-identical single-tenant path)."""
        if not self.enabled:
            return None
        # acs-lint: ignore[guarded-by] benign racy read of a one-way flag:
        # a request admitted during the begin_drain() window still drains
        # within the batcher's drain deadline
        if self._draining:
            self._count("shed_shutdown")
            return overload_response(SHUTDOWN_CODE, "shutting down")
        if tenant is not None and self.tenant_enabled:
            shed = self._tenant_shed(cls, tenant)
            if shed is not None:
                return shed
        if deadline is not None:
            remaining = deadline - self._time()
            ewma = self._ewma[cls]
            # MEAN estimate here: the pessimistic (mean + 4*dev) bound
            # multiplied across the pipeline would triple-count the
            # jitter margin and collapse to reject-all under load — the
            # eval-time expiry gate (batcher._drop_expired with the
            # estimate_high margin) is what protects the admitted p99
            estimate = ewma.estimate()
            # the wait estimate covers the full path: the queue already
            # ahead of this request, plus the batcher's eval pipeline
            # (own collection round + up to two in-flight batches).
            # Joining a deep queue with a tight budget only to expire at
            # dispatch wastes a slot AND the caller's time — reject NOW
            # instead of evaluating a decision the caller will have
            # abandoned
            per_row = ewma.estimate_per_row() or 0.0
            with self._lock:
                queued_ahead = self._depth[cls]
            estimate = (
                estimate * self.pipeline_batches + queued_ahead * per_row
            )
            if remaining < estimate * self.deadline_headroom:
                self._count("deadline_rejected")
                if self.telemetry is not None:
                    self.telemetry.admission_budget.observe(
                        max(0.0, remaining)
                    )
                return overload_response(
                    OVERLOAD_CODE,
                    f"deadline infeasible: {remaining * 1e3:.1f} ms budget "
                    f"< {estimate * self.deadline_headroom * 1e3:.1f} ms "
                    f"estimated latency ({queued_ahead} queued ahead)",
                )
        with self._lock:
            depth = self._depth[cls]
            if depth >= self.max_queue[cls]:
                shed = True
            else:
                shed = False
                self._depth[cls] = depth + 1
                if self._depth[cls] > self._max_depth_seen[cls]:
                    self._max_depth_seen[cls] = self._depth[cls]
                if tenant is not None and self.tenant_enabled:
                    self._tenant_depth[tenant] = (
                        self._tenant_depth.get(tenant, 0) + 1
                    )
        if shed:
            self._count("shed_queue_full")
            return overload_response(
                OVERLOAD_CODE,
                f"{cls} queue full ({self.max_queue[cls]})",
            )
        self._count("admitted")
        if self.telemetry is not None:
            self.telemetry.admission_queue_depth.observe(depth + 1)
            if deadline is not None:
                self.telemetry.admission_budget.observe(
                    max(0.0, deadline - self._time())
                )
        return None

    def release(self, cls: str, n: int = 1,
                tenant: Optional[str] = None) -> None:
        """The batcher collected ``n`` admitted rows off the queue."""
        if n <= 0:
            return
        with self._lock:
            self._depth[cls] = max(0, self._depth[cls] - n)
            if tenant is not None and tenant in self._tenant_depth:
                left = self._tenant_depth[tenant] - n
                if left > 0:
                    self._tenant_depth[tenant] = left
                else:
                    # drop empty slots so offboarded tenants don't pin
                    # dict entries forever
                    del self._tenant_depth[tenant]

    def expired(self, n: int = 1) -> None:
        """``n`` admitted rows were dropped at dispatch with an expired
        deadline (counted separately from submit-time rejection)."""
        self._count("deadline_expired", n)

    def shed_shutdown(self, n: int = 1) -> None:
        """``n`` already-queued rows were failed with the shutdown status
        at the drain deadline."""
        self._count("shed_shutdown", n)

    def depth(self, cls: str) -> int:
        with self._lock:
            return self._depth[cls]

    # --------------------------------------------------------- batch sizing

    def observe_batch(self, cls: str, seconds: float, rows: int) -> None:
        """Feed the latency EWMA and adapt the effective max-batch.  A
        request's end-to-end wait spans up to ``pipeline_batches`` batch
        evaluations, so the per-batch target is deadline_bound /
        pipeline_batches (with margin: +1): batches overshooting it halve
        the collection cap; comfortable full batches (< half the target)
        grow it back toward the configured max."""
        self._ewma[cls].observe(seconds, rows)
        if cls != INTERACTIVE or not self.adaptive_max_batch:
            return
        target = self.deadline_bound_s / (self.pipeline_batches + 1)
        with self._lock:
            current = self._adaptive_max
            if current is None:
                return  # suggest_max_batch not consulted yet
            if seconds > target and rows >= self.min_batch:
                self._adaptive_max = max(self.min_batch, current // 2)
            elif seconds < target / 2 and rows >= current:
                self._adaptive_max = current * 2

    def suggest_max_batch(self, configured_max: int) -> int:
        if not self.enabled or not self.adaptive_max_batch:
            return configured_max
        with self._lock:
            if self._adaptive_max is None:
                # slow start: begin at the floor and double on comfortable
                # FULL batches (observe_batch) — starting at the
                # configured max would let the first overload batches run
                # far past the deadline bound before halving converges
                self._adaptive_max = max(
                    1, min(int(configured_max), self.min_batch)
                )
            self._adaptive_max = min(self._adaptive_max, int(configured_max))
            return max(1, self._adaptive_max)

    def estimate(self, cls: str = INTERACTIVE) -> float:
        return self._ewma[cls].estimate()

    def estimate_high(self, cls: str = INTERACTIVE) -> float:
        """Jitter-pessimistic batch-latency bound (mean + 4*deviation) —
        what deadline feasibility and the eval-time expiry margin use."""
        return self._ewma[cls].estimate_high()

    # ---------------------------------------------------------------- drain

    def begin_drain(self) -> None:
        """Stop admitting (every subsequent admit sheds with the shutdown
        status); already-admitted work keeps flowing until the batcher's
        drain deadline."""
        with self._lock:
            self._draining = True

    @property
    def draining(self) -> bool:
        # acs-lint: ignore[guarded-by] benign racy read of a one-way flag
        return self._draining

    # ---------------------------------------------------------------- stats

    def stats(self) -> dict:
        with self._lock:
            out = {
                "enabled": self.enabled,
                "pipeline_batches": self.pipeline_batches,
                "draining": self._draining,
                **self._stats,
                "queue_depth": dict(self._depth),
                "max_queue_depth_seen": dict(self._max_depth_seen),
                "max_queue": dict(self.max_queue),
                "adaptive_max_batch": self._adaptive_max,
            }
            if self.tenant_enabled and self._tenant_depth:
                top = sorted(
                    self._tenant_depth.items(),
                    key=lambda kv: kv[1], reverse=True,
                )[:8]
                out["tenant_queue_depth"] = dict(top)
            breakers = dict(self.breakers)
        out["batch_latency_estimate_ms"] = {
            cls: round(ewma.estimate() * 1e3, 3)
            for cls, ewma in self._ewma.items()
        }
        out["breakers"] = {
            name: breaker.stats() for name, breaker in breakers.items()
        }
        return out
