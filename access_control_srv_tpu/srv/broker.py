"""Cross-process event/cache backend over TCP.

The in-process ``EventBus``/``SubjectCache`` (srv/events.py, srv/cache.py)
implement the reference's Kafka-topic and Redis-cache ROLES inside one
process.  This module provides the inter-process implementation behind the
same interfaces: a small broker process holds the topic logs (offsets,
replay) and the shared key-value store; workers connect over TCP with
newline-delimited JSON frames.

Mirrors the reference deployment shape (Kafka broker + Redis server as
separate processes, cfg/config.json events.kafka / redis): the HR-scope
rendezvous — request emitted by one process, response produced by another
(reference: src/core/accessController.ts:753-767, src/worker.ts:252-299)
— runs across OS processes (tests/test_broker.py drives it with a real
child process).

Protocol (one JSON object per line):
  {"op": "emit", "topic": t, "event": e, "message": m} -> {"offset": n}
  {"op": "read", "topic": t, "from": n}                -> {"events": [...]}
  {"op": "offset", "topic": t}                          -> {"offset": n}
  {"op": "subscribe", "topic": t, "from": n|null}       -> stream of
      {"topic": t, "event": e, "message": m, "offset": n}   (replay + live)
  {"op": "set"/"get"/"exists"/"evict_prefix", ...}      -> cache ops
  {"op": "offset_commit"/"offset_get", ...}             -> consumer offsets
"""

from __future__ import annotations

import hmac
import json
import os
import queue
import socket
import socketserver
import threading
from typing import Any, Callable, Optional


# subscription-stream liveness: how often an idle stream emits a
# heartbeat frame (and thereby notices a dead peer)
HEARTBEAT_INTERVAL = 5.0


def _send(wfile, obj: dict) -> None:
    wfile.write(json.dumps(obj).encode() + b"\n")
    wfile.flush()


class BrokerServer:
    """Topic logs + shared KV + consumer offsets behind one TCP port.

    ``data_dir`` enables durability: every mutation is appended to a
    JSON-lines journal and replayed on construction, so topic logs,
    consumer offsets and the KV store survive broker restarts — the role
    Kafka's commit log and Redis persistence play for the reference
    (src/worker.ts:123,354-361: offsets resumed per topic at subscribe).
    The journal is append-only; it is flushed per record but, by default,
    not fsynced (a broker-process crash loses nothing already flushed;
    only a host-level crash can drop the tail).  ``fsync_interval_s``
    closes that host-crash tail-loss window: when set, the journal is
    additionally fsynced whenever at least that many seconds have passed
    since the last fsync (0 fsyncs every record — Kafka's
    flush.messages=1 posture, at the corresponding write-latency cost).
    None (the default) preserves the flush-only semantics exactly.

    ``secret`` enables authentication: the first frame of every
    connection must be {"op": "auth", "secret": ...} or the connection is
    refused — the deployed-Kafka/Redis auth the reference inherits from
    its infrastructure.  The secret travels as CLEARTEXT JSON over TCP
    (as does all topic/KV traffic): the design assumption is loopback or
    an otherwise-trusted network segment, exactly like an unencrypted
    Kafka/Redis deployment; binding a non-loopback interface logs a
    warning and calls for transport-level protection (TLS tunnel,
    private VPC)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 data_dir: Optional[str] = None,
                 secret: Optional[str] = None,
                 fsync_interval_s: Optional[float] = None):
        if host not in ("127.0.0.1", "localhost", "::1"):
            import sys as _sys

            print(
                f"WARNING: broker binding non-loopback address {host!r}: "
                "the shared secret and all bus traffic travel as cleartext "
                "TCP — use a TLS tunnel or a trusted network segment",
                file=_sys.stderr,
            )
        self._topics: dict[str, list[tuple[str, Any]]] = {}   # guarded-by: _lock
        self._kv: dict[str, Any] = {}                         # guarded-by: _lock
        self._consumer_offsets: dict[str, int] = {}           # guarded-by: _lock
        self._subscribers: dict[str, list[queue.Queue]] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self.secret = secret
        self._journal = None  # guarded-by: _lock
        self.fsync_interval_s = (
            None if fsync_interval_s is None else float(fsync_interval_s)
        )
        self._last_fsync = 0.0  # guarded-by: _lock
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            path = os.path.join(data_dir, "broker.journal")
            if os.path.exists(path):
                self._replay_journal(path)
            self._journal = open(path, "a", encoding="utf-8")
        broker = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                authed = broker.secret is None
                for line in self.rfile:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        cmd = json.loads(line)
                    except ValueError:
                        _send(self.wfile, {"error": "bad frame"})
                        continue
                    if not authed:
                        if cmd.get("op") == "auth" and hmac.compare_digest(
                            str(cmd.get("secret") or ""), broker.secret
                        ):
                            authed = True
                            _send(self.wfile, {"ok": True})
                            continue
                        _send(self.wfile, {"error": "auth required"})
                        return
                    if cmd.get("op") == "subscribe":
                        broker._serve_subscription(self, cmd)
                        return  # connection now belongs to the stream
                    try:
                        _send(self.wfile, broker._dispatch(cmd))
                    except (BrokenPipeError, ConnectionResetError):
                        return

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self.address = f"{host}:{self.port}"
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )

    def start(self) -> "BrokerServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        with self._lock:
            if self._journal is not None:
                self._journal.close()
                self._journal = None

    # ----------------------------------------------------------- durability
    # holds: _lock (trivially exclusive: runs in __init__ before the server thread starts)
    def _replay_journal(self, path: str) -> None:
        """Rebuild topics / KV / consumer offsets from the journal; a torn
        trailing record (crash mid-append) is skipped."""
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail record
                kind = rec.get("k")
                if kind == "emit":
                    self._topics.setdefault(rec["t"], []).append(
                        (rec["e"], rec.get("m"))
                    )
                elif kind == "set":
                    self._kv[rec["key"]] = rec.get("v")
                elif kind == "evict":
                    for key in [
                        k for k in self._kv if k.startswith(rec["p"])
                    ]:
                        del self._kv[key]
                elif kind == "co":
                    self._consumer_offsets[rec["t"]] = rec["o"]

    def _log(self, rec: dict) -> None:  # holds: _lock
        """Append one journal record; caller holds self._lock."""
        if self._journal is not None:
            self._journal.write(json.dumps(rec) + "\n")
            self._journal.flush()
            if self.fsync_interval_s is not None:
                import time as _time

                now = _time.monotonic()
                if now - self._last_fsync >= self.fsync_interval_s:
                    os.fsync(self._journal.fileno())
                    self._last_fsync = now

    # ------------------------------------------------------------- dispatch
    def _dispatch(self, cmd: dict) -> dict:
        op = cmd.get("op")
        if op == "emit":
            topic, event = cmd["topic"], cmd["event"]
            message = cmd.get("message")
            with self._lock:
                log = self._topics.setdefault(topic, [])
                log.append((event, message))
                offset = len(log) - 1
                self._log({"k": "emit", "t": topic, "e": event,
                           "m": message})
                subs = list(self._subscribers.get(topic, []))
            frame = {"topic": topic, "event": event,
                     "message": message, "offset": offset}
            for q in subs:
                q.put(frame)
            return {"offset": offset}
        if op == "read":
            with self._lock:
                log = list(self._topics.get(cmd["topic"], []))
            start = cmd.get("from") or 0
            return {"events": [[e, m] for e, m in log[start:]]}
        if op == "offset":
            with self._lock:
                return {"offset": len(self._topics.get(cmd["topic"], []))}
        if op == "set":
            with self._lock:
                self._kv[cmd["key"]] = cmd.get("value")
                self._log({"k": "set", "key": cmd["key"],
                           "v": cmd.get("value")})
            return {"ok": True}
        if op == "get":
            with self._lock:
                return {"value": self._kv.get(cmd["key"]),
                        "exists": cmd["key"] in self._kv}
        if op == "exists":
            with self._lock:
                return {"exists": cmd["key"] in self._kv}
        if op == "evict_prefix":
            with self._lock:
                keys = [k for k in self._kv if k.startswith(cmd["prefix"])]
                for k in keys:
                    del self._kv[k]
                if keys:
                    self._log({"k": "evict", "p": cmd["prefix"]})
            return {"evicted": len(keys)}
        if op == "offset_commit":
            with self._lock:
                self._consumer_offsets[cmd["topic"]] = cmd["offset"]
                self._log({"k": "co", "t": cmd["topic"],
                           "o": cmd["offset"]})
            return {"ok": True}
        if op == "offset_get":
            with self._lock:
                return {"offset": self._consumer_offsets.get(cmd["topic"])}
        return {"error": f"unknown op {op!r}"}

    def _serve_subscription(self, handler, cmd: dict) -> None:
        """Replay from the requested offset, then stream live frames until
        the client disconnects."""
        topic = cmd["topic"]
        q: queue.Queue = queue.Queue()
        with self._lock:
            log = list(self._topics.get(topic, []))
            self._subscribers.setdefault(topic, []).append(q)
        try:
            start = cmd.get("from")
            if start is not None:
                for offset, (event, message) in list(enumerate(log))[start:]:
                    _send(handler.wfile, {"topic": topic, "event": event,
                                          "message": message,
                                          "offset": offset})
            # live frames for offsets not covered by the replay.  The
            # bounded get + heartbeat keeps dead subscriptions from pinning
            # a thread + queue forever on idle topics: writing the
            # heartbeat to a torn connection raises and the finally block
            # reaps the queue
            replayed_to = len(log)
            while True:
                try:
                    frame = q.get(timeout=HEARTBEAT_INTERVAL)
                except queue.Empty:
                    _send(handler.wfile, {"hb": True})
                    continue
                if frame["offset"] < replayed_to and start is not None:
                    continue  # raced with the replay window
                _send(handler.wfile, frame)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            with self._lock:
                subs = self._subscribers.get(topic, [])
                if q in subs:
                    subs.remove(q)


class _Rpc:
    """One request/response connection, serialized by a lock."""

    def __init__(self, address: str, secret: Optional[str] = None):
        host, port = address.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)), timeout=30)
        self._rfile = self._sock.makefile("rb")
        self._wfile = self._sock.makefile("wb")
        self._lock = threading.Lock()
        if secret is not None:
            resp = self.call({"op": "auth", "secret": secret})
            if not resp.get("ok"):
                self.close()
                raise ConnectionError(
                    f"broker auth failed: {resp.get('error', 'rejected')}"
                )

    def call(self, obj: dict) -> dict:
        with self._lock:
            _send(self._wfile, obj)
            line = self._rfile.readline()
        if not line:
            raise ConnectionError("broker connection closed")
        resp = json.loads(line)
        if resp.get("error") == "auth required":
            raise ConnectionError(
                "broker auth required: configure events:broker:secret"
            )
        return resp

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


# subscription reconnect: jittered exponential backoff bounds (seconds).
# The pump retries forever — a broker restart mid-deployment must never
# silently end a replica's CRUD subscription (the policy-replication feed).
RECONNECT_BACKOFF_MIN = 0.05
RECONNECT_BACKOFF_MAX = 2.0


class SocketTopic:
    """Topic interface (srv/events.py) backed by the broker."""

    def __init__(self, name: str, address: str, rpc: _Rpc,
                 secret: Optional[str] = None):
        self.name = name
        self._address = address
        self._rpc = rpc
        self._secret = secret
        self._streams: list[socket.socket] = []
        self._closed = threading.Event()

    @property
    def offset(self) -> int:
        return self._rpc.call({"op": "offset", "topic": self.name})["offset"]

    def emit(self, event_name: str, message: Any) -> int:
        return self._rpc.call(
            {"op": "emit", "topic": self.name,
             "event": event_name, "message": message}
        )["offset"]

    def _open_stream(self, from_offset: Optional[int]):
        """One subscription connection: auth + subscribe handshake, returns
        (socket, rfile).  Raises on any connection/auth failure."""
        host, port = self._address.rsplit(":", 1)
        sock = socket.create_connection((host, int(port)))
        wfile = sock.makefile("wb")
        rfile = sock.makefile("rb")
        if self._secret is not None:
            _send(wfile, {"op": "auth", "secret": self._secret})
            resp = json.loads(rfile.readline() or b"{}")
            if not resp.get("ok"):
                sock.close()
                raise ConnectionError("broker auth failed for subscription")
        _send(wfile, {"op": "subscribe", "topic": self.name,
                      "from": from_offset})
        return sock, rfile

    def on(
        self,
        listener: Callable[[str, Any, dict], None],
        starting_offset: Optional[int] = None,
    ) -> None:
        """Each listener gets its own streaming connection (replay from
        ``starting_offset``, then live), dispatched from a daemon thread —
        the Kafka-consumer analog of the in-process synchronous fanout.

        The pump survives broker restarts: on a dropped connection it
        reconnects with jittered exponential backoff and resubscribes from
        the offset AFTER the last frame it delivered, so no acked frame is
        redelivered and no frame emitted during the outage is lost (the
        broker's journal preserves the log across restarts).  A listener
        subscribed live-only (``starting_offset=None``) that has not yet
        seen a frame resumes from the topic head at reconnect time."""
        sock, rfile = self._open_stream(starting_offset)
        self._streams.append(sock)
        # mutable last-delivered offset, shared with close(): -1 = nothing
        # delivered yet
        state = {"last": (starting_offset - 1
                          if starting_offset is not None else -1)}

        def pump():
            import random as _random
            import time as _time

            nonlocal sock, rfile
            backoff = RECONNECT_BACKOFF_MIN
            while not self._closed.is_set():
                try:
                    for line in rfile:
                        frame = json.loads(line)
                        if "hb" in frame:  # liveness probe, not an event
                            continue
                        listener(
                            frame["event"], frame["message"],
                            {"offset": frame["offset"], "topic": self.name},
                        )
                        state["last"] = frame["offset"]
                        backoff = RECONNECT_BACKOFF_MIN
                    # EOF: broker closed the stream (restart/shutdown)
                except (OSError, ValueError):
                    pass
                if self._closed.is_set():
                    return
                # reconnect loop: resume from the frame after the last
                # delivered one (live-only streams that never saw a frame
                # resume live — from=None)
                while not self._closed.is_set():
                    _time.sleep(backoff * (1.0 + _random.random()))
                    backoff = min(backoff * 2.0, RECONNECT_BACKOFF_MAX)
                    try:
                        resume = (state["last"] + 1
                                  if state["last"] >= 0 else starting_offset)
                        new_sock, new_rfile = self._open_stream(resume)
                    except (OSError, ConnectionError, ValueError):
                        continue
                    if sock in self._streams:
                        self._streams.remove(sock)
                    sock, rfile = new_sock, new_rfile
                    self._streams.append(sock)
                    break

        threading.Thread(target=pump, daemon=True).start()

    def read(self, from_offset: int = 0) -> list[tuple[str, Any]]:
        events = self._rpc.call(
            {"op": "read", "topic": self.name, "from": from_offset}
        )["events"]
        return [(e, m) for e, m in events]

    def close(self) -> None:
        # stop pumps from reconnecting before tearing their connections
        self._closed.set()
        for sock in list(self._streams):
            # shutdown, not just close: the pump thread's makefile objects
            # hold fd references (socket._io_refs), so close() alone never
            # tears the connection — the broker would keep heartbeating a
            # zombie stream and the pump thread would block forever
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


class SocketEventBus:
    """EventBus interface (srv/events.py) backed by a broker process."""

    def __init__(self, address: str, secret: Optional[str] = None):
        self.address = address
        self._secret = secret
        self._rpc = _Rpc(address, secret=secret)
        self._topics: dict[str, SocketTopic] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def topic(self, name: str) -> SocketTopic:
        with self._lock:
            if name not in self._topics:
                self._topics[name] = SocketTopic(
                    name, self.address, self._rpc, secret=self._secret
                )
            return self._topics[name]

    def topics(self) -> dict[str, SocketTopic]:
        with self._lock:
            return dict(self._topics)

    def close(self) -> None:
        with self._lock:
            topics = list(self._topics.values())
        for topic in topics:
            topic.close()
        self._rpc.close()


class SocketSubjectCache:
    """SubjectCache interface (srv/cache.py) backed by the broker KV —
    the shared-Redis role: every worker process sees the same subject /
    HR-scope entries."""

    def __init__(self, address: str, secret: Optional[str] = None):
        self._rpc = _Rpc(address, secret=secret)

    def get(self, key: str) -> Any:
        return self._rpc.call({"op": "get", "key": key})["value"]

    def set(self, key: str, value: Any) -> None:
        self._rpc.call({"op": "set", "key": key, "value": value})

    def exists(self, key: str) -> bool:
        return self._rpc.call({"op": "exists", "key": key})["exists"]

    def evict_prefix(self, prefix: str) -> int:
        return self._rpc.call(
            {"op": "evict_prefix", "prefix": prefix}
        )["evicted"]

    def close(self) -> None:
        self._rpc.close()


class SocketOffsetStore:
    """OffsetStore interface (srv/events.py) on the broker (the chassis
    Redis DB-0 role)."""

    def __init__(self, address: str, secret: Optional[str] = None):
        self._rpc = _Rpc(address, secret=secret)

    def commit(self, topic: str, offset: int) -> None:
        self._rpc.call(
            {"op": "offset_commit", "topic": topic, "offset": offset}
        )

    def get(self, topic: str) -> Optional[int]:
        return self._rpc.call({"op": "offset_get", "topic": topic})["offset"]

    def close(self) -> None:
        self._rpc.close()
