"""Cross-process event/cache backend over TCP.

The in-process ``EventBus``/``SubjectCache`` (srv/events.py, srv/cache.py)
implement the reference's Kafka-topic and Redis-cache ROLES inside one
process.  This module provides the inter-process implementation behind the
same interfaces: a small broker process holds the topic logs (offsets,
replay) and the shared key-value store; workers connect over TCP with
newline-delimited JSON frames.

Mirrors the reference deployment shape (Kafka broker + Redis server as
separate processes, cfg/config.json events.kafka / redis): the HR-scope
rendezvous — request emitted by one process, response produced by another
(reference: src/core/accessController.ts:753-767, src/worker.ts:252-299)
— runs across OS processes (tests/test_broker.py drives it with a real
child process).

Protocol (one JSON object per line):
  {"op": "emit", "topic": t, "event": e, "message": m} -> {"offset": n}
  {"op": "read", "topic": t, "from": n}                -> {"events": [...]}
  {"op": "offset", "topic": t}                          -> {"offset": n}
  {"op": "subscribe", "topic": t, "from": n|null}       -> stream of
      {"topic": t, "event": e, "message": m, "offset": n}   (replay + live)
  {"op": "set"/"get"/"exists"/"evict_prefix", ...}      -> cache ops
  {"op": "offset_commit"/"offset_get", ...}             -> consumer offsets
"""

from __future__ import annotations

import json
import queue
import socket
import socketserver
import threading
from typing import Any, Callable, Optional


# subscription-stream liveness: how often an idle stream emits a
# heartbeat frame (and thereby notices a dead peer)
HEARTBEAT_INTERVAL = 5.0


def _send(wfile, obj: dict) -> None:
    wfile.write(json.dumps(obj).encode() + b"\n")
    wfile.flush()


class BrokerServer:
    """Topic logs + shared KV + consumer offsets behind one TCP port."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._topics: dict[str, list[tuple[str, Any]]] = {}
        self._kv: dict[str, Any] = {}
        self._consumer_offsets: dict[str, int] = {}
        self._subscribers: dict[str, list[queue.Queue]] = {}
        self._lock = threading.Lock()
        broker = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                for line in self.rfile:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        cmd = json.loads(line)
                    except ValueError:
                        _send(self.wfile, {"error": "bad frame"})
                        continue
                    if cmd.get("op") == "subscribe":
                        broker._serve_subscription(self, cmd)
                        return  # connection now belongs to the stream
                    try:
                        _send(self.wfile, broker._dispatch(cmd))
                    except (BrokenPipeError, ConnectionResetError):
                        return

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self.address = f"{host}:{self.port}"
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )

    def start(self) -> "BrokerServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    # ------------------------------------------------------------- dispatch
    def _dispatch(self, cmd: dict) -> dict:
        op = cmd.get("op")
        if op == "emit":
            topic, event = cmd["topic"], cmd["event"]
            message = cmd.get("message")
            with self._lock:
                log = self._topics.setdefault(topic, [])
                log.append((event, message))
                offset = len(log) - 1
                subs = list(self._subscribers.get(topic, []))
            frame = {"topic": topic, "event": event,
                     "message": message, "offset": offset}
            for q in subs:
                q.put(frame)
            return {"offset": offset}
        if op == "read":
            with self._lock:
                log = list(self._topics.get(cmd["topic"], []))
            start = cmd.get("from") or 0
            return {"events": [[e, m] for e, m in log[start:]]}
        if op == "offset":
            with self._lock:
                return {"offset": len(self._topics.get(cmd["topic"], []))}
        if op == "set":
            with self._lock:
                self._kv[cmd["key"]] = cmd.get("value")
            return {"ok": True}
        if op == "get":
            with self._lock:
                return {"value": self._kv.get(cmd["key"]),
                        "exists": cmd["key"] in self._kv}
        if op == "exists":
            with self._lock:
                return {"exists": cmd["key"] in self._kv}
        if op == "evict_prefix":
            with self._lock:
                keys = [k for k in self._kv if k.startswith(cmd["prefix"])]
                for k in keys:
                    del self._kv[k]
            return {"evicted": len(keys)}
        if op == "offset_commit":
            with self._lock:
                self._consumer_offsets[cmd["topic"]] = cmd["offset"]
            return {"ok": True}
        if op == "offset_get":
            with self._lock:
                return {"offset": self._consumer_offsets.get(cmd["topic"])}
        return {"error": f"unknown op {op!r}"}

    def _serve_subscription(self, handler, cmd: dict) -> None:
        """Replay from the requested offset, then stream live frames until
        the client disconnects."""
        topic = cmd["topic"]
        q: queue.Queue = queue.Queue()
        with self._lock:
            log = list(self._topics.get(topic, []))
            self._subscribers.setdefault(topic, []).append(q)
        try:
            start = cmd.get("from")
            if start is not None:
                for offset, (event, message) in list(enumerate(log))[start:]:
                    _send(handler.wfile, {"topic": topic, "event": event,
                                          "message": message,
                                          "offset": offset})
            # live frames for offsets not covered by the replay.  The
            # bounded get + heartbeat keeps dead subscriptions from pinning
            # a thread + queue forever on idle topics: writing the
            # heartbeat to a torn connection raises and the finally block
            # reaps the queue
            replayed_to = len(log)
            while True:
                try:
                    frame = q.get(timeout=HEARTBEAT_INTERVAL)
                except queue.Empty:
                    _send(handler.wfile, {"hb": True})
                    continue
                if frame["offset"] < replayed_to and start is not None:
                    continue  # raced with the replay window
                _send(handler.wfile, frame)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            with self._lock:
                subs = self._subscribers.get(topic, [])
                if q in subs:
                    subs.remove(q)


class _Rpc:
    """One request/response connection, serialized by a lock."""

    def __init__(self, address: str):
        host, port = address.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)), timeout=30)
        self._rfile = self._sock.makefile("rb")
        self._wfile = self._sock.makefile("wb")
        self._lock = threading.Lock()

    def call(self, obj: dict) -> dict:
        with self._lock:
            _send(self._wfile, obj)
            line = self._rfile.readline()
        if not line:
            raise ConnectionError("broker connection closed")
        return json.loads(line)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class SocketTopic:
    """Topic interface (srv/events.py) backed by the broker."""

    def __init__(self, name: str, address: str, rpc: _Rpc):
        self.name = name
        self._address = address
        self._rpc = rpc
        self._streams: list[socket.socket] = []

    @property
    def offset(self) -> int:
        return self._rpc.call({"op": "offset", "topic": self.name})["offset"]

    def emit(self, event_name: str, message: Any) -> int:
        return self._rpc.call(
            {"op": "emit", "topic": self.name,
             "event": event_name, "message": message}
        )["offset"]

    def on(
        self,
        listener: Callable[[str, Any, dict], None],
        starting_offset: Optional[int] = None,
    ) -> None:
        """Each listener gets its own streaming connection (replay from
        ``starting_offset``, then live), dispatched from a daemon thread —
        the Kafka-consumer analog of the in-process synchronous fanout."""
        host, port = self._address.rsplit(":", 1)
        sock = socket.create_connection((host, int(port)))
        wfile = sock.makefile("wb")
        rfile = sock.makefile("rb")
        _send(wfile, {"op": "subscribe", "topic": self.name,
                      "from": starting_offset})
        self._streams.append(sock)

        def pump():
            try:
                for line in rfile:
                    frame = json.loads(line)
                    if "hb" in frame:  # stream liveness probe, not an event
                        continue
                    listener(
                        frame["event"], frame["message"],
                        {"offset": frame["offset"], "topic": self.name},
                    )
            except (OSError, ValueError):
                pass

        threading.Thread(target=pump, daemon=True).start()

    def read(self, from_offset: int = 0) -> list[tuple[str, Any]]:
        events = self._rpc.call(
            {"op": "read", "topic": self.name, "from": from_offset}
        )["events"]
        return [(e, m) for e, m in events]

    def close(self) -> None:
        for sock in self._streams:
            # shutdown, not just close: the pump thread's makefile objects
            # hold fd references (socket._io_refs), so close() alone never
            # tears the connection — the broker would keep heartbeating a
            # zombie stream and the pump thread would block forever
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


class SocketEventBus:
    """EventBus interface (srv/events.py) backed by a broker process."""

    def __init__(self, address: str):
        self.address = address
        self._rpc = _Rpc(address)
        self._topics: dict[str, SocketTopic] = {}
        self._lock = threading.Lock()

    def topic(self, name: str) -> SocketTopic:
        with self._lock:
            if name not in self._topics:
                self._topics[name] = SocketTopic(name, self.address, self._rpc)
            return self._topics[name]

    def topics(self) -> dict[str, SocketTopic]:
        return dict(self._topics)

    def close(self) -> None:
        for topic in self._topics.values():
            topic.close()
        self._rpc.close()


class SocketSubjectCache:
    """SubjectCache interface (srv/cache.py) backed by the broker KV —
    the shared-Redis role: every worker process sees the same subject /
    HR-scope entries."""

    def __init__(self, address: str):
        self._rpc = _Rpc(address)

    def get(self, key: str) -> Any:
        return self._rpc.call({"op": "get", "key": key})["value"]

    def set(self, key: str, value: Any) -> None:
        self._rpc.call({"op": "set", "key": key, "value": value})

    def exists(self, key: str) -> bool:
        return self._rpc.call({"op": "exists", "key": key})["exists"]

    def evict_prefix(self, prefix: str) -> int:
        return self._rpc.call(
            {"op": "evict_prefix", "prefix": prefix}
        )["evicted"]

    def close(self) -> None:
        self._rpc.close()


class SocketOffsetStore:
    """OffsetStore interface (srv/events.py) on the broker (the chassis
    Redis DB-0 role)."""

    def __init__(self, address: str):
        self._rpc = _Rpc(address)

    def commit(self, topic: str, offset: int) -> None:
        self._rpc.call(
            {"op": "offset_commit", "topic": topic, "offset": offset}
        )

    def get(self, topic: str) -> Optional[int]:
        return self._rpc.call({"op": "offset_get", "topic": topic})["offset"]

    def close(self) -> None:
        self._rpc.close()
