"""Cross-process event/cache backend over TCP.

The in-process ``EventBus``/``SubjectCache`` (srv/events.py, srv/cache.py)
implement the reference's Kafka-topic and Redis-cache ROLES inside one
process.  This module provides the inter-process implementation behind the
same interfaces: a small broker process holds the topic logs (offsets,
replay) and the shared key-value store; workers connect over TCP with
newline-delimited JSON frames.

Mirrors the reference deployment shape (Kafka broker + Redis server as
separate processes, cfg/config.json events.kafka / redis): the HR-scope
rendezvous — request emitted by one process, response produced by another
(reference: src/core/accessController.ts:753-767, src/worker.ts:252-299)
— runs across OS processes (tests/test_broker.py drives it with a real
child process).

Protocol (one JSON object per line):
  {"op": "emit", "topic": t, "event": e, "message": m} -> {"offset": n}
  {"op": "read", "topic": t, "from": n}                -> {"events": [...]}
  {"op": "offset", "topic": t}                          -> {"offset": n}
  {"op": "subscribe", "topic": t, "from": n|null}       -> stream of
      {"topic": t, "event": e, "message": m, "offset": n}   (replay + live)
  {"op": "set"/"get"/"exists"/"evict_prefix", ...}      -> cache ops
  {"op": "offset_commit"/"offset_get", ...}             -> consumer offsets
"""

from __future__ import annotations

import hmac
import json
import os
import queue
import socket
import socketserver
import threading
import time
import zlib
from typing import Any, Callable, Optional

from .faults import REGISTRY as FAULTS


# subscription-stream liveness: how often an idle stream emits a
# heartbeat frame (and thereby notices a dead peer)
HEARTBEAT_INTERVAL = 5.0

# journal line format: "C<crc32 hex8> <json>" — the CRC covers the JSON
# text, so a torn append OR a flipped byte anywhere in the file fails
# closed at replay (the consistent prefix is kept, the rest dropped).
# Bare-JSON lines (pre-CRC journals, seeded journals) replay unchanged.
_CRC_PREFIX_LEN = 10  # "C" + 8 hex + " "


def _journal_line(rec: dict) -> str:
    body = json.dumps(rec)
    return f"C{zlib.crc32(body.encode()) & 0xFFFFFFFF:08x} {body}\n"


def _decode_journal_line(line: str) -> Optional[dict]:
    """One journal line -> record dict, or None when torn/corrupt."""
    if line.startswith("C") and len(line) > _CRC_PREFIX_LEN \
            and line[_CRC_PREFIX_LEN - 1] == " ":
        try:
            want = int(line[1:_CRC_PREFIX_LEN - 1], 16)
        except ValueError:
            return None
        body = line[_CRC_PREFIX_LEN:]
        if zlib.crc32(body.encode()) & 0xFFFFFFFF != want:
            return None
    else:
        body = line
    try:
        rec = json.loads(body)
    except ValueError:
        return None
    return rec if isinstance(rec, dict) else None


def _send(wfile, obj: dict) -> None:
    wfile.write(json.dumps(obj).encode() + b"\n")
    wfile.flush()


class BrokerServer:
    """Topic logs + shared KV + consumer offsets behind one TCP port.

    ``data_dir`` enables durability: every mutation is appended to a
    JSON-lines journal and replayed on construction, so topic logs,
    consumer offsets and the KV store survive broker restarts — the role
    Kafka's commit log and Redis persistence play for the reference
    (src/worker.ts:123,354-361: offsets resumed per topic at subscribe).
    The journal is append-only; it is flushed per record but, by default,
    not fsynced (a broker-process crash loses nothing already flushed;
    only a host-level crash can drop the tail).  ``fsync_interval_s``
    closes that host-crash tail-loss window: when set, the journal is
    additionally fsynced whenever at least that many seconds have passed
    since the last fsync (0 fsyncs every record — Kafka's
    flush.messages=1 posture, at the corresponding write-latency cost).
    None (the default) preserves the flush-only semantics exactly.

    ``secret`` enables authentication: the first frame of every
    connection must be {"op": "auth", "secret": ...} or the connection is
    refused — the deployed-Kafka/Redis auth the reference inherits from
    its infrastructure.  The secret travels as CLEARTEXT JSON over TCP
    (as does all topic/KV traffic): the design assumption is loopback or
    an otherwise-trusted network segment, exactly like an unencrypted
    Kafka/Redis deployment; binding a non-loopback interface logs a
    warning and calls for transport-level protection (TLS tunnel,
    private VPC)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 data_dir: Optional[str] = None,
                 secret: Optional[str] = None,
                 fsync_interval_s: Optional[float] = None,
                 snapshot_every: Optional[int] = None):
        if host not in ("127.0.0.1", "localhost", "::1"):
            import sys as _sys

            print(
                f"WARNING: broker binding non-loopback address {host!r}: "
                "the shared secret and all bus traffic travel as cleartext "
                "TCP — use a TLS tunnel or a trusted network segment",
                file=_sys.stderr,
            )
        self._topics: dict[str, list[tuple[str, Any]]] = {}   # guarded-by: _lock
        self._kv: dict[str, Any] = {}                         # guarded-by: _lock
        self._consumer_offsets: dict[str, int] = {}           # guarded-by: _lock
        self._subscribers: dict[str, list[queue.Queue]] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self.secret = secret
        self._journal = None  # guarded-by: _lock
        self.fsync_interval_s = (
            None if fsync_interval_s is None else float(fsync_interval_s)
        )
        self._last_fsync = 0.0  # guarded-by: _lock
        # snapshot + compaction (docs/FAULTS.md): every `snapshot_every`
        # journaled records the full topic/KV/offset state is written
        # crash-consistently (temp + fsync + rename) at an offset
        # watermark and the journal truncated behind it, so boot-by-
        # replay cost is bounded regardless of churn history.
        self._data_dir = data_dir
        self.snapshot_every = (
            None if not snapshot_every else int(snapshot_every)
        )
        self._watermark = 0       # guarded-by: _lock — records in snapshot
        self._tail_records = 0    # guarded-by: _lock — records since it
        self._snapshot_taken: Optional[float] = None  # guarded-by: _lock
        self.recovered: Optional[dict] = None  # journal-truncation report
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            self._journal_path = os.path.join(data_dir, "broker.journal")
            self._snapshot_path = os.path.join(data_dir, "broker.snapshot")
            self._load_snapshot()
            if os.path.exists(self._journal_path):
                self._replay_journal(self._journal_path)
            self._journal = open(self._journal_path, "a", encoding="utf-8")
        broker = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                authed = broker.secret is None
                for line in self.rfile:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        cmd = json.loads(line)
                    except ValueError:
                        _send(self.wfile, {"error": "bad frame"})
                        continue
                    if not authed:
                        if cmd.get("op") == "auth" and hmac.compare_digest(
                            str(cmd.get("secret") or ""), broker.secret
                        ):
                            authed = True
                            _send(self.wfile, {"ok": True})
                            continue
                        _send(self.wfile, {"error": "auth required"})
                        return
                    if cmd.get("op") == "subscribe":
                        broker._serve_subscription(self, cmd)
                        return  # connection now belongs to the stream
                    try:
                        _send(self.wfile, broker._dispatch(cmd))
                    except (BrokenPipeError, ConnectionResetError):
                        return

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self.address = f"{host}:{self.port}"
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )

    def start(self) -> "BrokerServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        with self._lock:
            if self._journal is not None:
                self._journal.close()
                self._journal = None

    # ----------------------------------------------------------- durability
    # holds: _lock (trivially exclusive: runs in __init__ before the server thread starts)
    def _load_snapshot(self) -> None:
        """Restore topic/KV/offset state from the snapshot file (the
        boot base the journal tail replays on top of).  A corrupt
        snapshot fails closed: ignored, boot falls back to whatever the
        journal holds."""
        path = self._snapshot_path
        if not os.path.exists(path):
            return
        try:
            with open(path, "r", encoding="utf-8") as fh:
                blob = json.load(fh)
            state_json = blob["state"]
            if zlib.crc32(state_json.encode()) & 0xFFFFFFFF != blob["crc"]:
                raise ValueError("snapshot CRC mismatch")
            state = json.loads(state_json)
        except (OSError, ValueError, KeyError, TypeError) as err:
            self.recovered = {"snapshot_error": repr(err)}
            return
        self._topics = {
            t: [(e, m) for e, m in log]
            for t, log in state.get("topics", {}).items()
        }
        self._kv = dict(state.get("kv", {}))
        self._consumer_offsets = dict(state.get("consumer_offsets", {}))
        self._watermark = int(state.get("watermark", 0))
        self._snapshot_taken = time.monotonic()

    # holds: _lock (trivially exclusive: runs in __init__ before the server thread starts)
    def _replay_journal(self, path: str) -> None:
        """Rebuild state from the journal tail (on top of any snapshot).
        The first torn or CRC-corrupt record ends the replay — the
        consistent prefix is kept and the file is truncated there, so a
        crash mid-append or a flipped byte mid-file can never be
        followed by silently re-ordered state."""
        truncate_at: Optional[int] = None
        offset = 0
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                line_len = len(line.encode("utf-8"))
                stripped = line.strip()
                if not stripped:
                    offset += line_len
                    continue
                rec = _decode_journal_line(stripped)
                if rec is None or not line.endswith("\n"):
                    truncate_at = offset
                    break
                offset += line_len
                self._apply_record(rec)
                self._tail_records += 1
        if truncate_at is not None:
            size = os.path.getsize(path)
            self.recovered = {
                "truncated_at": truncate_at,
                "dropped_bytes": size - truncate_at,
            }
            with open(path, "r+", encoding="utf-8") as fh:
                fh.truncate(truncate_at)

    def _apply_record(self, rec: dict) -> None:  # holds: _lock
        kind = rec.get("k")
        if kind == "emit":
            self._topics.setdefault(rec["t"], []).append(
                (rec["e"], rec.get("m"))
            )
        elif kind == "set":
            self._kv[rec["key"]] = rec.get("v")
        elif kind == "evict":
            for key in [
                k for k in self._kv if k.startswith(rec["p"])
            ]:
                del self._kv[key]
        elif kind == "co":
            self._consumer_offsets[rec["t"]] = rec["o"]

    def _log(self, rec: dict) -> None:  # holds: _lock
        """Append one journal record; caller holds self._lock."""
        if self._journal is not None:
            payload = _journal_line(rec)
            # failpoints (srv/faults.py): torn truncates the append
            # mid-record (replay CRC catches it); error/delay/hang act
            # as a failing/slow disk
            payload = FAULTS.tear("broker.journal.write", payload)
            self._journal.write(payload)
            self._journal.flush()
            self._tail_records += 1
            if self.fsync_interval_s is not None:
                now = time.monotonic()
                if now - self._last_fsync >= self.fsync_interval_s:
                    FAULTS.fire("broker.journal.fsync")
                    os.fsync(self._journal.fileno())
                    self._last_fsync = now
            if (self.snapshot_every is not None
                    and self._tail_records >= self.snapshot_every):
                self._snapshot_locked()

    def _snapshot_locked(self) -> None:  # holds: _lock
        """Crash-consistent snapshot at the current offset watermark:
        serialize full state, temp + fsync + rename, fsync the
        directory, then truncate the journal behind it.  A crash at ANY
        point leaves either (old snapshot + full journal) or (new
        snapshot + empty-or-newer journal) — never a torn mix."""
        if self._journal is None:
            return
        state = {
            "watermark": self._watermark + self._tail_records,
            "topics": {
                t: [[e, m] for e, m in log]
                for t, log in self._topics.items()
            },
            "kv": self._kv,
            "consumer_offsets": self._consumer_offsets,
        }
        state_json = json.dumps(state, separators=(",", ":"))
        blob = json.dumps({
            "version": 1,
            "crc": zlib.crc32(state_json.encode()) & 0xFFFFFFFF,
            "state": state_json,
        })
        tmp = self._snapshot_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(blob)
            fh.flush()
            # acs-lint: ignore[blocking-under-lock] snapshot atomicity: the
            # journal must stay frozen across the durability point, same
            # trade as the journal fsync itself
            os.fsync(fh.fileno())
        os.replace(tmp, self._snapshot_path)
        try:
            dir_fd = os.open(self._data_dir, os.O_RDONLY)
            try:
                # acs-lint: ignore[blocking-under-lock] see temp-file fsync
                os.fsync(dir_fd)
            finally:
                os.close(dir_fd)
        except OSError:
            pass  # non-POSIX dir-fsync: rename durability is best-effort
        # compaction: the journal restarts empty behind the snapshot
        self._journal.close()
        self._journal = open(self._journal_path, "w", encoding="utf-8")
        self._watermark = state["watermark"]
        self._tail_records = 0
        self._last_fsync = 0.0
        self._snapshot_taken = time.monotonic()

    def snapshot_now(self) -> dict:
        """Force a snapshot (command surface + tests); returns status."""
        with self._lock:
            if self._journal is not None:
                self._snapshot_locked()
        return self.snapshot_status()

    def snapshot_status(self) -> dict:
        with self._lock:
            taken = self._snapshot_taken
            return {
                "exists": bool(
                    self._data_dir
                    and os.path.exists(self._snapshot_path)
                ),
                "watermark": self._watermark,
                "tail_records": self._tail_records,
                "age_s": (None if taken is None
                          else time.monotonic() - taken),
                "recovered": self.recovered,
            }

    # ------------------------------------------------------------- dispatch
    def _dispatch(self, cmd: dict) -> dict:
        op = cmd.get("op")
        if op == "emit":
            topic, event = cmd["topic"], cmd["event"]
            message = cmd.get("message")
            with self._lock:
                log = self._topics.setdefault(topic, [])
                log.append((event, message))
                offset = len(log) - 1
                self._log({"k": "emit", "t": topic, "e": event,
                           "m": message})
                subs = list(self._subscribers.get(topic, []))
            frame = {"topic": topic, "event": event,
                     "message": message, "offset": offset}
            for q in subs:
                q.put(frame)
            return {"offset": offset}
        if op == "read":
            with self._lock:
                log = list(self._topics.get(cmd["topic"], []))
            start = cmd.get("from") or 0
            return {"events": [[e, m] for e, m in log[start:]]}
        if op == "offset":
            with self._lock:
                return {"offset": len(self._topics.get(cmd["topic"], []))}
        if op == "set":
            with self._lock:
                self._kv[cmd["key"]] = cmd.get("value")
                self._log({"k": "set", "key": cmd["key"],
                           "v": cmd.get("value")})
            return {"ok": True}
        if op == "get":
            with self._lock:
                return {"value": self._kv.get(cmd["key"]),
                        "exists": cmd["key"] in self._kv}
        if op == "exists":
            with self._lock:
                return {"exists": cmd["key"] in self._kv}
        if op == "evict_prefix":
            with self._lock:
                keys = [k for k in self._kv if k.startswith(cmd["prefix"])]
                for k in keys:
                    del self._kv[k]
                if keys:
                    self._log({"k": "evict", "p": cmd["prefix"]})
            return {"evicted": len(keys)}
        if op == "offset_commit":
            with self._lock:
                self._consumer_offsets[cmd["topic"]] = cmd["offset"]
                self._log({"k": "co", "t": cmd["topic"],
                           "o": cmd["offset"]})
            return {"ok": True}
        if op == "offset_get":
            with self._lock:
                return {"offset": self._consumer_offsets.get(cmd["topic"])}
        if op == "snapshot_status":
            return self.snapshot_status()
        if op == "snapshot":
            return self.snapshot_now()
        return {"error": f"unknown op {op!r}"}

    def _serve_subscription(self, handler, cmd: dict) -> None:
        """Replay from the requested offset, then stream live frames until
        the client disconnects."""
        topic = cmd["topic"]
        q: queue.Queue = queue.Queue()
        with self._lock:
            log = list(self._topics.get(topic, []))
            self._subscribers.setdefault(topic, []).append(q)
        try:
            start = cmd.get("from")
            if start is not None:
                for offset, (event, message) in list(enumerate(log))[start:]:
                    _send(handler.wfile, {"topic": topic, "event": event,
                                          "message": message,
                                          "offset": offset})
            # live frames for offsets not covered by the replay.  The
            # bounded get + heartbeat keeps dead subscriptions from pinning
            # a thread + queue forever on idle topics: writing the
            # heartbeat to a torn connection raises and the finally block
            # reaps the queue
            replayed_to = len(log)
            while True:
                try:
                    frame = q.get(timeout=HEARTBEAT_INTERVAL)
                except queue.Empty:
                    _send(handler.wfile, {"hb": True})
                    continue
                if frame["offset"] < replayed_to and start is not None:
                    continue  # raced with the replay window
                _send(handler.wfile, frame)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            with self._lock:
                subs = self._subscribers.get(topic, [])
                if q in subs:
                    subs.remove(q)


class _Rpc:
    """One request/response connection, serialized by a lock."""

    def __init__(self, address: str, secret: Optional[str] = None):
        host, port = address.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)), timeout=30)
        self._rfile = self._sock.makefile("rb")
        self._wfile = self._sock.makefile("wb")
        self._lock = threading.Lock()
        if secret is not None:
            resp = self.call({"op": "auth", "secret": secret})
            if not resp.get("ok"):
                self.close()
                raise ConnectionError(
                    f"broker auth failed: {resp.get('error', 'rejected')}"
                )

    def call(self, obj: dict) -> dict:
        with self._lock:
            _send(self._wfile, obj)
            line = self._rfile.readline()
        if not line:
            raise ConnectionError("broker connection closed")
        resp = json.loads(line)
        if resp.get("error") == "auth required":
            raise ConnectionError(
                "broker auth required: configure events:broker:secret"
            )
        return resp

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


# subscription reconnect: jittered exponential backoff bounds (seconds).
# The pump retries forever — a broker restart mid-deployment must never
# silently end a replica's CRUD subscription (the policy-replication feed).
RECONNECT_BACKOFF_MIN = 0.05
RECONNECT_BACKOFF_MAX = 2.0


class SocketTopic:
    """Topic interface (srv/events.py) backed by the broker."""

    def __init__(self, name: str, address: str, rpc: _Rpc,
                 secret: Optional[str] = None):
        self.name = name
        self._address = address
        self._rpc = rpc
        self._secret = secret
        self._streams: list[socket.socket] = []
        self._closed = threading.Event()

    @property
    def offset(self) -> int:
        return self._rpc.call({"op": "offset", "topic": self.name})["offset"]

    def emit(self, event_name: str, message: Any) -> int:
        return self._rpc.call(
            {"op": "emit", "topic": self.name,
             "event": event_name, "message": message}
        )["offset"]

    def _open_stream(self, from_offset: Optional[int]):
        """One subscription connection: auth + subscribe handshake, returns
        (socket, rfile).  Raises on any connection/auth failure."""
        host, port = self._address.rsplit(":", 1)
        sock = socket.create_connection((host, int(port)))
        wfile = sock.makefile("wb")
        rfile = sock.makefile("rb")
        if self._secret is not None:
            _send(wfile, {"op": "auth", "secret": self._secret})
            resp = json.loads(rfile.readline() or b"{}")
            if not resp.get("ok"):
                sock.close()
                raise ConnectionError("broker auth failed for subscription")
        _send(wfile, {"op": "subscribe", "topic": self.name,
                      "from": from_offset})
        return sock, rfile

    def on(
        self,
        listener: Callable[[str, Any, dict], None],
        starting_offset: Optional[int] = None,
    ) -> None:
        """Each listener gets its own streaming connection (replay from
        ``starting_offset``, then live), dispatched from a daemon thread —
        the Kafka-consumer analog of the in-process synchronous fanout.

        The pump survives broker restarts: on a dropped connection it
        reconnects with jittered exponential backoff and resubscribes from
        the offset AFTER the last frame it delivered, so no acked frame is
        redelivered and no frame emitted during the outage is lost (the
        broker's journal preserves the log across restarts).  A listener
        subscribed live-only (``starting_offset=None``) that has not yet
        seen a frame resumes from the topic head at reconnect time."""
        sock, rfile = self._open_stream(starting_offset)
        self._streams.append(sock)
        # mutable last-delivered offset, shared with close(): -1 = nothing
        # delivered yet
        state = {"last": (starting_offset - 1
                          if starting_offset is not None else -1)}

        def pump():
            import random as _random
            import time as _time

            nonlocal sock, rfile
            backoff = RECONNECT_BACKOFF_MIN
            while not self._closed.is_set():
                try:
                    for line in rfile:
                        frame = json.loads(line)
                        if "hb" in frame:  # liveness probe, not an event
                            continue
                        # failpoint: a dropped/slow subscription — error
                        # takes the exact reconnect path a real torn
                        # connection would (OSError below)
                        FAULTS.fire(
                            "broker.topic.pump",
                            exc=lambda: OSError(
                                "fault injected at broker.topic.pump"
                            ),
                        )
                        listener(
                            frame["event"], frame["message"],
                            {"offset": frame["offset"], "topic": self.name},
                        )
                        state["last"] = frame["offset"]
                        backoff = RECONNECT_BACKOFF_MIN
                    # EOF: broker closed the stream (restart/shutdown)
                except (OSError, ValueError):
                    pass
                if self._closed.is_set():
                    return
                # reconnect loop: resume from the frame after the last
                # delivered one (live-only streams that never saw a frame
                # resume live — from=None)
                while not self._closed.is_set():
                    _time.sleep(backoff * (1.0 + _random.random()))
                    backoff = min(backoff * 2.0, RECONNECT_BACKOFF_MAX)
                    try:
                        resume = (state["last"] + 1
                                  if state["last"] >= 0 else starting_offset)
                        new_sock, new_rfile = self._open_stream(resume)
                    except (OSError, ConnectionError, ValueError):
                        continue
                    if sock in self._streams:
                        self._streams.remove(sock)
                    sock, rfile = new_sock, new_rfile
                    self._streams.append(sock)
                    break

        threading.Thread(target=pump, daemon=True).start()

    def read(self, from_offset: int = 0) -> list[tuple[str, Any]]:
        events = self._rpc.call(
            {"op": "read", "topic": self.name, "from": from_offset}
        )["events"]
        return [(e, m) for e, m in events]

    def close(self) -> None:
        # stop pumps from reconnecting before tearing their connections
        self._closed.set()
        for sock in list(self._streams):
            # shutdown, not just close: the pump thread's makefile objects
            # hold fd references (socket._io_refs), so close() alone never
            # tears the connection — the broker would keep heartbeating a
            # zombie stream and the pump thread would block forever
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


class SocketEventBus:
    """EventBus interface (srv/events.py) backed by a broker process."""

    def __init__(self, address: str, secret: Optional[str] = None):
        self.address = address
        self._secret = secret
        self._rpc = _Rpc(address, secret=secret)
        self._topics: dict[str, SocketTopic] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def topic(self, name: str) -> SocketTopic:
        with self._lock:
            if name not in self._topics:
                self._topics[name] = SocketTopic(
                    name, self.address, self._rpc, secret=self._secret
                )
            return self._topics[name]

    def topics(self) -> dict[str, SocketTopic]:
        with self._lock:
            return dict(self._topics)

    def snapshot_status(self) -> dict:
        return self._rpc.call({"op": "snapshot_status"})

    def snapshot(self) -> dict:
        """Force a broker snapshot + journal compaction now."""
        return self._rpc.call({"op": "snapshot"})

    def close(self) -> None:
        with self._lock:
            topics = list(self._topics.values())
        for topic in topics:
            topic.close()
        self._rpc.close()


class SocketSubjectCache:
    """SubjectCache interface (srv/cache.py) backed by the broker KV —
    the shared-Redis role: every worker process sees the same subject /
    HR-scope entries."""

    def __init__(self, address: str, secret: Optional[str] = None):
        self._rpc = _Rpc(address, secret=secret)

    def get(self, key: str) -> Any:
        return self._rpc.call({"op": "get", "key": key})["value"]

    def set(self, key: str, value: Any) -> None:
        self._rpc.call({"op": "set", "key": key, "value": value})

    def exists(self, key: str) -> bool:
        return self._rpc.call({"op": "exists", "key": key})["exists"]

    def evict_prefix(self, prefix: str) -> int:
        return self._rpc.call(
            {"op": "evict_prefix", "prefix": prefix}
        )["evicted"]

    def close(self) -> None:
        self._rpc.close()


class SocketOffsetStore:
    """OffsetStore interface (srv/events.py) on the broker (the chassis
    Redis DB-0 role)."""

    def __init__(self, address: str, secret: Optional[str] = None):
        self._rpc = _Rpc(address, secret=secret)

    def commit(self, topic: str, offset: int) -> None:
        self._rpc.call(
            {"op": "offset_commit", "topic": topic, "offset": offset}
        )

    def get(self, topic: str) -> Optional[int]:
        return self._rpc.call({"op": "offset_get", "topic": topic})["offset"]

    def close(self) -> None:
        self._rpc.close()
