"""Micro-batching frontend for isAllowed.

The reference evaluates one request per gRPC call
(reference: src/accessControlService.ts:62-81); the TPU path earns its
throughput by batching.  Concurrent callers submit requests; a collector
drains the queue every ``window_ms`` (or at ``max_batch``) and evaluates
the whole batch through the hybrid evaluator, resolving each caller's
future.  Single outstanding requests skip the device path entirely (the
oracle answers faster than an encode + device round-trip).

Pipelining: evaluation runs on a dedicated single-worker executor while
the collector keeps collecting AND runs the host-side eligibility pipeline
(``evaluator.prepare_batch``: batched token resolution + HR-scope
rendezvous) for batch i+1 — host RPC latency for the next batch overlaps
device execution of the current one.  At most one batch is queued behind
the one evaluating, so backpressure still reaches callers through their
futures."""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Optional

from ..models.model import Request, Response


class MicroBatcher:
    def __init__(
        self,
        evaluator,
        window_ms: float = 2.0,
        max_batch: int = 4096,
        min_kernel_batch: int = 8,
    ):
        self.evaluator = evaluator
        self.window_s = window_ms / 1000.0
        self.max_batch = max_batch
        self.min_kernel_batch = min_kernel_batch
        self._queue: "queue.Queue[tuple[Request, Future]]" = queue.Queue()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._eval_pool: Optional[ThreadPoolExecutor] = None
        self._inflight: list = []  # evaluation futures, FIFO
        self._last_batch = 0  # previous round's size (regime detector)

    def start(self) -> None:
        if self._thread is None:
            self._eval_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="acs-batch-eval"
            )
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._eval_pool is not None:
            self._eval_pool.shutdown(wait=True)
            self._eval_pool = None
        self._inflight = []

    def submit(self, request: Request) -> "Future[Response]":
        future: "Future[Response]" = Future()
        # decision-cache fast path: a warm cacheable request resolves
        # immediately instead of waiting out the collection window (and
        # never occupies a batch slot).  The caller thread already ran
        # prepare_context (srv/service.py), so the fingerprint is stable.
        cache = getattr(self.evaluator, "decision_cache", None)
        if cache is not None and cache.enabled:
            engine = getattr(self.evaluator, "engine", None)
            urns = getattr(engine, "urns", None)
            subject_urn = (urns.get("subjectID") if urns else "") or ""
            hit = cache.get(cache.fingerprint(request, subject_urn))
            if hit is not None:
                count = getattr(self.evaluator, "_count_path", None)
                if count is not None:
                    count("cache-hit", 1)
                future.set_result(hit)
                return future
        self._queue.put((request, future))
        return future

    def is_allowed(self, request: Request, timeout: float = 30.0) -> Response:
        return self.submit(request).result(timeout=timeout)

    # ----------------------------------------------------------------- loop

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                first = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            batch = [first]
            # the collection window closes window_s after the FIRST item;
            # later arrivals only get the remaining slice, so a steady
            # trickle cannot stretch collection toward max_batch * window.
            # Adaptive first-item grace: in the IDLE regime (the previous
            # round collected under min_kernel_batch) a lone request only
            # pays a short grace instead of the full window — measured
            # on-chip, single-stream p50 tracks the window ~1:1 (window +
            # ~0.8 ms) while concurrent arrivals land within a fraction
            # of a millisecond.  In the BUSY regime the full window
            # applies from the first item, so sustained traffic with
            # inter-arrivals just above the grace still aggregates into
            # kernel-sized batches instead of degenerating to batch-of-1.
            close_at = time.monotonic() + self.window_s
            busy = self._last_batch >= self.min_kernel_batch
            grace = self.window_s if busy else min(self.window_s, 0.0002)
            try:
                if len(batch) < self.max_batch:
                    batch.append(self._queue.get(timeout=grace))
                while len(batch) < self.max_batch:
                    remaining = close_at - time.monotonic()
                    if remaining <= 0:
                        break
                    batch.append(self._queue.get(timeout=remaining))
            except queue.Empty:
                pass
            self._last_batch = len(batch)
            # host-side eligibility pipeline for THIS batch runs on the
            # collector thread while the PREVIOUS batch is still evaluating
            # on the eval worker — token resolution / HR rendezvous latency
            # overlaps device execution (prepare_batch is idempotent; a
            # failure here just leaves rows unprepared, and the encoder
            # degrades them to the oracle)
            prepare = getattr(self.evaluator, "prepare_batch", None)
            if prepare is not None:
                try:
                    prepare([req for req, _ in batch])
                except Exception:
                    pass
            # bounded pipeline: one batch evaluating + one queued at most
            while len(self._inflight) >= 2:
                self._inflight.pop(0).result()
            self._inflight = [f for f in self._inflight if not f.done()]
            self._inflight.append(
                self._eval_pool.submit(self._eval_batch, batch)
            )
        for fut in self._inflight:
            fut.result()
        self._inflight = []

    def _eval_batch(self, batch: list) -> None:
        """Evaluate one collected batch and resolve its futures; runs on
        the single eval worker so batches complete in submission order."""
        requests = [req for req, _ in batch]
        responses = None
        if len(batch) >= self.min_kernel_batch:
            try:
                responses = self.evaluator.is_allowed_batch(requests)
            except Exception:
                # one poisoned request must not deny the whole batch;
                # retry each request individually below
                responses = None
        if responses is not None:
            for (_, future), response in zip(batch, responses):
                future.set_result(response)
        else:
            for req, future in batch:
                try:
                    future.set_result(self.evaluator.is_allowed(req))
                except Exception as err:
                    if not future.done():
                        future.set_exception(err)
