"""Micro-batching frontend for isAllowed (and, under admission control,
whatIsAllowed as the bulk traffic class).

The reference evaluates one request per gRPC call
(reference: src/accessControlService.ts:62-81); the TPU path earns its
throughput by batching.  Concurrent callers submit requests; a collector
drains the queue every ``window_ms`` (or at ``max_batch``) and evaluates
the whole batch through the hybrid evaluator, resolving each caller's
future.  Single outstanding requests skip the device path entirely (the
oracle answers faster than an encode + device round-trip).

Pipelining: evaluation runs on a dedicated single-worker executor while
the collector keeps collecting AND runs the host-side eligibility pipeline
(``evaluator.prepare_batch``: batched token resolution + HR-scope
rendezvous) for batch i+1 — host RPC latency for the next batch overlaps
device execution of the current one.  At most one batch is queued behind
the one evaluating, so backpressure still reaches callers through their
futures.

Admission control (srv/admission.py, config ``admission`` block): with a
controller wired, submits pass a bounded-queue + deadline-feasibility
gate (shed -> fast INDETERMINATE with the overload status, never a
fabricated decision), rows whose deadline expired while queued are
dropped at dispatch, the collection cap adapts to the batch-latency EWMA,
and a second BULK queue carries whatIsAllowed reverse queries with a
fairness guarantee: under interactive saturation a bulk round still runs
every ``bulk_interval`` interactive rounds, so neither class starves the
other.  Without a controller (or with ``admission.enabled`` false) the
serving path is byte-identical to the pre-admission behavior.

Shutdown drains: ``stop()`` stops admitting, flushes already-admitted
batches to completion bounded by a drain deadline, and resolves anything
still queued with a distinct shutdown status instead of leaving caller
futures hanging."""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Optional

from ..models.model import Request, Response, ReverseQuery
from .admission import (
    DEADLINE_CODE,
    SHUTDOWN_CODE,
    AdmissionController,
    BULK,
    INTERACTIVE,
    overload_response,
)


class MicroBatcher:
    def __init__(
        self,
        evaluator,
        window_ms: float = 2.0,
        max_batch: int = 4096,
        min_kernel_batch: int = 8,
        admission: Optional[AdmissionController] = None,
        observability=None,
        pipeline_depth: int = 2,
    ):
        self.evaluator = evaluator
        self.window_s = window_ms / 1000.0
        self.max_batch = max_batch
        self.min_kernel_batch = min_kernel_batch
        self.admission = admission
        # device pipeline depth (config evaluator:pipeline_depth — the
        # same value admission's feasibility estimate reads).  Depth <= 2
        # is the LEGACY path, byte-identical to pre-pipeline behavior:
        # blocking evaluate on the eval worker, at most depth batches in
        # flight.  Depth > 2 splits evaluation into dispatch (encode +
        # device enqueue, on the eval worker, in collection order) and
        # finalize (materialize + decode + future resolution, on a
        # dedicated worker, FIFO) so H2D/eval of batch i overlaps prep of
        # i+1 and decode of i-1 — requires the evaluator's async split
        # (HybridEvaluator.is_allowed_batch_async).
        self.pipeline_depth = max(1, int(pipeline_depth))
        self._async_pipeline = (
            self.pipeline_depth > 2
            and hasattr(evaluator, "is_allowed_batch_async")
        )
        # observability hub (srv/tracing.Observability): records the
        # admission and queue-wait stages.  None keeps submit/dispatch on
        # the exact pre-observability path.
        self.obs = observability
        # queue items are (request, future, deadline) — deadline is an
        # absolute monotonic instant or None
        self._queue: "queue.Queue[tuple[Request, Future, Optional[float]]]" \
            = queue.Queue()
        self._bulk: "queue.Queue[tuple[Request, Future, Optional[float]]]" \
            = queue.Queue()
        self._stop = threading.Event()
        self._stopping = False  # set before _stop: submits shed immediately
        self._drain_deadline: Optional[float] = None
        self._thread: Optional[threading.Thread] = None
        self._eval_pool: Optional[ThreadPoolExecutor] = None
        self._finalize_pool: Optional[ThreadPoolExecutor] = None
        self._inflight: list = []  # evaluation futures, FIFO
        self._last_batch = 0  # previous round's size (regime detector)
        self._rounds_since_bulk = 0
        # multi-tenant registry (srv/tenancy.TenantRegistry), wired by the
        # worker when the ``tenancy`` config block is enabled.  None keeps
        # every row — tenant-tagged or not — on the default-domain path.
        self.tenancy = None

    def start(self) -> None:
        if self._thread is None:
            self._stopping = False
            self._stop.clear()
            self._eval_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="acs-batch-eval"
            )
            if self._async_pipeline:
                # finalize worker: materializes device results, decodes
                # and resolves caller futures in dispatch order (FIFO)
                self._finalize_pool = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="acs-batch-finalize"
                )
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

    def stop(self, drain_s: Optional[float] = None) -> None:
        """Graceful drain: stop admitting, flush already-admitted batches
        to completion (bounded by ``drain_s``, default from the admission
        controller or 5 s), then fail anything still queued with the
        shutdown status."""
        if drain_s is None:
            drain_s = (
                self.admission.drain_deadline_s
                if self.admission is not None else 5.0
            )
        self._stopping = True
        if self.admission is not None:
            self.admission.begin_drain()
        self._drain_deadline = time.monotonic() + max(0.0, float(drain_s))
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(5.0, float(drain_s) + 5.0))
            self._thread = None
        if self._eval_pool is not None:
            self._eval_pool.shutdown(wait=True)
            self._eval_pool = None
        if self._finalize_pool is not None:
            self._finalize_pool.shutdown(wait=True)
            self._finalize_pool = None
        self._inflight = []
        # anything the drain loop could not flush before the deadline:
        # resolve with the shutdown status instead of leaving the caller's
        # future hanging forever
        self._fail_queued(self._queue, INTERACTIVE)
        self._fail_queued(self._bulk, BULK)

    def _fail_queued(self, q: "queue.Queue", cls: str) -> None:
        items = []
        while True:
            try:
                items.append(q.get_nowait())
            except queue.Empty:
                break
        for _, future, _ in items:
            if not future.done():
                future.set_result(
                    self._shutdown_result(cls)
                )
        if items and self.admission is not None:
            self._release(cls, items)
            self.admission.shed_shutdown(len(items))

    def _release(self, cls: str, items: list) -> None:
        """Release admission slots for collected rows — grouped per
        tenant so the quota ledger tracks the class ledger exactly."""
        counts: dict = {}
        for req, _, _ in items:
            tenant = getattr(req, "_tenant", None)
            counts[tenant] = counts.get(tenant, 0) + 1
        for tenant, n in counts.items():
            self.admission.release(cls, n, tenant=tenant)

    @staticmethod
    def _shutdown_result(cls: str):
        response = overload_response(
            SHUTDOWN_CODE, "shut down before evaluation"
        )
        if cls == BULK:
            return ReverseQuery(
                policy_sets=[], obligations=[],
                operation_status=response.operation_status,
            )
        return response

    # ---------------------------------------------------------------- submit

    def submit(
        self, request: Request, deadline: Optional[float] = None
    ) -> "Future[Response]":
        future: "Future[Response]" = Future()
        # decision-cache fast path: a warm cacheable request resolves
        # immediately instead of waiting out the collection window (and
        # never occupies a batch slot or an admission slot).  The caller
        # thread already ran prepare_context (srv/service.py), so the
        # fingerprint is stable.
        cache = getattr(self.evaluator, "decision_cache", None)
        if cache is not None and cache.enabled:
            obs_tracer = self.obs.tracer if self.obs is not None else None
            t_cache = time.perf_counter() if obs_tracer is not None else 0.0
            engine = getattr(self.evaluator, "engine", None)
            urns = getattr(engine, "urns", None)
            subject_urn = (urns.get("subjectID") if urns else "") or ""
            hit = cache.get(cache.fingerprint(request, subject_urn))
            if hit is not None:
                count = getattr(self.evaluator, "_count_path", None)
                if count is not None:
                    count("cache-hit", 1)
                if obs_tracer is not None:
                    from .tracing import STAGE_CACHE

                    obs_tracer.record(getattr(request, "_span", None),
                                      STAGE_CACHE,
                                      time.perf_counter() - t_cache)
                    hit._path = "cache-hit"
                future.set_result(hit)
                return future
        if self._stopping:
            future.set_result(self._shutdown_result(INTERACTIVE))
            return future
        tracer = self.obs.tracer if self.obs is not None else None
        if self.admission is not None:
            t0 = time.perf_counter() if tracer is not None else 0.0
            shed = self.admission.admit(
                INTERACTIVE, deadline,
                tenant=getattr(request, "_tenant", None),
            )
            if tracer is not None:
                from .tracing import STAGE_ADMISSION

                tracer.record(getattr(request, "_span", None),
                              STAGE_ADMISSION, time.perf_counter() - t0)
            if shed is not None:
                future.set_result(shed)
                return future
        if tracer is not None:
            # queue-wait start: closed at collection in _dispatch_*
            request._t_enqueue = time.perf_counter()
            span = getattr(request, "_span", None)
            if span is not None:
                span.mark_enqueue()
        self._queue.put((request, future, deadline))
        return future

    def submit_reverse(
        self, request: Request, deadline: Optional[float] = None
    ) -> "Future":
        """Bulk-class submission: a whatIsAllowed reverse query resolved
        with a ReverseQuery.  Only routed here under admission control
        (srv/service.py keeps the direct caller-thread walk otherwise).

        Deliberately NO decision-cache interaction, in either direction
        (contrast ``submit`` above): reverse queries resolve with policy
        trees, not decisions, so there is nothing meaningful to serve
        from — or insert into — the isAllowed cache, and a bulk audit
        sweep (srv/audit_sweep.py) walking a whole permission lattice
        through here must never evict interactive tenants' warm working
        sets.  Regression-pinned: tests/test_audit_sweep.py
        ``test_sweep_never_pollutes_decision_cache``."""
        future: Future = Future()
        if self._stopping:
            future.set_result(self._shutdown_result(BULK))
            return future
        if self.admission is not None:
            shed = self.admission.admit(
                BULK, deadline, tenant=getattr(request, "_tenant", None),
            )
            if shed is not None:
                future.set_result(ReverseQuery(
                    policy_sets=[], obligations=[],
                    operation_status=shed.operation_status,
                ))
                return future
        self._bulk.put((request, future, deadline))
        return future

    def is_allowed(self, request: Request, timeout: float = 30.0) -> Response:
        return self.submit(request).result(timeout=timeout)

    # ----------------------------------------------------------------- loop

    def _effective_max_batch(self) -> int:
        if self.admission is not None:
            return self.admission.suggest_max_batch(self.max_batch)
        return self.max_batch

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                # pending bulk work shortens the idle poll so a lone
                # reverse query is not parked for the full 100 ms
                poll = 0.005 if not self._bulk.empty() else 0.1
                first = self._queue.get(timeout=poll)
            except queue.Empty:
                # idle interactive round: bulk work proceeds immediately
                # instead of waiting out the fairness interval
                if not self._bulk.empty():
                    self._serve_bulk()
                continue
            batch = [first]
            max_batch = self._effective_max_batch()
            # the collection window closes window_s after the FIRST item;
            # later arrivals only get the remaining slice, so a steady
            # trickle cannot stretch collection toward max_batch * window.
            # Adaptive first-item grace: in the IDLE regime (the previous
            # round collected under min_kernel_batch) a lone request only
            # pays a short grace instead of the full window — measured
            # on-chip, single-stream p50 tracks the window ~1:1 (window +
            # ~0.8 ms) while concurrent arrivals land within a fraction
            # of a millisecond.  In the BUSY regime the full window
            # applies from the first item, so sustained traffic with
            # inter-arrivals just above the grace still aggregates into
            # kernel-sized batches instead of degenerating to batch-of-1.
            close_at = time.monotonic() + self.window_s
            busy = self._last_batch >= self.min_kernel_batch
            grace = self.window_s if busy else min(self.window_s, 0.0002)
            try:
                if len(batch) < max_batch:
                    batch.append(self._queue.get(timeout=grace))
                while len(batch) < max_batch:
                    remaining = close_at - time.monotonic()
                    if remaining <= 0:
                        break
                    batch.append(self._queue.get(timeout=remaining))
            except queue.Empty:
                pass
            self._last_batch = len(batch)
            self._dispatch_interactive(batch)
            # two-class fairness: under interactive saturation, a bulk
            # round still runs every ``bulk_interval`` interactive rounds
            self._rounds_since_bulk += 1
            interval = (
                self.admission.bulk_interval
                if self.admission is not None else 4
            )
            if (
                not self._bulk.empty()
                and self._rounds_since_bulk >= interval
            ):
                self._serve_bulk()
        # ------------------------------------------------------------ drain
        # stop admitting happened in stop(); flush what was already
        # admitted, bounded by the drain deadline, so accepted work is
        # answered rather than abandoned
        drain_until = self._drain_deadline or time.monotonic()
        while time.monotonic() < drain_until:
            batch = []
            max_batch = self._effective_max_batch()
            while len(batch) < max_batch:
                try:
                    batch.append(self._queue.get_nowait())
                except queue.Empty:
                    break
            if batch:
                self._dispatch_interactive(batch)
            elif not self._bulk.empty():
                self._serve_bulk()
            else:
                break
        for fut in self._inflight:
            try:
                fut.result(timeout=max(0.1, drain_until - time.monotonic()))
            except Exception:  # noqa: BLE001 — drain best-effort
                pass
        self._inflight = []

    # ------------------------------------------------------------- dispatch

    def _dispatch_interactive(self, batch: list) -> None:
        if self.admission is not None:
            self._release(INTERACTIVE, batch)
            batch = self._drop_expired(batch)
            if not batch:
                return
        # tenant partition: rows tagged with a tenant id (and a registry
        # to serve them) peel off to their tenant's evaluator — one
        # collection window mixes tenants, the device sees one kernel
        # call per tenant group on the class-shared program.  With no
        # registry or no tags this is a no-op and the batch flows down
        # the exact single-tenant path.
        if self.tenancy is not None:
            groups: dict = {}
            default_rows = []
            for item in batch:
                tenant = getattr(item[0], "_tenant", None)
                if tenant is None:
                    default_rows.append(item)
                else:
                    groups.setdefault(tenant, []).append(item)
            if groups:
                while len(self._inflight) >= self._inflight_bound():
                    self._inflight.pop(0).result()
                self._inflight = [
                    f for f in self._inflight if not f.done()
                ]
                self._inflight.append(
                    self._eval_pool.submit(self._eval_tenants, groups)
                )
                if not default_rows:
                    return
                batch = default_rows
        tracer = self.obs.tracer if self.obs is not None else None
        if tracer is not None:
            from .tracing import STAGE_QUEUE_WAIT

            now = time.perf_counter()
            for request, _, _ in batch:
                t_enqueue = getattr(request, "_t_enqueue", None)
                if t_enqueue is not None:
                    tracer.record(getattr(request, "_span", None),
                                  STAGE_QUEUE_WAIT, now - t_enqueue)
        # host-side eligibility pipeline for THIS batch runs on the
        # collector thread while the PREVIOUS batch is still evaluating
        # on the eval worker — token resolution / HR rendezvous latency
        # overlaps device execution (prepare_batch is idempotent; a
        # failure here just leaves rows unprepared, and the encoder
        # degrades them to the oracle)
        prepare = getattr(self.evaluator, "prepare_batch", None)
        if prepare is not None:
            try:
                prepare([req for req, _, _ in batch])
            except Exception:
                pass
        # bounded pipeline: at most pipeline_depth batches between
        # collection and decode completion (legacy depth 2: one batch
        # evaluating + one queued at most)
        while len(self._inflight) >= self._inflight_bound():
            self._inflight.pop(0).result()
        self._inflight = [f for f in self._inflight if not f.done()]
        if self._async_pipeline:
            # dispatch/finalize split: encode + device enqueue runs on
            # the eval worker in collection order; materialize + decode +
            # future resolution on the finalize worker, FIFO — device
            # execution of batch i overlaps dispatch of i+1 and decode
            # of i-1
            done: Future = Future()
            self._eval_pool.submit(self._dispatch_async, batch, done)
            self._inflight.append(done)
        else:
            self._inflight.append(
                self._eval_pool.submit(self._eval_batch, batch)
            )

    def _inflight_bound(self) -> int:
        """Depth bound on batches between collection and finalize: the
        configured pipeline depth on the async path, the legacy bound
        (at most 2: one evaluating + one queued) otherwise — depth 1
        degenerates to fully synchronous dispatch either way."""
        if self._async_pipeline:
            return self.pipeline_depth
        return min(self.pipeline_depth, 2)

    def _dispatch_async(self, batch: list, done: "Future") -> None:
        """Dispatch stage (eval worker): drop rows that expired while the
        pipeline was full, run the evaluator's dispatch half (prepare /
        cache lookups / encode + device enqueue), then hand the finalize
        half to the finalize worker.  ``done`` resolves when the batch is
        fully finalized — the collector's depth bound waits on it."""
        t0 = time.perf_counter()
        try:
            if self.admission is not None:
                batch = self._drop_expired(
                    batch,
                    margin_s=self.admission.estimate_high(INTERACTIVE),
                )
                if not batch:
                    done.set_result(None)
                    return
            finalize = None
            if len(batch) >= self.min_kernel_batch:
                try:
                    finalize = self.evaluator.is_allowed_batch_async(
                        [req for req, _, _ in batch]
                    )
                except Exception:
                    # poisoned dispatch: fall back per-request at finalize
                    finalize = None
            self._finalize_pool.submit(
                self._finalize_batch, batch, finalize, t0, done
            )
        except BaseException:
            if not done.done():
                done.set_result(None)
            raise

    def _finalize_batch(self, batch: list, finalize, t0: float,
                        done: "Future") -> None:
        """Finalize stage (finalize worker, FIFO): materialize the device
        result, decode, resolve caller futures — the async twin of
        ``_eval_batch``'s resolution half."""
        try:
            responses = None
            if finalize is not None:
                try:
                    responses = finalize()
                except Exception:
                    # one poisoned request must not deny the whole batch
                    responses = None
            if responses is not None:
                for (_, future, _), response in zip(batch, responses):
                    future.set_result(response)
            else:
                for req, future, _ in batch:
                    try:
                        future.set_result(self.evaluator.is_allowed(req))
                    except Exception as err:
                        if not future.done():
                            future.set_exception(err)
            if self.admission is not None:
                self.admission.observe_batch(
                    INTERACTIVE, time.perf_counter() - t0, len(batch)
                )
        finally:
            if not done.done():
                done.set_result(None)

    def _drop_expired(self, batch: list, margin_s: float = 0.0) -> list:
        """Rows whose deadline passed while queued resolve with the
        deadline status NOW — evaluating them would burn a batch slot on
        an answer the caller has already abandoned.  ``margin_s`` extends
        the cut to rows that cannot SURVIVE the work ahead: the eval-time
        pass uses the batch-latency estimate so a row with 1 ms of budget
        never rides a 10 ms batch into a late answer."""
        now = time.monotonic() + margin_s
        live = []
        expired = 0
        for item in batch:
            deadline = item[2]
            if deadline is not None and deadline <= now:
                expired += 1
                if not item[1].done():
                    item[1].set_result(overload_response(
                        DEADLINE_CODE, "deadline expired before evaluation"
                    ))
            else:
                live.append(item)
        if expired and self.admission is not None:
            self.admission.expired(expired)
        return live

    def _drop_expired_bulk(self, items: list) -> list:
        """Bulk-class twin of ``_drop_expired``: expired reverse queries
        resolve with a deadline-status ReverseQuery."""
        now = time.monotonic()
        live = []
        expired = 0
        for item in items:
            deadline = item[2]
            if deadline is not None and deadline <= now:
                expired += 1
                if not item[1].done():
                    item[1].set_result(ReverseQuery(
                        policy_sets=[], obligations=[],
                        operation_status=overload_response(
                            DEADLINE_CODE,
                            "deadline expired before evaluation",
                        ).operation_status,
                    ))
            else:
                live.append(item)
        if expired and self.admission is not None:
            self.admission.expired(expired)
        return live

    def _serve_bulk(self) -> None:
        """Drain one bulk round (bounded by max_batch) onto the eval
        pipeline; reverse queries batch through the device-assisted
        what_is_allowed_batch path."""
        self._rounds_since_bulk = 0
        items = []
        while len(items) < self.max_batch:
            try:
                items.append(self._bulk.get_nowait())
            except queue.Empty:
                break
        if not items:
            return
        if self.admission is not None:
            self._release(BULK, items)
            items = self._drop_expired_bulk(items)
        if not items:
            return
        while len(self._inflight) >= self._inflight_bound():
            self._inflight.pop(0).result()
        self._inflight = [f for f in self._inflight if not f.done()]
        self._inflight.append(
            self._eval_pool.submit(self._eval_bulk, items)
        )

    # ------------------------------------------------------------ evaluation

    def _eval_batch(self, batch: list) -> None:
        """Evaluate one collected batch and resolve its futures; runs on
        the single eval worker so batches complete in submission order."""
        t0 = time.perf_counter()
        if self.admission is not None:
            # second expiry pass: rows can outlive their deadline while
            # waiting behind the in-flight batches of the depth-2 eval
            # pipeline — drop them here, at the last instant before the
            # evaluation actually starts, including rows whose remaining
            # budget cannot cover this batch's estimated duration
            batch = self._drop_expired(
                batch, margin_s=self.admission.estimate_high(INTERACTIVE)
            )
            if not batch:
                return
        requests = [req for req, _, _ in batch]
        responses = None
        if len(batch) >= self.min_kernel_batch:
            try:
                responses = self.evaluator.is_allowed_batch(requests)
            except Exception:
                # one poisoned request must not deny the whole batch;
                # retry each request individually below
                responses = None
        if responses is not None:
            for (_, future, _), response in zip(batch, responses):
                future.set_result(response)
        else:
            for req, future, _ in batch:
                try:
                    future.set_result(self.evaluator.is_allowed(req))
                except Exception as err:
                    if not future.done():
                        future.set_exception(err)
        if self.admission is not None:
            self.admission.observe_batch(
                INTERACTIVE, time.perf_counter() - t0, len(batch)
            )

    def _eval_tenants(self, groups: dict) -> None:
        """Evaluate tenant-tagged rows group-by-group on the eval worker;
        each group resolves against its own tenant's tables through the
        tenancy registry (class-shared jitted program, per-tenant table
        arguments).  Unknown tenants get an honest INDETERMINATE — never
        a default-domain decision (that would be an isolation leak)."""
        t0 = time.perf_counter()
        total = 0
        tenant_inc = getattr(
            getattr(self.tenancy, "telemetry", None), "tenant_inc", None
        )
        for tenant, items in groups.items():
            if self.admission is not None:
                items = self._drop_expired(
                    items,
                    margin_s=self.admission.estimate_high(INTERACTIVE),
                )
                if not items:
                    continue
            total += len(items)
            try:
                evaluator = self.tenancy.evaluator_for(tenant)
            except Exception:  # noqa: BLE001 — registry must not poison rows
                evaluator = None
            if evaluator is None:
                from .tenancy import unknown_tenant_response

                for _, future, _ in items:
                    if not future.done():
                        future.set_result(unknown_tenant_response(tenant))
                continue
            requests = [req for req, _, _ in items]
            prepare = getattr(evaluator, "prepare_batch", None)
            if prepare is not None:
                try:
                    prepare(requests)
                except Exception:
                    pass
            responses = None
            if len(items) >= self.min_kernel_batch:
                try:
                    responses = evaluator.is_allowed_batch(requests)
                except Exception:
                    responses = None
            if responses is not None:
                for (_, future, _), response in zip(items, responses):
                    future.set_result(response)
            else:
                for req, future, _ in items:
                    try:
                        future.set_result(evaluator.is_allowed(req))
                    except Exception as err:
                        if not future.done():
                            future.set_exception(err)
            if tenant_inc is not None:
                tenant_inc("decision", tenant, len(items))
        if total and self.admission is not None:
            self.admission.observe_batch(
                INTERACTIVE, time.perf_counter() - t0, total
            )

    def _eval_bulk(self, items: list) -> None:
        """Evaluate one bulk (reverse-query) round on the eval worker."""
        t0 = time.perf_counter()
        if self.admission is not None:
            items = self._drop_expired_bulk(items)
            if not items:
                return
        if self.tenancy is not None:
            items = self._serve_tenant_bulk(items)
            if not items:
                return
        requests = [req for req, _, _ in items]
        try:
            results = self.evaluator.what_is_allowed_batch(requests)
        except Exception:
            results = None
        if results is not None:
            for (_, future, _), rq in zip(items, results):
                future.set_result(rq)
        else:
            for req, future, _ in items:
                try:
                    future.set_result(self.evaluator.what_is_allowed(req))
                except Exception as err:
                    if not future.done():
                        future.set_exception(err)
        if self.admission is not None:
            self.admission.observe_batch(
                BULK, time.perf_counter() - t0, len(items)
            )

    def _serve_tenant_bulk(self, items: list) -> list:
        """Resolve tenant-tagged reverse queries against their tenant's
        evaluator; returns the untagged remainder for the default path."""
        default_items = []
        for item in items:
            req, future, _ = item
            tenant = getattr(req, "_tenant", None)
            if tenant is None:
                default_items.append(item)
                continue
            try:
                evaluator = self.tenancy.evaluator_for(tenant)
            except Exception:  # noqa: BLE001
                evaluator = None
            if evaluator is None:
                from .tenancy import unknown_tenant_response

                if not future.done():
                    future.set_result(ReverseQuery(
                        policy_sets=[], obligations=[],
                        operation_status=unknown_tenant_response(
                            tenant
                        ).operation_status,
                    ))
                continue
            try:
                future.set_result(evaluator.what_is_allowed(req))
            except Exception as err:
                if not future.done():
                    future.set_exception(err)
        return default_items
