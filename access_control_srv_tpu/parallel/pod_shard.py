"""Pod-sharded policy tree: set-axis model partitioning with shard-local
delta patching.

parallel/rule_shard.py shards the RULE axis — good for a handful of huge
policies, but every shard still replicates the full set/policy metadata
and the partition must be rebuilt from scratch on any mutation (it is not
delta-patchable).  This module shards the SET axis of ONE pod-level
capacity-bucketed compile (ops/delta.py): shard ``d`` owns the padded set
slots ``[d*S_loc, (d+1)*S_loc)`` with a compacted per-shard target
subtable, so a 1M-rule tree that cannot fit one chip's capacity splits
into per-shard tables that do, while the encoder, candidate index,
decision cache and reverse kernel keep operating on the single pod-level
compiled tree (one entity vocab, one condition list, one request
encoding).

Why the set axis: the delta patcher relowers affected sets IN PLACE at
stable slots (``apply_events`` never moves a set's slot, and target rows
are owned per set via ``target_owners``), so slot ownership is stable
under churn and a CRUD event touching one set re-slices exactly one
shard.  The unaffected shards' host tables are reused BY REFERENCE —
byte-identical, as the audit row `sharded-tree-program-identity` asserts
— and the jitted shard_map program is registered in the evaluator's
shared-jit table, so an in-capacity patch costs ZERO new XLA compiles on
any shard.

Cross-shard combining (the lattice reduce, proof sketch in
docs/SHARDING.md): whole sets are shard-local, so every order-sensitive
combining algorithm (first-DENY / first-PERMIT / first-applicable per
policy, same per set) runs inside one shard via the shared stage helpers
(ops/kernel.py `_policy_contributions` / `_per_set_effects`).  Only two
merges cross the ``model`` axis, and both are min/max reductions over
packed positional keys — associative, commutative, and order-safe
because globally unique positions occupy the high bits:

* last-set-wins: ``pmax`` over ``pack_rule_key(global_set_pos + 1,
  set_eff, set_cach)`` — max key == max position == last contributing
  set, payload rides in the low 3 bits;
* condition aborts: ``pmin`` over global flat rule order finds the first
  aborting rule; the unique owning shard broadcasts its code/cacheable
  via ``pmax`` (same scheme as rule_shard).

Only O(1) ints per request cross the ICI — never per-set or per-rule
data.

Distributed bring-up: on a real pod each process contributes its local
devices to the ``model`` axis after ``maybe_initialize_distributed``
(parallel/cluster.py, behind ``cluster:distributed``); off-chip the
LocalCluster drives the same code over virtual CPU devices
(``--xla_force_host_platform_device_count``).  See docs/SHARDING.md.
"""

from __future__ import annotations

from dataclasses import dataclass
from hashlib import blake2b

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.compile import CompiledPolicies
from ..ops.delta import _bucket, _POL_FILLS, _RULE_FILLS, _SET_FILLS
from ..ops.encode import RequestBatch
from ..ops.kernel import (
    BIG,
    _match_targets,
    _per_set_effects,
    _policy_contributions,
    _policy_gates,
    _rule_predicates,
    pack_rule_key,
    tree_needs_rel,
    unpack_rule_key,
)
from .mesh import pad_batch, wrap_shard_map
from .rule_shard import _T_FIELDS

# fields sliced along the leading set axis; target-table fields
# (_T_FIELDS) are compacted per shard; acl_consts is replicated;
# hrv_role/hrv_scope are host-only (the encoder's owner bitplanes carry
# the HR verdicts, see rule_shard) and never reach the device
_SET_AXIS_FIELDS = tuple(_SET_FILLS) + tuple(_POL_FILLS) + tuple(_RULE_FILLS)
_FILL_BY_NAME = {**_SET_FILLS, **_POL_FILLS, **_RULE_FILLS}


@dataclass
class ShardTables:
    """One shard's host-side tables: set-axis slices at ``s_local`` slots
    plus the compacted target subtable at ``t_live`` rows (padded to the
    kernel's sticky t-bucket only at stack time, so the fingerprint is
    invariant under pod-wide bucket growth)."""

    index: int
    s_lo: int                      # first owned global set slot
    arrays: dict                   # name -> np.ndarray
    t_live: int                    # compacted target rows (pre-padding)
    fingerprint: str               # blake2b-16 over the live tables


def _shard_fingerprint(arrays: dict, s_lo: int, t_live: int) -> str:
    h = blake2b(digest_size=16)
    h.update(f"s_lo={s_lo};t_live={t_live};".encode())
    for name in sorted(arrays):
        v = np.ascontiguousarray(arrays[name])
        h.update(name.encode())
        h.update(str(v.dtype).encode())
        h.update(str(v.shape).encode())
        h.update(v.tobytes())
    return h.hexdigest()


def slice_shard(compiled: CompiledPolicies, index: int, s_local: int
                ) -> ShardTables:
    """Slice shard ``index``'s set slots out of the pod tables and compact
    its target subtable: a synthetic all-zeros row at local index 0 backs
    every "no target" reference, followed by only the rows this shard's
    sets/policies/rules actually reference.  The blank anchor matters for
    byte-identity: pod target rows are ordinary allocatable slots that
    in-place patches rewrite, so anchoring padding on pod row 0 would
    leak another shard's churn into this shard's bytes.  Pure per-shard:
    re-slicing one shard after a patch cannot observe any other shard's
    content."""
    a = compiled.arrays
    S = a["set_valid"].shape[0]
    lo = min(index * s_local, S)
    hi = min(lo + s_local, S)
    sl: dict[str, np.ndarray] = {}
    for name in _SET_AXIS_FIELDS:
        chunk = a[name][lo:hi]
        if hi - lo < s_local:  # pad the tail shard with inert slots
            pad_shape = (s_local - (hi - lo),) + chunk.shape[1:]
            fill = _FILL_BY_NAME[name]
            chunk = np.concatenate(
                [chunk, np.full(pad_shape, fill, chunk.dtype)], axis=0
            )
        sl[name] = np.ascontiguousarray(chunk)

    needed: set[int] = set()
    needed |= set(np.unique(sl["rule_target"][sl["rule_has_target"]]).tolist())
    needed |= set(np.unique(sl["pol_target"][sl["pol_has_target"]]).tolist())
    needed |= set(np.unique(sl["set_target"][sl["set_has_target"]]).tolist())
    order = sorted(needed)
    # remap defaults to 0 = the blank anchor, so dangling target indexes
    # on has_target=False entries can never alias a live local row
    remap = np.zeros(a["t_role"].shape[0], np.int64)
    for new, old in enumerate(order):
        remap[old] = new + 1
    for name in _T_FIELDS:
        rows = a[name][order]
        blank = np.zeros((1,) + rows.shape[1:], rows.dtype)
        sl[name] = np.ascontiguousarray(
            np.concatenate([blank, rows], axis=0)
        )
    for kind in ("rule", "pol", "set"):
        sl[f"{kind}_target"] = np.where(
            sl[f"{kind}_has_target"],
            remap[sl[f"{kind}_target"]],
            0,
        ).astype(np.int32)
    sl["acl_consts"] = np.asarray(a["acl_consts"])

    t_live = len(order) + 1
    return ShardTables(
        index=index, s_lo=lo, arrays=sl, t_live=t_live,
        fingerprint=_shard_fingerprint(sl, lo, t_live),
    )


def partition_sets(compiled: CompiledPolicies, n_shards: int
                   ) -> tuple[list[ShardTables], int]:
    """Split the (capacity-padded) set axis into ``n_shards`` contiguous
    chunks of ``s_local`` slots each; returns (shards, s_local)."""
    S = compiled.arrays["set_valid"].shape[0]
    s_local = -(-S // n_shards)
    return (
        [slice_shard(compiled, d, s_local) for d in range(n_shards)],
        s_local,
    )


def _evaluate_set_chunk(c, r, s_offset, model_axis, explain: bool = False,
                        with_rel: bool = False):
    """Per-device evaluation of one SET chunk for one request.  Stages A-F
    run locally through the shared single-device helpers (whole sets are
    shard-local, so every combining algorithm is local); only the
    last-set-wins tail and the abort-first scan reduce across ``model``
    via packed positional keys (order-safe: unique positions in the high
    bits, payload in the low bits).

    ``explain=True`` appends the packed provenance output (ops/kernel
    _combine_and_decide encoding, GLOBAL positions).  The winning set's
    global position already rides in ``k_win``'s high bits, so the unique
    owning shard recovers the full provenance locally and broadcasts the
    packed code with one extra ``pmax`` (codes are >= 1 whenever any set
    contributed; non-owners contribute 0)."""
    m = _match_targets(c, r, with_rel=with_rel)
    reached, acl_rule, has_cond, cond_t, cond_a, cond_c = _rule_predicates(
        c, r, m
    )
    pol_gate, set_gate, pol_subject = _policy_gates(c, r, m)
    if explain:
        (contrib_present, contrib_eff, contrib_cach, abort_rule,
         sel_c, no_rules_contrib) = _policy_contributions(
            c, reached, acl_rule, has_cond, cond_t, cond_a,
            pol_gate, set_gate, pol_subject, explain=True,
        )
        set_eff, set_cach, set_any, s_sel_c = _per_set_effects(
            c, contrib_present, contrib_eff, contrib_cach, explain=True
        )
    else:
        contrib_present, contrib_eff, contrib_cach, abort_rule = (
            _policy_contributions(
                c, reached, acl_rule, has_cond, cond_t, cond_a,
                pol_gate, set_gate, pol_subject,
            )
        )
        set_eff, set_cach, set_any = _per_set_effects(
            c, contrib_present, contrib_eff, contrib_cach
        )

    # ---- last-set-wins across shards: pmax over packed positional keys
    S_l = set_eff.shape[0]
    gpos = s_offset + jnp.arange(S_l)
    k_set = jnp.where(
        set_any,
        pack_rule_key(gpos + 1, set_eff, set_cach.astype(jnp.int32) & 1),
        0,
    )
    k_win = jax.lax.pmax(jnp.max(k_set), model_axis)
    have = k_win > 0
    eff_w, cach_w = unpack_rule_key(k_win)
    decision = jnp.where(have, eff_w, 0)
    cacheable = jnp.where(have, cach_w.astype(jnp.int32), -1)
    status = jnp.int32(200)

    # ---- condition aborts: first in GLOBAL flat rule order (pmin finds
    # the winner; the unique owning shard broadcasts code/cacheable)
    KPn, KRn = abort_rule.shape[1], abort_rule.shape[2]
    flat_order = (
        gpos[:, None, None] * (KPn * KRn)
        + jnp.arange(KPn)[None, :, None] * KRn
        + jnp.arange(KRn)[None, None, :]
    )
    local_abort_pos = jnp.min(jnp.where(abort_rule, flat_order, BIG))
    abort_pos = jax.lax.pmin(local_abort_pos, model_axis)
    has_abort = abort_pos < BIG
    i_own = (local_abort_pos == abort_pos) & has_abort
    abort_flat = jnp.argmin(jnp.where(abort_rule, flat_order, BIG))
    code_local = jnp.where(
        i_own, jnp.take(cond_c.reshape(-1), abort_flat), 0
    )
    cach_local = jnp.where(
        i_own,
        jnp.take(c["rule_cacheable_raw"].reshape(-1), abort_flat).astype(
            jnp.int32
        ) + 1,
        0,
    )
    abort_code = jax.lax.pmax(code_local, model_axis)
    abort_cach = jax.lax.pmax(cach_local, model_axis) - 1

    decision = jnp.where(has_abort, 2, decision)
    cacheable = jnp.where(has_abort, abort_cach, cacheable)
    status = jnp.where(has_abort, abort_code, status)
    if not explain:
        return (
            decision.astype(jnp.int32),
            cacheable.astype(jnp.int32),
            status.astype(jnp.int32),
        )

    # ---- explain recovery: the shard owning the winning set packs the
    # provenance code locally; one pmax broadcasts it (codes >= 1 when
    # any set contributed, so 0 from non-owners never wins)
    win_s_local = jnp.argmax(k_set)
    s_own = (jnp.max(k_set) == k_win) & have
    win_flat = (s_offset + win_s_local) * KPn + jnp.take(s_sel_c, win_s_local)
    win_kr = jnp.take(
        sel_c.reshape(-1),
        win_s_local * KPn + jnp.take(s_sel_c, win_s_local),
    )
    no_rules_win = jnp.take(
        no_rules_contrib.reshape(-1),
        win_s_local * KPn + jnp.take(s_sel_c, win_s_local),
    )
    rule_pos = win_flat * KRn + win_kr
    expl_local = jnp.where(
        s_own,
        jnp.where(no_rules_win, (win_flat << 2) | 2, (rule_pos << 2) | 1),
        0,
    )
    expl = jax.lax.pmax(expl_local.astype(jnp.int32), model_axis)
    expl = jnp.where(has_abort, (abort_pos << 2) | 3, expl)
    return (
        decision.astype(jnp.int32),
        cacheable.astype(jnp.int32),
        status.astype(jnp.int32),
        expl.astype(jnp.int32),
    )


class PodShardedKernel:
    """Set-axis sharded kernel over a 2-axis mesh: requests shard over
    ``data``, the pod-level compiled set slots over ``model``; per-shard
    compacted target subtables; ICI traffic is O(1) packed keys.

    Unlike RuleShardedKernel this kernel IS delta-patchable: ``patched``
    consumes ``apply_events``'s ``patched_slots`` and re-slices only the
    owning shards, so the evaluator keeps the incremental path enabled
    when ``parallel:pod_shards`` is configured."""

    supports_delta = True
    supports_shard_patch = True

    def __init__(self, compiled: CompiledPolicies, mesh: Mesh,
                 data_axis: str = "data", model_axis: str = "model",
                 shared_jits: dict | None = None, prev_t_cap: int = 0,
                 explain: bool = False,
                 _shards: list[ShardTables] | None = None,
                 _applied: list[int] | None = None):
        if not compiled.supported:
            raise ValueError(
                f"policy tree unsupported: {compiled.unsupported_reason}"
            )
        self.compiled = compiled
        self.mesh = mesh
        self.data_axis = data_axis
        self.model_axis = model_axis
        self.n_data = mesh.shape[data_axis]
        self.n_shards = mesh.shape[model_axis]
        self._shared = shared_jits if shared_jits is not None else {}
        self.explain = bool(explain)
        self.explain_strides = (compiled.KP, compiled.KR)

        if _shards is None:
            self.shards, self.s_local = partition_sets(
                compiled, self.n_shards
            )
        else:
            self.shards = _shards
            self.s_local = _shards[0].arrays["set_valid"].shape[0]
        # sticky per-pod target-row bucket (pow2, 1.25x headroom): patches
        # that stay inside it keep every stacked shape stable, so the
        # shared jit is reused and the patch costs zero new XLA compiles
        self.t_cap = max(
            prev_t_cap,
            _bucket(max(sh.t_live for sh in self.shards), 1.25, 8),
        )
        # per-shard applied-patch watermark since the last full partition
        # (surfaced through shard_identity for the convergence oracle)
        self.applied = list(_applied) if _applied is not None else (
            [0] * self.n_shards
        )

        self._place()
        self._run = self._ensure_jit()

    # ------------------------------------------------------------ placement
    def _place(self) -> None:
        spec = NamedSharding(self.mesh, P(self.model_axis))
        stacked: dict[str, np.ndarray] = {}
        for name in self.shards[0].arrays:
            parts = []
            for sh in self.shards:
                v = sh.arrays[name]
                if name in _T_FIELDS and v.shape[0] < self.t_cap:
                    # pad the compacted subtable to the sticky bucket by
                    # repeating row 0 (inert: no live index reaches pads)
                    reps = np.repeat(v[:1], self.t_cap - v.shape[0], axis=0)
                    v = np.concatenate([v, reps], axis=0)
                parts.append(v)
            stacked[name] = np.stack(parts)
        self._c = {
            k: jax.device_put(jnp.asarray(v), spec)
            for k, v in stacked.items()
        }
        self._offsets = jax.device_put(
            jnp.asarray(
                np.array([sh.s_lo for sh in self.shards], np.int32)
            ),
            spec,
        )

    def _ensure_jit(self):
        """The jitted shard_map program, registered under the shared-jit
        table (srv/evaluator.py) so patched/recompiled kernels with
        identical table shapes reuse the existing executables."""
        with_rel = tree_needs_rel(self.compiled.arrays)
        key = ("pod", self.model_axis, self.n_shards, with_rel)
        if self.explain:
            key = key + ("explain",)
        jitted = self._shared.get(key)
        if jitted is not None:
            return jitted

        model_axis = self.model_axis
        explain = self.explain
        c_specs = {k: P(model_axis) for k in self._c}

        def run(c, offsets, batch_arrays, rgx_set, pfx_neq):
            c_local = {k: v[0] for k, v in c.items()}
            s_offset = offsets[0]

            def one(ra):
                rr = {**ra, "rgx_set": rgx_set, "pfx_neq": pfx_neq}
                return _evaluate_set_chunk(
                    c_local, rr, s_offset, model_axis, explain=explain,
                    with_rel=with_rel,
                )

            return jax.vmap(one)(batch_arrays)

        wrapped = wrap_shard_map(
            run,
            mesh=self.mesh,
            in_specs=(c_specs, P(model_axis), P(self.data_axis), P(), P()),
            out_specs=(P(self.data_axis),) * (4 if explain else 3),
        )
        jitted = jax.jit(wrapped)
        self._shared[key] = jitted
        return jitted

    # ------------------------------------------------------- shard-local patch
    def patched(self, new_compiled: CompiledPolicies,
                patched_slots: list[int]) -> "PodShardedKernel":
        """Shard-local relower: re-slice ONLY the shards owning
        ``patched_slots`` (apply_events stats), reusing every other
        shard's host tables by reference — their bytes cannot have
        changed, because the delta patcher rewrites only rows owned by
        the affected sets (ops/delta.py ``target_owners`` ledger) and set
        slots never move under patch.  The shared jit is reused, so an
        in-capacity patch costs zero new XLA compiles on any shard."""
        owners = sorted({
            min(int(s) // self.s_local, self.n_shards - 1)
            for s in patched_slots
        })
        shards = list(self.shards)
        for d in owners:
            shards[d] = slice_shard(new_compiled, d, self.s_local)
        applied = list(self.applied)
        for d in owners:
            applied[d] += 1
        return PodShardedKernel(
            new_compiled, self.mesh,
            data_axis=self.data_axis, model_axis=self.model_axis,
            shared_jits=self._shared, prev_t_cap=self.t_cap,
            explain=self.explain, _shards=shards, _applied=applied,
        )

    # ------------------------------------------------------------- identity
    def pod_fingerprint(self) -> str:
        """The combined pod fingerprint: a digest over the per-shard
        fingerprints in shard order (what the router/chaos convergence
        oracle compares across replicas)."""
        h = blake2b(digest_size=16)
        for sh in self.shards:
            h.update(sh.fingerprint.encode())
        return h.hexdigest()

    def shard_identity(self) -> dict:
        return {
            "n_shards": self.n_shards,
            "s_local": self.s_local,
            "t_bucket": self.t_cap,
            "pod_fingerprint": self.pod_fingerprint(),
            "shards": [
                {
                    "index": sh.index,
                    "fingerprint": sh.fingerprint,
                    "set_slots": [sh.s_lo, sh.s_lo + self.s_local],
                    "t_rows_live": sh.t_live,
                    "applied_patches": self.applied[sh.index],
                }
                for sh in self.shards
            ],
        }

    # ------------------------------------------------------------- evaluate
    def evaluate(self, batch: RequestBatch):
        return self.evaluate_async(batch)()

    def evaluate_async(self, batch: RequestBatch):
        """Dispatch without blocking (returns the materialize callable —
        the pod-sharded leg of the depth-N serving pipeline).  Batch and
        regex-matrix axes pad to power-of-two buckets divisible by the
        data-axis size, same scheme as the other kernels."""
        # failpoint (srv/faults.py): host-side dispatch boundary — fires
        # before any device work, so the lowered program is unchanged
        from ..srv.faults import REGISTRY as _faults

        _faults.fire("device.dispatch")
        arrays = dict(batch.arrays)
        arrays["cond_true"] = np.ascontiguousarray(batch.cond_true.T)
        arrays["cond_abort"] = np.ascontiguousarray(batch.cond_abort.T)
        arrays["cond_code"] = np.ascontiguousarray(batch.cond_code.T)

        from ..ops.kernel import pad_cols, pow2_bucket

        per_shard = -(-batch.B // self.n_data)
        bucket = self.n_data * pow2_bucket(per_shard)
        arrays, _ = pad_batch(arrays, batch.B, bucket)
        e_bucket = pow2_bucket(batch.rgx_set.shape[1])

        out = self._run(
            self._c,
            self._offsets,
            {k: jnp.asarray(v) for k, v in arrays.items()},
            jnp.asarray(pad_cols(batch.rgx_set, e_bucket)),
            jnp.asarray(pad_cols(batch.pfx_neq, e_bucket)),
        )

        def materialize():
            _faults.fire("device.materialize")
            return tuple(np.asarray(x)[: batch.B] for x in out)

        return materialize
