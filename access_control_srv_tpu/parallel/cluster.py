"""Cluster process management: N-replica bring-up on one host, and the
on-chip pod hook.

``LocalCluster`` is the whole serving tier in one object — a durable
broker process, N worker replica processes (each a full Worker whose
PolicyReplicator replays the broker's journaled CRUD log at boot and
applies live frames through the delta path, srv/store.py), and a
ClusterRouter (srv/router.py) front door.  Everything runs on CPU with
plain subprocesses, so the tier is testable anywhere; on a TPU pod the
same replicas run one per host with ``cluster:distributed`` enabled and
``maybe_initialize_distributed`` wiring jax.distributed underneath.

Convergence invariant (docs/CLUSTER.md): replicas that applied the same
CRUD log prefix hold byte-identical compiled tables — the
``program_identity`` command (policy epoch + table fingerprint) is the
probe, and tests/test_cluster_chaos.py kills a replica mid-churn to
prove a restarted process converges back to it.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time
from typing import Optional


def maybe_initialize_distributed(cfg, process_id: int | None = None) -> bool:
    """``jax.distributed.initialize`` behind the ``cluster:distributed``
    config block: on-chip pods (one replica process per TPU host) opt in
    by setting ``enabled`` with the coordinator address and process
    count; the CPU N-process tier keeps it off and pays nothing.
    Returns True when distributed init actually ran."""
    def get(path: str, default=None):
        if hasattr(cfg, "get") and not isinstance(cfg, dict):
            return cfg.get(path, default)
        node = cfg
        for part in path.split(":"):
            if not isinstance(node, dict) or part not in node:
                return default
            node = node[part]
        return node

    if not get("cluster:distributed:enabled", False):
        return False
    coordinator = get("cluster:distributed:coordinator", "127.0.0.1:8476")
    num_processes = int(get("cluster:distributed:num_processes", 1))
    if process_id is None:
        process_id = int(os.environ.get("ACS_CLUSTER_PROCESS_ID", "0"))
    try:
        import jax

        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
        return True
    except Exception:  # noqa: BLE001 — single-host / already-initialized
        return False


def _spawn(args: list[str], ready_prefix: str, timeout_s: float,
           cwd: Optional[str] = None, env: Optional[dict] = None):
    """Start a CLI subprocess and wait for its ``ready_prefix`` stdout
    line; returns (process, address).  A drain thread keeps consuming
    stdout afterwards so the pipe never backpressures the child."""
    # failpoint (srv/faults.py): replica/broker spawn — error models a
    # scheduler refusing the placement, delay a slow cold boot
    from ..srv.faults import REGISTRY as _faults

    _faults.fire("cluster.spawn")
    proc = subprocess.Popen(
        args, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, cwd=cwd, env=env,
    )
    addr = None
    deadline = time.monotonic() + timeout_s
    lines: list[str] = []
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        lines.append(line)
        if line.startswith(ready_prefix):
            addr = line[len(ready_prefix):].strip()
            break
    if addr is None:
        proc.kill()
        proc.wait(timeout=5)
        raise RuntimeError(
            f"subprocess never reported {ready_prefix!r}: "
            f"{''.join(lines[-20:])!r}"
        )

    def drain(stream=proc.stdout):
        try:
            for _ in stream:
                pass
        except Exception:  # noqa: BLE001
            pass

    threading.Thread(target=drain, daemon=True).start()
    return proc, addr


class ReplicaProcess:
    """One worker replica as a child process: its own config dir (written
    here), its own gRPC port, booted through the ordinary CLI so the
    process is exactly what production runs."""

    def __init__(self, config: dict, base_dir: str, name: str,
                 timeout_s: float = 120.0):
        self.name = name
        self.config_dir = os.path.join(base_dir, name)
        os.makedirs(self.config_dir, exist_ok=True)
        with open(os.path.join(self.config_dir, "config.json"), "w") as fh:
            json.dump(config, fh, indent=1)
        self.timeout_s = timeout_s
        self.proc: Optional[subprocess.Popen] = None
        self.addr: Optional[str] = None

    def start(self) -> "ReplicaProcess":
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        self.proc, self.addr = _spawn(
            [sys.executable, "-m", "access_control_srv_tpu",
             "--config-dir", self.config_dir, "--addr", "127.0.0.1:0"],
            "serving on ", self.timeout_s,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
            env=env,
        )
        return self

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def kill(self) -> None:
        """SIGKILL — the chaos path: no drain, no goodbye."""
        if self.proc is not None:
            self.proc.kill()
            self.proc.wait(timeout=10)

    def stop(self, timeout_s: float = 15.0) -> None:
        """SIGTERM — the graceful path (worker drains in-flight work)."""
        if self.proc is None:
            return
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=10)


class LocalCluster:
    """Broker + N replicas + router, owned end to end.

    ``seed_cfg`` (seed_data YAML paths) is loaded ONCE, by the cluster,
    as CRUD frames emitted straight into the broker's journaled topics
    before any replica boots — the journal, not the YAML, is the
    cluster's durable policy store, so every replica (first boot or
    chaos restart) converges by replaying the same log through its
    PolicyReplicator and all replicas report the same policy epoch."""

    def __init__(self, n_replicas: int = 2, seed_cfg: dict | None = None,
                 cfg_extra: dict | None = None,
                 router_cfg: dict | None = None,
                 base_dir: str | None = None,
                 replica_timeout_s: float = 120.0,
                 broker_snapshot_every: int | None = None):
        self.n_replicas = int(n_replicas)
        self.seed_cfg = seed_cfg or {}
        self.cfg_extra = cfg_extra or {}
        self.router_cfg = router_cfg or {}
        self._own_base = base_dir is None
        self.base_dir = base_dir or tempfile.mkdtemp(prefix="acs-cluster-")
        self.replica_timeout_s = replica_timeout_s
        # snapshot + journal-compaction cadence (srv/broker.py): None
        # keeps full-journal replay; chaos tests reuse base_dir across
        # stop/start so a rebooted cluster recovers from snapshot + tail
        self.broker_snapshot_every = broker_snapshot_every
        self.broker_proc: Optional[subprocess.Popen] = None
        self.broker_addr: Optional[str] = None
        self.replicas: list[ReplicaProcess] = []
        self.router = None

    # ---------------------------------------------------------- lifecycle

    def start(self) -> "LocalCluster":
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        broker_dir = os.path.join(self.base_dir, "broker")
        os.makedirs(broker_dir, exist_ok=True)
        broker_args = [
            sys.executable, "-m", "access_control_srv_tpu", "--broker",
            "--addr", "127.0.0.1:0", "--broker-data-dir", broker_dir,
        ]
        if self.broker_snapshot_every is not None:
            broker_args += [
                "--broker-snapshot-every", str(self.broker_snapshot_every)
            ]
        self.broker_proc, self.broker_addr = _spawn(
            broker_args, "broker listening on ", 30.0, cwd=repo_root,
        )
        # reused base_dir (chaos reboot): the journal/snapshot already
        # hold the policy state — re-seeding would double every frame
        if self.seed_cfg and not self._journal_populated(broker_dir):
            self._seed_journal()
        for i in range(self.n_replicas):
            self.replicas.append(
                ReplicaProcess(self._replica_config(i), self.base_dir,
                               f"replica-{i}",
                               self.replica_timeout_s).start()
            )
        from ..srv.router import ClusterRouter

        self.router = ClusterRouter(
            [r.addr for r in self.replicas], cfg=self.router_cfg,
        ).start()
        return self

    @staticmethod
    def _journal_populated(broker_dir: str) -> bool:
        """True when the broker dir already carries durable state (a
        non-empty journal or a snapshot) — i.e. this start() is a reboot
        over an existing base_dir, not a first boot."""
        journal = os.path.join(broker_dir, "broker.journal")
        snapshot = os.path.join(broker_dir, "broker.snapshot")
        if os.path.exists(snapshot):
            return True
        try:
            return os.path.getsize(journal) > 0
        except OSError:
            return False

    def _seed_journal(self) -> None:
        """Write the seed YAMLs into the broker's journaled CRUD topics
        as ordinary Created frames (the same wire shape
        store.ResourceService._emit produces) so every replica's boot
        replay — not a per-process YAML load — installs the seed state."""
        from ..srv.broker import SocketEventBus
        from ..srv.worker import _yaml_list

        kind_event = {"rule": "rule", "policy": "policy",
                      "policy_set": "policySet"}
        bus = SocketEventBus(self.broker_addr)
        try:
            for kind, key in (("rule", "rules"), ("policy", "policies"),
                              ("policy_set", "policy_sets")):
                path = self.seed_cfg.get(key)
                if not path:
                    continue
                topic = bus.topic(f"io.restorecommerce.{kind}s.resource")
                for doc in _yaml_list(path):
                    topic.emit(
                        f"{kind_event[kind]}Created",
                        {"payload": doc, "origin": "cluster-seed"},
                    )
        finally:
            bus.close()

    def _replica_config(self, index: int) -> dict:
        cfg: dict = {
            "policies": {"type": "database"},
            "events": {"broker": {"address": self.broker_addr}},
        }
        for key, value in self.cfg_extra.items():
            if isinstance(value, dict) and isinstance(cfg.get(key), dict):
                cfg[key] = {**cfg[key], **value}
            else:
                cfg[key] = value
        return cfg

    def restart_replica(self, index: int) -> ReplicaProcess:
        """Boot a fresh process for a dead replica slot (same config dir:
        the journal replay, not local state, restores its policy tree)
        and swap its new address into the router."""
        old = self.replicas[index]
        replacement = ReplicaProcess(
            self._replica_config(index), self.base_dir,
            old.name, self.replica_timeout_s
        ).start()
        self.replicas[index] = replacement
        if self.router is not None:
            if old.addr:
                self.router.remove_replica(old.addr)
            self.router.add_replica(
                replacement.addr, self.router_cfg.get("breaker") or {}
            )
        return replacement

    def stop(self) -> None:
        if self.router is not None:
            self.router.stop()
        for replica in self.replicas:
            try:
                replica.stop()
            except Exception:  # noqa: BLE001
                pass
        if self.broker_proc is not None:
            self.broker_proc.terminate()
            try:
                self.broker_proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.broker_proc.kill()
                self.broker_proc.wait(timeout=10)
        if self._own_base:
            shutil.rmtree(self.base_dir, ignore_errors=True)
