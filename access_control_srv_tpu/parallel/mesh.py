"""Mesh construction and batch-axis sharding for the decision kernel.

The parallel layout: requests are **data-parallel** over the ``data`` mesh
axis; compiled policy tensors and regex matrices are replicated (they are
the "model"); decisions gather back over ICI.  This is the TPU-native
replacement for the reference's horizontal scaling of stateless Node
replicas behind gRPC (SURVEY.md section 2.4): one process, N chips, one
sharded batch.

The kernel is pure and shape-static, so sharding is expressed entirely with
``jax.sharding.NamedSharding`` on the batch axis — XLA inserts the
collectives.  For trees too large to replicate per chip, the rule-axis
(model-parallel) variant lives in parallel/rule_shard.py and is reachable
from config via ``parallel:model_devices`` (make_mesh2 builds the 2-axis
data x model mesh).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.compile import CompiledPolicies
from ..ops.encode import RequestBatch
from ..ops.kernel import _evaluate_one, bake_policy_constants


def resolve_shard_map():
    """The running jax's ``shard_map`` entry point: ``jax.shard_map`` on
    >= 0.5, ``jax.experimental.shard_map.shard_map`` before.  One probe
    shared by every sharded kernel (rule_shard, pod_shard) so a jax
    upgrade changes exactly one call site."""
    shard_map = getattr(jax, "shard_map", None)
    if shard_map is None:  # jax < 0.5
        from jax.experimental.shard_map import shard_map
    return shard_map


def wrap_shard_map(fn, *, mesh: Mesh, in_specs, out_specs):
    """``shard_map(fn, ...)`` with replication checking off, spelling the
    flag for the running jax (``check_vma`` on >= 0.6, ``check_rep``
    before).  The sharded kernels' cross-device reductions intentionally
    leave per-device values unreplicated until the packed-key collectives
    merge them, so the static replication checker must be disabled."""
    shard_map = resolve_shard_map()
    kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    try:
        return shard_map(fn, check_vma=False, **kwargs)
    except TypeError:  # pre-0.6 jax spells the flag check_rep
        return shard_map(fn, check_rep=False, **kwargs)


def make_mesh(n_devices: int | None = None, axis: str = "data") -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


def make_mesh2(
    n_data: int, n_model: int,
    data_axis: str = "data", model_axis: str = "model",
) -> Mesh:
    """Two-axis (data x model) mesh for the rule-sharded kernel: requests
    shard over ``data_axis``, the rule axis of the compiled policy tensors
    over ``model_axis`` (parallel/rule_shard.py).  Built from the first
    ``n_data * n_model`` devices; ICI-adjacent devices land on the model
    axis (the per-(set, policy) packed-key reductions ride it)."""
    devices = jax.devices()
    need = n_data * n_model
    if len(devices) < need:
        raise ValueError(
            f"mesh needs {need} devices (data {n_data} x model {n_model}); "
            f"only {len(devices)} available"
        )
    grid = np.array(devices[:need]).reshape(n_data, n_model)
    return Mesh(grid, (data_axis, model_axis))


def pad_batch(arrays: dict, B: int, multiple: int) -> tuple[dict, int]:
    """Pad the leading batch axis up to a multiple (repeating row 0) so it
    shards evenly; returns (padded arrays, padded size)."""
    if B % multiple == 0:
        return arrays, B
    pad = multiple - (B % multiple)
    out = {}
    for k, v in arrays.items():
        pad_rows = np.repeat(v[:1], pad, axis=0)
        out[k] = np.concatenate([v, pad_rows], axis=0)
    return out, B + pad


class ShardedDecisionKernel:
    """The decision kernel jitted with batch-axis sharding over a mesh."""

    def __init__(self, compiled: CompiledPolicies, mesh: Mesh, axis: str = "data",
                 explain: bool = False):
        if not compiled.supported:
            raise ValueError(
                f"policy tree unsupported by kernel: {compiled.unsupported_reason}"
            )
        self.compiled = compiled
        self.mesh = mesh
        self.axis = axis
        self.n_devices = mesh.devices.size
        self.explain = bool(explain)
        self.explain_strides = (compiled.KP, compiled.KR)
        self._batch_sharding = NamedSharding(mesh, P(axis))
        self._repl = NamedSharding(mesh, P())

        def run(c, batch_arrays, rgx_set, pfx_neq):
            # batch_arrays carries the per-request encodings plus the
            # transposed condition bits (cond_true/abort/code as [B, C])
            def one(ra):
                rr = {
                    **{k: v for k, v in ra.items() if not k.startswith("cond_")},
                    "rgx_set": rgx_set,
                    "pfx_neq": pfx_neq,
                    "cond_true": ra["cond_true"],
                    "cond_abort": ra["cond_abort"],
                    "cond_code": ra["cond_code"],
                }
                return _evaluate_one(c, rr, explain=explain)

            return jax.vmap(one)(batch_arrays)

        out_shardings = (self._batch_sharding,) * (4 if explain else 3)
        if bake_policy_constants(compiled):
            # small tree: bake as constants (see ops.kernel.DecisionKernel)
            c_const = {k: jnp.asarray(v) for k, v in compiled.arrays.items()}
            self._run = jax.jit(
                partial(run, c_const),
                in_shardings=(None, self._repl, self._repl),
                out_shardings=out_shardings,
            )
        else:
            # replicate the policy tensors across the mesh once and pass
            # them as arguments
            self._c = {
                k: jax.device_put(jnp.asarray(v), self._repl)
                for k, v in compiled.arrays.items()
            }
            self._jit = jax.jit(
                run,
                in_shardings=(self._repl, None, self._repl, self._repl),
                out_shardings=out_shardings,
            )
            self._run = lambda *args: self._jit(self._c, *args)

    def evaluate(self, batch: RequestBatch):
        return self.evaluate_async(batch)()

    def evaluate_async(self, batch: RequestBatch):
        """Dispatch without blocking; returns the materialize callable
        (the data-parallel leg of the depth-N serving pipeline)."""
        # failpoint (srv/faults.py): host-side dispatch boundary — fires
        # before any device work, so the lowered program is unchanged
        from ..srv.faults import REGISTRY as _faults

        _faults.fire("device.dispatch")
        arrays = dict(batch.arrays)
        arrays["cond_true"] = np.ascontiguousarray(batch.cond_true.T)
        arrays["cond_abort"] = np.ascontiguousarray(batch.cond_abort.T)
        arrays["cond_code"] = np.ascontiguousarray(batch.cond_code.T)
        arrays, _ = pad_batch(arrays, batch.B, self.n_devices)
        dev_arrays = {
            k: jax.device_put(v, self._batch_sharding) for k, v in arrays.items()
        }
        out = self._run(
            dev_arrays,
            jnp.asarray(batch.rgx_set),
            jnp.asarray(batch.pfx_neq),
        )
        def materialize():
            _faults.fire("device.materialize")
            return tuple(np.asarray(x)[: batch.B] for x in out)

        return materialize
