"""Device-mesh sharding for the batched evaluator, and the pod-scale
replica cluster (parallel/cluster.py + srv/router.py)."""

from .cluster import (
    LocalCluster,
    ReplicaProcess,
    maybe_initialize_distributed,
)
from .mesh import (
    ShardedDecisionKernel,
    make_mesh,
    make_mesh2,
    pad_batch,
    resolve_shard_map,
    wrap_shard_map,
)
from .pod_shard import PodShardedKernel

__all__ = [
    "LocalCluster",
    "PodShardedKernel",
    "ReplicaProcess",
    "ShardedDecisionKernel",
    "make_mesh",
    "make_mesh2",
    "maybe_initialize_distributed",
    "pad_batch",
    "resolve_shard_map",
    "wrap_shard_map",
]
