"""Device-mesh sharding for the batched evaluator."""

from .mesh import ShardedDecisionKernel, make_mesh, make_mesh2, pad_batch

__all__ = ["ShardedDecisionKernel", "make_mesh", "make_mesh2", "pad_batch"]
