"""Rule-axis (model-parallel) sharded decision kernel.

For policy trees too large to replicate per chip, rules are partitioned
into contiguous chunks along the rule axis and distributed over the mesh's
``model`` axis; requests stay data-parallel over ``data``.  Each device
evaluates target matching + rule collection for its own chunk against a
**compacted per-shard target subtable** (only the target rows its rules
reference, plus all policy/set targets), so both hot stages shard.

The reference's combining algorithms are order-sensitive (first-DENY /
first-PERMIT / first-applicable / last-collected in insertion order,
reference: src/core/accessController.ts:846-893), so cross-device
combination uses **packed positional reductions**: each device reduces its
chunk to per-(set, policy) int32 keys ``global_rule_pos * 8 + effect * 2 +
cacheable`` and the mesh reduces with ``lax.pmin`` / ``lax.pmax`` over the
``model`` axis — the position occupies the high bits, so ordering by key
is ordering by rule position, and the winning rule's effect+cacheable ride
along in the low bits.  Only ``O(S * KP)`` ints cross the ICI per request,
never per-rule data.

Condition aborts preempt in global flat rule order: a ``pmin`` over flat
positions finds the winner, and the owning device contributes its
code/cacheable via a max-reduction (positions are unique so exactly one
device matches).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.compile import CompiledPolicies
from ..ops.encode import RequestBatch
from ..ops.kernel import (
    BIG,
    _combine_sets,
    _match_targets,
    _policy_gates,
    _rule_predicates,
    pack_rule_key,
    unpack_rule_key,
    tree_needs_rel,
)

# target-table fields partitioned per shard (see compile.py _TargetTable).
# t_rel_idx stays a GLOBAL relation-vocab index (the packed closure planes
# are vocab-wide), so no per-shard remap; t_rel_path is host-only and
# never ships.
_T_FIELDS = [
    "t_n_subjects", "t_role", "t_has_role", "t_scoping", "t_has_scoping",
    "t_hr_check", "t_skip_acl", "t_sub_ids", "t_sub_vals", "t_act_ids",
    "t_act_vals", "t_ent_vals", "t_ent_w", "t_ent_tails", "t_op_vals",
    "t_prop_vals", "t_prop_sfx", "t_has_props", "t_n_res", "t_rs_idx",
    "t_rel_idx", "t_rel_direct",
]


@dataclass
class _Partitioned:
    arrays: dict[str, np.ndarray]  # stacked [D, ...] per-shard arrays
    kr_local: int
    kr_offsets: np.ndarray  # [D]


def partition_rules(compiled: CompiledPolicies, n_shards: int) -> _Partitioned:
    """Slice the rule axis into contiguous chunks and compact each chunk's
    target subtable; policy/set metadata is replicated into every shard."""
    a = compiled.arrays
    S, KP, KR = compiled.S, compiled.KP, compiled.KR
    kr_local = -(-KR // n_shards)

    shard_arrays: list[dict[str, np.ndarray]] = []
    t_sizes = []
    for d in range(n_shards):
        # clamp: with more shards than rule columns the tail shards hold
        # only padding (all-invalid rules)
        lo = min(d * kr_local, KR)
        hi = min(lo + kr_local, KR)
        sl: dict[str, np.ndarray] = {}
        for name in ("rule_valid", "rule_effect", "rule_cacheable_raw",
                     "rule_cacheable_eff", "rule_has_target", "rule_target",
                     "rule_cond"):
            chunk = a[name][:, :, lo:hi]
            if hi - lo < kr_local:  # pad the tail shard
                pad_width = kr_local - (hi - lo)
                fill = (
                    False if chunk.dtype == bool
                    else (0 if name in ("rule_effect", "rule_target") else -1)
                )
                chunk = np.concatenate(
                    [chunk,
                     np.full((S, KP, pad_width), fill, chunk.dtype)], axis=2
                )
            sl[name] = chunk
        # compact target rows: local rule targets + all policy/set targets
        needed = set(np.unique(sl["rule_target"][sl["rule_has_target"]]))
        needed |= set(np.unique(a["pol_target"][a["pol_has_target"]]))
        needed |= set(np.unique(a["set_target"][a["set_has_target"]]))
        needed.add(0)  # row 0 backs the "no target" index
        order = sorted(needed)
        remap = np.zeros(a["t_role"].shape[0], np.int64)
        for new, old in enumerate(order):
            remap[old] = new
        for name in _T_FIELDS:
            sl[name] = a[name][order]
        sl["rule_target"] = remap[sl["rule_target"]].astype(np.int32)
        sl["pol_target"] = remap[a["pol_target"]].astype(np.int32)
        sl["set_target"] = remap[a["set_target"]].astype(np.int32)
        shard_arrays.append(sl)
        t_sizes.append(len(order))

    t_max = max(t_sizes)
    for sl in shard_arrays:
        t_have = sl["t_role"].shape[0]
        if t_have < t_max:  # pad subtables to a common T (repeat row 0)
            for name in _T_FIELDS:
                reps = np.repeat(sl[name][:1], t_max - t_have, axis=0)
                sl[name] = np.concatenate([sl[name], reps], axis=0)

    # replicate policy/set metadata into the stacked layout
    # hrv_role/hrv_scope stay host-side: stage B consumes the encoder's
    # packed owner bitplanes, so only t_rs_idx (a target-table column)
    # reaches the device
    replicated = [
        "set_valid", "set_ca", "set_has_target", "pol_valid", "pol_ca",
        "pol_effect", "pol_cacheable", "pol_has_target", "pol_has_subjects",
        "pol_n_rules", "pol_eff_ctx", "pol_has_props", "pol_ent_vals",
        "acl_consts",
    ]
    stacked: dict[str, np.ndarray] = {}
    for name in list(shard_arrays[0]):
        stacked[name] = np.stack([sl[name] for sl in shard_arrays])
    for name in replicated:
        stacked[name] = np.stack([a[name]] * n_shards)
    return _Partitioned(
        arrays=stacked,
        kr_local=kr_local,
        kr_offsets=np.arange(n_shards, dtype=np.int32) * kr_local,
    )


def _evaluate_chunk(c, r, kr_offset, kr_total, model_axis,
                    explain: bool = False, with_rel: bool = False):
    """Per-device evaluation of one rule chunk for one request, with
    cross-``model`` packed positional reductions.  Stages A-D reuse the
    single-device kernel helpers against this shard's compacted target
    subtable; only rule collection (E) and the abort scan differ.

    ``explain=True`` appends the packed provenance output (ops/kernel
    _combine_and_decide encoding).  The cross-shard lattice reductions
    already carry GLOBAL rule positions in the key high bits, so after
    the pmin/pmax merges every device holds the winner's identity — the
    explain code is recovered locally with zero extra collectives."""
    m = _match_targets(c, r, with_rel=with_rel)
    reached, acl_rule, has_cond, cond_t, cond_a, cond_c = _rule_predicates(c, r, m)
    pol_gate, set_gate, pol_subject = _policy_gates(c, r, m)

    # ---- rule collection on the local chunk
    scope = set_gate[:, None, None] & pol_gate[:, :, None]
    abort_rule = reached & has_cond & cond_a & scope
    matches = reached & (~has_cond | cond_t) & ~(has_cond & cond_a) & acl_rule
    coll = matches & pol_subject[:, :, None] & scope  # [S, KP, KR_local]

    KRl = coll.shape[2]
    # GLOBAL rule positions inside each (set, policy), packed with the
    # (effect, cacheable) payload via the shared combine-reduction key
    pos = (kr_offset + jnp.arange(KRl))[None, None, :]
    key_lo = pack_rule_key(pos, c["rule_effect"], c["rule_cacheable_eff"])
    key_hi = pack_rule_key(pos + 1, c["rule_effect"], c["rule_cacheable_eff"])
    BIGKEY = jnp.int32(2_000_000_000)

    def pmin_key(mask):
        local = jnp.min(jnp.where(mask, key_lo, BIGKEY), axis=2)
        return jax.lax.pmin(local, model_axis)

    def pmax_key(mask):
        local = jnp.max(jnp.where(mask, key_hi, 0), axis=2)
        return jax.lax.pmax(local, model_axis)

    k_first_deny = pmin_key(coll & (c["rule_effect"] == 2))
    k_first_permit = pmin_key(coll & (c["rule_effect"] == 1))
    k_first = pmin_key(coll)
    k_last = pmax_key(coll)
    any_coll = k_last > 0

    # k_last packs pos+1; subtracting 8 aligns its payload with the pmin
    # packing so one unpack below serves both branches
    sel_key_do = jnp.where(k_first_deny < BIGKEY,
                           k_first_deny, jnp.where(any_coll, k_last - 8, 0))
    sel_key_po = jnp.where(k_first_permit < BIGKEY,
                           k_first_permit, jnp.where(any_coll, k_last - 8, 0))
    sel_key_fa = jnp.where(k_first < BIGKEY, k_first, 0)
    sel_key = jnp.select(
        [c["pol_ca"] == 0, c["pol_ca"] == 1, c["pol_ca"] == 2],
        [sel_key_do, sel_key_po, sel_key_fa],
        default=jnp.zeros_like(sel_key_do),
    )
    rule_eff_sel, rule_cach_sel = unpack_rule_key(sel_key)

    no_rules_contrib = (
        c["pol_valid"]
        & set_gate[:, None]
        & pol_gate
        & (c["pol_n_rules"] == 0)
        & (c["pol_effect"] > 0)
    )
    contrib_present = no_rules_contrib | any_coll
    contrib_eff = jnp.where(no_rules_contrib, c["pol_effect"], rule_eff_sel)
    contrib_cach = jnp.where(
        no_rules_contrib, c["pol_cacheable"], rule_cach_sel.astype(bool)
    )

    # ---- combine policy effects + last-set-wins (identical on every
    # device after the reductions)
    if explain:
        decision, cacheable, win_s, have, s_sel_c = _combine_sets(
            c, contrib_present, contrib_eff, contrib_cach, explain=True
        )
    else:
        decision, cacheable = _combine_sets(
            c, contrib_present, contrib_eff, contrib_cach
        )
    status = jnp.int32(200)

    # ---- condition aborts: first in GLOBAL flat rule order
    S, KPn = coll.shape[0], coll.shape[1]
    flat_order = (
        jnp.arange(S)[:, None, None] * (KPn * kr_total)
        + jnp.arange(KPn)[None, :, None] * kr_total
        + (kr_offset + jnp.arange(KRl))[None, None, :]
    )
    local_abort_pos = jnp.min(jnp.where(abort_rule, flat_order, BIG))
    abort_pos = jax.lax.pmin(local_abort_pos, model_axis)
    has_abort = abort_pos < BIG
    # exactly one device owns the winning position (positions are unique),
    # so max-reductions broadcast its code/cacheable
    i_own = (local_abort_pos == abort_pos) & has_abort
    abort_flat = jnp.argmin(jnp.where(abort_rule, flat_order, BIG))
    code_local = jnp.where(
        i_own, jnp.take(cond_c.reshape(-1), abort_flat), 0
    )
    cach_local = jnp.where(
        i_own,
        jnp.take(c["rule_cacheable_raw"].reshape(-1), abort_flat).astype(
            jnp.int32
        ) + 1,
        0,
    )
    abort_code = jax.lax.pmax(code_local, model_axis)
    abort_cach = jax.lax.pmax(cach_local, model_axis) - 1

    decision = jnp.where(has_abort, 2, decision)
    cacheable = jnp.where(has_abort, abort_cach, cacheable)
    status = jnp.where(has_abort, abort_code, status)

    if not explain:
        return decision.astype(jnp.int32), cacheable, status.astype(jnp.int32)

    # ---- explain recovery (replicated): ``sel_key`` already merged the
    # cross-shard reductions, so its high bits name the winning GLOBAL
    # kr; strides for host decode are (KP, kr_total)
    win_kp = jnp.take(s_sel_c, win_s)
    win_flat = win_s * KPn + win_kp
    win_kr_global = jnp.take(sel_key.reshape(-1), win_flat) >> 3
    no_rules_win = jnp.take(no_rules_contrib.reshape(-1), win_flat)
    rule_pos = win_flat * kr_total + win_kr_global
    expl = jnp.where(
        have,
        jnp.where(no_rules_win, (win_flat << 2) | 2, (rule_pos << 2) | 1),
        0,
    )
    expl = jnp.where(has_abort, (abort_pos << 2) | 3, expl)
    return (decision.astype(jnp.int32), cacheable,
            status.astype(jnp.int32), expl.astype(jnp.int32))


class RuleShardedKernel:
    """Two-axis sharded kernel: requests over ``data``, rules over
    ``model``; per-shard compacted target subtables; ICI traffic is the
    per-(set, policy) packed keys only.

    Hot-update note: this kernel is NOT delta-patchable (ops/delta.py) —
    ``partition_rules`` re-slices and re-compacts per shard, so a mutated
    tree needs a fresh partition + device placement anyway.  The evaluator
    therefore disables the incremental path whenever ``model_axis`` is
    configured (srv/evaluator.py) and every mutation takes the
    version-pinned full recompile; ``supports_delta`` makes the contract
    explicit for callers probing kernels generically."""

    supports_delta = False

    def __init__(self, compiled: CompiledPolicies, mesh: Mesh,
                 data_axis: str = "data", model_axis: str = "model",
                 explain: bool = False):
        if not compiled.supported:
            raise ValueError(
                f"policy tree unsupported: {compiled.unsupported_reason}"
            )
        self.compiled = compiled
        self.mesh = mesh
        self.data_axis = data_axis
        self.model_axis = model_axis
        self.n_data = mesh.shape[data_axis]
        self.n_model = mesh.shape[model_axis]
        self.explain = bool(explain)

        part = partition_rules(compiled, self.n_model)
        self._kr_total = part.kr_local * self.n_model
        # decode strides: rule flat positions use the PADDED global kr
        # extent, not compiled.KR (host decode must use these)
        self.explain_strides = (compiled.KP, self._kr_total)
        self._c = {
            k: jax.device_put(
                jnp.asarray(v), NamedSharding(mesh, P(model_axis))
            )
            for k, v in part.arrays.items()
        }
        self._offsets = jax.device_put(
            jnp.asarray(part.kr_offsets), NamedSharding(mesh, P(model_axis))
        )
        kr_total = self._kr_total
        with_rel = tree_needs_rel(compiled.arrays)

        c_specs = {k: P(model_axis) for k in self._c}

        def run(c, offsets, batch_arrays, rgx_set, pfx_neq):
            c_local = {k: v[0] for k, v in c.items()}
            kr_offset = offsets[0]

            def one(ra):
                rr = {**ra, "rgx_set": rgx_set, "pfx_neq": pfx_neq}
                return _evaluate_chunk(
                    c_local, rr, kr_offset, kr_total, model_axis,
                    explain=explain, with_rel=with_rel,
                )

            return jax.vmap(one)(batch_arrays)

        from .mesh import wrap_shard_map

        wrapped = wrap_shard_map(
            run,
            mesh=mesh,
            in_specs=(c_specs, P(model_axis), P(data_axis), P(), P()),
            out_specs=(P(data_axis),) * (4 if explain else 3),
        )
        self._run = jax.jit(wrapped)

    def evaluate(self, batch: RequestBatch):
        return self.evaluate_async(batch)()

    def evaluate_async(self, batch: RequestBatch):
        """Dispatch without blocking (returns the materialize callable —
        the rule-sharded leg of the depth-N serving pipeline).

        Batch and regex-matrix axes are padded to power-of-two buckets
        (divisible by the data-axis size) before entering jit — the same
        scheme as DecisionKernel.evaluate, so serving traffic with varying
        batch sizes reuses a handful of compiled programs instead of
        triggering a fresh XLA compile per distinct size."""
        # failpoint (srv/faults.py): host-side dispatch boundary — fires
        # before any device work, so the lowered program is unchanged
        from ..srv.faults import REGISTRY as _faults

        _faults.fire("device.dispatch")
        arrays = dict(batch.arrays)
        arrays["cond_true"] = np.ascontiguousarray(batch.cond_true.T)
        arrays["cond_abort"] = np.ascontiguousarray(batch.cond_abort.T)
        arrays["cond_code"] = np.ascontiguousarray(batch.cond_code.T)
        from .mesh import pad_batch

        # bucket = n_data * next_pow2(ceil(B / n_data)): stable shapes AND
        # even sharding across the data axis
        from ..ops.kernel import pad_cols, pow2_bucket

        per_shard = -(-batch.B // self.n_data)
        bucket = self.n_data * pow2_bucket(per_shard)
        arrays, _ = pad_batch(arrays, batch.B, bucket)

        e_bucket = pow2_bucket(batch.rgx_set.shape[1])

        out = self._run(
            self._c,
            self._offsets,
            {k: jnp.asarray(v) for k, v in arrays.items()},
            jnp.asarray(pad_cols(batch.rgx_set, e_bucket)),
            jnp.asarray(pad_cols(batch.pfx_neq, e_bucket)),
        )
        def materialize():
            _faults.fire("device.materialize")
            return tuple(np.asarray(x)[: batch.B] for x in out)

        return materialize
