"""acs-lint: AST-based concurrency and hot-path invariant analysis.

Run as ``python -m access_control_srv_tpu.analysis``; library entry is
``run_analysis``.  Zero runtime dependencies beyond the stdlib — the
analyzer never imports the modules it checks, so it runs in any
environment (CI images without jax included).  Rule catalog, annotation
syntax, and the suppression policy live in docs/ANALYSIS.md; the
runtime lock-order complement is ``analysis.locktrace``.
"""

from .baseline import BaselineEntry, diff as baseline_diff, load as load_baseline
from .checks import check_module
from .findings import ALL_RULES, Finding, Suppression
from .runner import (
    DEFAULT_BASELINE,
    PACKAGE_ROOT,
    Report,
    render_report,
    run_analysis,
)

__all__ = [
    "ALL_RULES",
    "BaselineEntry",
    "DEFAULT_BASELINE",
    "Finding",
    "PACKAGE_ROOT",
    "Report",
    "Suppression",
    "baseline_diff",
    "check_module",
    "load_baseline",
    "render_report",
    "run_analysis",
]
