"""Runtime lock-order detector: the dynamic complement of acs-lint.

The static passes (checks.py) prove per-lock discipline — guarded state
is only touched with its lock held — but deadlock needs a SECOND kind of
invariant: a globally consistent acquisition ORDER.  Two threads taking
``A then B`` and ``B then A`` can both be lock-clean and still deadlock
under the right interleaving; no single-module lexical analysis sees it,
and chaos soaks only catch it when the scheduler cooperates.

``LockOrderWatch`` removes the scheduler from the equation: while
installed, every ``threading.Lock``/``RLock`` CREATED is wrapped, each
thread tracks its stack of held wrapped locks, and every acquisition
with locks already held records a directed edge ``held -> acquiring`` in
a process-wide graph.  A cycle in that graph is a deadlock the schedule
merely hasn't dealt yet — the two orders only need to have HAPPENED, not
to have overlapped, so a single-threaded test that takes ``A,B`` then
``B,A`` sequentially still convicts.

Scope and honesty:

* Only locks created while the watch is installed are tracked (patching
  the factory functions cannot reach pre-existing instances).  Tests
  install the watch before constructing the system under soak.
* Nodes are per-INSTANCE, labeled by creation site.  Sibling locks from
  one construction site (shard locks) stay distinct, so a consistent
  shard-ordering protocol is not a false cycle.
* Re-entrant re-acquisition of a held RLock records no edge.
* ``threading.Condition`` works unmodified: the wrappers delegate the
  private ``_release_save``/``_acquire_restore``/``_is_owned`` hooks.

Usage (tests/test_cluster_chaos.py, tests/test_pipeline.py soaks)::

    with lock_order_watch() as watch:
        ...  # build + drive the system
    watch.assert_acyclic()  # raises LockOrderError with the cycle
"""

from __future__ import annotations

import threading
from contextlib import contextmanager

__all__ = [
    "LockOrderError",
    "LockOrderWatch",
    "lock_order_watch",
]


class LockOrderError(AssertionError):
    """A lock-order cycle was observed; ``cycle`` holds the node labels
    in acquisition-edge order (first label repeats at the end)."""

    def __init__(self, cycle: list[str]):
        self.cycle = cycle
        super().__init__(
            "lock-order cycle (deadlock the scheduler hasn't dealt yet): "
            + "  ->  ".join(cycle)
        )


def _creation_site() -> str:
    """``file:line`` of the frame that called threading.Lock()/RLock(),
    skipping this module's own frames."""
    import sys

    frame = sys._getframe(2)
    while frame is not None and frame.f_globals.get("__name__") == __name__:
        frame = frame.f_back
    if frame is None:  # pragma: no cover — interpreter teardown
        return "<unknown>"
    return f"{frame.f_code.co_filename}:{frame.f_lineno}"


class _TrackedLock:
    """Wrapper over a real lock primitive feeding the order graph.

    Presents the full Lock/RLock surface plus the private hooks
    ``threading.Condition`` uses, so a tracked lock can serve as a
    condition's underlying lock.
    """

    def __init__(self, watch: "LockOrderWatch", inner, site: str, seq: int):
        self._watch = watch
        self._inner = inner
        self.label = f"{site}#{seq}"

    # ------------------------------------------------------------ acquire
    def acquire(self, *args, **kwargs):
        acquired = self._inner.acquire(*args, **kwargs)
        if acquired:
            self._watch._on_acquire(self)
        return acquired

    def release(self):
        self._watch._on_release(self)
        return self._inner.release()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    def locked(self):
        return self._inner.locked()

    # ------------------------------------- threading.Condition delegation
    # Condition lifts these from its lock when present; the wrapper always
    # has them, so it must emulate Condition's own fallbacks when the
    # inner primitive (a plain Lock) lacks the private hooks.
    def _release_save(self):
        # the condition fully releases a held (possibly re-entrant) lock
        # around wait(): mirror that in the held stack
        self._watch._on_release(self, full=True)
        inner = self._inner
        if hasattr(inner, "_release_save"):
            return inner._release_save()
        inner.release()
        return None

    def _acquire_restore(self, state):
        inner = self._inner
        if hasattr(inner, "_acquire_restore"):
            inner._acquire_restore(state)
        else:
            inner.acquire()
        self._watch._on_acquire(self)

    def _is_owned(self):
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def __repr__(self):
        return f"<tracked {self._inner!r} @ {self.label}>"


class LockOrderWatch:
    """Process-wide acquisition-order graph over tracked lock instances.

    ``install()`` patches ``threading.Lock``/``threading.RLock`` (the
    factory callables) so every lock constructed afterwards is tracked;
    ``uninstall()`` restores them.  The graph and its edge provenance
    survive uninstall for assertion."""

    def __init__(self):
        self._lock = threading.Lock()
        # edges: held_label -> {acquired_label}; provenance keeps one
        # (held, acquired) -> thread name sample for the error message
        self._edges: dict[str, set[str]] = {}       # guarded-by: _lock
        self._provenance: dict[tuple, str] = {}     # guarded-by: _lock
        self._labels: set[str] = set()              # guarded-by: _lock
        self._seq = 0                               # guarded-by: _lock
        self._held = threading.local()  # per-thread stack of _TrackedLock
        self._orig_lock = None
        self._orig_rlock = None

    # ------------------------------------------------------------ factory
    def _next_seq(self) -> int:
        with self._lock:
            self._seq += 1
            return self._seq

    def _make(self, factory) -> _TrackedLock:
        tracked = _TrackedLock(
            self, factory(), _creation_site(), self._next_seq()
        )
        with self._lock:
            self._labels.add(tracked.label)
        return tracked

    def install(self) -> "LockOrderWatch":
        if self._orig_lock is not None:
            raise RuntimeError("LockOrderWatch already installed")
        self._orig_lock = threading.Lock
        self._orig_rlock = threading.RLock
        threading.Lock = lambda: self._make(self._orig_lock)
        threading.RLock = lambda: self._make(self._orig_rlock)
        return self

    def uninstall(self) -> None:
        if self._orig_lock is None:
            return
        threading.Lock = self._orig_lock
        threading.RLock = self._orig_rlock
        self._orig_lock = None
        self._orig_rlock = None

    # ----------------------------------------------------------- tracking
    def _stack(self) -> list:
        stack = getattr(self._held, "stack", None)
        if stack is None:
            stack = self._held.stack = []
        return stack

    def _on_acquire(self, lock: _TrackedLock) -> None:
        stack = self._stack()
        if any(held is lock for held in stack):
            stack.append(lock)  # re-entrant RLock: no new edge
            return
        if stack:
            holder = threading.current_thread().name
            with self._lock:
                for held in stack:
                    self._edges.setdefault(held.label, set()).add(lock.label)
                    self._provenance.setdefault(
                        (held.label, lock.label), holder
                    )
        stack.append(lock)

    def _on_release(self, lock: _TrackedLock, full: bool = False) -> None:
        stack = self._stack()
        # remove the most recent occurrence (LIFO discipline is the
        # overwhelmingly common case; out-of-order release still tracks)
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is lock:
                del stack[i]
                if not full:
                    return
        # full=True (condition wait) drops every re-entrant occurrence

    # ---------------------------------------------------------- assertion
    def edges(self) -> dict[str, set[str]]:
        with self._lock:
            return {k: set(v) for k, v in self._edges.items()}

    def find_cycle(self) -> list[str] | None:
        """First cycle in the acquisition graph as a label path (closed:
        path[0] == path[-1]); None when acyclic."""
        graph = self.edges()
        WHITE, GRAY, BLACK = 0, 1, 2
        color = dict.fromkeys(graph, WHITE)
        parent: dict[str, str] = {}

        def dfs(root: str) -> list[str] | None:
            stack = [(root, iter(sorted(graph.get(root, ()))))]
            color[root] = GRAY
            while stack:
                node, it = stack[-1]
                advanced = False
                for succ in it:
                    state = color.get(succ, WHITE)
                    if state == GRAY:
                        cycle = [succ, node]
                        cur = node
                        while cur != succ:
                            cur = parent[cur]
                            cycle.append(cur)
                        cycle.reverse()
                        return cycle
                    if state == WHITE:
                        color[succ] = GRAY
                        parent[succ] = node
                        stack.append((succ, iter(sorted(graph.get(succ, ())))))
                        advanced = True
                        break
                if not advanced:
                    color[node] = BLACK
                    stack.pop()
            return None

        for root in sorted(graph):
            if color.get(root, WHITE) == WHITE:
                cycle = dfs(root)
                if cycle is not None:
                    return cycle
        return None

    def assert_acyclic(self) -> None:
        cycle = self.find_cycle()
        if cycle is not None:
            raise LockOrderError(cycle)


@contextmanager
def lock_order_watch():
    """Install a fresh watch for the duration of the block; the caller
    asserts (``watch.assert_acyclic()``) AFTER the block, once the system
    under soak has been torn down."""
    watch = LockOrderWatch()
    watch.install()
    try:
        yield watch
    finally:
        watch.uninstall()
