"""Comment-annotation extraction for acs-lint.

The annotation language is deliberately tiny and lives in ordinary
comments so annotated modules carry zero import-time cost:

``# guarded-by: _lock``
    On an attribute-initialising assignment (``self._data = {}`` in
    ``__init__``, a class-level declaration, or a module-level global):
    every later read/write of that attribute must happen inside a
    lexical ``with <base>.<lock>`` block over the SAME base expression,
    or inside a ``# holds:``-annotated helper.

``# holds: _lock``
    On a ``def`` line (or the line directly above it): the method is
    only ever called with the named lock(s) already held — its guarded
    accesses are exempt, and blocking calls inside it are treated as
    under-lock.

``# acs-lint: ignore[rule1, rule2] <one-line reason>``
    On the offending line (or any physical line of a multi-line
    statement): suppresses those rules for that statement.  Counted by
    the runner, never silent.

``# acs-lint: host-only``
    Anywhere in a module: declares the module host-only — any ``jax``
    import (even lazy, inside a function) becomes a finding.  The
    declaration living in the module itself is what lets
    TPU_COMPAT.md's host-only claims cite a machine-checked rule.
"""

from __future__ import annotations

import io
import re
import tokenize

_GUARDED_BY = re.compile(r"guarded-by:\s*([A-Za-z_]\w*)")
_HOLDS = re.compile(r"holds:\s*([A-Za-z_][\w,\s]*)")
_IGNORE = re.compile(r"acs-lint:\s*ignore\[([\w\-,\s]+)\]\s*(.*)")
_HOST_ONLY = re.compile(r"acs-lint:\s*host-only\b")


class ModuleComments:
    """Per-line comment index for one module, with annotation parsers.

    Built from ``tokenize`` (not the AST) because comments are invisible
    to ``ast.parse`` — this is the only place the analyzer looks at raw
    source text.
    """

    def __init__(self, source: str):
        self.by_line: dict[int, str] = {}
        # lines that are comment-ONLY: an ignore there also covers the
        # next statement (the eslint-disable-next-line convention),
        # while a trailing comment never leaks onto the line below
        self.standalone: set[int] = {
            lineno
            for lineno, text in enumerate(source.splitlines(), start=1)
            if text.lstrip().startswith("#")
        }
        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in tokens:
                if tok.type == tokenize.COMMENT:
                    self.by_line[tok.start[0]] = tok.string.lstrip("#").strip()
        except tokenize.TokenError:
            # a truncated final line still yields every earlier comment;
            # the AST parse will surface real syntax errors
            pass
        self.host_only = any(
            _HOST_ONLY.search(text) for text in self.by_line.values()
        )

    # ---------------------------------------------------------- annotations

    def guarded_by(self, line: int) -> str | None:
        """Lock name from a ``guarded-by:`` comment on this line."""
        match = _GUARDED_BY.search(self.by_line.get(line, ""))
        return match.group(1) if match else None

    def holds(self, line: int) -> set[str]:
        """Lock names from a ``holds:`` comment on this line or the line
        directly above (for defs whose signature fills the line)."""
        for candidate in (line, line - 1):
            match = _HOLDS.search(self.by_line.get(candidate, ""))
            if match:
                return {
                    name.strip()
                    for name in match.group(1).split(",")
                    if name.strip()
                }
        return set()

    def ignored_rules(self, first_line: int,
                      last_line: int | None = None) -> dict[str, str]:
        """``{rule: reason}`` for every ``acs-lint: ignore[...]`` comment
        on any physical line of the statement span."""
        out: dict[str, str] = {}
        lines = list(range(first_line, (last_line or first_line) + 1))
        # a standalone comment BLOCK directly above the statement also
        # covers it, so a suppression's reason can run to several lines
        above = first_line - 1
        while above in self.standalone:
            lines.append(above)
            above -= 1
        for line in lines:
            match = _IGNORE.search(self.by_line.get(line, ""))
            if match:
                reason = match.group(2).strip()
                for rule in match.group(1).split(","):
                    rule = rule.strip()
                    if rule:
                        out[rule] = reason
        return out
