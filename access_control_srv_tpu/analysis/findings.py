"""Finding model for acs-lint.

A finding is identified by ``(path, rule, symbol)`` — deliberately **no
line numbers** — so refactors that move code without changing what it
does don't churn the checked-in baseline (docs/ANALYSIS.md).  ``line``
and ``message`` ride along for human output only and never participate
in identity, sorting beyond tie-breaks, or serialization to the
baseline.
"""

from __future__ import annotations

from dataclasses import dataclass, field

# the rule catalog (docs/ANALYSIS.md) — names are stable: they appear in
# inline suppressions (# acs-lint: ignore[rule]) and baseline.json
RULE_GUARDED_BY = "guarded-by"
RULE_BLOCKING_UNDER_LOCK = "blocking-under-lock"
RULE_WALL_CLOCK = "wall-clock"
RULE_HOST_ONLY_JAX = "host-only-jax"
RULE_THREAD_LIFECYCLE = "thread-lifecycle"
RULE_DISPATCH_PURITY = "dispatch-purity"

ALL_RULES = (
    RULE_GUARDED_BY,
    RULE_BLOCKING_UNDER_LOCK,
    RULE_WALL_CLOCK,
    RULE_HOST_ONLY_JAX,
    RULE_THREAD_LIFECYCLE,
    RULE_DISPATCH_PURITY,
)


@dataclass(frozen=True)
class Finding:
    """One rule violation at one symbol in one module."""

    path: str    # repo-relative posix path of the module
    rule: str    # one of ALL_RULES
    symbol: str  # qualified symbol, e.g. "DecisionCache.get:self._epoch"
    message: str = field(default="", compare=False)
    line: int = field(default=0, compare=False)

    @property
    def key(self) -> tuple[str, str, str]:
        return (self.path, self.rule, self.symbol)

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.rule}] "
                f"{self.symbol} — {self.message}")


@dataclass(frozen=True)
class Suppression:
    """An inline ``# acs-lint: ignore[rule]`` that actually absorbed a
    finding — the tool counts these so silenced findings stay visible."""

    path: str
    rule: str
    symbol: str
    line: int
    reason: str = ""


def dedupe(findings: list[Finding]) -> list[Finding]:
    """Stable de-duplication by identity key (the first occurrence's
    line/message win — it's the lexically earliest site)."""
    seen: set[tuple[str, str, str]] = set()
    out: list[Finding] = []
    for finding in findings:
        if finding.key in seen:
            continue
        seen.add(finding.key)
        out.append(finding)
    return out
