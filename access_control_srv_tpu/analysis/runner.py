"""acs-lint runner: walk a tree, run every pass, gate on the baseline.

``run_analysis`` is the library entry (used by tests and the
``static-invariants-clean`` audit row); ``__main__`` wraps it as
``python -m access_control_srv_tpu.analysis``.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path

from . import baseline as baseline_mod
from .baseline import BaselineDiff
from .checks import check_module
from .findings import Finding, Suppression

# the shipped scan root: the package itself
PACKAGE_ROOT = Path(__file__).resolve().parents[1]
REPO_ROOT = PACKAGE_ROOT.parent
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"

# generated modules are not ours to lint
_SKIP_SUFFIXES = ("_pb2.py", "_pb2_grpc.py")


@dataclass
class Report:
    findings: list[Finding] = field(default_factory=list)
    suppressions: list[Suppression] = field(default_factory=list)
    errors: list[str] = field(default_factory=list)  # unparsable modules
    modules: int = 0
    diff: BaselineDiff | None = None

    @property
    def ok(self) -> bool:
        if self.errors:
            return False
        if self.diff is not None:
            return self.diff.clean
        return not self.findings

    def by_rule(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for finding in self.findings:
            out[finding.rule] = out.get(finding.rule, 0) + 1
        return out

    def to_json(self) -> dict:
        out = {
            "modules": self.modules,
            "findings": [
                {"path": f.path, "rule": f.rule, "symbol": f.symbol,
                 "line": f.line, "message": f.message}
                for f in self.findings
            ],
            "suppressions": [
                {"path": s.path, "rule": s.rule, "symbol": s.symbol,
                 "line": s.line, "reason": s.reason}
                for s in self.suppressions
            ],
            "errors": list(self.errors),
            "by_rule": self.by_rule(),
            "ok": self.ok,
        }
        if self.diff is not None:
            out["baseline"] = {
                "matched": self.diff.matched,
                "new": [list(f.key) for f in self.diff.new],
                "stale": [list(e.key) for e in self.diff.stale],
                "unjustified": [list(e.key)
                                for e in self.diff.unjustified],
            }
        return out


def iter_modules(root: Path):
    for path in sorted(root.rglob("*.py")):
        if any(path.name.endswith(sfx) for sfx in _SKIP_SUFFIXES):
            continue
        yield path


def run_analysis(root: str | Path = PACKAGE_ROOT,
                 baseline: str | Path | None = None,
                 rel_to: str | Path | None = None) -> Report:
    """Analyze every module under ``root``.  With ``baseline``, the
    report's ``ok`` reflects the baseline gate (new finding OR stale
    entry OR missing justification fails); without, any finding fails.

    ``rel_to`` controls the path prefix in finding identity (defaults
    to the repo root for the shipped tree, ``root`` otherwise so fixture
    trees produce stable keys wherever they're checked out)."""
    root = Path(root).resolve()
    if rel_to is None:
        rel_to = REPO_ROOT if root.is_relative_to(REPO_ROOT) else root
    rel_to = Path(rel_to).resolve()
    report = Report()
    for path in iter_modules(root):
        rel = path.relative_to(rel_to).as_posix()
        try:
            source = path.read_text()
            findings, suppressions = check_module(rel, source)
        except (SyntaxError, UnicodeDecodeError) as err:
            report.errors.append(f"{rel}: {err}")
            continue
        report.modules += 1
        report.findings.extend(findings)
        report.suppressions.extend(suppressions)
    report.findings.sort(key=lambda f: f.key)
    if baseline is not None:
        entries = baseline_mod.load(baseline)
        report.diff = baseline_mod.diff(report.findings, entries)
    return report


def render_report(report: Report, verbose: bool = False) -> str:
    lines: list[str] = []
    diff = report.diff
    shown = report.findings if diff is None else diff.new
    for finding in shown:
        lines.append(finding.render())
    if diff is not None:
        for entry in diff.stale:
            lines.append(
                f"{entry.path}: [stale-baseline] {entry.rule} "
                f"{entry.symbol} — baselined finding no longer exists; "
                "remove the entry (a stale suppression can swallow a "
                "future regression)"
            )
        for entry in diff.unjustified:
            lines.append(
                f"{entry.path}: [unjustified-baseline] {entry.rule} "
                f"{entry.symbol} — baseline entries require a one-line "
                "justification"
            )
    for error in report.errors:
        lines.append(f"[parse-error] {error}")
    if verbose:
        for sup in report.suppressions:
            lines.append(
                f"{sup.path}:{sup.line}: [suppressed:{sup.rule}] "
                f"{sup.symbol} — {sup.reason or '(no reason given)'}"
            )
    counted = len(report.suppressions)
    baselined = diff.matched if diff is not None else 0
    status = "clean" if report.ok else "FAILED"
    lines.append(
        f"acs-lint: {status} — {report.modules} modules, "
        f"{len(report.findings)} findings "
        f"({baselined} baselined), {counted} inline suppressions"
    )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m access_control_srv_tpu.analysis",
        description="acs-lint: concurrency and hot-path invariant "
                    "analysis (docs/ANALYSIS.md)",
    )
    parser.add_argument("--root", default=str(PACKAGE_ROOT),
                        help="tree to analyze (default: the package)")
    parser.add_argument("--baseline", default=str(DEFAULT_BASELINE),
                        help="baseline JSON (default: the checked-in "
                             "analysis/baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline: report every finding")
    parser.add_argument("--write-baseline", action="store_true",
                        help="rewrite the baseline from current findings "
                             "(carries over existing justifications)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable report on stdout")
    parser.add_argument("--verbose", action="store_true",
                        help="also list counted inline suppressions")
    args = parser.parse_args(argv)

    baseline_path = None if args.no_baseline else args.baseline
    report = run_analysis(args.root, baseline=baseline_path)

    if args.write_baseline:
        carried = {
            e.key: e.justification
            for e in baseline_mod.load(args.baseline)
        }
        baseline_mod.save(args.baseline, report.findings, carried)
        print(f"wrote {args.baseline} "
              f"({len(report.findings)} suppressions)")
        return 0

    if args.json:
        print(json.dumps(report.to_json(), indent=1))
    else:
        print(render_report(report, verbose=args.verbose))
    return 0 if report.ok else 1
