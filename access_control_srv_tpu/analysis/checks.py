"""The acs-lint pass families: lock discipline + hot-path purity.

Everything here is one ``ast`` walk per module (plus a tokenize pass for
comments, annotations.py) — zero runtime dependencies beyond stdlib, so
the analyzer can run in any environment the package imports in,
including CI images without jax.

Rules (names in findings.py, rationale in docs/ANALYSIS.md):

- ``guarded-by``           read/write of an annotated attribute outside
                           a lexical ``with <base>.<lock>`` over the
                           same base (or a ``holds:`` helper)
- ``blocking-under-lock``  RPC / queue / socket / sleep / device-sync
                           call lexically inside a ``with <lock>`` body
- ``wall-clock``           any ``time.time()`` — deadline/TTL math must
                           use ``time.monotonic()`` (PR 5's budgets)
- ``host-only-jax``        ``jax`` import in a module declared
                           ``# acs-lint: host-only``
- ``thread-lifecycle``     a ``threading.Thread`` neither daemonized nor
                           joined anywhere in its module
- ``dispatch-purity``      ``block_until_ready`` / ``np.asarray`` of a
                           dispatch result inside the dispatch half of
                           an ``evaluate_async`` (the materialize thunk
                           — nested def/lambda — is exempt)
"""

from __future__ import annotations

import ast
import re

from .annotations import ModuleComments
from .findings import (
    Finding,
    RULE_BLOCKING_UNDER_LOCK,
    RULE_DISPATCH_PURITY,
    RULE_GUARDED_BY,
    RULE_HOST_ONLY_JAX,
    RULE_THREAD_LIFECYCLE,
    RULE_WALL_CLOCK,
    Suppression,
    dedupe,
)

# with-context names treated as locks for blocking-under-lock: anything
# whose final attribute/name looks lock-ish, plus every lock registered
# through a guarded-by annotation in the module
_LOCKISH = re.compile(r"(?i)(lock|cond|mutex)")

# method names that block the calling thread: device sync, sleeps,
# joins, socket/file-durability I/O, RPC entry points
_BLOCKING_METHODS = {
    "block_until_ready", "sleep", "recv", "recv_into", "sendall",
    "accept", "connect", "readline", "urlopen", "fsync", "with_call",
    "result", "getaddrinfo", "create_connection",
}
# .join blocks only on threads/processes — str.join and os.path.join are
# pure; require a threadish receiver before flagging
_THREADISH = re.compile(r"(?i)(thread|proc|worker|timer|pump|executor)")
# cond.wait/wait_for ON the held condition is the legitimate
# condition-variable pattern; on anything else it's a blocked thread
_WAIT_METHODS = {"wait", "wait_for"}
# .get blocks only on queues — flagged when the receiver looks like a
# queue or the call passes Queue.get's block/timeout kwargs
_QUEUEISH = re.compile(r"(?i)(queue|jobs|inbox|mailbox|\bq\b)")

_TIME_MODULES = {"time", "_time"}


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001 — unparse gaps on exotic nodes
        return "<expr>"


class _ModuleIndex(ast.NodeVisitor):
    """Pre-pass: guard registry, holds map, thread join/daemon sites."""

    def __init__(self, comments: ModuleComments):
        self.comments = comments
        # attribute name -> set of lock names that may guard it (union
        # across classes: guarded access requires `with <base>.<lock>`
        # over the SAME base text, so cross-class collisions stay safe)
        self.attr_guards: dict[str, set[str]] = {}
        # module-global name -> set of lock names
        self.name_guards: dict[str, set[str]] = {}
        # id(FunctionDef) -> lock names the caller must hold
        self.holds: dict[int, set[str]] = {}
        # base texts that .join()/daemon-assign somewhere in the module
        self.joined_bases: set[str] = set()
        self.daemonized_bases: set[str] = set()
        self._class_depth = 0

    # ------------------------------------------------------------- guards

    def _register_assign(self, node, targets) -> None:
        lock = self.comments.guarded_by(node.lineno)
        if not lock:
            return
        for target in targets:
            if (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"):
                self.attr_guards.setdefault(target.attr, set()).add(lock)
            elif isinstance(target, ast.Name):
                if self._class_depth:
                    self.attr_guards.setdefault(target.id, set()).add(lock)
                else:
                    self.name_guards.setdefault(target.id, set()).add(lock)

    def visit_Assign(self, node: ast.Assign) -> None:
        self._register_assign(node, node.targets)
        # `t.daemon = True` after construction counts as daemonized
        if (len(node.targets) == 1
                and isinstance(node.targets[0], ast.Attribute)
                and node.targets[0].attr == "daemon"
                and isinstance(node.value, ast.Constant)
                and node.value.value is True):
            self.daemonized_bases.add(_unparse(node.targets[0].value))
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._register_assign(node, [node.target])
        self.generic_visit(node)

    # -------------------------------------------------------------- holds

    def _register_def(self, node) -> None:
        locks = self.comments.holds(node.lineno)
        if locks:
            self.holds[id(node)] = locks
        self.generic_visit(node)

    def visit_FunctionDef(self, node):  # noqa: N802
        self._register_def(node)

    def visit_AsyncFunctionDef(self, node):  # noqa: N802
        self._register_def(node)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class_depth += 1
        self.generic_visit(node)
        self._class_depth -= 1

    # ------------------------------------------------------------- joins

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr == "join":
            self.joined_bases.add(_unparse(func.value))
        self.generic_visit(node)


def _is_thread_ctor(func: ast.AST) -> bool:
    if isinstance(func, ast.Attribute) and func.attr == "Thread":
        return True
    return isinstance(func, ast.Name) and func.id == "Thread"


def _daemon_kwarg_true(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
            return kw.value.value is True
    return False


class ModuleChecker(ast.NodeVisitor):
    """The main walk: lock discipline + purity over one module."""

    def __init__(self, path: str, source: str, tree: ast.Module,
                 comments: ModuleComments):
        self.path = path
        self.tree = tree
        self.comments = comments
        self.index = _ModuleIndex(comments)
        self.index.visit(tree)
        self.findings: list[Finding] = []
        self.suppressions: list[Suppression] = []
        # lexical state
        self._func_stack: list[ast.AST] = []
        self._class_stack: list[str] = []
        # active `with` locks: (base_text or None, lock_name, full_text)
        self._withlocks: list[tuple[str | None, str, str]] = []
        self._known_locks = set()
        for locks in self.index.attr_guards.values():
            self._known_locks |= locks
        for locks in self.index.name_guards.values():
            self._known_locks |= locks
        self._thread_calls_handled: set[int] = set()

    # --------------------------------------------------------------- emit

    def _qualname(self) -> str:
        parts = list(self._class_stack)
        for func in self._func_stack:
            name = getattr(func, "name", "<lambda>")
            parts.append(name)
        return ".".join(parts) or "<module>"

    def _emit(self, rule: str, symbol: str, message: str,
              node: ast.AST) -> None:
        first = getattr(node, "lineno", 1)
        last = getattr(node, "end_lineno", first)
        ignored = self.comments.ignored_rules(first, last)
        if rule in ignored:
            self.suppressions.append(Suppression(
                path=self.path, rule=rule, symbol=symbol,
                line=first, reason=ignored[rule],
            ))
            return
        self.findings.append(Finding(
            path=self.path, rule=rule, symbol=symbol,
            message=message, line=first,
        ))

    # ------------------------------------------------------------ imports

    def _check_import(self, node, modname: str) -> None:
        if not self.comments.host_only:
            return
        if modname == "jax" or modname.startswith("jax."):
            self._emit(
                RULE_HOST_ONLY_JAX,
                f"{self._qualname()}:import {modname}",
                "module is declared `# acs-lint: host-only` but imports "
                "jax — host-only modules must never touch the device "
                "runtime (TPU_COMPAT.md zero-device-ops rows)",
                node,
            )

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            self._check_import(node, alias.name)
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module:
            self._check_import(node, node.module)
        self.generic_visit(node)

    # ----------------------------------------------------- scope tracking

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class_stack.append(node.name)
        # a class body opens a fresh lexical scope: with-locks from an
        # enclosing function don't cover a nested class (rare, safe)
        saved, self._withlocks = self._withlocks, []
        self.generic_visit(node)
        self._withlocks = saved
        self._class_stack.pop()

    def _visit_func(self, node) -> None:
        self._func_stack.append(node)
        saved, self._withlocks = self._withlocks, []
        self.generic_visit(node)
        self._withlocks = saved
        self._func_stack.pop()

    def visit_FunctionDef(self, node):  # noqa: N802
        if node.name == "evaluate_async":
            self._check_dispatch_purity(node)
        self._visit_func(node)

    def visit_AsyncFunctionDef(self, node):  # noqa: N802
        self._visit_func(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # lambdas KEEP the enclosing with-lock context: predicates like
        # `cond.wait_for(lambda: token in self._released)` evaluate with
        # the condition held — clearing the context would flag the
        # canonical condition-variable pattern
        self._func_stack.append(node)
        self.generic_visit(node)
        self._func_stack.pop()

    def visit_With(self, node: ast.With) -> None:
        pushed = 0
        for item in node.items:
            ce = item.context_expr
            if isinstance(ce, ast.Attribute):
                self._withlocks.append(
                    (_unparse(ce.value), ce.attr, _unparse(ce)))
                pushed += 1
            elif isinstance(ce, ast.Name):
                self._withlocks.append((None, ce.id, ce.id))
                pushed += 1
            for expr in filter(None, (item.context_expr,
                                      item.optional_vars)):
                self.visit(expr)
        for stmt in node.body:
            self.visit(stmt)
        del self._withlocks[len(self._withlocks) - pushed:]

    visit_AsyncWith = visit_With

    # ------------------------------------------------------ lock discipline

    def _holds_any(self, locks: set[str]) -> bool:
        for func in self._func_stack:
            if self.index.holds.get(id(func), set()) & locks:
                return True
        return False

    def _in_init_of_self(self, base: str) -> bool:
        if base != "self":
            return False
        return any(getattr(f, "name", "") in ("__init__", "__new__")
                   for f in self._func_stack)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        locks = self.index.attr_guards.get(node.attr)
        if locks:
            base = _unparse(node.value)
            held = any(
                lock in locks and base_text == base
                for base_text, lock, _full in self._withlocks
            )
            if (not held and not self._holds_any(locks)
                    and not self._in_init_of_self(base)):
                want = " or ".join(sorted(locks))
                self._emit(
                    RULE_GUARDED_BY,
                    f"{self._qualname()}:{base}.{node.attr}",
                    f"`{base}.{node.attr}` is guarded-by `{want}` but "
                    f"accessed outside `with {base}.{want}` (and no "
                    "enclosing `# holds:` annotation)",
                    node,
                )
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        locks = self.index.name_guards.get(node.id)
        if locks and self._func_stack:
            held = any(
                base_text is None and lock in locks
                for base_text, lock, _full in self._withlocks
            )
            if not held and not self._holds_any(locks):
                want = " or ".join(sorted(locks))
                self._emit(
                    RULE_GUARDED_BY,
                    f"{self._qualname()}:{node.id}",
                    f"global `{node.id}` is guarded-by `{want}` but "
                    f"accessed outside `with {want}`",
                    node,
                )
        self.generic_visit(node)

    # ----------------------------------------------------------- blocking

    def _lockish_withs(self) -> list[tuple[str | None, str, str]]:
        return [
            entry for entry in self._withlocks
            if _LOCKISH.search(entry[1]) or entry[1] in self._known_locks
        ]

    def _holds_locks(self) -> set[str]:
        out: set[str] = set()
        for func in self._func_stack:
            out |= self.index.holds.get(id(func), set())
        return out

    def _check_blocking(self, node: ast.Call) -> None:
        # a ``# holds:`` helper runs with the named lock held by contract,
        # so its blocking calls stall contenders exactly like a lexical
        # ``with`` — both count as held context here
        held = self._lockish_withs()
        held += [(None, lock, f"{lock} (held per # holds:)")
                 for lock in sorted(self._holds_locks())]
        if not held:
            return
        func = node.func
        if not isinstance(func, ast.Attribute):
            return
        method = func.attr
        base_text = _unparse(func.value)
        blocking = False
        if method in _BLOCKING_METHODS:
            blocking = True
        elif method == "join":
            blocking = bool(_THREADISH.search(base_text))
        elif method in _WAIT_METHODS:
            # cond.wait()/wait_for() ON a held condition is the pattern
            # that releases the lock while waiting — anything else
            # blocks with the lock held
            blocking = all(base_text != full for _b, _l, full in held)
        elif method == "get":
            has_block_kwargs = any(
                kw.arg in ("timeout", "block") for kw in node.keywords
            )
            blocking = has_block_kwargs or bool(_QUEUEISH.search(base_text))
        if blocking:
            inside = ", ".join(full for _b, _l, full in held)
            self._emit(
                RULE_BLOCKING_UNDER_LOCK,
                f"{self._qualname()}:{base_text}.{method}",
                f"blocking call `{base_text}.{method}(...)` lexically "
                f"inside `with {inside}` — holders stall every thread "
                "contending for the lock",
                node,
            )

    # --------------------------------------------------------- wall clock

    def _check_wall_clock(self, node: ast.Call) -> None:
        func = node.func
        if (isinstance(func, ast.Attribute) and func.attr == "time"
                and isinstance(func.value, ast.Name)
                and func.value.id in _TIME_MODULES):
            self._emit(
                RULE_WALL_CLOCK,
                f"{self._qualname()}:time.time",
                "wall-clock time.time() jumps under NTP slew — use "
                "time.monotonic() (or srv/clock.monotonic_wall for "
                "epoch-anchored stamps); suppress only for human-facing "
                "display values",
                node,
            )

    # ------------------------------------------------------ thread rules

    def _check_thread(self, node: ast.Call,
                      target_text: str | None) -> None:
        if _daemon_kwarg_true(node):
            return
        if target_text and (
                target_text in self.index.joined_bases
                or target_text in self.index.daemonized_bases):
            return
        what = target_text or "<unassigned>"
        self._emit(
            RULE_THREAD_LIFECYCLE,
            f"{self._qualname()}:Thread({what})",
            f"threading.Thread bound to `{what}` is neither "
            "daemon=True nor .join()ed anywhere in this module — "
            "non-daemon threads outlive stop() and hang interpreter "
            "shutdown",
            node,
        )

    def visit_Assign(self, node: ast.Assign) -> None:
        if (isinstance(node.value, ast.Call)
                and _is_thread_ctor(node.value.func)
                and len(node.targets) == 1):
            self._thread_calls_handled.add(id(node.value))
            self._check_thread(node.value, _unparse(node.targets[0]))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if (_is_thread_ctor(node.func)
                and id(node) not in self._thread_calls_handled):
            self._check_thread(node, None)
        self._check_blocking(node)
        self._check_wall_clock(node)
        self.generic_visit(node)

    # -------------------------------------------------- dispatch purity

    def _check_dispatch_purity(self, node) -> None:
        """The dispatch half of evaluate_async must only enqueue device
        work; materialization belongs in the returned thunk (nested
        def/lambda), or the pipeline's overlap collapses to sync."""

        def body_nodes(root):
            """Walk excluding nested function bodies (the thunk)."""
            stack = list(ast.iter_child_nodes(root))
            while stack:
                child = stack.pop()
                yield child
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                stack.extend(ast.iter_child_nodes(child))

        call_bound: set[str] = set()
        for child in body_nodes(node):
            if (isinstance(child, ast.Assign)
                    and isinstance(child.value, ast.Call)):
                for target in child.targets:
                    if isinstance(target, ast.Name):
                        call_bound.add(target.id)
        qual = ".".join(self._class_stack + [node.name])
        for child in body_nodes(node):
            if not isinstance(child, ast.Call):
                continue
            func = child.func
            if (isinstance(func, ast.Attribute)
                    and func.attr == "block_until_ready"):
                self._sync_finding(qual, "block_until_ready", child)
            if (isinstance(func, ast.Attribute) and func.attr == "asarray"
                    and isinstance(func.value, ast.Name)
                    and func.value.id == "np"
                    and child.args
                    and isinstance(child.args[0], ast.Name)
                    and child.args[0].id in call_bound):
                self._sync_finding(
                    qual, f"np.asarray({child.args[0].id})", child)

    def _sync_finding(self, qual: str, what: str, node: ast.AST) -> None:
        self._emit(
            RULE_DISPATCH_PURITY,
            f"{qual}:{what}",
            f"`{what}` in the dispatch half of evaluate_async forces a "
            "device sync before the thunk runs — materialization "
            "belongs in the returned thunk (docs/PIPELINE.md)",
            node,
        )


def check_module(path: str, source: str) -> tuple[list[Finding],
                                                  list[Suppression]]:
    """Run every pass over one module's source; returns (findings,
    counted inline suppressions).  ``path`` is the repo-relative posix
    path used in finding identity."""
    tree = ast.parse(source, filename=path)
    comments = ModuleComments(source)
    checker = ModuleChecker(path, source, tree, comments)
    checker.visit(tree)
    return dedupe(checker.findings), checker.suppressions
