"""Checked-in suppression baseline for acs-lint.

``analysis/baseline.json`` holds the findings the team has looked at
and accepted, each with a one-line justification.  Entries are keyed
``(path, rule, symbol)`` — no line numbers, so refactors that move code
don't churn the file.  The runner fails on BOTH directions of drift:

- a finding not in the baseline (new violation), and
- a baseline entry whose finding no longer exists (stale suppression —
  the code was fixed or the symbol renamed; the entry must be removed
  so the suppression can't silently swallow a future regression).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from pathlib import Path

from .findings import Finding

BASELINE_VERSION = 1


@dataclass(frozen=True)
class BaselineEntry:
    path: str
    rule: str
    symbol: str
    justification: str = ""

    @property
    def key(self) -> tuple[str, str, str]:
        return (self.path, self.rule, self.symbol)


@dataclass
class BaselineDiff:
    new: list[Finding]            # findings with no baseline entry
    stale: list[BaselineEntry]    # entries with no live finding
    unjustified: list[BaselineEntry]  # entries missing a justification
    matched: int = 0

    @property
    def clean(self) -> bool:
        return not (self.new or self.stale or self.unjustified)


def load(path: str | Path) -> list[BaselineEntry]:
    path = Path(path)
    if not path.exists():
        return []
    data = json.loads(path.read_text())
    return [
        BaselineEntry(
            path=entry["path"], rule=entry["rule"],
            symbol=entry["symbol"],
            justification=entry.get("justification", ""),
        )
        for entry in data.get("suppressions", [])
    ]


def save(path: str | Path, findings: list[Finding],
         justifications: dict[tuple[str, str, str], str] | None = None
         ) -> None:
    """Serialize findings as a fresh baseline (``--write-baseline``).
    Existing justifications are carried over by key; new entries get an
    empty justification the runner will refuse until filled in."""
    justifications = justifications or {}
    entries = [
        {
            "path": f.path, "rule": f.rule, "symbol": f.symbol,
            "justification": justifications.get(f.key, ""),
        }
        for f in sorted(findings, key=lambda f: f.key)
    ]
    Path(path).write_text(json.dumps(
        {"version": BASELINE_VERSION, "suppressions": entries}, indent=1,
    ) + "\n")


def diff(findings: list[Finding],
         entries: list[BaselineEntry]) -> BaselineDiff:
    finding_keys = {f.key for f in findings}
    entry_keys = {e.key for e in entries}
    return BaselineDiff(
        new=sorted((f for f in findings if f.key not in entry_keys),
                   key=lambda f: f.key),
        stale=sorted((e for e in entries if e.key not in finding_keys),
                     key=lambda e: e.key),
        unjustified=sorted(
            (e for e in entries
             if e.key in finding_keys and not e.justification.strip()),
            key=lambda e: e.key),
        matched=len(finding_keys & entry_keys),
    )
