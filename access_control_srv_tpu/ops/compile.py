"""Policy compiler: lowers the PolicySet -> Policy -> Rule tree into dense
integer/bool tensors for the batched decision kernel.

Layout: a padded ``[S, KP, KR]`` tree (sets x max-policies x max-rules) so
the combining algorithms become masked reductions along static axes, plus a
flat target table of ``T`` rows (set/policy/rule targets) whose match bits
the kernel computes once per request and gathers per node.

Everything order-dependent in the reference is resolved at compile time:

- ``pol_eff_ctx``: the carried-over ``policyEffect`` visible when each
  policy's target is matched (reference: src/core/accessController.ts:130,
  138-148 — only ``policy.effect`` ever feeds it; the combining-algorithm
  branch is dead code);
- ``rule_cacheable_eff``: prefix-AND evaluation_cacheable semantics
  (reference: :202-211, 277-282);
- flat rule order for condition-abort priority (reference: :240-270 returns
  on the first aborting rule in set->policy->rule iteration order).

Trees outside the kernel's representable subset (attribute counts beyond
the caps, targets mixing multiple entities with properties, missing
combining algorithms on populated nodes) are flagged ``supported=False``
and served entirely by the scalar oracle.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..core.relation_path import parse_path
from ..models.model import PolicySet, Target
from ..models.urns import Urns
from .interner import ABSENT, StringInterner

# attribute-count caps per target row (tensor padding widths)
K_SUB = 6   # subject attribute pairs
K_ACT = 3   # action attribute pairs
K_ENT = 2   # entity attributes in resources
K_OP = 2    # operation attributes in resources
K_PROP = 12  # property attributes in resources

CA_CODES = {
    "urn:oasis:names:tc:xacml:3.0:rule-combining-algorithm:deny-overrides": 0,
    "urn:oasis:names:tc:xacml:3.0:rule-combining-algorithm:permit-overrides": 1,
    "urn:oasis:names:tc:xacml:3.0:rule-combining-algorithm:first-applicable": 2,
}

EFFECT_CODES = {None: 0, "": 0, "PERMIT": 1, "DENY": 2}

DECISION_NAMES = {0: "INDETERMINATE", 1: "PERMIT", 2: "DENY"}


@dataclass
class CompiledCondition:
    """A host-assisted rule predicate: the condition source plus its
    context query (pre-resolved per request before the kernel runs)."""

    rule_flat_index: int
    condition: str
    context_query: Optional[object] = None
    # identity path of the owning rule: ("rule", set_id, pol_key, rule_key)
    # — lets the delta patcher (ops/delta.py) re-home flat indices without
    # re-deriving ownership from the tree
    owner: Optional[tuple] = None


@dataclass
class CompiledPolicies:
    interner: StringInterner
    urns: Urns
    arrays: dict[str, np.ndarray]
    conditions: list[CompiledCondition]
    entity_vocab: list[str]          # distinct target entity values (regex rows)
    entity_vocab_ids: dict[int, int]  # interned value id -> vocab row
    # distinct relation-path expressions on target subjects (the ReBAC
    # bitplane vocabulary, ops/relation.py); host-only like entity_vocab
    rel_vocab: list[str] = field(default_factory=list)
    rel_vocab_ids: dict[int, int] = field(default_factory=dict)
    supported: bool = True
    unsupported_reason: str = ""
    S: int = 0
    KP: int = 0
    KR: int = 0
    T: int = 0
    version: int = 0
    # node identity -> target-table row, recorded during lowering
    # (("set", sid) / ("pol", sid, pkey) / ("rule", sid, pkey, rkey)):
    # the delta patcher's stable-slot map for in-place row rewrites
    target_owners: dict = field(default_factory=dict)

    @property
    def n_rules(self) -> int:
        return int(self.arrays["rule_valid"].sum()) if self.S else 0

    @property
    def has_hr_targets(self) -> bool:
        return bool(self.arrays["t_has_scoping"].any())

    @property
    def has_rel_targets(self) -> bool:
        t = self.arrays.get("t_rel_idx")
        return t is not None and bool((np.asarray(t) >= 0).any())


def _pad(values: list[int], width: int) -> list[int]:
    return (values + [ABSENT] * width)[:width]


# target-table column name -> (row dict key, numpy dtype); one source of
# truth shared by _TargetTable.to_arrays and the delta patcher's in-place
# row writer (ops/delta.py)
TARGET_COLUMNS: list[tuple[str, str, type]] = [
    ("t_n_subjects", "n_subjects", np.int32),
    ("t_role", "role", np.int32),
    ("t_has_role", "has_role", bool),
    ("t_scoping", "scoping", np.int32),
    ("t_has_scoping", "has_scoping", bool),
    ("t_hr_check", "hr_check", bool),
    ("t_skip_acl", "skip_acl", bool),
    ("t_sub_ids", "sub_ids", np.int32),
    ("t_sub_vals", "sub_vals", np.int32),
    ("t_act_ids", "act_ids", np.int32),
    ("t_act_vals", "act_vals", np.int32),
    ("t_ent_vals", "ent_vals", np.int32),
    ("t_ent_w", "ent_w", np.int32),
    ("t_ent_tails", "ent_tails", np.int32),
    ("t_op_vals", "op_vals", np.int32),
    ("t_prop_vals", "prop_vals", np.int32),
    ("t_prop_sfx", "prop_sfx", np.int32),
    ("t_has_props", "has_props", bool),
    ("t_n_res", "n_res", np.int32),
    # relation-path requirement (ReBAC, docs/REBAC.md): the interned path
    # expression (host-only routing, never shipped to device), its
    # relation-vocab row (gathers into the packed r_rel_bits planes) and
    # the !direct flag selecting the literal-tuples-only plane
    ("t_rel_path", "rel_path", np.int32),
    ("t_rel_idx", "rel_idx", np.int32),
    ("t_rel_direct", "rel_direct", bool),
]


def lower_target(
    target: Optional[Target],
    interner: StringInterner,
    urns: Urns,
    vocab_row,
    rel_row=None,
) -> tuple[dict, Optional[str]]:
    """Lower ONE target into its row dict (the closed-form per-row
    representation the kernel gathers from).  ``vocab_row(value) -> int``
    allocates/looks up the entity regex-vocab row — the fresh compiler
    appends, the delta patcher allocates inside a fixed capacity.
    ``rel_row(path) -> int`` does the same for the relation-path vocab
    (ops/relation.py bitplanes); None marks relation-bearing targets
    unsupported.

    Returns (row, unsupported_reason_or_None); shared by the from-scratch
    compile below and the in-place set relowering in ops/delta.py so the
    two paths are bit-identical by construction."""
    it = interner.intern
    row: dict = {}
    t = target or Target()
    unsupported: Optional[str] = None

    role_urn = urns.get("role")
    scoping_urn = urns.get("roleScopingEntity")
    skip_acl_urn = urns.get("skipACL")
    hr_urn = urns.get("hierarchicalRoleScoping")
    entity_urn = urns.get("entity")
    property_urn = urns.get("property")
    operation_urn = urns.get("operation")
    relation_urn = urns.get("relation")

    role = None
    scoping = None
    hr_check = "true"
    skip_acl = False
    sub_pairs = []
    rel_paths: list[str] = []
    for a in t.subjects or []:
        if a.id == relation_urn:
            # relation requirements gate through the packed bitplanes
            # (stage B analog), not the subject pair-subset match — the
            # scalar oracle filters them identically
            # (core/engine._check_subject_matches)
            rel_paths.append(a.value or "")
            continue
        sub_pairs.append((it(a.id), it(a.value)))
        if a.id == role_urn:
            role = a.value
        elif a.id == hr_urn:
            hr_check = a.value
        elif a.id == scoping_urn:
            scoping = a.value
        if a.id == skip_acl_urn:
            skip_acl = True

    act_pairs = [(it(a.id), it(a.value)) for a in (t.actions or [])]

    ent_vals, op_vals, prop_vals = [], [], []
    for a in t.resources or []:
        if a.id == entity_urn:
            ent_vals.append(a.value)
        elif a.id == operation_urn:
            op_vals.append(a.value)
        elif a.id == property_urn:
            prop_vals.append(a.value)
        # other resource attribute ids never match anything in the
        # reference matcher; they only affect nothing (ref :492-576)

    if len(sub_pairs) > K_SUB or len(act_pairs) > K_ACT:
        unsupported = "subject/action attribute count exceeds caps"
    if len(ent_vals) > K_ENT or len(op_vals) > K_OP or len(prop_vals) > K_PROP:
        unsupported = "resource attribute count exceeds caps"
    for v in ent_vals:
        try:
            re.compile(v[v.rfind(":") + 1:].split(".")[-1])
        except re.error:
            unsupported = f"invalid regex in entity value {v!r}"
    if len(ent_vals) > 1 and prop_vals:
        # requestEntityURN ambiguity: multiple entities + properties mix
        # per-attribute state the closed form cannot represent
        unsupported = "target mixes multiple entities with properties"

    rel_parsed = None
    if len(rel_paths) > 1:
        unsupported = "multiple relation attributes on one target"
    elif rel_paths:
        try:
            rel_parsed = parse_path(rel_paths[0])
        except ValueError:
            unsupported = f"invalid relation path {rel_paths[0]!r}"
        if rel_parsed is not None and rel_row is None:
            unsupported = "relation path without a relation vocab"

    ent_ids = [it(v) for v in ent_vals]
    row["n_subjects"] = len(t.subjects or [])
    row["role"] = it(role) if role is not None else ABSENT
    row["has_role"] = role is not None
    row["scoping"] = it(scoping) if scoping is not None else ABSENT
    row["has_scoping"] = scoping is not None
    row["hr_check"] = hr_check == "true"
    row["skip_acl"] = skip_acl
    row["sub_ids"] = _pad([p[0] for p in sub_pairs], K_SUB)
    row["sub_vals"] = _pad([p[1] for p in sub_pairs], K_SUB)
    row["act_ids"] = _pad([p[0] for p in act_pairs], K_ACT)
    row["act_vals"] = _pad([p[1] for p in act_pairs], K_ACT)
    row["ent_vals"] = _pad(ent_ids, K_ENT)
    row["ent_w"] = _pad([vocab_row(v) for v in ent_vals], K_ENT)
    row["ent_tails"] = _pad([interner.tail_id[i] for i in ent_ids], K_ENT)
    row["op_vals"] = _pad([it(v) for v in op_vals], K_OP)
    prop_ids = [it(v) for v in prop_vals]
    row["prop_vals"] = _pad(prop_ids, K_PROP)
    row["prop_sfx"] = _pad([interner.suffix_id[i] for i in prop_ids], K_PROP)
    row["has_props"] = len(prop_vals) > 0
    row["n_res"] = len(t.resources or [])
    if rel_parsed is not None and rel_row is not None and unsupported is None:
        row["rel_path"] = it(rel_paths[0])
        row["rel_idx"] = rel_row(rel_paths[0])
        row["rel_direct"] = rel_parsed.direct
    else:
        row["rel_path"] = ABSENT
        row["rel_idx"] = ABSENT
        row["rel_direct"] = False
    return row, unsupported


class _TargetTable:
    def __init__(self, interner: StringInterner, urns: Urns):
        self.interner = interner
        self.urns = urns
        self.rows: list[dict] = []
        self.entity_vocab: list[str] = []
        self.entity_vocab_ids: dict[int, int] = {}
        self.rel_vocab: list[str] = []
        self.rel_vocab_ids: dict[int, int] = {}
        self.unsupported: Optional[str] = None
        self.owners: dict[tuple, int] = {}

    def _vocab_row(self, value: str) -> int:
        vid = self.interner.intern(value)
        row = self.entity_vocab_ids.get(vid)
        if row is None:
            row = len(self.entity_vocab)
            self.entity_vocab.append(value)
            self.entity_vocab_ids[vid] = row
        return row

    def _rel_row(self, value: str) -> int:
        vid = self.interner.intern(value)
        row = self.rel_vocab_ids.get(vid)
        if row is None:
            row = len(self.rel_vocab)
            self.rel_vocab.append(value)
            self.rel_vocab_ids[vid] = row
        return row

    def add(self, target: Optional[Target], owner: Optional[tuple] = None) -> int:
        """Lower a target into a row; returns the row index."""
        row, unsupported = lower_target(
            target, self.interner, self.urns, self._vocab_row, self._rel_row
        )
        if unsupported:
            self.unsupported = unsupported
        self.rows.append(row)
        idx = len(self.rows) - 1
        if owner is not None:
            self.owners[owner] = idx
        return idx

    def row_info(self, idx: int) -> tuple[bool, list[int]]:
        """(has_props, padded entity value ids) of a lowered row — the
        policy-level denormalized columns the set lowerer copies."""
        row = self.rows[idx]
        return row["has_props"], row["ent_vals"]

    def to_arrays(self) -> dict[str, np.ndarray]:
        return {
            name: np.array([r[key] for r in self.rows], dtype=dtype)
            for name, key, dtype in TARGET_COLUMNS
        }


class _ConditionSink:
    """Append-only condition registry for the from-scratch compile; the
    delta patcher substitutes an identity-checked reuse sink
    (ops/delta.py) so patched trees keep the condition list — and the
    [C, B] device shapes derived from it — byte-stable."""

    def __init__(self):
        self.conditions: list[CompiledCondition] = []

    def add(self, owner: tuple, flat_index: int, condition: str,
            context_query) -> int:
        idx = len(self.conditions)
        self.conditions.append(
            CompiledCondition(
                rule_flat_index=flat_index,
                condition=condition,
                context_query=context_query,
                owner=owner,
            )
        )
        return idx


def lower_set_into(a, s, ps, table, cond_sink, KP: int, KR: int
                   ) -> Optional[str]:
    """Lower ONE policy set into slot ``s`` of the padded arrays ``a``.

    Factored out of compile_policies so the delta patcher (ops/delta.py)
    can relower a mutated set in place — same loop, same write order, so
    patched slots are value-identical to a from-scratch compile of the
    same subtree.  ``table.add`` allocates/reuses target rows, ``cond_sink
    .add`` allocates/reuses condition slots; returns the first unsupported
    reason found at set/policy granularity (target-level reasons land on
    ``table.unsupported``)."""
    unsupported: Optional[str] = None
    a["set_valid"][s] = True
    ca = CA_CODES.get(ps.combining_algorithm, ABSENT)
    a["set_ca"][s] = ca
    if ps.target is not None:
        a["set_has_target"][s] = True
        a["set_target"][s] = table.add(ps.target, owner=("set", ps.id))
    policies = list(ps.combinables.items())
    if ca == ABSENT and any(p is not None for _, p in policies):
        unsupported = f"unknown combining algorithm on set {ps.id!r}"
    eff_ctx = 0  # carried-over policyEffect, per set
    for kp, (pol_key, pol) in enumerate(policies):
        if pol is None:
            continue
        a["pol_valid"][s, kp] = True
        if pol.effect:
            eff_ctx = EFFECT_CODES.get(pol.effect, 0)
        a["pol_eff_ctx"][s, kp] = eff_ctx
        a["pol_ca"][s, kp] = CA_CODES.get(pol.combining_algorithm, ABSENT)
        a["pol_effect"][s, kp] = EFFECT_CODES.get(pol.effect, 0)
        a["pol_cacheable"][s, kp] = bool(pol.evaluation_cacheable)
        if pol.target is not None:
            a["pol_has_target"][s, kp] = True
            row_idx = table.add(pol.target, owner=("pol", ps.id, pol_key))
            a["pol_target"][s, kp] = row_idx
            a["pol_has_subjects"][s, kp] = bool(pol.target.subjects)
            has_props, ent_vals = table.row_info(row_idx)
            a["pol_has_props"][s, kp] = has_props
            a["pol_ent_vals"][s, kp] = ent_vals
        rules = list(pol.combinables.items())
        a["pol_n_rules"][s, kp] = len(rules)
        if a["pol_ca"][s, kp] == ABSENT and any(
            r is not None for _, r in rules
        ):
            unsupported = f"unknown combining algorithm on policy {pol.id!r}"
        cache_prefix = True
        for kr, (rule_key, rule) in enumerate(rules):
            if rule is None:
                continue
            a["rule_valid"][s, kp, kr] = True
            a["rule_effect"][s, kp, kr] = EFFECT_CODES.get(rule.effect, 0)
            raw = bool(rule.evaluation_cacheable)
            a["rule_cacheable_raw"][s, kp, kr] = raw
            cache_prefix = cache_prefix and raw
            a["rule_cacheable_eff"][s, kp, kr] = raw and cache_prefix
            if rule.target is not None:
                a["rule_has_target"][s, kp, kr] = True
                a["rule_target"][s, kp, kr] = table.add(
                    rule.target, owner=("rule", ps.id, pol_key, rule_key)
                )
            if rule.condition:
                a["rule_cond"][s, kp, kr] = cond_sink.add(
                    ("rule", ps.id, pol_key, rule_key),
                    (s * KP + kp) * KR + kr,
                    rule.condition,
                    rule.context_query,
                )
    return unsupported


def compile_policies(
    policy_sets: dict[str, Optional[PolicySet]] | list[PolicySet],
    urns: Urns | None = None,
    version: int = 0,
) -> CompiledPolicies:
    urns = urns or Urns()
    interner = StringInterner()
    table = _TargetTable(interner, urns)

    if isinstance(policy_sets, dict):
        sets = [ps for ps in policy_sets.values() if ps is not None]
    else:
        sets = [ps for ps in policy_sets if ps is not None]

    S = max(len(sets), 1)
    KP = max((len(ps.combinables) for ps in sets), default=0) or 1
    KR = 1
    for ps in sets:
        for pol in ps.combinables.values():
            if pol is not None:
                KR = max(KR, len(pol.combinables))

    unsupported: Optional[str] = None
    cond_sink = _ConditionSink()

    def zeros(dtype=np.int32, shape=None):
        return np.full(shape, ABSENT if dtype == np.int32 else False, dtype=dtype)

    a = {
        "set_valid": zeros(bool, (S,)),
        "set_ca": zeros(np.int32, (S,)),
        "set_has_target": zeros(bool, (S,)),
        "set_target": np.zeros((S,), np.int32),
        "pol_valid": zeros(bool, (S, KP)),
        "pol_ca": zeros(np.int32, (S, KP)),
        "pol_effect": np.zeros((S, KP), np.int32),
        "pol_cacheable": zeros(bool, (S, KP)),
        "pol_has_target": zeros(bool, (S, KP)),
        "pol_target": np.zeros((S, KP), np.int32),
        "pol_has_subjects": zeros(bool, (S, KP)),
        "pol_n_rules": np.zeros((S, KP), np.int32),
        "pol_eff_ctx": np.zeros((S, KP), np.int32),
        "pol_has_props": zeros(bool, (S, KP)),
        "pol_ent_vals": np.full((S, KP, K_ENT), ABSENT, np.int32),
        "rule_valid": zeros(bool, (S, KP, KR)),
        "rule_effect": np.zeros((S, KP, KR), np.int32),
        "rule_cacheable_raw": zeros(bool, (S, KP, KR)),
        "rule_cacheable_eff": zeros(bool, (S, KP, KR)),
        "rule_has_target": zeros(bool, (S, KP, KR)),
        "rule_target": np.zeros((S, KP, KR), np.int32),
        "rule_cond": np.full((S, KP, KR), ABSENT, np.int32),
    }

    for s, ps in enumerate(sets):
        reason = lower_set_into(a, s, ps, table, cond_sink, KP, KR)
        if reason:
            unsupported = reason

    if not table.rows:
        table.add(None)
    if table.unsupported:
        unsupported = table.unsupported

    arrays = dict(a)
    arrays.update(table.to_arrays())
    # (role, scoping) vocabulary for stage B: the owner-membership
    # verdicts are factored per distinct (t_role, t_scoping) pair —
    # typically far fewer than T — computed host-side at encode
    # (ops/encode.pack_owner_bitplanes) and gathered back per target row
    # through the packed bitplanes (kernel _hr_pass_from_bits).  The
    # vocab arrays are global (group-invariant under prefilter
    # compaction) and host-only; t_rs_idx is a regular target-table
    # column so row subsets keep it aligned.
    rs_pairs = np.stack(
        [arrays["t_role"], arrays["t_scoping"]], axis=1
    )
    rs_vocab, t_rs = np.unique(rs_pairs, axis=0, return_inverse=True)
    arrays["t_rs_idx"] = t_rs.reshape(-1).astype(np.int32)
    arrays["hrv_role"] = np.ascontiguousarray(rs_vocab[:, 0], np.int32)
    arrays["hrv_scope"] = np.ascontiguousarray(rs_vocab[:, 1], np.int32)
    # relation-path vocabulary (interned expressions, host-only like
    # hrv_*): t_rel_idx rows gather the packed r_rel_bits planes by this
    # order; the serving store builds its verdict tables in the same order
    # (srv/relations.RelationTupleStore.tables_for)
    arrays["relv_path"] = np.array(
        [interner.intern(v) for v in table.rel_vocab], np.int32
    )
    # interned URN ids the ACL kernel stage compares against (reference:
    # verifyACL.ts:37-44, 138-150): [role attr id, user entity, actionID
    # attr id, create, read, modify, delete]
    arrays["acl_consts"] = np.array(
        [
            interner.intern(urns.get("role")),
            interner.intern(urns.get("user")),
            interner.intern(urns.get("actionID")),
            interner.intern(urns.get("create")),
            interner.intern(urns.get("read")),
            interner.intern(urns.get("modify")),
            interner.intern(urns.get("delete")),
        ],
        np.int32,
    )

    compiled = CompiledPolicies(
        interner=interner,
        urns=urns,
        arrays=arrays,
        conditions=cond_sink.conditions,
        entity_vocab=table.entity_vocab,
        entity_vocab_ids=table.entity_vocab_ids,
        rel_vocab=table.rel_vocab,
        rel_vocab_ids=table.rel_vocab_ids,
        supported=unsupported is None,
        unsupported_reason=unsupported or "",
        S=S,
        KP=KP,
        KR=KR,
        T=len(table.rows),
        version=version,
        target_owners=table.owners,
    )
    return compiled
