"""Relation-closure bitplanes: the ReBAC analog of the owner-bit packer.

``ops/encode.pack_owner_bitplanes`` folds the HR owner-tree membership
host-side into packed A/B fail bits the kernel reads back with
``_owner_bit_reader``.  This module generalizes that exact layout to
arbitrary relation closures: per (request row, relation-vocab entry) the
reachable-subject verdicts of the targeted resource instances are packed
into the same int32 bitplane format —

  r_rel_runs [B, NRU] — the distinct instance-bearing entity runs per row
      (ABSENT-padded), bit group g of every vocab entry refers to run
      r_rel_runs[g]; identical construction to r_own_runs.
  r_rel_bits [B, NWORDS] — packed fail bits per (row, vocab entry), laid
      out by ops/encode.owner_bit_layout(RELV, NRU, 0): ebits = 2*NRU,
      bit g = plane A (full closure: rewrites + userset expansion) fails,
      bit NRU+g = plane B (!direct: literal tuples only) fails.

The membership source is a precomputed flat verdict table (built by the
serving store, srv/relations.py): per (vocab entry v, plane p) segment
``obj_offs[v*2+p] : obj_offs[v*2+p+1]`` of sorted int64 object keys
``(ent_id << 32) | inst_id``, plus one globally sorted int64 ``pairs``
array of ``(object_row << 32) | subject_id`` — a verdict is two binary
searches, so packing a batch is O(B * NI * RELV * log) numpy work with
zero per-tuple cost at decision time.  The native (C++) wire encoder
implements the same two searches bit-identically
(native/host_encoder.cpp acs_pack_relation_bits).

Decisions are fail-closed: a missing table (no store attached) behaves
as an empty tuple set, matching the scalar oracle
(core/relation_path.check_relation_path with graph=None).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .compile import CompiledPolicies
from .encode import _pow2_at_least, owner_bit_layout
from .interner import ABSENT


def relation_bits_needed(compiled: CompiledPolicies) -> bool:
    """True when some target row carries a relation-path requirement
    (mirrors ops/kernel.tree_needs_rel without importing the kernel)."""
    t = compiled.arrays.get("t_rel_idx")
    return t is not None and bool((np.asarray(t) >= 0).any())


def empty_relation_tables(relv: int) -> dict[str, np.ndarray]:
    """The fail-closed table for ``relv`` vocab entries: zero objects, so
    every checked instance fails both planes."""
    return {
        "obj_offs": np.zeros((2 * relv + 1,), np.int64),
        "obj_keys": np.zeros((0,), np.int64),
        "pairs": np.zeros((0,), np.int64),
    }


def _plane_pass(tables: dict, idx: int, keys: np.ndarray, subj: np.ndarray
                ) -> np.ndarray:
    """Membership verdicts for one (vocab, plane) segment: ``keys`` are
    packed object keys, ``subj`` the (broadcastable) packed subject ids;
    returns bool shaped like keys."""
    obj_offs = tables["obj_offs"]
    obj_keys = tables["obj_keys"]
    pairs = tables["pairs"]
    lo = int(obj_offs[idx])
    hi = int(obj_offs[idx + 1])
    if hi <= lo:
        return np.zeros(keys.shape, bool)
    pos = np.searchsorted(obj_keys[lo:hi], keys)
    found = pos < (hi - lo)
    row = lo + np.minimum(pos, hi - lo - 1)
    found &= obj_keys[row] == keys
    pk = (row.astype(np.int64) << 32) | subj
    npair = pairs.shape[0]
    if npair == 0:
        return np.zeros(keys.shape, bool)
    pp = np.searchsorted(pairs, pk)
    ok = pp < npair
    ok &= pairs[np.minimum(pp, npair - 1)] == pk
    return found & ok


def pack_relation_bitplanes(
    arrays: dict[str, np.ndarray],
    compiled: CompiledPolicies,
    tables: Optional[dict] = None,
    skip: bool = False,
) -> dict[str, np.ndarray]:
    """Pack the per-batch relation verdicts.  Pure function of the raw
    encoder arrays + the store's flat tables, so the Python and native
    encode paths share it structurally (the C++ packer reproduces it bit
    for bit).  ``skip=True`` or a relation-free tree emits 1-wide dummies
    no compiled program ever reads."""
    B = arrays["r_ent_vals"].shape[0]
    if skip or not relation_bits_needed(compiled):
        return {
            "r_rel_runs": np.full((B, 1), ABSENT, np.int32),
            "r_rel_bits": np.zeros((B, 1), np.int32),
        }
    relv_path = np.asarray(compiled.arrays["relv_path"])
    RELV = int(relv_path.shape[0])
    if tables is None:
        tables = empty_relation_tables(RELV)

    inst_run = arrays["r_inst_run"]
    valid_i = arrays["r_inst_valid"] & (inst_run >= 0)  # [B, NI]
    # distinct instance-bearing runs per row (identical construction to
    # pack_owner_bitplanes so both planes share one run grouping scheme)
    big = np.int32(1 << 30)
    runs_sorted = np.sort(np.where(valid_i, inst_run, big), axis=1)
    fresh = np.ones(runs_sorted.shape, bool)
    fresh[:, 1:] = runs_sorted[:, 1:] != runs_sorted[:, :-1]
    fresh &= runs_sorted < big
    counts = fresh.sum(axis=1)
    nru = _pow2_at_least(int(counts.max()) if B else 1, 1)
    rel_runs = np.full((B, nru), ABSENT, np.int32)
    b_idx, j_idx = np.nonzero(fresh)
    pos = (np.cumsum(fresh, axis=1) - 1)[b_idx, j_idx]
    rel_runs[b_idx, pos] = runs_sorted[b_idx, j_idx]

    ebits, epw, wpe, nwords = owner_bit_layout(RELV, nru, 0)
    words = np.zeros((B, nwords), np.uint32)
    if B:
        NI = inst_run.shape[1]
        run_c = np.clip(inst_run, 0, None)
        ent = np.take_along_axis(arrays["r_ent_vals"], run_c, axis=1)  # [B,NI]
        inst = arrays["r_inst_id"]
        keys = (
            (np.clip(ent, 0, None).astype(np.int64) << 32)
            | np.clip(inst, 0, None).astype(np.int64)
        )  # [B, NI]
        key_ok = valid_i & (ent >= 0) & (inst >= 0)
        subj = arrays["r_subject_id"].astype(np.int64)  # [B]
        subj_ok = subj >= 0
        subj_pk = np.clip(subj, 0, None)[:, None]
        flat_keys = keys
        bad_full = np.empty((B, RELV, NI), bool)
        bad_dir = np.empty((B, RELV, NI), bool)
        for v in range(RELV):
            ok_f = _plane_pass(tables, v * 2, flat_keys, subj_pk)
            ok_d = _plane_pass(tables, v * 2 + 1, flat_keys, subj_pk)
            ok_f &= key_ok & subj_ok[:, None]
            ok_d &= key_ok & subj_ok[:, None]
            bad_full[:, v, :] = valid_i & ~ok_f
            bad_dir[:, v, :] = valid_i & ~ok_d
        g_one = (
            (inst_run[:, :, None] == rel_runs[:, None, :])
            & valid_i[:, :, None]
        ).astype(np.float32)  # [B, NI, NRU]
        a_run = np.matmul(bad_full.astype(np.float32), g_one) > 0
        b_run = np.matmul(bad_dir.astype(np.float32), g_one) > 0
        bits3 = np.concatenate([a_run, b_run], axis=2)  # [B, RELV, 2*nru]
        flat = np.arange(RELV * ebits)
        v_of, k_of = flat // ebits, flat % ebits
        if epw:
            w_of = v_of // epw
            off = ((v_of % epw) * ebits + k_of).astype(np.uint64)
        else:
            w_of = v_of * wpe + k_of // 32
            off = (k_of % 32).astype(np.uint64)
        starts = np.nonzero(np.diff(w_of, prepend=-1))[0]
        contrib = bits3.reshape(B, RELV * ebits).astype(np.uint64) << off
        words[:] = np.add.reduceat(contrib, starts, axis=1).astype(np.uint32)
    return {
        "r_rel_runs": rel_runs,
        "r_rel_bits": np.ascontiguousarray(words).view(np.int32),
    }
