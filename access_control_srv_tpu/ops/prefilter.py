"""Host-side candidate pre-filtering for large rule counts.

The dense kernel's per-request work is O(total target rows): every rule's
target row is matched against every request even though a rule whose
target names entity X can never match a request that only names entity Y
(reference target semantics: a resource-bearing target matches only via an
exact entity hit, a regex entity hit, or an operation hit —
src/core/accessController.ts:465-654).  With 100k rules that dense sweep
is the whole cost.

This module restores O(matching rules): batch rows are grouped by their
*resource signature* (distinct entity value ids + operation ids); for each
signature the rule axis is compacted to the candidate subset

  - rules with no target / no resource attributes (match anything),
  - rules whose target entities exactly match a signature entity,
  - rules whose target entities regex-match one (vocab regex matrices are
    already computed per batch),
  - rules whose target operations match a signature operation,

left-packed along KR in original order.  Because combining algorithms are
order-sensitive but only *relatively* so (first-DENY / first-PERMIT /
first-applicable over collected rules, reference :846-893), dropping rules
that provably cannot match and preserving relative order leaves every
decision bit-identical.  Policy/set target rows are always retained, so
set gates, policy gates, carried policyEffect and the multi-entity recheck
(which reads policy-level arrays) are untouched.

Execution is ONE device dispatch per batch: the signature subtrees are
padded to a common shape and stacked on a leading group axis [G, ...];
each request row carries its group index and gathers its own subtree
inside the vmapped kernel.  Per-signature compacted trees and per-
signature-set stacks are cached, so steady-state traffic pays neither
compaction nor host->device transfer of policy data again.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .compile import CompiledPolicies
from .encode import RequestBatch
from .staging import HostBufferPool, default_pool
from .kernel import (
    DecisionKernel,
    _action_kind,
    _combine_and_decide_flat,
    _evaluate_one,
    _hr_pass_from_bits,
    _match_targets,
    _multi_entity_ok,
    _policy_gates_core,
    _rel_pass_from_bits,
    _rule_conditions,
    half_pow2_bucket,
    lead_padding,
    pad_cols,
    pow2_bucket,
    tree_needs_hr,
    tree_needs_rel,
)

# varying arrays the signature runner gathers per row (stage E-G inputs);
# everything stage-A/target-table-shaped is folded into the per-signature
# rule/policy-level planes instead (_sig_planes_for)
_SIG_C_KEYS = [
    "rule_valid", "rule_effect", "rule_cacheable_raw", "rule_cacheable_eff",
    "rule_has_target", "rule_cond",
]
_SIG_R_KEYS = [
    "r_sub_ids", "r_sub_vals", "r_roles", "r_act_ids", "r_act_vals",
    "r_n_entity_attrs", "r_n_ra", "r_acl_short",
]
# additional per-row arrays when the tree carries HR-bearing targets:
# stage B's owner side travels as host-packed bitplanes (two narrow int32
# columns instead of the former ra3/ra2/hr/owner-pair arrays — ~5x less
# per-row transfer on the stress-hr shape); its collection state stays
# per-signature
_SIG_R_KEYS_HR = _SIG_R_KEYS + ["r_ctx_present", "r_own_runs", "r_own_bits"]
# int32-packed columns that are semantically bool
_SIG_BOOL_KEYS = {"r_ctx_present"}

_RULE_FIELDS = [
    "rule_valid", "rule_effect", "rule_cacheable_raw", "rule_cacheable_eff",
    "rule_has_target", "rule_target", "rule_cond",
]


def _is_varying(name: str) -> bool:
    """Arrays that differ between signature subtrees (compacted rule axis,
    compacted target subtable, remapped target indices); everything else is
    group-invariant policy/set metadata shared across the stack."""
    return (
        name in _RULE_FIELDS
        or name in ("pol_target", "set_target", "rule_orig_flat")
        or name.startswith("t_")
    )

# rules below this count are cheaper to sweep densely than to group/compact
MIN_RULES = 512

_donate_ok_cache: Optional[bool] = None


def donation_supported() -> bool:
    """Donate the packed per-row device buffer to the sig runner so XLA
    reuses its HBM for outputs.  CPU backend excluded: jnp/device_put can
    alias host numpy memory zero-copy there, and donating an aliased
    buffer would let XLA scribble over a pooled staging buffer."""
    global _donate_ok_cache
    if _donate_ok_cache is None:
        _donate_ok_cache = jax.default_backend() in ("tpu", "gpu")
    return _donate_ok_cache


def candidate_rows(
    compiled: CompiledPolicies,
    ent_ids: np.ndarray,
    ent_cols: np.ndarray,
    op_ids: np.ndarray,
    act_vals: np.ndarray,
    rgx_set: np.ndarray,
) -> np.ndarray:
    """[T] bool: target rows that could produce a match for a request
    whose distinct entity value ids are ``ent_ids`` (batch entity columns
    ``ent_cols``), operation ids ``op_ids`` and action attribute values
    ``act_vals``.

    Resource side: no-resource targets, exact entity hits, regex entity
    hits, operation hits.  Action side: every target action attribute must
    find an id+value pair in the request (kernel ``act_ok``), so a target
    action VALUE absent from the request's action values disqualifies the
    row — value-only filtering is conservative (id mismatches are left for
    the kernel), which keeps signature aliasing safe."""
    a = compiled.arrays
    tv = a["t_ent_vals"]  # [T, K_ENT]
    cand = a["t_n_res"] == 0
    if ent_ids.size:
        cand = cand | (np.isin(tv, ent_ids) & (tv >= 0)).any(axis=1)
        # regex candidacy: any target vocab row regex-hits a batch entity col
        w = a["t_ent_w"]  # [T, K_ENT]
        hits = rgx_set[np.clip(w, 0, None)][:, :, ent_cols]  # [T, K, |cols|]
        cand = cand | (hits & (w >= 0)[:, :, None]).any(axis=(1, 2))
    if op_ids.size:
        ov = a["t_op_vals"]
        cand = cand | (np.isin(ov, op_ids) & (ov >= 0)).any(axis=1)
    av = a["t_act_vals"]  # [T, K_ACT]
    act_compat = ((av < 0) | np.isin(av, act_vals)).all(axis=1)
    return cand & act_compat


def compact_rules(
    compiled: CompiledPolicies, row_cand: np.ndarray,
    explain: bool = False,
) -> CompiledPolicies:
    """Left-pack candidate rules along KR (order-preserving) and compact
    the target subtable to the rows the kept rules + all policy/set
    targets reference.  Mirrors parallel/rule_shard.py:partition_rules'
    compaction, but driven by candidacy instead of chunk boundaries.

    ``explain=True`` additionally records ``rule_orig_flat`` [S, KP, KRp]:
    each compacted slot's ORIGINAL flat rule position (s*KP + kp)*KR + kr,
    so explain recovery (_combine_and_decide_flat) reports provenance in
    pre-compaction coordinates.  Only materialized when asked — the array
    would otherwise change the sig runner's argument pytree and with it
    the lowered program bytes."""
    a = compiled.arrays
    cand = a["rule_valid"] & (~a["rule_has_target"] | row_cand[a["rule_target"]])

    counts = cand.sum(axis=2)
    krp = pow2_bucket(int(counts.max()) if counts.size else 0, floor=4)
    krp = min(krp, compiled.KR) if compiled.KR else krp
    order = np.argsort(~cand, axis=2, kind="stable")  # candidates first
    new: dict[str, np.ndarray] = {}
    for name in _RULE_FIELDS:
        new[name] = np.take_along_axis(a[name], order, axis=2)[:, :, :krp]
    new["rule_valid"] = np.take_along_axis(cand, order, axis=2)[:, :, :krp]
    if explain:
        S, KP, KR = a["rule_valid"].shape
        base = (
            np.arange(S, dtype=np.int64)[:, None, None] * KP
            + np.arange(KP, dtype=np.int64)[None, :, None]
        ) * KR
        new["rule_orig_flat"] = (
            base + order[:, :, :krp]
        ).astype(np.int32)

    needed = set(
        np.unique(new["rule_target"][new["rule_valid"] & new["rule_has_target"]])
    )
    needed |= set(np.unique(a["pol_target"][a["pol_has_target"]]))
    needed |= set(np.unique(a["set_target"][a["set_has_target"]]))
    needed.add(0)  # row 0 backs the "no target" index
    rows = sorted(needed)
    remap = np.zeros(a["t_role"].shape[0], np.int64)
    for j, old in enumerate(rows):
        remap[old] = j
    for name, arr in a.items():
        if name.startswith("t_"):
            new[name] = arr[rows]
        elif name not in new:
            new[name] = arr
    new["rule_target"] = remap[new["rule_target"]].astype(np.int32)
    new["pol_target"] = remap[a["pol_target"]].astype(np.int32)
    new["set_target"] = remap[a["set_target"]].astype(np.int32)
    return replace(compiled, arrays=new, KR=krp, T=len(rows))


def _pad_sub(arr: np.ndarray, name: str, krp: int, tp: int) -> np.ndarray:
    """Pad one compacted-subtree array to the stack's common KR/T."""
    if name in _RULE_FIELDS or name == "rule_orig_flat":
        width = krp - arr.shape[2]
        if width > 0:
            fill = (
                False if arr.dtype == bool
                else (0 if name in ("rule_effect", "rule_target") else -1)
            )
            arr = np.concatenate(
                [arr, np.full(arr.shape[:2] + (width,), fill, arr.dtype)],
                axis=2,
            )
        return arr
    if name.startswith("t_") and arr.shape[0] < tp:
        reps = np.repeat(arr[:1], tp - arr.shape[0], axis=0)
        arr = np.concatenate([arr, reps], axis=0)
    return arr


class PrefilteredKernel:
    """Drop-in DecisionKernel: groups the batch by resource signature,
    compacts the rule axis per signature, and evaluates the whole batch in
    one dispatch over stacked subtrees.  Decisions are bit-identical to
    the dense kernel (differential: tests/test_prefilter.py); trees under
    MIN_RULES rules skip the machinery entirely."""

    def __init__(self, compiled: CompiledPolicies, cache_size: int = 1024,
                 mesh=None, axis: str = "data", max_groups: int = 512,
                 telemetry=None, dynamic_policies: bool = False,
                 shared_jits: Optional[dict] = None,
                 staging: Optional[HostBufferPool] = None,
                 explain: bool = False):
        """``mesh``: optional jax.sharding.Mesh — requests shard
        data-parallel over ``axis`` while the stacked subtrees and regex
        matrices replicate (the multi-chip layout of parallel/mesh.py
        applied to the candidate-compacted dispatch).

        ``max_groups``: cardinality guard — a batch whose rows span more
        than this many distinct resource signatures is split into
        group-bounded segments evaluated separately, so adversarial
        traffic (every request a novel entity set) degrades to more
        dispatches instead of unbounded stack memory ([G, ...] device
        arrays scale with G).

        ``telemetry``: optional srv.telemetry.Telemetry; counts signature
        compaction/stack cache hits and misses and guard splits.

        ``dynamic_policies``: hot-update mode (ops/delta.py) — the group-
        invariant policy metadata enters every jitted runner as an
        ARGUMENT instead of a baked closure constant, and the jitted
        callables live in ``shared_jits`` so a kernel swapped in over
        patched tables with identical shapes reuses the compiled
        executables (zero new XLA compilations per in-capacity
        mutation)."""
        if not compiled.supported:
            raise ValueError(
                f"policy tree unsupported by kernel: {compiled.unsupported_reason}"
            )
        self.compiled = compiled
        self.cache_size = cache_size
        self.mesh = mesh
        self.axis = axis
        self.max_groups = max_groups
        self.telemetry = telemetry
        self.dynamic_policies = dynamic_policies
        self.explain = bool(explain)
        # compacted slots decode through rule_orig_flat back to ORIGINAL
        # coordinates, so host decode uses the uncompacted strides
        self.explain_strides = (compiled.KP, compiled.KR)
        self._shared = shared_jits if shared_jits is not None else {}
        # pooled host staging (ops/staging.py): the packed sig-path row
        # buffer and the slot/readback maps recycle across batches so a
        # depth-N pipeline allocates nothing per batch on this path;
        # buffers release at materialize (after the output fetch, which
        # orders behind every consumer of the inputs)
        self.staging = staging if staging is not None else default_pool()
        self._subs: dict[tuple, CompiledPolicies] = {}
        self._stacks: dict[tuple, dict[str, jnp.ndarray]] = {}
        self._bits: dict[tuple, dict[str, jnp.ndarray]] = {}
        self._ginfo: dict[tuple, tuple] = {}
        self._bits_fn = None
        self._dense: DecisionKernel | None = None
        self._runs: dict[tuple, object] = {}
        # signature-plane fast path: stage A's resource/action planes (and
        # stage B's collection state / op hits, when the tree carries HR
        # targets) depend only on the (entity, operation, action)
        # signature the batch is already grouped by, so they are
        # precomputed once per signature and the per-row device work
        # collapses to the subject fold + owner checks + rule/policy
        # stages.  Batches with ACL pairs / request properties fall back
        # to the full per-row matcher.
        self.needs_hr = tree_needs_hr(compiled.arrays)
        self.needs_rel = tree_needs_rel(compiled.arrays)
        self.active = compiled.n_rules >= MIN_RULES
        if not self.active:
            if mesh is not None:
                # small trees delegate to the batch-sharded dense kernel so
                # a configured mesh is honored on every tree size
                from ..parallel.mesh import ShardedDecisionKernel

                self._dense = ShardedDecisionKernel(
                    compiled, mesh, axis, explain=self.explain
                )
            else:
                self._dense = DecisionKernel(
                    compiled, dynamic_policies=dynamic_policies,
                    shared_jits=self._shared, explain=self.explain,
                )
        # hrv_role/hrv_scope are host-only since the owner-bitplane
        # rewrite (consumed by encode's packer, never by a device program);
        # t_rel_path/relv_path likewise (relation packer + store only) —
        # both are t_-prefixed/varying anyway, but keep the exclusion
        # explicit for the invariant set
        self._c_inv = {
            k: jnp.asarray(v) for k, v in compiled.arrays.items()
            if not _is_varying(k)
            and k not in ("hrv_role", "hrv_scope", "relv_path")
        }

    def _runner(self, with_acl: bool, with_hr: bool, with_rel: bool = False):
        explain = self.explain
        key = (with_acl, with_hr, with_rel) + (
            ("explain",) if explain else ()
        )
        run = self._runs.get(key)
        if run is None:
            def body(c_inv, cs, g_idx, batch_arrays, rgx_set, pfx_neq,
                     cond_true, cond_abort, cond_code):
                def one(g, ra, ct, ca, cc):
                    # per-row gather of the group-VARYING arrays only;
                    # policy/set metadata is identical across subtrees
                    c = {**c_inv,
                         **jax.tree_util.tree_map(lambda x: x[g], cs)}
                    rr = {**ra, "rgx_set": rgx_set, "pfx_neq": pfx_neq,
                          "cond_true": ct, "cond_abort": ca, "cond_code": cc}
                    return _evaluate_one(c, rr, with_acl, with_hr,
                                         explain=explain, with_rel=with_rel)

                return jax.vmap(one)(
                    g_idx, batch_arrays,
                    cond_true.T, cond_abort.T, cond_code.T,
                )

            shardings = None
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                repl = NamedSharding(self.mesh, P())
                data = NamedSharding(self.mesh, P(self.axis))
                cond = NamedSharding(self.mesh, P(None, self.axis))
                shardings = ((repl, data, data, repl, repl,
                              cond, cond, cond),
                             (data,) * (4 if explain else 3))
            run = self._wrap_runner(("pref", key), body, shardings)
            self._runs[key] = run
        return run

    def _wrap_runner(self, shared_key, body, shardings, donate=()):
        """Jit ``body(c_inv, *args)``.  Dynamic mode: c_inv is a real
        argument and the jitted callable is shared across kernel swaps
        (same shapes -> same executable, zero recompiles per patch).
        Static mode: c_inv is baked as jit constants ([S,KP]-scale only),
        exactly the pre-delta behavior.

        ``donate``: argnums of ``body`` (c_inv included in the numbering)
        whose device buffers the caller gives up per call — XLA reuses
        their memory for outputs.  Only honored on backends where
        device_put copies (donation_supported); per-batch streaming
        buffers are the intended donees."""
        donate = tuple(donate) if donation_supported() else ()
        if not self.dynamic_policies:
            from functools import partial

            bound = partial(body, self._c_inv)
            don_b = tuple(i - 1 for i in donate)
            if shardings is None:
                return jax.jit(bound, donate_argnums=don_b)
            return jax.jit(bound, in_shardings=shardings[0],
                           out_shardings=shardings[1],
                           donate_argnums=don_b)
        jitted = self._shared.get(shared_key)
        if jitted is None:
            if shardings is None:
                jitted = jax.jit(body, donate_argnums=donate)
            else:
                from jax.sharding import NamedSharding, PartitionSpec as P

                repl = NamedSharding(self.mesh, P())
                jitted = jax.jit(body, in_shardings=(repl,) + shardings[0],
                                 out_shardings=shardings[1],
                                 donate_argnums=donate)
            self._shared[shared_key] = jitted
        return lambda *args: jitted(self._c_inv, *args)

    def _sig_runner(self, schedule: tuple, needs_pairs: bool = True,
                    with_hr: bool = False, with_rel: bool = False):
        """The signature-plane kernel in GROUP-DENSE slot layout: stage A
        (resource/action target matching) is pre-gathered to rule/policy/
        set granularity per signature (_planes_for), and the batch arrives
        sorted by signature and packed into ``[NSLOT, R]`` row slots where
        every slot's rows share ONE group — so the group tables/planes are
        gathered once per *slot* and every hot op is a broadcast against
        the slot's ``[R, ...]`` rows.

        Why not gather per row: XLA re-reads a gathered operand per fused
        consumer, so per-row ``x[g]`` indexing of the ``[G, S, KP, KR]``
        stacks cost ~35 GB of HBM traffic per 16k-row batch on the 100k-
        rule tree (measured via compiled.cost_analysis, round 5) — ~22
        consumers x [B, M] int32.  Slot granularity cuts that to
        ``NSLOT/B`` (~2%) of the per-row traffic and leaves the per-row
        work pure elementwise.

        ``schedule`` describes the packed per-row int32 buffer: every
        request array + the transposed condition bits travel in ONE
        host->device transfer (the TPU tunnel pays per-transfer latency —
        ~35 small puts per call were costing ~10x the compute), and the
        three outputs return stacked as one [NSLOT, 3, R] readback."""
        explain = self.explain
        n_out = 4 if explain else 3
        key = ("sig", schedule, needs_pairs, with_hr, with_rel) + (
            ("explain",) if explain else ()
        )
        run = self._runs.get(key)
        if run is None:
            def sub_fold(r, n_sub, has_role, role, sub_ids, sub_vals):
                # checkSubjectMatches at plane granularity (reference:
                # accessController.ts:793-823); broadcasts over the
                # plane's leading shape.  The small request-side dims
                # (roles, subject pairs) are unrolled as Python loops so
                # every materialized intermediate keeps the plane's M-flat
                # trailing dim — a [.., M, k] comparison with k<128 pads
                # to the TPU's (8, 128) tile, inflating HBM traffic up to
                # 256x (measured: 54 GB/batch on the 100k tree, round 5).
                # ``needs_pairs`` is a static property of the signature
                # set: when every subject-bearing row is role-targeted,
                # the pair subset check is skipped entirely.
                KRR = int(r["r_roles"].shape[0])
                role_ok = jnp.zeros(role.shape, bool)
                for j in range(KRR):
                    role_ok = role_ok | (
                        (role == r["r_roles"][j]) & (r["r_roles"][j] >= 0)
                    )
                if not needs_pairs:
                    return (n_sub == 0) | role_ok
                KSt = int(sub_ids.shape[-1])
                KSr = int(r["r_sub_ids"].shape[0])
                pairs_ok = jnp.ones(n_sub.shape, bool)
                for i in range(KSt):
                    sid = sub_ids[..., i]
                    sval = sub_vals[..., i]
                    hit = jnp.zeros(sid.shape, bool)
                    for j in range(KSr):
                        hit = hit | (
                            (sid == r["r_sub_ids"][j])
                            & (sval == r["r_sub_vals"][j])
                            & (r["r_sub_ids"][j] >= 0)
                        )
                    pairs_ok = pairs_ok & ((sid < 0) | hit)
                return (n_sub == 0) | jnp.where(has_role, role_ok, pairs_ok)

            def body(c_inv, cs, planes, slot_g, mega_rows, grid2row,
                     gp_orig):
                # slot scatter/gather lives ON DEVICE: the compact [B, W]
                # row buffer transfers once and a take() spreads it into
                # the [NSLOT, R, W] grid (shipping the padded grid from
                # the host cost ~2x the bytes and a synchronous scatter);
                # results gather straight back to original row order so
                # the readback is a dense [3, B]
                NS, R = grid2row.shape
                grid = jnp.take(
                    mega_rows, grid2row.reshape(-1), axis=0
                ).reshape(NS, R, -1)

                def slot_fn(g, rows):
                    # ONE gather of the group tables/planes per slot; the
                    # inner vmap's rows all share them as broadcasts
                    c = {**c_inv,
                         **jax.tree_util.tree_map(lambda x: x[g], cs)}
                    sg = jax.tree_util.tree_map(lambda x: x[g], planes)
                    return jnp.stack(
                        jax.vmap(lambda row: one(c, sg, row))(rows)
                    )

                def one(c, sg, row):
                    offset = 0
                    ra = {}
                    for k, w, tail in schedule:
                        v = row[offset:offset + w]
                        offset += w
                        v = v.reshape(tail) if tail else v[0]
                        ra[k] = (v != 0) if k in _SIG_BOOL_KEYS else v
                    rr = {
                        **ra,
                        "cond_true": ra["cond_true"] != 0,
                        "cond_abort": ra["cond_abort"] != 0,
                        "cond_code": ra["cond_code"],
                    }

                    # rule-level work runs on [S, KP*KR] planes: the flat
                    # last axis keeps TPU lanes full (KR=16 trailing dims
                    # pad to 128) and bounds batch memory
                    S_, KP_, KR_ = c["rule_effect"].shape

                    def flat(x):
                        return x.reshape(S_, KP_ * KR_)

                    rl_sub = sub_fold(
                        rr, flat(sg["rl_n_sub"]), flat(sg["rl_has_role"]),
                        flat(sg["rl_role"]),
                        sg["rl_sub_ids"].reshape(S_, KP_ * KR_, -1),
                        sg["rl_sub_vals"].reshape(S_, KP_ * KR_, -1),
                    )  # [S, M]
                    pl_sub = sub_fold(rr, sg["pl_n_sub"], sg["pl_has_role"],
                                      sg["pl_role"], sg["pl_sub_ids"],
                                      sg["pl_sub_vals"])  # [S, KP]
                    sl_sub = sub_fold(rr, sg["sl_n_sub"], sg["sl_has_role"],
                                      sg["sl_role"], sg["sl_sub_ids"],
                                      sg["sl_sub_vals"])  # [S]

                    rht_f = flat(c["rule_has_target"])
                    tm_rule = ~rht_f | (
                        rl_sub & (flat(sg["rl_ex"]) | flat(sg["rl_rg"]))
                    )
                    reached = flat(c["rule_valid"]) & tm_rule
                    if with_hr:
                        # stage B at plane granularity: collection state
                        # and op hits are per-signature (sg planes); the
                        # owner side arrives as host-packed bitplanes
                        # (encode.pack_owner_bitplanes) — one tiny int
                        # gather + shift unpacks per plane, no matmuls
                        # (reference: hierarchicalScope.ts:10-258)
                        M_ = KP_ * KR_
                        hr_rule = _hr_pass_from_bits(
                            rr, flat(sg["rl_rs"]),
                            sg["rl_collect"].reshape(S_, M_, -1),
                            sg["rl_op_hit"].reshape(S_, M_, -1),
                            flat(sg["rl_hrchk"]), flat(sg["rl_triv"]),
                        )  # [S, M]
                        hr_pol = _hr_pass_from_bits(
                            rr, sg["pl_rs"], sg["pl_collect"],
                            sg["pl_op_hit"], sg["pl_hrchk"], sg["pl_triv"],
                        )  # [S, KP]
                        reached = reached & (~rht_f | hr_rule)
                        pol_subject = (
                            ~c["pol_has_subjects"] | hr_pol
                        )  # [S, KP]
                    else:
                        pol_subject = None
                    if with_rel:
                        # relation-path fold (ReBAC) at plane granularity:
                        # same collection planes, packed closure bitplanes
                        # from encode (ops/relation.pack_relation_bitplanes)
                        M_ = KP_ * KR_
                        rel_rule = _rel_pass_from_bits(
                            rr, flat(sg["rl_rel_idx"]),
                            sg["rl_collect"].reshape(S_, M_, -1),
                            flat(sg["rl_rel_dir"]),
                            flat(sg["rl_rel_idx"]) < 0,
                        )  # [S, M]
                        rel_pol = _rel_pass_from_bits(
                            rr, sg["pl_rel_idx"], sg["pl_collect"],
                            sg["pl_rel_dir"], sg["pl_rel_idx"] < 0,
                        )  # [S, KP]
                        reached = reached & (~rht_f | rel_rule)
                        pol_rel = ~c["pol_has_subjects"] | rel_pol
                        pol_subject = (
                            pol_rel if pol_subject is None
                            else pol_subject & pol_rel
                        )
                    kind = _action_kind(c, rr)
                    short = rr["r_acl_short"]
                    acl_row = flat(sg["rl_skip"]) | (short == 1) | (
                        (short == 0) & (rr["r_n_ra"] > 0) & (kind > 0)
                    )
                    acl_rule = ~rht_f | acl_row
                    # condition wiring on the flat rule axis (a [S, KP, KR]
                    # take would pad the KR-16 tail to the 128-lane tile)
                    has_cond, cond_t, cond_a, cond_c = _rule_conditions(
                        {"rule_cond": flat(c["rule_cond"])}, rr
                    )

                    # policy gates via the shared core (reference:
                    # accessController.ts:130-195): subject fold
                    # distributes over the deny/permit plane selection
                    multi_gate = jnp.where(
                        rr["r_n_entity_attrs"] > 1, sg["multi_ok"], True
                    )
                    pol_gate = _policy_gates_core(
                        c,
                        sg["pp_ex_p"] & pl_sub, sg["pp_ex_d"] & pl_sub,
                        sg["pp_rg_p"] & pl_sub, sg["pp_rg_d"] & pl_sub,
                        multi_gate,
                    )
                    set_gate = (
                        ~c["set_has_target"] | (sg["ss_ex_p"] & sl_sub)
                    ) & c["set_valid"]

                    return _combine_and_decide_flat(
                        c, reached, acl_rule, has_cond, cond_t, cond_a,
                        cond_c, pol_gate, set_gate,
                        pol_subject=pol_subject, explain=explain,
                    )

                out = jax.vmap(slot_fn)(slot_g, grid)  # [NSLOT, n_out, R]
                out_flat = out.transpose(0, 2, 1).reshape(NS * R, n_out)
                return jnp.take(out_flat, gp_orig, axis=0).T  # [n_out, B]

            shardings = None
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                repl = NamedSharding(self.mesh, P())
                data = NamedSharding(self.mesh, P(self.axis))
                shardings = ((repl, repl, data, repl, data, repl), repl)
            # the packed per-row buffer (arg 4: c_inv, cs, planes, slot_g,
            # mega_rows, ...) is donated: it is per-batch streaming data
            # the host never reads back, so XLA may reuse its HBM for the
            # [NSLOT, 3, R] outputs (no-op on CPU — donation_supported)
            run = self._wrap_runner(key, body, shardings, donate=(4,))
            self._runs[key] = run
        return run

    def _planes_for(self, keys: tuple, groups: list[dict], stacked,
                    widths: tuple, rgx_np, pfx_np):
        """Per-signature stage-A planes pre-gathered to rule/policy/set
        granularity ([G, S, KP, KR] / [G, S, KP] / [G, S]), cached
        alongside the stack.  Computed in ONE vmapped dispatch of the
        components-mode matcher over per-group pseudo-requests (the
        signature's entities/operations/actions, no subjects/properties)
        against the stacked target tables — regex outcomes are
        deterministic per (vocab row, entity value), so the planes are
        batch-independent.  The expensive [S,KP,KR]-at-target-table
        gathers happen HERE, once per signature set, never per row."""
        bits = self._bits.pop(keys, None)
        if bits is None:
            NR, NOP, NACT = widths
            G = len(groups)
            W = rgx_np.shape[0]
            p_ent = np.full((G, NR), -1, np.int32)
            p_ent_e = np.zeros((G, NR), np.int32)
            p_ent_valid = np.zeros((G, NR), bool)
            p_ops = np.full((G, NOP), -1, np.int32)
            p_act_ids = np.full((G, NACT), -1, np.int32)
            p_act_vals = np.full((G, NACT), -1, np.int32)
            p_rgx = np.zeros((G, W, max(NR, 1)), bool)
            p_pfx = np.zeros((G, W, max(NR, 1)), bool)
            for g, info in enumerate(groups):
                ents = info["ordered_ents"]
                cols = info["ordered_cols"]
                for j, (e, col) in enumerate(zip(ents, cols)):
                    p_ent[g, j] = e
                    p_ent_e[g, j] = j
                    if e >= 0:
                        p_ent_valid[g, j] = True
                        p_rgx[g, :, j] = rgx_np[:, col]
                        p_pfx[g, :, j] = pfx_np[:, col]
                ops = info["op_ids"]
                p_ops[g, : len(ops)] = ops
                pairs = info["act_pairs"]
                for j, (aid, aval) in enumerate(pairs):
                    p_act_ids[g, j] = aid
                    p_act_vals[g, j] = aval
            neg1 = np.full((G, 1), -1, np.int32)
            pseudo = {
                "r_ent_vals": p_ent,
                "r_ent_e": p_ent_e,
                "r_ent_valid": p_ent_valid,
                "r_op_vals": p_ops,
                "r_act_ids": p_act_ids,
                "r_act_vals": p_act_vals,
                "r_sub_ids": neg1,
                "r_sub_vals": neg1,
                "r_roles": neg1,
                "r_prop_vals": neg1,
                "r_prop_sfx": neg1,
                "r_prop_run": neg1,
                "r_prop_tail": neg1,
                "r_has_props": np.zeros((G,), bool),
                "rgx_set": p_rgx,
                "pfx_neq": p_pfx,
            }
            if self._bits_fn is None:
                with_hr = self.needs_hr
                with_rel = self.needs_rel

                def bits_fn(c_inv, cs, rr):
                    def one(g, r_row):
                        c = {**c_inv,
                             **jax.tree_util.tree_map(lambda x: x[g], cs)}
                        comp = _match_targets(
                            c, r_row, with_hr=with_hr, components=True,
                            with_rel=with_rel,
                        )
                        act = comp["sig_act_ok"]
                        rt = c["rule_target"]
                        pt = c["pol_target"]
                        st = c["set_target"]
                        deny = c["rule_effect"] == 2

                        def g_(tab, idx):
                            return jnp.take(tab, idx, axis=0)

                        # multi-entity recheck is signature-determined
                        # (reference: :429-463); pseudo ents ARE the sig
                        multi_ok = _multi_entity_ok(
                            c, r_row["r_ent_vals"], r_row["r_ent_valid"]
                        )
                        hr_planes = {}
                        if with_hr:
                            hr_triv = (c["t_n_subjects"] == 0) | ~c[
                                "t_has_scoping"
                            ]
                            hr_planes = {
                                "rl_collect": jnp.take(
                                    comp["sig_collect"], rt, axis=0
                                ),
                                "rl_op_hit": jnp.take(
                                    comp["sig_op_hit"], rt, axis=0
                                ),
                                "rl_triv": jnp.take(hr_triv, rt, axis=0),
                                "rl_rs": jnp.take(
                                    c["t_rs_idx"], rt, axis=0
                                ),
                                "rl_hrchk": jnp.take(
                                    c["t_hr_check"], rt, axis=0
                                ),
                                "pl_collect": jnp.take(
                                    comp["sig_collect"], pt, axis=0
                                ),
                                "pl_op_hit": jnp.take(
                                    comp["sig_op_hit"], pt, axis=0
                                ),
                                "pl_triv": jnp.take(hr_triv, pt, axis=0),
                                "pl_rs": jnp.take(
                                    c["t_rs_idx"], pt, axis=0
                                ),
                                "pl_hrchk": jnp.take(
                                    c["t_hr_check"], pt, axis=0
                                ),
                            }
                        rel_planes = {}
                        if with_rel:
                            rel_planes = {
                                "rl_rel_idx": jnp.take(
                                    c["t_rel_idx"], rt, axis=0
                                ),
                                "rl_rel_dir": jnp.take(
                                    c["t_rel_direct"], rt, axis=0
                                ),
                                "pl_rel_idx": jnp.take(
                                    c["t_rel_idx"], pt, axis=0
                                ),
                                "pl_rel_dir": jnp.take(
                                    c["t_rel_direct"], pt, axis=0
                                ),
                            }
                            if not with_hr:
                                # collection planes otherwise come with
                                # the HR set; rel-only trees need them too
                                rel_planes["rl_collect"] = jnp.take(
                                    comp["sig_collect"], rt, axis=0
                                )
                                rel_planes["pl_collect"] = jnp.take(
                                    comp["sig_collect"], pt, axis=0
                                )
                        return {
                            **hr_planes,
                            **rel_planes,
                            "rl_ex": jnp.where(
                                deny, g_(comp["sig_res_ex_d"], rt),
                                g_(comp["sig_res_ex_p"], rt)
                            ) & g_(act, rt),
                            "rl_rg": jnp.where(
                                deny, g_(comp["sig_res_rg_d"], rt),
                                g_(comp["sig_res_rg_p"], rt)
                            ) & g_(act, rt),
                            "rl_role": g_(c["t_role"], rt),
                            "rl_has_role": g_(c["t_has_role"], rt),
                            "rl_n_sub": g_(c["t_n_subjects"], rt),
                            "rl_sub_ids": g_(c["t_sub_ids"], rt),
                            "rl_sub_vals": g_(c["t_sub_vals"], rt),
                            "rl_skip": g_(c["t_skip_acl"], rt),
                            "pp_ex_p": g_(comp["sig_res_ex_p"], pt) & g_(act, pt),
                            "pp_ex_d": g_(comp["sig_res_ex_d"], pt) & g_(act, pt),
                            "pp_rg_p": g_(comp["sig_res_rg_p"], pt) & g_(act, pt),
                            "pp_rg_d": g_(comp["sig_res_rg_d"], pt) & g_(act, pt),
                            "pl_role": g_(c["t_role"], pt),
                            "pl_has_role": g_(c["t_has_role"], pt),
                            "pl_n_sub": g_(c["t_n_subjects"], pt),
                            "pl_sub_ids": g_(c["t_sub_ids"], pt),
                            "pl_sub_vals": g_(c["t_sub_vals"], pt),
                            "ss_ex_p": g_(comp["sig_res_ex_p"], st) & g_(act, st),
                            "sl_role": g_(c["t_role"], st),
                            "sl_has_role": g_(c["t_has_role"], st),
                            "sl_n_sub": g_(c["t_n_subjects"], st),
                            "sl_sub_ids": g_(c["t_sub_ids"], st),
                            "sl_sub_vals": g_(c["t_sub_vals"], st),
                            "multi_ok": multi_ok,
                        }

                    G = rr["r_ent_vals"].shape[0]
                    return jax.vmap(one)(jnp.arange(G), rr)

                self._bits_fn = self._wrap_runner(
                    ("bits", self.needs_hr, self.needs_rel), bits_fn, None
                )
            varying = {k: v for k, v in stacked.items()}
            bits = jax.tree_util.tree_map(
                jnp.asarray,
                self._bits_fn(
                    varying,
                    {k: jnp.asarray(v) for k, v in pseudo.items()},
                ),
            )
            if len(self._bits) >= 16:
                self._bits.pop(next(iter(self._bits)))
        self._bits[keys] = bits
        return bits

    # ---------------------------------------------------------------- caches
    def _count(self, key: str, n: int = 1) -> None:
        if self.telemetry is not None and n:
            self.telemetry.paths.inc(key, n)

    def _sub(self, key, ent_ids, ent_cols, op_ids, act_vals,
             rgx_set) -> CompiledPolicies:
        sub = self._subs.pop(key, None)  # LRU: reinsert at the tail
        if sub is None:
            self._count("prefilter-sub-miss")
            rows = candidate_rows(
                self.compiled, ent_ids, ent_cols, op_ids, act_vals, rgx_set
            )
            sub = compact_rules(self.compiled, rows, explain=self.explain)
            if len(self._subs) >= self.cache_size:
                self._subs.pop(next(iter(self._subs)))
        else:
            self._count("prefilter-sub-hit")
        self._subs[key] = sub
        return sub

    def _stack(
        self, keys: tuple, subs: list[CompiledPolicies]
    ) -> dict[str, jnp.ndarray]:
        stacked = self._stacks.pop(keys, None)
        if stacked is None:
            self._count("prefilter-stack-miss")
            krp = pow2_bucket(max(s.KR for s in subs), floor=4)
            tp = pow2_bucket(max(s.T for s in subs), floor=8)
            stacked = {
                name: jnp.asarray(np.stack(
                    [_pad_sub(s.arrays[name], name, krp, tp) for s in subs]
                ))
                for name in subs[0].arrays
                if _is_varying(name)
            }
            if len(self._stacks) >= 16:
                self._stacks.pop(next(iter(self._stacks)))
        else:
            self._count("prefilter-stack-hit")
        self._stacks[keys] = stacked
        return stacked

    # -------------------------------------------------------------- evaluate
    def evaluate(self, batch: RequestBatch):
        out = self.evaluate_async(batch)
        return out()

    def evaluate_async(self, batch: RequestBatch):
        """Run host prep + dispatch WITHOUT blocking on the result;
        returns a zero-arg callable that materializes the (decision,
        cacheable, status) tuple.  Callers that stream batches overlap
        batch i+1's host-side signature/packing work with batch i's
        device execution — host prep and the device chain are the same
        order of magnitude on the tunnel backend, so pipelining nearly
        doubles steady-state throughput."""
        if not self.active:
            # small trees: the dense/sharded kernel's own async dispatch
            return self._dense.evaluate_async(batch)

        # failpoint (srv/faults.py): host-side dispatch boundary — fires
        # before any device work, so the lowered program is unchanged
        from ..srv.faults import REGISTRY as _faults

        _faults.fire("device.dispatch")

        ents = np.asarray(batch.arrays["r_ent_vals"])  # [B, NR]
        cols = np.asarray(batch.arrays["r_ent_e"])     # [B, NR]
        valid = np.asarray(batch.arrays["r_ent_valid"])
        ops = np.asarray(batch.arrays["r_op_vals"])    # [B, NOP]
        act_ids = np.asarray(batch.arrays["r_act_ids"])
        acts = np.asarray(batch.arrays["r_act_vals"])  # [B, NACT]
        B, NR = ents.shape
        NOP = ops.shape[1]
        NACT = acts.shape[1]

        # signature-plane eligibility: no ACL pairs / request properties
        # in this batch (those rows need the full per-row matcher)
        use_sig = (
            not bool((np.asarray(batch.arrays["r_acl_ent"]) >= 0).any())
            and not bool(np.asarray(batch.arrays["r_has_props"]).any())
        )

        # sig path: group rows by ORDERED entity runs (the sticky/
        # prefix-reset state machines are order-sensitive) + sorted ops +
        # sorted action (id, val) pairs.  Fallback path: stage A runs per
        # row anyway, so the coarser order-insensitive signature maximizes
        # group sharing (permuted multi-entity requests share one group).
        ents_m = np.where(valid, ents, -1)
        pair_key = (act_ids.astype(np.int64) << 32) | (
            acts.astype(np.int64) & 0xFFFFFFFF
        )
        order = np.argsort(pair_key, axis=1, kind="stable")
        act_ids_s = np.take_along_axis(act_ids, order, 1)
        act_vals_s = np.take_along_axis(acts, order, 1)
        if use_sig:
            sig = np.concatenate(
                [ents_m, np.sort(ops, 1), act_ids_s, act_vals_s], axis=1
            )
        else:
            sig = np.concatenate(
                [np.sort(ents_m, 1), np.sort(ops, 1), np.sort(acts, 1)],
                axis=1,
            )
        # exact mixed-radix packing of the signature columns into one
        # int64 key when the value ranges fit (they essentially always
        # do): np.unique on a flat int64 vector is ~10x the axis=0
        # lexsort at 16k rows, and the packing is order-preserving so the
        # group order matches the lexicographic fallback
        shifted = sig.astype(np.int64) + 1  # -1 padding -> 0
        radix = shifted.max(axis=0) + 1
        if float(np.prod(radix.astype(np.float64))) < 2.0 ** 62:
            key = np.zeros(B, np.int64)
            for j in range(sig.shape[1]):
                key = key * radix[j] + shifted[:, j]
            _, first_idx, inv = np.unique(
                key, return_index=True, return_inverse=True
            )
            uniq = sig[first_idx]
        else:
            uniq, inv = np.unique(sig, axis=0, return_inverse=True)
        inv = inv.reshape(B)

        if uniq.shape[0] > self.max_groups:
            # cardinality guard: segment the batch so each dispatch spans
            # at most max_groups signatures — adversarial all-novel-
            # signature traffic degrades to more dispatches instead of
            # unbounded [G, ...] stack memory
            self._count("prefilter-guard-splits")
            row_order = np.argsort(inv, kind="stable")
            seg_slices = []
            start = 0
            seen = 0
            last_group = -1
            for pos, gidx in enumerate(inv[row_order].tolist()):
                if gidx != last_group:
                    seen += 1
                    last_group = gidx
                    if seen > self.max_groups:
                        seg_slices.append(row_order[start:pos])
                        start = pos
                        seen = 1
            seg_slices.append(row_order[start:])
            outs = [np.zeros((B,), np.int32)
                    for _ in range(4 if self.explain else 3)]
            for idx in seg_slices:
                sub_batch = RequestBatch(
                    B=len(idx),
                    arrays={k: np.ascontiguousarray(np.asarray(v)[idx])
                            for k, v in batch.arrays.items()},
                    rgx_set=batch.rgx_set,
                    pfx_neq=batch.pfx_neq,
                    cond_true=np.ascontiguousarray(batch.cond_true[:, idx]),
                    cond_abort=np.ascontiguousarray(batch.cond_abort[:, idx]),
                    cond_code=np.ascontiguousarray(batch.cond_code[:, idx]),
                    eligible=np.asarray(batch.eligible)[idx],
                )
                seg_out = self.evaluate(sub_batch)
                for o, s in zip(outs, seg_out):
                    o[idx] = s
            res = tuple(outs)

            def materialize():
                _faults.fire("device.materialize")
                return res

            return materialize

        # entity value id -> batch entity column (positional in the runs)
        id_to_col = dict(zip(ents[valid].tolist(), cols[valid].tolist()))

        rgx_np = np.asarray(batch.rgx_set)
        pfx_np = np.asarray(batch.pfx_neq)
        keys = []
        groups = []
        subs = []  # held directly: cache eviction cannot orphan this batch
        for g in range(uniq.shape[0]):
            sig_row = uniq[g]
            # steady-state traffic repeats signatures: the parsed group
            # info (unique ids, cache keys, pair lists) is memoized by the
            # raw signature bytes so a recurring group costs two dict
            # lookups instead of three np.unique calls (~40 ms/batch at
            # 288 groups before memoization)
            gkey = (sig_row.tobytes(), NR, NOP, NACT, use_sig,
                    self.compiled.version)
            ginfo = self._ginfo.get(gkey)
            if ginfo is None:
                ordered = sig_row[:NR]
                ent_ids = np.unique(ordered[ordered >= 0])
                op_row = sig_row[NR:NR + NOP]
                op_ids = np.unique(op_row[op_row >= 0])
                if use_sig:
                    aid_row = sig_row[NR + NOP:NR + NOP + NACT]
                    aval_row = sig_row[NR + NOP + NACT:]
                else:
                    aid_row = np.full((0,), -1, sig_row.dtype)
                    aval_row = sig_row[NR + NOP:]
                pair_valid = (aid_row >= 0) | (
                    aval_row[: aid_row.shape[0]] >= 0
                )
                act_vals = np.unique(aval_row[aval_row >= 0])
                # compaction cache key stays sorted (order-insensitive
                # rule candidacy -> permuted signatures share one
                # compacted subtree)
                sub_key = (tuple(ent_ids.tolist()), tuple(op_ids.tolist()),
                           tuple(act_vals.tolist()), self.compiled.version)
                if use_sig:
                    key_entry = (tuple(ordered.tolist()),
                                 tuple(op_ids.tolist()),
                                 tuple(aid_row[pair_valid].tolist()),
                                 tuple(aval_row[pair_valid].tolist()),
                                 self.compiled.version)
                    group_entry = {
                        "ordered_ents": ordered.tolist(),
                        "op_ids": op_ids,
                        "act_pairs": list(zip(
                            aid_row[pair_valid].tolist(),
                            aval_row[pair_valid].tolist(),
                        )),
                    }
                else:
                    key_entry = sub_key
                    group_entry = None
                ginfo = (sub_key, key_entry, group_entry, ent_ids,
                         op_ids, act_vals)
                if len(self._ginfo) >= 8192:
                    self._ginfo.pop(next(iter(self._ginfo)))
                self._ginfo[gkey] = ginfo
            sub_key, key_entry, group_entry, ent_ids, op_ids, act_vals = ginfo
            ent_cols = np.array(
                [id_to_col[int(e)] for e in ent_ids], np.int64
            )
            subs.append(
                self._sub(sub_key, ent_ids, ent_cols, op_ids, act_vals,
                          rgx_np)
            )
            keys.append(key_entry)
            if group_entry is not None:
                # ordered_cols is batch-positional (regex matrix columns),
                # so it is derived fresh per batch
                groups.append({
                    **group_entry,
                    "ordered_cols": [
                        id_to_col.get(int(e), 0)
                        for e in group_entry["ordered_ents"]
                    ],
                })
        stacked = self._stack(tuple(keys), subs)

        _, bucket, e_bucket, pad_lead = lead_padding(batch)
        if self.mesh is not None:
            # even sharding over the data axis: both are powers of two in
            # practice, but guard the general case
            n_data = self.mesh.shape[self.axis]
            if bucket % n_data:
                bucket = -(-bucket // n_data) * n_data

            def pad_lead(a, _bucket=bucket):  # noqa: F811
                a = np.asarray(a)
                if a.shape[0] == _bucket:
                    return a
                fill = np.zeros((_bucket - a.shape[0],) + a.shape[1:],
                                a.dtype)
                return np.concatenate([a, fill], axis=0)

        if use_sig:
            bits = self._planes_for(
                tuple(keys), groups, stacked, (NR, NOP, NACT),
                rgx_np, pfx_np,
            )
            # pack the whole per-row side into ONE int32 buffer [B, W];
            # the buffer (and the slot/readback maps below) comes from the
            # staging pool and is released at materialize — the depth-N
            # pipeline allocates nothing per batch on this path
            r_keys = list(_SIG_R_KEYS_HR if self.needs_hr else _SIG_R_KEYS)
            if self.needs_rel:
                # relation closure planes ride the same packed row buffer
                r_keys += ["r_rel_runs", "r_rel_bits"]
            schedule = []
            widths = []
            for k in r_keys:
                a = np.asarray(batch.arrays[k])
                tail = a.shape[1:]
                w = int(np.prod(tail)) if tail else 1
                widths.append(w)
                schedule.append((k, w, tuple(tail)))
            C = batch.cond_true.shape[0]
            for nm in ("cond_true", "cond_abort", "cond_code"):
                schedule.append((nm, C, (C,)))
            W = sum(widths) + 3 * C
            # the runner's jit shapes must not track raw B: pad the row
            # buffer (and the readback map, below) to the half-pow2
            # bucket so varying serving batch sizes reuse compiles
            b_pad = half_pow2_bucket(B, floor=8)
            pool = self.staging
            leases: list = []

            def take(shape):
                buf = pool.acquire(shape, np.int32)
                leases.append(buf)
                return buf

            try:
                mega_rows = take((b_pad, W))
                off = 0
                for k, w in zip(r_keys, widths):
                    a = np.asarray(batch.arrays[k])
                    np.copyto(mega_rows[:B, off:off + w], a.reshape(B, w),
                              casting="unsafe")
                    off += w
                for arr in (batch.cond_true, batch.cond_abort,
                            batch.cond_code):
                    np.copyto(mega_rows[:B, off:off + C],
                              np.asarray(arr).T, casting="unsafe")
                    off += C
                if b_pad != B:
                    mega_rows[B:].fill(0)

                # group-dense slot layout (see _sig_runner): rows sorted
                # by signature, packed into [NSLOT, R] slots that each
                # share one group; padding is bounded by G * R extra rows
                # and oversized groups simply span multiple slots.  R
                # derives from BUCKETED batch/group counts only (and
                # nslot pads to half-pow2 buckets), so signature-mix skew
                # cannot multiply compiled (ns_pad, R) shape variants of
                # the heavy runner
                G = uniq.shape[0]
                gb = pow2_bucket(G, floor=1)
                R = min(4096, pow2_bucket(
                    max(8, 2 * pow2_bucket(B) // gb), floor=8,
                ))
                # near-unique signature mixes (G approaching B) would
                # inflate the slot grid by the R floor; cap total padded
                # rows at ~4x the bucketed batch so adversarial traffic
                # degrades bounded (8-row sublane tile is the hard floor)
                R = min(R, max(8, pow2_bucket(
                    4 * pow2_bucket(B) // gb, floor=8,
                )))
                row_order = np.argsort(inv, kind="stable")
                counts = np.bincount(inv, minlength=G)
                slots_per_g = -(-counts // R)
                slot_base = np.concatenate(([0], np.cumsum(slots_per_g)))
                nslot = int(slot_base[-1])
                ns_pad = half_pow2_bucket(nslot, floor=8)
                if self.mesh is not None:
                    n_data = self.mesh.shape[self.axis]
                    if ns_pad % n_data:
                        ns_pad = -(-ns_pad // n_data) * n_data
                starts = np.concatenate(([0], np.cumsum(counts)))
                rk = np.arange(B) - starts[inv[row_order]]
                grid_pos = (
                    (slot_base[inv[row_order]] + rk // R) * R + rk % R
                ).astype(np.int64)
                slot_g = take((ns_pad,))
                slot_g.fill(0)
                slot_g[:nslot] = np.repeat(
                    np.arange(G, dtype=np.int32), slots_per_g
                )
                # device-side scatter maps: grid position -> source row
                # (pad positions read row 0, discarded) and original row
                # -> grid position (the readback gather); pooled, so the
                # recycled buffers are zero-filled before the scatter
                grid2row_flat = take((ns_pad * R,))
                grid2row_flat.fill(0)
                grid2row_flat[grid_pos] = row_order
                grid2row = grid2row_flat.reshape(ns_pad, R)
                gp_orig = take((b_pad,))
                gp_orig.fill(0)
                gp_orig[row_order] = grid_pos.astype(np.int32)

                # static: does ANY subject-bearing target row in this
                # stack match by attribute pairs instead of role?
                needs_pairs = bool(
                    (~np.asarray(stacked["t_has_role"])
                     & (np.asarray(stacked["t_n_subjects"]) > 0)).any()
                )
                run = self._sig_runner(
                    tuple(schedule), needs_pairs, with_hr=self.needs_hr,
                    with_rel=self.needs_rel,
                )
                # rule_orig_flat rides along only in explain mode — adding
                # it unconditionally would change the runner's argument
                # pytree (and so the lowered program bytes) when off
                c_keys = (
                    _SIG_C_KEYS + ["rule_orig_flat"]
                    if self.explain else _SIG_C_KEYS
                )
                cs = {k: v for k, v in stacked.items()
                      if k in c_keys}
                # explicit async H2D put: handing the numpy buffers
                # straight to pjit transfers them synchronously on the
                # critical path (~10x slower for the packed buffer on the
                # tunnel backend)
                if self.mesh is None:
                    slot_g_d, mega_rows_d, grid2row_d, gp_orig_d = \
                        jax.device_put(
                            (slot_g, mega_rows, grid2row, gp_orig)
                        )
                else:
                    from jax.sharding import (
                        NamedSharding,
                        PartitionSpec as P,
                    )

                    data = NamedSharding(self.mesh, P(self.axis))
                    repl = NamedSharding(self.mesh, P())
                    slot_g_d = jax.device_put(slot_g, data)
                    grid2row_d = jax.device_put(grid2row, data)
                    mega_rows_d = jax.device_put(mega_rows, repl)
                    gp_orig_d = jax.device_put(gp_orig, repl)
                out_dev = run(cs, bits, slot_g_d, mega_rows_d, grid2row_d,
                              gp_orig_d)
            except BaseException:
                # a failed dispatch (compile error, bad shapes) must not
                # leak its leases — recurring errors would drain the pool
                pool.release_all(leases)
                raise

            n_out = 4 if self.explain else 3

            def materialize():
                # the output fetch orders after every consumer of the
                # inputs, so the staging leases are safe to recycle only
                # AFTER this line — releasing earlier could leak rows
                # between batches on the zero-copy CPU backend
                _faults.fire("device.materialize")
                out = np.asarray(out_dev)  # [n_out, b_pad]
                if leases:
                    pool.release_all(leases)
                    leases.clear()
                return tuple(out[i][:B] for i in range(n_out))

            return materialize
        run = self._runner(
            bool((np.asarray(batch.arrays["r_acl_ent"]) >= 0).any()),
            tree_needs_hr(stacked),
            tree_needs_rel(stacked),
        )
        out = run(
            stacked,
            jnp.asarray(pad_lead(inv.astype(np.int32).reshape(B))),
            {k: jnp.asarray(pad_lead(np.asarray(v)))
             for k, v in batch.arrays.items()},
            jnp.asarray(pad_cols(rgx_np, e_bucket)),
            jnp.asarray(pad_cols(np.asarray(batch.pfx_neq), e_bucket)),
            jnp.asarray(pad_cols(batch.cond_true, bucket)),
            jnp.asarray(pad_cols(batch.cond_abort, bucket)),
            jnp.asarray(pad_cols(batch.cond_code, bucket)),
        )
        def materialize():
            _faults.fire("device.materialize")
            return tuple(np.asarray(x)[:B] for x in out)

        return materialize
