"""Host-side candidate pre-filtering for large rule counts.

The dense kernel's per-request work is O(total target rows): every rule's
target row is matched against every request even though a rule whose
target names entity X can never match a request that only names entity Y
(reference target semantics: a resource-bearing target matches only via an
exact entity hit, a regex entity hit, or an operation hit —
src/core/accessController.ts:465-654).  With 100k rules that dense sweep
is the whole cost.

This module restores O(matching rules): batch rows are grouped by their
*resource signature* (distinct entity value ids + operation ids); for each
signature the rule axis is compacted to the candidate subset

  - rules with no target / no resource attributes (match anything),
  - rules whose target entities exactly match a signature entity,
  - rules whose target entities regex-match one (vocab regex matrices are
    already computed per batch),
  - rules whose target operations match a signature operation,

left-packed along KR in original order.  Because combining algorithms are
order-sensitive but only *relatively* so (first-DENY / first-PERMIT /
first-applicable over collected rules, reference :846-893), dropping rules
that provably cannot match and preserving relative order leaves every
decision bit-identical.  Policy/set target rows are always retained, so
set gates, policy gates, carried policyEffect and the multi-entity recheck
(which reads policy-level arrays) are untouched.

Execution is ONE device dispatch per batch: the signature subtrees are
padded to a common shape and stacked on a leading group axis [G, ...];
each request row carries its group index and gathers its own subtree
inside the vmapped kernel.  Per-signature compacted trees and per-
signature-set stacks are cached, so steady-state traffic pays neither
compaction nor host->device transfer of policy data again.
"""

from __future__ import annotations

from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np

from .compile import CompiledPolicies
from .encode import RequestBatch
from .kernel import (
    DecisionKernel,
    _evaluate_one,
    lead_padding,
    pad_cols,
    pow2_bucket,
    tree_needs_hr,
)

_RULE_FIELDS = [
    "rule_valid", "rule_effect", "rule_cacheable_raw", "rule_cacheable_eff",
    "rule_has_target", "rule_target", "rule_cond",
]


def _is_varying(name: str) -> bool:
    """Arrays that differ between signature subtrees (compacted rule axis,
    compacted target subtable, remapped target indices); everything else is
    group-invariant policy/set metadata shared across the stack."""
    return (
        name in _RULE_FIELDS
        or name in ("pol_target", "set_target")
        or name.startswith("t_")
    )

# rules below this count are cheaper to sweep densely than to group/compact
MIN_RULES = 512


def candidate_rows(
    compiled: CompiledPolicies,
    ent_ids: np.ndarray,
    ent_cols: np.ndarray,
    op_ids: np.ndarray,
    act_vals: np.ndarray,
    rgx_set: np.ndarray,
) -> np.ndarray:
    """[T] bool: target rows that could produce a match for a request
    whose distinct entity value ids are ``ent_ids`` (batch entity columns
    ``ent_cols``), operation ids ``op_ids`` and action attribute values
    ``act_vals``.

    Resource side: no-resource targets, exact entity hits, regex entity
    hits, operation hits.  Action side: every target action attribute must
    find an id+value pair in the request (kernel ``act_ok``), so a target
    action VALUE absent from the request's action values disqualifies the
    row — value-only filtering is conservative (id mismatches are left for
    the kernel), which keeps signature aliasing safe."""
    a = compiled.arrays
    tv = a["t_ent_vals"]  # [T, K_ENT]
    cand = a["t_n_res"] == 0
    if ent_ids.size:
        cand = cand | (np.isin(tv, ent_ids) & (tv >= 0)).any(axis=1)
        # regex candidacy: any target vocab row regex-hits a batch entity col
        w = a["t_ent_w"]  # [T, K_ENT]
        hits = rgx_set[np.clip(w, 0, None)][:, :, ent_cols]  # [T, K, |cols|]
        cand = cand | (hits & (w >= 0)[:, :, None]).any(axis=(1, 2))
    if op_ids.size:
        ov = a["t_op_vals"]
        cand = cand | (np.isin(ov, op_ids) & (ov >= 0)).any(axis=1)
    av = a["t_act_vals"]  # [T, K_ACT]
    act_compat = ((av < 0) | np.isin(av, act_vals)).all(axis=1)
    return cand & act_compat


def compact_rules(
    compiled: CompiledPolicies, row_cand: np.ndarray
) -> CompiledPolicies:
    """Left-pack candidate rules along KR (order-preserving) and compact
    the target subtable to the rows the kept rules + all policy/set
    targets reference.  Mirrors parallel/rule_shard.py:partition_rules'
    compaction, but driven by candidacy instead of chunk boundaries."""
    a = compiled.arrays
    cand = a["rule_valid"] & (~a["rule_has_target"] | row_cand[a["rule_target"]])

    counts = cand.sum(axis=2)
    krp = pow2_bucket(int(counts.max()) if counts.size else 0, floor=4)
    krp = min(krp, compiled.KR) if compiled.KR else krp
    order = np.argsort(~cand, axis=2, kind="stable")  # candidates first
    new: dict[str, np.ndarray] = {}
    for name in _RULE_FIELDS:
        new[name] = np.take_along_axis(a[name], order, axis=2)[:, :, :krp]
    new["rule_valid"] = np.take_along_axis(cand, order, axis=2)[:, :, :krp]

    needed = set(
        np.unique(new["rule_target"][new["rule_valid"] & new["rule_has_target"]])
    )
    needed |= set(np.unique(a["pol_target"][a["pol_has_target"]]))
    needed |= set(np.unique(a["set_target"][a["set_has_target"]]))
    needed.add(0)  # row 0 backs the "no target" index
    rows = sorted(needed)
    remap = np.zeros(a["t_role"].shape[0], np.int64)
    for j, old in enumerate(rows):
        remap[old] = j
    for name, arr in a.items():
        if name.startswith("t_"):
            new[name] = arr[rows]
        elif name not in new:
            new[name] = arr
    new["rule_target"] = remap[new["rule_target"]].astype(np.int32)
    new["pol_target"] = remap[a["pol_target"]].astype(np.int32)
    new["set_target"] = remap[a["set_target"]].astype(np.int32)
    return replace(compiled, arrays=new, KR=krp, T=len(rows))


def _pad_sub(arr: np.ndarray, name: str, krp: int, tp: int) -> np.ndarray:
    """Pad one compacted-subtree array to the stack's common KR/T."""
    if name in _RULE_FIELDS:
        width = krp - arr.shape[2]
        if width > 0:
            fill = (
                False if arr.dtype == bool
                else (0 if name in ("rule_effect", "rule_target") else -1)
            )
            arr = np.concatenate(
                [arr, np.full(arr.shape[:2] + (width,), fill, arr.dtype)],
                axis=2,
            )
        return arr
    if name.startswith("t_") and arr.shape[0] < tp:
        reps = np.repeat(arr[:1], tp - arr.shape[0], axis=0)
        arr = np.concatenate([arr, reps], axis=0)
    return arr


class PrefilteredKernel:
    """Drop-in DecisionKernel: groups the batch by resource signature,
    compacts the rule axis per signature, and evaluates the whole batch in
    one dispatch over stacked subtrees.  Decisions are bit-identical to
    the dense kernel (differential: tests/test_prefilter.py); trees under
    MIN_RULES rules skip the machinery entirely."""

    def __init__(self, compiled: CompiledPolicies, cache_size: int = 1024,
                 mesh=None, axis: str = "data"):
        """``mesh``: optional jax.sharding.Mesh — requests shard
        data-parallel over ``axis`` while the stacked subtrees and regex
        matrices replicate (the multi-chip layout of parallel/mesh.py
        applied to the candidate-compacted dispatch)."""
        if not compiled.supported:
            raise ValueError(
                f"policy tree unsupported by kernel: {compiled.unsupported_reason}"
            )
        self.compiled = compiled
        self.cache_size = cache_size
        self.mesh = mesh
        self.axis = axis
        self._subs: dict[tuple, CompiledPolicies] = {}
        self._stacks: dict[tuple, dict[str, jnp.ndarray]] = {}
        self._dense: DecisionKernel | None = None
        self._runs: dict[tuple, object] = {}
        self.active = compiled.n_rules >= MIN_RULES
        if not self.active:
            if mesh is not None:
                # small trees delegate to the batch-sharded dense kernel so
                # a configured mesh is honored on every tree size
                from ..parallel.mesh import ShardedDecisionKernel

                self._dense = ShardedDecisionKernel(compiled, mesh, axis)
            else:
                self._dense = DecisionKernel(compiled)
        self._c_inv = {
            k: jnp.asarray(v) for k, v in compiled.arrays.items()
            if not _is_varying(k)
        }

    def _runner(self, with_acl: bool, with_hr: bool):
        key = (with_acl, with_hr)
        run = self._runs.get(key)
        if run is None:
            c_inv = self._c_inv  # baked as jit constants: [S,KP]-scale only

            def run(cs, g_idx, batch_arrays, rgx_set, pfx_neq,
                    cond_true, cond_abort, cond_code):
                def one(g, ra, ct, ca, cc):
                    # per-row gather of the group-VARYING arrays only;
                    # policy/set metadata is identical across subtrees
                    c = {**c_inv,
                         **jax.tree_util.tree_map(lambda x: x[g], cs)}
                    rr = {**ra, "rgx_set": rgx_set, "pfx_neq": pfx_neq,
                          "cond_true": ct, "cond_abort": ca, "cond_code": cc}
                    return _evaluate_one(c, rr, with_acl, with_hr)

                return jax.vmap(one)(
                    g_idx, batch_arrays,
                    cond_true.T, cond_abort.T, cond_code.T,
                )

            if self.mesh is None:
                run = jax.jit(run)
            else:
                from jax.sharding import NamedSharding, PartitionSpec as P

                repl = NamedSharding(self.mesh, P())
                data = NamedSharding(self.mesh, P(self.axis))
                cond = NamedSharding(self.mesh, P(None, self.axis))
                run = jax.jit(
                    run,
                    in_shardings=(repl, data, data, repl, repl,
                                  cond, cond, cond),
                    out_shardings=(data, data, data),
                )
            self._runs[key] = run
        return run

    # ---------------------------------------------------------------- caches
    def _sub(self, key, ent_ids, ent_cols, op_ids, act_vals,
             rgx_set) -> CompiledPolicies:
        sub = self._subs.pop(key, None)  # LRU: reinsert at the tail
        if sub is None:
            rows = candidate_rows(
                self.compiled, ent_ids, ent_cols, op_ids, act_vals, rgx_set
            )
            sub = compact_rules(self.compiled, rows)
            if len(self._subs) >= self.cache_size:
                self._subs.pop(next(iter(self._subs)))
        self._subs[key] = sub
        return sub

    def _stack(
        self, keys: tuple, subs: list[CompiledPolicies]
    ) -> dict[str, jnp.ndarray]:
        stacked = self._stacks.pop(keys, None)
        if stacked is None:
            krp = pow2_bucket(max(s.KR for s in subs), floor=4)
            tp = pow2_bucket(max(s.T for s in subs), floor=8)
            stacked = {
                name: jnp.asarray(np.stack(
                    [_pad_sub(s.arrays[name], name, krp, tp) for s in subs]
                ))
                for name in subs[0].arrays
                if _is_varying(name)
            }
            if len(self._stacks) >= 16:
                self._stacks.pop(next(iter(self._stacks)))
        self._stacks[keys] = stacked
        return stacked

    # -------------------------------------------------------------- evaluate
    def evaluate(self, batch: RequestBatch):
        if not self.active:
            return self._dense.evaluate(batch)

        ents = np.asarray(batch.arrays["r_ent_vals"])  # [B, NR]
        cols = np.asarray(batch.arrays["r_ent_e"])     # [B, NR]
        ops = np.asarray(batch.arrays["r_op_vals"])    # [B, NOP]
        acts = np.asarray(batch.arrays["r_act_vals"])  # [B, NACT]
        B, NR = ents.shape
        NOP = ops.shape[1]

        sig = np.concatenate(
            [np.sort(ents, 1), np.sort(ops, 1), np.sort(acts, 1)], axis=1
        )
        uniq, inv = np.unique(sig, axis=0, return_inverse=True)

        # entity value id -> batch entity column (positional in the runs)
        valid = ents >= 0
        id_to_col = dict(zip(ents[valid].tolist(), cols[valid].tolist()))

        rgx_np = np.asarray(batch.rgx_set)
        keys = []
        subs = []  # held directly: cache eviction cannot orphan this batch
        for g in range(uniq.shape[0]):
            sig_row = uniq[g]
            ent_ids = np.unique(sig_row[:NR][sig_row[:NR] >= 0])
            op_ids = np.unique(sig_row[NR:NR + NOP][sig_row[NR:NR + NOP] >= 0])
            act_vals = np.unique(
                sig_row[NR + NOP:][sig_row[NR + NOP:] >= 0]
            )
            ent_cols = np.array(
                [id_to_col[int(e)] for e in ent_ids], np.int64
            )
            key = (tuple(ent_ids.tolist()), tuple(op_ids.tolist()),
                   tuple(act_vals.tolist()), self.compiled.version)
            subs.append(
                self._sub(key, ent_ids, ent_cols, op_ids, act_vals, rgx_np)
            )
            keys.append(key)
        stacked = self._stack(tuple(keys), subs)

        _, bucket, e_bucket, pad_lead = lead_padding(batch)
        if self.mesh is not None:
            # even sharding over the data axis: both are powers of two in
            # practice, but guard the general case
            n_data = self.mesh.shape[self.axis]
            if bucket % n_data:
                bucket = -(-bucket // n_data) * n_data

            def pad_lead(a, _bucket=bucket):  # noqa: F811
                a = np.asarray(a)
                if a.shape[0] == _bucket:
                    return a
                fill = np.zeros((_bucket - a.shape[0],) + a.shape[1:],
                                a.dtype)
                return np.concatenate([a, fill], axis=0)

        g_idx = pad_lead(inv.astype(np.int32).reshape(B))
        run = self._runner(
            bool((np.asarray(batch.arrays["r_acl_ent"]) >= 0).any()),
            tree_needs_hr(stacked),
        )
        out = run(
            stacked,
            jnp.asarray(g_idx),
            {k: jnp.asarray(pad_lead(np.asarray(v)))
             for k, v in batch.arrays.items()},
            jnp.asarray(pad_cols(rgx_np, e_bucket)),
            jnp.asarray(pad_cols(np.asarray(batch.pfx_neq), e_bucket)),
            jnp.asarray(pad_cols(batch.cond_true, bucket)),
            jnp.asarray(pad_cols(batch.cond_abort, bucket)),
            jnp.asarray(pad_cols(batch.cond_code, bucket)),
        )
        return tuple(np.asarray(x)[:B] for x in out)
