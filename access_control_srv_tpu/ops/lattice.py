# acs-lint: host-only — lattice enumeration, folding and snapshot I/O
# never touch the device; the sweep rides the existing reverse kernel
# through srv layers (srv/audit_sweep.py).
"""Permission-lattice enumeration, combining-fold, snapshots and diffs.

The policy-mining literature (PAPERS.md: LLMAC, DLBAC) consumes
*effective-permission matrices* — "who can do what" over a subject x
resource x action lattice.  The reverse/wia kernel (ops/reverse.py)
already answers one lattice cell per request at ~37x scalar speed; this
module supplies everything around it that stays on the host:

- :class:`LatticeSpec` — the three axes plus the attribute URNs used to
  synthesize one ``whatIsAllowed`` request per cell, with a chunked
  request iterator for bounded-memory sweeps.
- :func:`fold_reverse_query` — collapses a ``ReverseQuery`` tree into a
  per-cell verdict by replaying the engine's combining algorithms
  (core/engine.py ``decide``) over the matched rules, carrying the
  deciding rule id (the PR 16 explain provenance) into the snapshot.
- :class:`SnapshotWriter` / :func:`load_snapshot` — a streamed JSONL
  snapshot (header + sparse cell lines + summary footer, axis values
  masked exactly like the PR 6 decision-audit log) and a packed 2-bit
  bitmap sidecar (4 cells/byte) for compact machine diffing.
- :func:`diff_snapshots` — cross-version diff naming, per changed cell,
  the deciding rule on both sides.

Verdicts are an *optimistic* bound for conditional rules: ``whatIsAllowed``
returns matched rules without evaluating conditions, so any cell whose
winning tree contains a rule with a condition (or context query) is
flagged ``conditional`` and coded separately in the bitmap — exactly the
caveat the reference PDP documents for whatIsAllowed consumers.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Iterator, Optional

import numpy as np

from ..models.model import (
    Attribute,
    Decision,
    Effect,
    Request,
    ReverseQuery,
    Target,
)
from ..models.urns import DEFAULT_URNS

SNAPSHOT_KIND = "acs-lattice-snapshot"
SNAPSHOT_VERSION = 1

# 2-bit bitmap codes (4 cells per byte, subject-major cell order)
CODE_NOT_APPLICABLE = 0
CODE_PERMIT = 1
CODE_DENY = 2
CODE_CONDITIONAL = 3

_MASK = "***"

# combining-algorithm resolution: full XACML URNs (core/engine.py
# DEFAULT_COMBINING_ALGORITHMS), the loader's camelCase aliases, and the
# bare method names custom registrations commonly map to.
_COMBINING_METHODS = {
    "urn:oasis:names:tc:xacml:3.0:rule-combining-algorithm:deny-overrides":
        "deny_overrides",
    "urn:oasis:names:tc:xacml:3.0:rule-combining-algorithm:permit-overrides":
        "permit_overrides",
    "urn:oasis:names:tc:xacml:3.0:rule-combining-algorithm:first-applicable":
        "first_applicable",
    "denyOverrides": "deny_overrides",
    "permitOverrides": "permit_overrides",
    "firstApplicable": "first_applicable",
    "deny_overrides": "deny_overrides",
    "permit_overrides": "permit_overrides",
    "first_applicable": "first_applicable",
}


def _mask_fields() -> tuple:
    # the serving layer's mask list is the source of truth (PR 6 audit
    # log); imported lazily so this module stays importable standalone
    try:
        from ..srv.telemetry import _LOWERED_MASK_FIELDS

        return _LOWERED_MASK_FIELDS
    except Exception:  # pragma: no cover - srv layer always present in-tree
        return ("password", "token", "apikey", "api_key", "authorization")


def mask_value(attr_id: str, value: Any) -> Any:
    """The decision-audit-log masking rule (srv/tracing.DecisionAuditLog):
    a value whose attribute id names a secret is replaced with ``***``
    before it can reach an exported artifact."""
    lowered = str(attr_id).lower()
    if any(f in lowered for f in _mask_fields()):
        return _MASK
    return value


# ------------------------------------------------------------------ lattice


@dataclass(frozen=True)
class LatticeSpec:
    """The audit lattice: ``subjects`` are ``(subject_id, role)`` pairs,
    ``resources`` are ``(resource_id, entity_urn)`` pairs, ``actions``
    are action URNs.  Cell order is subject-major:
    ``index = (si * len(resources) + ri) * len(actions) + ai``."""

    subjects: tuple
    resources: tuple
    actions: tuple
    subject_id_urn: str = DEFAULT_URNS["subjectID"]
    role_urn: str = DEFAULT_URNS["role"]
    entity_urn: str = DEFAULT_URNS["entity"]
    action_urn: str = DEFAULT_URNS["actionID"]

    @property
    def shape(self) -> tuple:
        return (len(self.subjects), len(self.resources), len(self.actions))

    @property
    def n_cells(self) -> int:
        s, r, a = self.shape
        return s * r * a

    def unravel(self, index: int) -> tuple:
        n_r, n_a = len(self.resources), len(self.actions)
        ai = index % n_a
        ri = (index // n_a) % n_r
        si = index // (n_a * n_r)
        return si, ri, ai

    def request(self, index: int) -> Request:
        """One wia request per cell, in the shape the reverse kernel's
        differential suite pins (role + subjectID subject attributes,
        entity resource attribute, actionID action attribute, and the
        role association mirrored into the context)."""
        si, ri, ai = self.unravel(index)
        subject_id, role = self.subjects[si]
        _, entity = self.resources[ri]
        action = self.actions[ai]
        subjects = []
        if role:
            subjects.append(Attribute(id=self.role_urn, value=role))
        subjects.append(Attribute(id=self.subject_id_urn, value=subject_id))
        return Request(
            target=Target(
                subjects=subjects,
                resources=[Attribute(id=self.entity_urn, value=entity)],
                actions=[Attribute(id=self.action_urn, value=action)],
            ),
            context={
                "resources": [],
                "subject": {
                    "id": subject_id,
                    "role_associations": (
                        [{"role": role, "attributes": []}] if role else []
                    ),
                    "hierarchical_scopes": [],
                },
            },
        )

    def chunks(self, chunk_size: int, start: int = 0) -> Iterator[list]:
        """Bounded-memory enumeration: yields lists of ``(index, Request)``
        of at most ``chunk_size`` cells; only one chunk is ever alive."""
        chunk_size = max(1, int(chunk_size))
        chunk: list = []
        for index in range(start, self.n_cells):
            chunk.append((index, self.request(index)))
            if len(chunk) >= chunk_size:
                yield chunk
                chunk = []
        if chunk:
            yield chunk

    # ------------------------------------------------------------- builders

    @classmethod
    def stress(
        cls,
        n_subjects: int,
        n_resources: int,
        actions: tuple = ("read",),
        roles: int = 97,
        entities: int = 64,
        urns=None,
    ) -> "LatticeSpec":
        """The synthetic-stress-tree lattice (bench_all._stress_doc):
        subjects cycle ``role-{0..roles-1}``, resources cycle the
        ``stress{0..entities-1}`` entity types, actions resolve through
        the URN registry (bare names like ``"read"`` or full URNs)."""
        from ..models import Urns

        urns = urns or Urns()
        subjects = tuple(
            (f"u{i}", f"role-{i % roles}") for i in range(int(n_subjects))
        )
        resources = tuple(
            (
                f"res{i}",
                "urn:restorecommerce:acs:model:"
                f"stress{i % entities}.Stress{i % entities}",
            )
            for i in range(int(n_resources))
        )
        resolved = tuple(
            a if ":" in a else urns[a] for a in tuple(actions)
        )
        return cls(subjects=subjects, resources=resources, actions=resolved)

    @classmethod
    def from_config(cls, block: dict, urns=None) -> "LatticeSpec":
        """Config-file lattice grammar (docs/AUDIT.md): each axis is
        either an integer (stress-shaped synthetic axis) or an explicit
        list — subjects ``{"id": ..., "role": ...}``, resources
        ``{"id": ..., "entity": ...}``, actions bare names or URNs.
        Optional ``*_urn`` keys override the attribute ids (masked like
        every audit attribute if they name a secret)."""
        from ..models import Urns

        urns = urns or Urns()
        block = block or {}

        raw_s = block.get("subjects", 16)
        if isinstance(raw_s, int):
            subjects = tuple((f"u{i}", f"role-{i % 97}") for i in range(raw_s))
        else:
            subjects = tuple(
                (str(s.get("id", f"u{i}")), s.get("role"))
                if isinstance(s, dict) else (str(s), None)
                for i, s in enumerate(raw_s)
            )
        raw_r = block.get("resources", 16)
        if isinstance(raw_r, int):
            resources = tuple(
                (
                    f"res{i}",
                    "urn:restorecommerce:acs:model:"
                    f"stress{i % 64}.Stress{i % 64}",
                )
                for i in range(raw_r)
            )
        else:
            resources = tuple(
                (str(r.get("id", f"res{i}")), str(r.get("entity", "")))
                if isinstance(r, dict) else (f"res{i}", str(r))
                for i, r in enumerate(raw_r)
            )
        raw_a = block.get("actions", ["read"])
        actions = tuple(a if ":" in a else urns[a] for a in raw_a)
        kwargs = {}
        for key in ("subject_id_urn", "role_urn", "entity_urn", "action_urn"):
            if block.get(key):
                kwargs[key] = str(block[key])
        return cls(
            subjects=subjects, resources=resources, actions=actions, **kwargs
        )

    def masked_axes(self) -> dict:
        """Axis metadata for the snapshot header, with every value passed
        through the audit-log masking rule keyed on its attribute URN —
        a secret-named subject-id URN (tokens as principals) can never
        leak principal values into an exported matrix."""
        return {
            "subjects": [
                {
                    "id": mask_value(self.subject_id_urn, sid),
                    "role": mask_value(self.role_urn, role),
                }
                for sid, role in self.subjects
            ],
            "resources": [
                {
                    "id": mask_value(self.entity_urn, rid),
                    "entity": mask_value(self.entity_urn, entity),
                }
                for rid, entity in self.resources
            ],
            "actions": [mask_value(self.action_urn, a) for a in self.actions],
        }


# --------------------------------------------------------------------- fold


@dataclass(frozen=True)
class CellVerdict:
    """One lattice cell: the folded decision, the deciding rule (or
    no-rules policy) id, and whether any contributing rule carries an
    unevaluated condition/context query (optimistic bound)."""

    decision: str
    rule_id: Optional[str] = None
    conditional: bool = False
    shed_code: Optional[int] = None

    @property
    def code(self) -> int:
        if self.conditional and self.decision in (
            Decision.PERMIT, Decision.DENY
        ):
            return CODE_CONDITIONAL
        if self.decision == Decision.PERMIT:
            return CODE_PERMIT
        if self.decision == Decision.DENY:
            return CODE_DENY
        return CODE_NOT_APPLICABLE


def _decide(algorithm: str, effects: list, combining_map) -> Optional[tuple]:
    """The engine's ``decide`` over ``(effect, source, conditional)``
    triples (core/engine.py:890-970 semantics, byte-for-byte):
    deny-overrides takes the FIRST DENY else the LAST effect,
    permit-overrides symmetrically, first-applicable the first.  The
    result is conditional when *any* collected effect is — a condition
    flipping any contributor could change which effect wins."""
    method = None
    if combining_map:
        method = combining_map.get(algorithm)
    if method is None:
        method = _COMBINING_METHODS.get(algorithm)
    if method is None:
        return None
    conditional = any(c for _, _, c in effects)
    if method == "first_applicable":
        chosen = effects[0]
    elif method == "deny_overrides":
        chosen = effects[-1]
        for e in effects:
            if e[0] == Effect.DENY:
                chosen = e
                break
    elif method == "permit_overrides":
        chosen = effects[-1]
        for e in effects:
            if e[0] == Effect.PERMIT:
                chosen = e
                break
    else:
        return None
    return (chosen[0], chosen[1], conditional)


def fold_reverse_query(
    rq: ReverseQuery, combining_map: Optional[dict] = None
) -> CellVerdict:
    """Collapse a ``whatIsAllowed`` tree to the decision ``isAllowed``
    would reach on the same request, replaying the engine's collection
    order: matched rules fold under the policy's combining algorithm,
    a matched no-rules policy contributes its own effect, policies fold
    under the set's algorithm, and across sets the LAST set with effects
    wins (the engine's cross-set overwrite).  ``combining_map`` extends
    URN resolution for custom registrations (ShadowEvaluator's
    ``combining_algorithms``); an unresolvable algorithm yields an
    honest INDETERMINATE, never a guess."""
    status = getattr(rq, "operation_status", None)
    if status is not None and getattr(status, "code", 200) != 200:
        return CellVerdict(
            Decision.INDETERMINATE, shed_code=int(status.code)
        )
    winning: Optional[tuple] = None
    unresolved = False
    for policy_set in rq.policy_sets:
        policy_effects: list = []
        for policy in policy_set.policies:
            if policy.rules:
                rule_effects = [
                    (
                        rule.effect,
                        rule.id,
                        bool(rule.condition) or rule.context_query is not None,
                    )
                    for rule in policy.rules
                    if rule.effect
                ]
                if rule_effects:
                    folded = _decide(
                        policy.combining_algorithm, rule_effects,
                        combining_map,
                    )
                    if folded is None:
                        unresolved = True
                    else:
                        policy_effects.append(folded)
            elif policy.effect and not policy.has_rules:
                # a rule-less policy matched on its own target: its
                # effect stands in for a rule (engine.py:285-292)
                policy_effects.append((policy.effect, policy.id, False))
        if policy_effects:
            folded = _decide(
                policy_set.combining_algorithm, policy_effects, combining_map
            )
            if folded is None:
                unresolved = True
            else:
                winning = folded
    if winning is None:
        return CellVerdict(Decision.INDETERMINATE, conditional=unresolved)
    return CellVerdict(
        Decision.from_effect(winning[0]), winning[1], winning[2]
    )


# ----------------------------------------------------------------- snapshot


class SnapshotWriter:
    """Streamed effective-permission snapshot: one JSONL file (header,
    sparse cell lines referencing axis *indices* only, summary footer)
    plus a packed 2-bit bitmap sidecar.  Memory is O(n_cells / 4) for
    the bitmap — never O(cells) of JSON — so a 1k x 1k sweep holds
    ~250 KiB regardless of how it is chunked."""

    def __init__(
        self,
        path: str,
        spec: LatticeSpec,
        source: str = "production",
        policy_epoch: Optional[int] = None,
        meta: Optional[dict] = None,
    ):
        self.path = str(path)
        self.bitmap_path = self.path + ".bits.npy"
        self.spec = spec
        self._codes = np.zeros(spec.n_cells, dtype=np.uint8)
        self._counts = {
            "cells": 0, "permit": 0, "deny": 0, "conditional": 0,
            "indeterminate": 0, "sheds": 0,
        }
        self._closed = False
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._fh = open(self.path, "w", encoding="utf-8")
        header = {
            "kind": SNAPSHOT_KIND,
            "version": SNAPSHOT_VERSION,
            "source": source,
            "policy_epoch": policy_epoch,
            "shape": list(spec.shape),
            "order": "subject-major",
            "bitmap": {
                "path": os.path.basename(self.bitmap_path),
                "bits_per_cell": 2,
                "codes": {
                    "not_applicable": CODE_NOT_APPLICABLE,
                    "permit": CODE_PERMIT,
                    "deny": CODE_DENY,
                    "conditional": CODE_CONDITIONAL,
                },
            },
            "axes": spec.masked_axes(),
        }
        if meta:
            header["meta"] = meta
        self._fh.write(json.dumps(header, default=repr) + "\n")

    def write(self, index: int, verdict: CellVerdict) -> None:
        """Record one cell.  NOT_APPLICABLE cells stay implicit (bitmap
        zero, no JSONL line) — the sparse encoding that keeps a mostly
        empty matrix small; sheds are written explicitly so an audit
        consumer can distinguish 'no access' from 'not measured'."""
        self._counts["cells"] += 1
        code = verdict.code
        self._codes[index] = code
        if verdict.shed_code is not None:
            self._counts["sheds"] += 1
            row = {
                "c": list(self.spec.unravel(index)),
                "d": verdict.decision,
                "s": verdict.shed_code,
            }
        elif code == CODE_NOT_APPLICABLE:
            self._counts["indeterminate"] += 1
            return
        else:
            key = {
                CODE_PERMIT: "permit", CODE_DENY: "deny",
                CODE_CONDITIONAL: "conditional",
            }[code]
            self._counts[key] += 1
            row = {
                "c": list(self.spec.unravel(index)),
                "d": verdict.decision,
                "r": verdict.rule_id,
            }
            if verdict.conditional:
                row["q"] = True
        self._fh.write(json.dumps(row) + "\n")

    def close(self) -> dict:
        if self._closed:
            return dict(self._counts)
        self._closed = True
        summary = {"kind": "acs-lattice-summary", **self._counts}
        self._fh.write(json.dumps(summary) + "\n")
        self._fh.close()
        np.save(self.bitmap_path, pack_codes(self._codes))
        return dict(self._counts)


def pack_codes(codes: np.ndarray) -> np.ndarray:
    """2-bit pack: 4 cells per byte, cell ``i`` at bits ``2*(i%4)``."""
    codes = np.asarray(codes, dtype=np.uint8)
    pad = (-len(codes)) % 4
    if pad:
        codes = np.concatenate([codes, np.zeros(pad, dtype=np.uint8)])
    lanes = codes.reshape(-1, 4)
    packed = np.zeros(len(lanes), dtype=np.uint8)
    for lane in range(4):
        packed |= (lanes[:, lane] & 0x3) << (2 * lane)
    return packed


def unpack_codes(packed: np.ndarray, n_cells: int) -> np.ndarray:
    packed = np.asarray(packed, dtype=np.uint8)
    out = np.zeros(len(packed) * 4, dtype=np.uint8)
    for lane in range(4):
        out[lane::4] = (packed >> (2 * lane)) & 0x3
    return out[:n_cells]


def load_bitmap(path: str, n_cells: int) -> np.ndarray:
    return unpack_codes(np.load(path), n_cells)


def load_snapshot(path: str) -> tuple:
    """Read a snapshot JSONL: ``(header, cells, summary)`` where cells
    maps ``(si, ri, ai)`` -> the sparse cell dict."""
    header: Optional[dict] = None
    summary: Optional[dict] = None
    cells: dict = {}
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            if row.get("kind") == SNAPSHOT_KIND:
                header = row
            elif row.get("kind") == "acs-lattice-summary":
                summary = row
            else:
                cells[tuple(row["c"])] = row
    if header is None:
        raise ValueError(f"{path}: not an {SNAPSHOT_KIND} file")
    return header, cells, summary


# --------------------------------------------------------------------- diff


def diff_cells(cells_a: dict, cells_b: dict, limit: int = 4096) -> dict:
    """Cross-version diff over two sparse cell maps: every cell whose
    ``(decision, deciding rule)`` pair changed, with both sides named —
    the artifact a policy reviewer reads to see exactly what a candidate
    tree would change.  ``limit`` bounds the enumerated cells (the
    summary counts stay exact); truncation is explicit, never silent."""
    changed = []
    transitions: dict = {}
    rules: set = set()
    truncated = 0
    for key in sorted(set(cells_a) | set(cells_b)):
        a, b = cells_a.get(key), cells_b.get(key)
        da = a.get("d", Decision.INDETERMINATE) if a else "NOT_APPLICABLE"
        db = b.get("d", Decision.INDETERMINATE) if b else "NOT_APPLICABLE"
        ra = a.get("r") if a else None
        rb = b.get("r") if b else None
        if da == db and ra == rb:
            continue
        transition = f"{da}->{db}"
        transitions[transition] = transitions.get(transition, 0) + 1
        for rule in (ra, rb):
            if rule:
                rules.add(rule)
        if len(changed) < limit:
            changed.append({
                "cell": list(key),
                "a": {"decision": da, "rule": ra},
                "b": {"decision": db, "rule": rb},
            })
        else:
            truncated += 1
    return {
        "cells_changed": sum(transitions.values()),
        "transitions": transitions,
        "rules": sorted(rules),
        "cells": changed,
        "truncated": truncated,
    }


def diff_snapshots(path_a: str, path_b: str, limit: int = 4096) -> dict:
    """Diff two snapshot files (same lattice shape required)."""
    header_a, cells_a, _ = load_snapshot(path_a)
    header_b, cells_b, _ = load_snapshot(path_b)
    if header_a.get("shape") != header_b.get("shape"):
        raise ValueError(
            "lattice shapes differ: "
            f"{header_a.get('shape')} vs {header_b.get('shape')}"
        )
    out = diff_cells(cells_a, cells_b, limit=limit)
    out["shape"] = header_a.get("shape")
    out["a"] = {
        "path": path_a,
        "source": header_a.get("source"),
        "policy_epoch": header_a.get("policy_epoch"),
    }
    out["b"] = {
        "path": path_b,
        "source": header_b.get("source"),
        "policy_epoch": header_b.get("policy_epoch"),
    }
    return out
