"""Request batch encoder: wire-shaped requests -> dense integer tensors.

Per-request Python cost is kept to attribute parsing + dict lookups; all
string work (interning, regex evaluation, substring-relevance verification)
is cached per *distinct* string across the batch.

A request is **kernel-eligible** only when its shape fits the closed-form
matcher the kernel implements; ineligible requests are flagged and served
by the scalar oracle instead (decisions stay bit-identical either way).
Ineligibility triggers:

- a subject token the host pipeline has NOT resolved (identity resolution
  / HR-scope rendezvous is a host protocol, reference:
  src/core/accessController.ts:110-123).  Resolved token rows — prepared
  by srv/evaluator.prepare_batch or engine.prepare_context — encode their
  resolved subject and stay on device; failed resolutions degrade per-row
  to the oracle;
- attribute counts beyond the padding caps (including ACL scoping-entity/
  instance counts and distinct HR-tree role counts);
- malformed property URNs, properties preceding their entity, or
  entity-name substring relevance diverging from id equality (the
  reference matches properties to entities by substring, reference:
  :515-516);
- conditions with context queries when a resource adapter is configured
  AND the row's walk could observe the reference's context merge (the
  reference mutates request.context across rules in that path, reference:
  :238-254).  Rows reaching exactly one query rule whose merge provably
  stays invisible get the query PREFETCHED host-side and ride the kernel
  (_prefetch_context_queries); see docs/ELIGIBILITY.md for the full
  taxonomy and degradation ladder.
"""

from __future__ import annotations

import copy
import re
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..core.common import find_ctx_resource, get_field
from ..core.conditions import condition_matches
from ..core.hierarchical_scope import regex_entity_compare
from ..models.model import Request
from .compile import CompiledPolicies
from .interner import ABSENT

# per-request padding caps: FLOORS of the adaptive scheme.  Each batch is
# pre-scanned and every dimension is bucketed to the next power of two of
# the batch maximum (floor = these defaults, hard ceiling = _CAPS_CEIL),
# so deep-HR / wide-ACL traffic stays kernel-eligible instead of falling
# to the scalar oracle, while common traffic keeps one compiled shape.
# The native (C++) wire encoder takes the same caps at runtime: the wire
# path encodes at the floor and re-encodes over-cap rows (batch.overcap)
# at _CAPS_CEIL, so deep rows stay on the native fast path too.
NR = 4      # entity runs
NI = 4      # resource instances
NP = 8      # property attributes
NSUB = 8    # subject attribute pairs
NACT = 4    # action attribute pairs
NOP = 2     # operation attributes
NOWN = 4    # owner pairs per instance
NRA = 8     # role-association triples / pairs
NHR = 32    # flattened HR-scope pairs
NROLE = 4   # subject roles
NACLE = 4   # distinct ACL scoping entities per request
NACLI = 8   # ACL instances per scoping entity
NHRR = 8    # distinct HR-tree roles (verifyACL flatten) per request

_CAPS_FLOOR = {
    "NR": NR, "NI": NI, "NP": NP, "NSUB": NSUB, "NACT": NACT, "NOP": NOP,
    "NOWN": NOWN, "NRA": NRA, "NHR": NHR, "NROLE": NROLE, "NACLE": NACLE,
    "NACLI": NACLI, "NHRR": NHRR,
}
_CAPS_CEIL = {
    "NR": 16, "NI": 32, "NP": 64, "NSUB": 32, "NACT": 16, "NOP": 8,
    "NOWN": 32, "NRA": 128, "NHR": 1024, "NROLE": 16, "NACLE": 16,
    "NACLI": 64, "NHRR": 32,
}


def _pow2_at_least(n: int, floor: int) -> int:
    if n <= floor:
        return floor
    return 1 << (n - 1).bit_length()


def request_needs(request, urns) -> dict[str, int]:
    """Raw per-request padding needs (upper-bound estimates) for every cap
    dimension; compute_caps aggregates these over a batch, the evaluator
    uses them to split mixed traffic so deep/wide rows do not inflate the
    compiled shapes of the whole batch."""
    entity_urn = urns.get("entity")
    property_urn = urns.get("property")
    operation_urn = urns.get("operation")
    resource_id_urn = urns.get("resourceID")
    scoping_urn = urns.get("roleScopingEntity")
    scoping_inst_urn = urns.get("roleScopingInstance")
    owner_ent_urn = urns.get("ownerEntity")
    owner_inst_urn = urns.get("ownerInstance")
    acl_ind_urn = urns.get("aclIndicatoryEntity")

    need = dict.fromkeys(_CAPS_FLOOR, 0)

    def bump(key, val):
        if val > need[key]:
            need[key] = val

    target = request.target
    if not target:
        return need
    bump("NSUB", len(target.subjects or []))
    bump("NACT", len(target.actions or []))
    runs = props = ops = insts = 0
    seen_run = False
    for attr in target.resources or []:
        if attr.id == entity_urn:
            runs += 1
            seen_run = True
        elif attr.id == property_urn:
            props += 1
        elif attr.id == operation_urn:
            ops += 1
        elif attr.id == resource_id_urn and seen_run:
            insts += 1
    bump("NR", runs)
    bump("NP", props)
    bump("NOP", ops)
    bump("NI", insts)

    context = request.context
    subject = get_field(context, "subject") or {} if context else {}
    role_assocs = get_field(subject, "role_associations") or []
    roles, ra3, ra2 = set(), 0, set()
    for ra in role_assocs:
        role = get_field(ra, "role")
        if role is not None:
            roles.add(role)
        for ra_attr in get_field(ra, "attributes") or []:
            if get_field(ra_attr, "id") != scoping_urn:
                continue
            ent = get_field(ra_attr, "value")
            ra2.add((role, ent))
            for inst in get_field(ra_attr, "attributes") or []:
                if get_field(inst, "id") == scoping_inst_urn:
                    ra3 += 1
    bump("NROLE", len(roles))
    bump("NRA", max(ra3, len(ra2)))

    scopes = get_field(subject, "hierarchical_scopes")
    hr_pairs: list = []
    _flatten_hr(scopes, hr_pairs)
    bump("NHR", len(set(hr_pairs)))
    acl_hr: list = []
    _flatten_acl_hr(scopes, acl_hr)
    bump("NHR", len(set(acl_hr)))
    bump("NHRR", len({r for r, _ in acl_hr if r is not None}))

    acl_ents, acl_insts_total, own_max = set(), 0, 0
    for res in (get_field(context, "resources") or []) if context else []:
        meta = get_field(res, "meta")
        for acl in (get_field(meta, "acls") or []) if meta else []:
            if get_field(acl, "id") == acl_ind_urn:
                acl_ents.add(get_field(acl, "value"))
                acl_insts_total += len(get_field(acl, "attributes") or [])
        own = 0
        for owner in (get_field(meta, "owners") or []) if meta else []:
            if get_field(owner, "id") != owner_ent_urn:
                continue
            own += sum(
                1 for i in (get_field(owner, "attributes") or [])
                if get_field(i, "id") == owner_inst_urn
            )
        own_max = max(own_max, own)
    bump("NACLE", len(acl_ents))
    bump("NACLI", acl_insts_total)
    bump("NOWN", own_max)
    return need


def fits_floor(needs: dict[str, int]) -> bool:
    """True when a request's needs fit the floor caps (the steady-state
    compiled shape)."""
    return all(needs[k] <= _CAPS_FLOOR[k] for k in _CAPS_FLOOR)


def compute_caps(requests, urns) -> dict[str, int]:
    """Pre-scan the batch and bucket every padding dimension to the next
    power of two above the batch maximum (floor = module defaults, hard
    ceiling = _CAPS_CEIL).  Estimates only need to be upper bounds per
    dimension — the fill loop still marks genuinely over-cap rows
    ineligible, so an under-estimate degrades to oracle fallback, never to
    a wrong decision."""
    need = dict.fromkeys(_CAPS_FLOOR, 0)
    for request in requests:
        for key, val in request_needs(request, urns).items():
            if val > need[key]:
                need[key] = val
    return {
        key: min(_CAPS_CEIL[key], _pow2_at_least(need[key], _CAPS_FLOOR[key]))
        for key in _CAPS_FLOOR
    }


def urn_tail(value: str) -> str:
    """The reference's ``entity_name`` in the property-relevance check: the
    URN segment after the last ':' (accessController.ts:515-516).  Must match
    StringInterner.tail_id so r_prop_tail compares against t_ent_tails."""
    value = value or ""
    return value[value.rfind(":") + 1:] if ":" in value else value


@dataclass
class RequestBatch:
    B: int
    arrays: dict[str, np.ndarray]
    # regex matrices over (target entity vocab W) x (batch entity values E)
    rgx_set: np.ndarray
    pfx_neq: np.ndarray
    # host-assisted condition results [C, B]
    cond_true: np.ndarray
    cond_abort: np.ndarray
    cond_code: np.ndarray
    eligible: np.ndarray
    requests: list[Request] = field(default_factory=list)
    # per-reason counts for rows that fell back to the scalar oracle
    ineligible_reasons: dict[str, int] = field(default_factory=dict)
    # (condition index, row) -> error text for abort rows (the reference's
    # operation_status.message, recovered without an oracle re-run)
    cond_msg: dict = field(default_factory=dict)
    # rows ineligible ONLY because a padding cap overflowed (native wire
    # encoder); the serving path re-encodes them at the ceiling shapes
    overcap: Optional[np.ndarray] = None
    # pooled-staging lease (native zero-copy encode): a zero-arg callable
    # returning this batch's buffers to their arena.  MUST only run after
    # the consuming computation has materialized — on the CPU backend the
    # device arrays can alias these buffers zero-copy.  None for batches
    # built from fresh allocations.
    staging: Optional[object] = None

    def release_staging(self) -> None:
        release, self.staging = self.staging, None
        if release is not None:
            release()


class _RegexCache:
    """(target entity value, request entity value) -> regex-branch results,
    mirroring the reference comparison (reference: accessController.ts:526-566)."""

    def __init__(self, entity_vocab: list[str]):
        self.vocab = entity_vocab
        self.cache: dict[str, tuple[list[bool], list[bool]]] = {}

    def lookup(self, req_value: str) -> tuple[list[bool], list[bool]]:
        hit = self.cache.get(req_value)
        if hit is not None:
            return hit
        set_col, neq_col = [], []
        for rule_val in self.vocab:
            matched, prefix_mismatch = regex_entity_compare(rule_val, req_value)
            set_col.append(matched)
            neq_col.append(prefix_mismatch)
        self.cache[req_value] = (set_col, neq_col)
        return set_col, neq_col


def _flatten_hr(scopes, out: list[tuple[Optional[str], str]]):
    """(top-level role, node id) pairs for every node of each top-level
    subtree (reference: hierarchicalScope.ts:207-220 filters by top role
    then flattens the subtree)."""
    for top in scopes or []:
        role = get_field(top, "role")
        stack = [top]
        while stack:
            node = stack.pop()
            node_id = get_field(node, "id")
            if node_id:
                out.append((role, node_id))
            stack.extend(get_field(node, "children") or [])


def _flatten_acl_hr(nodes, out: list, role=None):
    """verifyACL's OWN tree flatten (reference: verifyACL.ts:119-129
    getRoleOrgMapping): pre-order, a node's ``role`` field overrides the
    inherited one for itself AND its subtree — unlike the HR matcher's
    flatten above, which keys every node by the top-level role only."""
    for node in nodes or []:
        key = get_field(node, "role")
        if key is None:
            key = role
        node_id = get_field(node, "id")
        if node_id:
            out.append((key, node_id))
        children = get_field(node, "children") or []
        if children:
            _flatten_acl_hr(children, out, key)


def alloc_row_arrays(B: int, caps: dict[str, int] | None = None
                     ) -> dict[str, np.ndarray]:
    """The per-request kernel row arrays; shared by the Python encoder
    (adaptive ``caps`` from compute_caps) and the native (C++) wire
    encoder, which fills the same buffers in place at the FLOOR shapes
    (the ctypes pointer order lives in native/__init__._ARRAY_ORDER)."""
    if caps is not None:
        NR = caps["NR"]; NI = caps["NI"]; NP = caps["NP"]
        NSUB = caps["NSUB"]; NACT = caps["NACT"]; NOP = caps["NOP"]
        NOWN = caps["NOWN"]; NRA = caps["NRA"]; NHR = caps["NHR"]
        NROLE = caps["NROLE"]; NACLE = caps["NACLE"]
        NACLI = caps["NACLI"]; NHRR = caps["NHRR"]
    else:
        NR, NI, NP, NSUB, NACT, NOP, NOWN, NRA, NHR, NROLE, NACLE, NACLI, \
            NHRR = (_CAPS_FLOOR[k] for k in (
                "NR", "NI", "NP", "NSUB", "NACT", "NOP", "NOWN", "NRA",
                "NHR", "NROLE", "NACLE", "NACLI", "NHRR"))
    return {
        "r_sub_ids": np.full((B, NSUB), ABSENT, np.int32),
        "r_sub_vals": np.full((B, NSUB), ABSENT, np.int32),
        "r_roles": np.full((B, NROLE), ABSENT, np.int32),
        "r_act_ids": np.full((B, NACT), ABSENT, np.int32),
        "r_act_vals": np.full((B, NACT), ABSENT, np.int32),
        "r_ent_vals": np.full((B, NR), ABSENT, np.int32),
        "r_ent_e": np.zeros((B, NR), np.int32),
        "r_ent_valid": np.zeros((B, NR), bool),
        "r_inst_run": np.full((B, NI), ABSENT, np.int32),
        "r_inst_id": np.full((B, NI), ABSENT, np.int32),
        "r_inst_valid": np.zeros((B, NI), bool),
        "r_inst_present": np.zeros((B, NI), bool),
        "r_inst_has_owners": np.zeros((B, NI), bool),
        "r_inst_owner_ent": np.full((B, NI, NOWN), ABSENT, np.int32),
        "r_inst_owner_inst": np.full((B, NI, NOWN), ABSENT, np.int32),
        "r_prop_vals": np.full((B, NP), ABSENT, np.int32),
        "r_prop_sfx": np.full((B, NP), ABSENT, np.int32),
        "r_prop_run": np.full((B, NP), ABSENT, np.int32),
        "r_prop_tail": np.full((B, NP), ABSENT, np.int32),
        "r_op_vals": np.full((B, NOP), ABSENT, np.int32),
        "r_op_present": np.zeros((B, NOP), bool),
        "r_op_has_owners": np.zeros((B, NOP), bool),
        "r_op_owner_ent": np.full((B, NOP, NOWN), ABSENT, np.int32),
        "r_op_owner_inst": np.full((B, NOP, NOWN), ABSENT, np.int32),
        "r_ra3": np.full((B, NRA, 3), ABSENT, np.int32),
        "r_ra2": np.full((B, NRA, 2), ABSENT, np.int32),
        "r_n_ra": np.zeros((B,), np.int32),
        "r_hr": np.full((B, NHR, 2), ABSENT, np.int32),
        "r_ctx_present": np.zeros((B,), bool),
        "r_n_entity_attrs": np.zeros((B,), np.int32),
        "r_has_props": np.zeros((B,), bool),
        "r_has_target": np.zeros((B,), bool),
        # verify_acl ACL-pair inputs (reference: verifyACL.ts:37-88,
        # 119-136, 148-248). acl_short: 0 = pairs mode, 1 = early all-clear
        # (a targeted resource without ACL metadata, :56-59), 2 = malformed
        # ACL fail (:72-82). Both encoders (Python and the C++ wire
        # encoder) fill these; only over-cap, ABSENT-valued or
        # malformed-JSON ACL shapes fall back to the scalar oracle.
        "r_acl_short": np.zeros((B,), np.int32),
        "r_acl_ent": np.full((B, NACLE), ABSENT, np.int32),
        "r_acl_inst": np.full((B, NACLE, NACLI), ABSENT, np.int32),
        # verifyACL's role->org flatten (per-node role override) and its
        # distinct role keys in first-occurrence order (:119-136)
        "r_acl_hr": np.full((B, NHR, 2), ABSENT, np.int32),
        "r_hr_roles": np.full((B, NHRR), ABSENT, np.int32),
        "r_subject_id": np.full((B,), ABSENT, np.int32),
    }


# arrays alloc_row_arrays fills with ABSENT (everything else zero-fills);
# reset_row_arrays must track alloc_row_arrays exactly so a recycled arena
# buffer is indistinguishable from a fresh allocation
_ABSENT_FILLED = frozenset({
    "r_sub_ids", "r_sub_vals", "r_roles", "r_act_ids", "r_act_vals",
    "r_ent_vals", "r_inst_run", "r_inst_id",
    "r_inst_owner_ent", "r_inst_owner_inst",
    "r_prop_vals", "r_prop_sfx", "r_prop_run", "r_prop_tail", "r_op_vals",
    "r_op_owner_ent", "r_op_owner_inst", "r_ra3", "r_ra2", "r_hr",
    "r_acl_ent", "r_acl_inst", "r_acl_hr", "r_hr_roles", "r_subject_id",
})


def reset_row_arrays(a: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
    """Re-fill recycled row arrays in place to the alloc_row_arrays fill
    values (C-speed memset, zero allocation) — the native encoder writes
    only the slots a request uses and relies on the rest holding the
    fill value, so arena reuse must restore it."""
    for name, arr in a.items():
        arr.fill(ABSENT if name in _ABSENT_FILLED else 0)
    return a


def owner_bit_layout(rv: int, nru: int, nop: int) -> tuple[int, int, int, int]:
    """Packed owner-bitplane layout, shared by the host packer below and
    the kernel's unpack (ops/kernel._owner_bit_reader).

    Per (request row, role-scope-vocab entry) the packed verdicts are
    ``ebits = 2*nru + 2*nop`` bits, in order:

      [0, nru)            A: instance-group g fails when its run is
                          collected AND the target row has hr_check
                          (direct OR hierarchical owner match required)
      [nru, 2*nru)        B: same with hr_check disabled (direct only)
      [2*nru, 2*nru+nop)  opA: operation slot j fails when op-hit, with
                          hr_check
      [.., 2*nru+2*nop)   opB: same with hr_check disabled

    Returns (ebits, epw, wpe, nwords): when ebits <= 32 entries pack
    ``epw = 32 // ebits`` per int32 word (entry v -> word v // epw, bit
    offset (v % epw) * ebits) and ``wpe`` is 1; wider entries (ceiling
    caps) span ``wpe = ceil(ebits / 32)`` words each (bit k of entry v ->
    word v * wpe + k // 32, offset k % 32) and ``epw`` is 0."""
    ebits = 2 * (nru + nop)
    if ebits <= 32:
        epw = 32 // ebits
        return ebits, epw, 1, -(-rv // epw)
    wpe = -(-ebits // 32)
    return ebits, 0, wpe, rv * wpe


def owner_bits_needed(compiled: CompiledPolicies) -> bool:
    """Stage B runs only when some target row carries BOTH subjects and a
    scoping entity (mirrors ops/kernel.tree_needs_hr without importing the
    kernel module — kernel imports this one)."""
    a = compiled.arrays
    return bool(
        (np.asarray(a["t_has_scoping"]) & (np.asarray(a["t_n_subjects"]) > 0)).any()
    )


def _owner_verdicts(hrv_role, hrv_scope, ra3, ra2, hr, own_ent, own_inst):
    """Vectorized owner pair checks against role associations / HR closure
    at (row, role-scope-vocab entry, owner-bearing slot) granularity —
    the host-side replacement for the kernel's former stage-B device
    matmuls, identical semantics (reference: hierarchicalScope.ts:165-245).
    Counts stay exact in f32 (NRA/NHR < 2^24).  Returns (direct, hier)
    bool [b, RV, N]."""
    b, N, NOWN = own_ent.shape
    rv = hrv_role.shape[0]
    qe = own_ent.reshape(b, N * NOWN)
    qi = own_inst.reshape(b, N * NOWN)
    ent_m = (qe[:, None, :] == hrv_scope[None, :, None]) & (qe >= 0)[:, None, :]
    # direct: (role, scoping, owner-instance) in ra3
    ra3_valid = ra3[:, :, 1] >= 0
    rs3 = (
        (ra3[:, :, 0][:, :, None] == hrv_role[None, None, :])
        & (ra3[:, :, 1][:, :, None] == hrv_scope[None, None, :])
        & ra3_valid[:, :, None]
    )  # [b, NRA, RV]
    i3 = ra3[:, :, 2][:, :, None] == qi[:, None, :]  # [b, NRA, Q]
    dcnt = np.matmul(
        rs3.transpose(0, 2, 1).astype(np.float32), i3.astype(np.float32)
    )  # [b, RV, Q]
    direct = ent_m & (dcnt > 0)
    # hierarchical: (role, scoping) in ra2 and (role, owner-inst) in hr
    ra2_valid = ra2[:, :, 1] >= 0
    ra2_ok = (
        (ra2[:, :, 0][:, :, None] == hrv_role[None, None, :])
        & (ra2[:, :, 1][:, :, None] == hrv_scope[None, None, :])
        & ra2_valid[:, :, None]
    ).any(axis=1)  # [b, RV]
    hr_valid = hr[:, :, 1] >= 0
    rh = (
        hr[:, :, 0][:, :, None] == hrv_role[None, None, :]
    ) & hr_valid[:, :, None]  # [b, NHR, RV]
    ih = hr[:, :, 1][:, :, None] == qi[:, None, :]  # [b, NHR, Q]
    hcnt = np.matmul(
        rh.transpose(0, 2, 1).astype(np.float32), ih.astype(np.float32)
    )  # [b, RV, Q]
    hier = ent_m & (hcnt > 0) & ra2_ok[:, :, None]
    return (
        direct.reshape(b, rv, N, NOWN).any(axis=3),
        hier.reshape(b, rv, N, NOWN).any(axis=3),
    )


def pack_owner_bitplanes(
    arrays: dict[str, np.ndarray],
    compiled: CompiledPolicies,
    skip: bool = False,
) -> dict[str, np.ndarray]:
    """Host-precomputed stage-B owner verdicts, packed per
    ``owner_bit_layout``:

      r_own_runs [B, NRU] — the distinct instance-bearing entity runs per
          row (ABSENT-padded); bit group g of every vocab entry refers to
          run r_own_runs[g].
      r_own_bits [B, NWORDS] — the packed A/B/opA/opB fail bits per
          (row, vocab entry).

    Pure function of the raw encoder arrays, so BOTH encoders share it:
    the Python encoder calls it inline and the native (C++) wire encoder
    defers to it after filling the raw arrays (native/__init__.py) —
    bit-identity between the two paths is then structural.  ``skip=True``
    (or a tree without HR-bearing targets) emits 1-wide dummies that
    stage-B-free kernels never read."""
    B = arrays["r_ent_vals"].shape[0]
    if skip or not owner_bits_needed(compiled):
        return {
            "r_own_runs": np.full((B, 1), ABSENT, np.int32),
            "r_own_bits": np.zeros((B, 1), np.int32),
        }
    hrv_role = np.asarray(compiled.arrays["hrv_role"])
    hrv_scope = np.asarray(compiled.arrays["hrv_scope"])
    RV = hrv_role.shape[0]
    NOP = arrays["r_op_vals"].shape[1]

    inst_run = arrays["r_inst_run"]
    valid_i = arrays["r_inst_valid"] & (inst_run >= 0)  # [B, NI]
    # distinct instance-bearing runs per row, power-of-two bucketed so the
    # compiled kernel shapes stay bounded (almost always 1)
    big = np.int32(1 << 30)
    runs_sorted = np.sort(np.where(valid_i, inst_run, big), axis=1)
    fresh = np.ones(runs_sorted.shape, bool)
    fresh[:, 1:] = runs_sorted[:, 1:] != runs_sorted[:, :-1]
    fresh &= runs_sorted < big
    counts = fresh.sum(axis=1)
    nru = _pow2_at_least(int(counts.max()) if B else 1, 1)
    own_runs = np.full((B, nru), ABSENT, np.int32)
    b_idx, j_idx = np.nonzero(fresh)
    pos = (np.cumsum(fresh, axis=1) - 1)[b_idx, j_idx]
    own_runs[b_idx, pos] = runs_sorted[b_idx, j_idx]

    ebits, epw, wpe, nwords = owner_bit_layout(RV, nru, NOP)
    words = np.zeros((B, nwords), np.uint32)
    if B:
        # chunk the batch so the [b, RV, NHR]-scale broadcasts stay within
        # a fixed working-set budget even for deep-HR ceiling caps
        NHR = max(arrays["r_hr"].shape[1], 1)
        per_row = RV * max(NHR, arrays["r_inst_owner_ent"].shape[1] * 8) * 4
        chunk = max(64, min(B, (64 << 20) // max(per_row, 1)))
        miss_i = ~(arrays["r_inst_present"] & arrays["r_inst_has_owners"])
        op_valid = arrays["r_op_vals"] >= 0
        op_miss = ~(arrays["r_op_present"] & arrays["r_op_has_owners"])
        g_one = (
            inst_run[:, :, None] == own_runs[:, None, :]
        ) & valid_i[:, :, None]  # [B, NI, NRU]
        # within-word bit offsets / word index per flat (entry, bit) —
        # monotone in flat order, so packing reduces with one reduceat
        flat = np.arange(RV * ebits)
        v_of, k_of = flat // ebits, flat % ebits
        if epw:
            w_of = v_of // epw
            off = ((v_of % epw) * ebits + k_of).astype(np.uint64)
        else:
            w_of = v_of * wpe + k_of // 32
            off = (k_of % 32).astype(np.uint64)
        starts = np.nonzero(np.diff(w_of, prepend=-1))[0]
        for lo in range(0, B, chunk):
            hi = min(B, lo + chunk)
            sl = slice(lo, hi)
            dir_i, hier_i = _owner_verdicts(
                hrv_role, hrv_scope, arrays["r_ra3"][sl], arrays["r_ra2"][sl],
                arrays["r_hr"][sl], arrays["r_inst_owner_ent"][sl],
                arrays["r_inst_owner_inst"][sl],
            )  # [b, RV, NI]
            dir_o, hier_o = _owner_verdicts(
                hrv_role, hrv_scope, arrays["r_ra3"][sl], arrays["r_ra2"][sl],
                arrays["r_hr"][sl], arrays["r_op_owner_ent"][sl],
                arrays["r_op_owner_inst"][sl],
            )  # [b, RV, NOP]
            bad_a = valid_i[sl][:, None, :] & (
                miss_i[sl][:, None, :] | ~(dir_i | hier_i)
            )
            bad_b = valid_i[sl][:, None, :] & (miss_i[sl][:, None, :] | ~dir_i)
            g1 = g_one[sl].astype(np.float32)
            a_run = np.matmul(bad_a.astype(np.float32), g1) > 0  # [b, RV, NRU]
            b_run = np.matmul(bad_b.astype(np.float32), g1) > 0
            op_a = op_valid[sl][:, None, :] & (
                op_miss[sl][:, None, :] | ~(dir_o | hier_o)
            )
            op_b = op_valid[sl][:, None, :] & (op_miss[sl][:, None, :] | ~dir_o)
            bits3 = np.concatenate([a_run, b_run, op_a, op_b], axis=2)
            contrib = bits3.reshape(hi - lo, RV * ebits).astype(np.uint64) << off
            words[sl] = np.add.reduceat(contrib, starts, axis=1).astype(
                np.uint32
            )
    return {
        "r_own_runs": own_runs,
        "r_own_bits": np.ascontiguousarray(words).view(np.int32),
    }


def encode_requests(
    requests: list[Request],
    compiled: CompiledPolicies,
    resource_adapter=None,
    skip_conditions: bool = False,
    caps: dict[str, int] | None = None,
    skip_owner_bits: bool = False,
    relation_tables: Optional[dict] = None,
    skip_relation_bits: bool = False,
) -> RequestBatch:
    """``skip_conditions=True`` skips the host-assisted condition pre-pass
    (and its adapter-driven batch degradation): whatIsAllowed never
    evaluates conditions (the reverse query copies them verbatim into the
    RQ tree, reference accessController.ts:383-400), so its encoder calls
    must not pay for them.

    ``caps`` overrides the adaptive per-batch padding caps (the native
    wire encoder's fixed floor shapes use this for parity testing)."""
    urns = compiled.urns
    it = compiled.interner.intern
    B = len(requests)
    W = max(len(compiled.entity_vocab), 1)

    # adaptive per-batch padding caps (shadow the module floors; every
    # reference below uses the batch-bucketed values)
    if caps is None:
        caps = compute_caps(requests, urns)
    NR = caps["NR"]; NI = caps["NI"]; NP = caps["NP"]
    NSUB = caps["NSUB"]; NACT = caps["NACT"]; NOP = caps["NOP"]
    NOWN = caps["NOWN"]; NRA = caps["NRA"]; NHR = caps["NHR"]
    NROLE = caps["NROLE"]; NACLE = caps["NACLE"]; NACLI = caps["NACLI"]
    NHRR = caps["NHRR"]

    entity_urn = urns.get("entity")
    property_urn = urns.get("property")
    operation_urn = urns.get("operation")
    resource_id_urn = urns.get("resourceID")
    role_urn = urns.get("role")
    scoping_urn = urns.get("roleScopingEntity")
    scoping_inst_urn = urns.get("roleScopingInstance")
    owner_ent_urn = urns.get("ownerEntity")
    owner_inst_urn = urns.get("ownerInstance")
    acl_ind_urn = urns.get("aclIndicatoryEntity")
    acl_inst_urn = urns.get("aclInstance")

    rgx = _RegexCache(compiled.entity_vocab)
    batch_entity_values: list[str] = []
    batch_entity_idx: dict[str, int] = {}
    # substring-relevance verification cache: (vocab tail, prop value)
    relevance_ok: dict[tuple[str, str], bool] = {}
    vocab_tails = [urn_tail(v) for v in compiled.entity_vocab]
    # two distinct target entity values sharing a tail would make substring
    # relevance ambiguous against id equality
    tails_ambiguous = len(set(vocab_tails)) != len(vocab_tails)

    def batch_entity(value: str) -> int:
        idx = batch_entity_idx.get(value)
        if idx is None:
            idx = len(batch_entity_values)
            batch_entity_idx[value] = idx
            batch_entity_values.append(value)
        return idx

    a = alloc_row_arrays(B, caps)
    eligible = np.ones((B,), bool)
    ineligible_reasons: dict[str, int] = {}

    def mark(b, reason="other"):
        eligible[b] = False
        ineligible_reasons[reason] = ineligible_reasons.get(reason, 0) + 1

    for b, request in enumerate(requests):
        target = request.target
        if not target:
            mark(b, "no-target")  # host-side 400 DENY
            continue
        a["r_has_target"][b] = True
        context = request.context
        raw_subject = get_field(context, "subject")
        subject = raw_subject or {}
        if get_field(subject, "token"):
            # Token-bearing rows stay kernel-eligible once the host
            # pipeline has resolved them (srv/evaluator.prepare_batch /
            # core/engine.prepare_context): resolution mutates the subject
            # in place and the oracle's own prepare_context is a no-op
            # afterwards, so kernel and oracle evaluate the identical
            # resolved context by construction.  Unprepared rows (wire/
            # native path, direct encodes) and failed resolutions degrade
            # per-row to the oracle exactly as before.
            if not getattr(request, "_context_prepared", False):
                mark(b, "token-subject")
                continue
            if not getattr(request, "_token_resolved", False):
                mark(b, "token-unresolved")
                continue
        if raw_subject is None:
            # quirk parity: a matched rule's ACL check dereferences
            # context.subject without a guard in the reference
            # (verifyACL.ts:112) unless a resourceID/operation
            # attribute's missing ACL metadata triggered the early
            # all-clear (:56-59) — subject-less rows can therefore throw,
            # which the kernel formula cannot represent.  ALL subject-less
            # rows go to the oracle (conservative: some could stay on
            # device via the early pass, but this is error-path traffic
            # and the simple rule is mirrored bit-for-bit by the native
            # C++ encoder)
            mark(b, "no-subject")
            continue

        # ---- subject / roles / actions
        subs = target.subjects or []
        acts = target.actions or []
        if len(subs) > NSUB or len(acts) > NACT:
            mark(b, "subject-action-cap")
            continue
        for j, attr in enumerate(subs):
            a["r_sub_ids"][b, j] = it(attr.id)
            a["r_sub_vals"][b, j] = it(attr.value)
        for j, attr in enumerate(acts):
            a["r_act_ids"][b, j] = it(attr.id)
            a["r_act_vals"][b, j] = it(attr.value)

        role_assocs = get_field(subject, "role_associations") or []
        roles = []
        for ra in role_assocs:
            role = get_field(ra, "role")
            if role is not None and role not in roles:
                roles.append(role)
        if len(roles) > NROLE:
            mark(b, "role-cap")
            continue
        for j, role in enumerate(roles):
            a["r_roles"][b, j] = it(role)

        # ---- resources: parse (entity, id*, prop*) runs / operations
        runs: list[dict] = []
        props: list[tuple[str, Optional[dict]]] = []
        ops: list[str] = []
        current_run: Optional[dict] = None
        ok = True
        for attr in target.resources or []:
            if attr.id == entity_urn:
                current_run = {"value": attr.value, "instances": []}
                runs.append(current_run)
            elif attr.id == resource_id_urn:
                if current_run is None:
                    # ids before any entity are never collected by the
                    # matcher/HR loops; ignore for the kernel
                    continue
                current_run["instances"].append(attr.value)
            elif attr.id == property_urn:
                # run index -1 when the property precedes any entity attr:
                # the reference never checks it (entityMatch still false)
                props.append((attr.value or "", len(runs) - 1))
            elif attr.id == operation_urn:
                ops.append(attr.value)
            else:
                ok = False  # unknown resource attribute id
                break
        if not ok or len(runs) > NR or len(props) > NP or len(ops) > NOP:
            mark(b, "resource-shape")
            continue
        if sum(len(r["instances"]) for r in runs) > NI:
            mark(b, "instance-cap")
            continue
        if tails_ambiguous and props:
            mark(b, "ambiguous-entity-tails")
            continue
        # verify substring relevance == tail equality for every
        # (vocab entity, request property) pair
        relevance_broken = False
        for value, _run_idx in props:
            for vt in vocab_tails:
                key = (vt, value)
                good = relevance_ok.get(key)
                if good is None:
                    prop_tail = urn_tail(value.split("#", 1)[0])
                    good = (vt in value) == (vt == prop_tail)
                    relevance_ok[key] = good
            # any pair breaking the equivalence disqualifies the request
            if any(not relevance_ok[(vt, value)] for vt in vocab_tails):
                relevance_broken = True
                break
        if relevance_broken:
            mark(b, "property-relevance")
            continue

        ctx_resources = get_field(context, "resources") or [] if context else []

        # ---- ACL pair collection (reference: verifyACL.ts:49-88): walk the
        # targeted resource attributes in order; the first one without ACL
        # metadata is the early all-clear, a malformed ACL fails, otherwise
        # (entity -> instances) accumulate across resources
        acl_short = 0
        acl_ents: list[int] = []
        acl_insts: list[list[int]] = []
        acl_ent_pos: dict[int, int] = {}
        for attr in target.resources or []:
            if attr.id != resource_id_urn and attr.id != operation_urn:
                continue
            ctx_res = find_ctx_resource(ctx_resources, attr.value)
            acl_list = None
            if ctx_res is not None:
                meta = get_field(ctx_res, "meta")
                acls = get_field(meta, "acls") if meta else None
                if acls and len(acls) > 0:
                    acl_list = acls
            if not acl_list:
                acl_short = 1  # no ACL metadata: verification passes
                break
            malformed = False
            for acl in acl_list:
                if get_field(acl, "id") == acl_ind_urn:
                    ent_id = it(get_field(acl, "value"))
                    pos = acl_ent_pos.get(ent_id)
                    if pos is None:
                        pos = len(acl_ents)
                        acl_ent_pos[ent_id] = pos
                        acl_ents.append(ent_id)
                        acl_insts.append([])
                    acl_attrs = get_field(acl, "attributes")
                    if not acl_attrs:
                        malformed = True  # missing ACL instances
                        break
                    for attribute in acl_attrs:
                        if get_field(attribute, "id") == acl_inst_urn:
                            acl_insts[pos].append(
                                it(get_field(attribute, "value"))
                            )
                        else:
                            malformed = True  # missing ACL instance value
                            break
                    if malformed:
                        break
                else:
                    malformed = True  # missing ACL indicatory entity
                    break
            if malformed:
                acl_short = 2
                break
        if acl_short == 0 and (
            len(acl_ents) > NACLE
            or any(len(insts) > NACLI for insts in acl_insts)
        ):
            mark(b, "acl-cap")  # oracle fallback
            continue
        if acl_short == 0 and (
            any(e < 0 for e in acl_ents)
            or any(i < 0 for insts in acl_insts for i in insts)
        ):
            # a None/missing ACL entity or instance value interns to ABSENT;
            # the kernel's validity masks would silently drop it and pass
            # where the reference fails closed (verifyACL.ts keys its map on
            # undefined) -- fall back to the oracle instead
            mark(b, "acl-absent-value")
            continue
        a["r_acl_short"][b] = acl_short
        if acl_short == 0:
            for j, ent_id in enumerate(acl_ents):
                a["r_acl_ent"][b, j] = ent_id
                for k, inst_id in enumerate(acl_insts[j]):
                    a["r_acl_inst"][b, j, k] = inst_id
        sid = get_field(subject, "id")
        a["r_subject_id"][b] = it(sid) if isinstance(sid, str) else ABSENT

        a["r_ctx_present"][b] = bool(context)
        a["r_n_entity_attrs"][b] = len(runs)
        a["r_has_props"][b] = len(props) > 0

        inst_slot = 0
        overflow = False
        for j, run in enumerate(runs):
            a["r_ent_vals"][b, j] = it(run["value"])
            a["r_ent_e"][b, j] = batch_entity(run["value"])
            a["r_ent_valid"][b, j] = True
            for inst in run["instances"]:
                ctx_res = find_ctx_resource(ctx_resources, inst)
                a["r_inst_run"][b, inst_slot] = j
                a["r_inst_id"][b, inst_slot] = (
                    it(inst) if isinstance(inst, str) else ABSENT
                )
                a["r_inst_valid"][b, inst_slot] = True
                if ctx_res is not None:
                    a["r_inst_present"][b, inst_slot] = True
                    owners = get_field(get_field(ctx_res, "meta"), "owners") or []
                    a["r_inst_has_owners"][b, inst_slot] = len(owners) > 0
                    if not _encode_owners(
                        a["r_inst_owner_ent"], a["r_inst_owner_inst"],
                        (b, inst_slot), owners, owner_ent_urn,
                        owner_inst_urn, it, NOWN,
                    ):
                        overflow = True
                inst_slot += 1
        for j, (value, run_idx) in enumerate(props):
            vid = it(value)
            a["r_prop_vals"][b, j] = vid
            a["r_prop_sfx"][b, j] = compiled.interner.suffix_id[vid]
            a["r_prop_run"][b, j] = run_idx
            prefix = value.split("#", 1)[0]
            a["r_prop_tail"][b, j] = it(urn_tail(prefix))
        for j, op_value in enumerate(ops):
            a["r_op_vals"][b, j] = it(op_value)
            ctx_res = None
            for res in ctx_resources:
                if get_field(res, "id") == op_value:
                    ctx_res = res
                    break
            if ctx_res is not None:
                a["r_op_present"][b, j] = True
                owners = get_field(get_field(ctx_res, "meta"), "owners") or []
                a["r_op_has_owners"][b, j] = len(owners) > 0
                if not _encode_owners(
                    a["r_op_owner_ent"], a["r_op_owner_inst"],
                    (b, j), owners, owner_ent_urn, owner_inst_urn, it, NOWN,
                ):
                    overflow = True

        # ---- role-association triples / pairs + HR closure
        ra3, ra2 = [], []
        for ra in role_assocs:
            role_id = it(get_field(ra, "role"))
            for ra_attr in get_field(ra, "attributes") or []:
                if get_field(ra_attr, "id") != scoping_urn:
                    continue
                ent_id = it(get_field(ra_attr, "value"))
                pair = (role_id, ent_id)
                if pair not in ra2:
                    ra2.append(pair)
                for inst in get_field(ra_attr, "attributes") or []:
                    if get_field(inst, "id") == scoping_inst_urn:
                        ra3.append((role_id, ent_id, it(get_field(inst, "value"))))
        hierarchical_scopes = get_field(subject, "hierarchical_scopes")
        if hierarchical_scopes is None and len(role_assocs) > 0:
            # with role associations present the oracle raises
            # InvalidRequestContext for a missing scope list (the reference
            # throws in both verifyACL and the HR phase); keep such
            # requests on the oracle path
            mark(b, "missing-hr-scopes")
            continue
        hr_pairs: list[tuple[Optional[str], str]] = []
        _flatten_hr(hierarchical_scopes, hr_pairs)
        hr_enc = []
        for role, org in hr_pairs:
            entry = (it(role) if role is not None else ABSENT, it(org))
            if entry not in hr_enc:
                hr_enc.append(entry)
        # verifyACL's own flatten: per-node role override, pre-order; the
        # distinct role keys (None excluded — it can never be a rule's
        # scoped role) keep first-occurrence order because the create-path
        # scan is order-sensitive (reference: verifyACL.ts:160-171)
        acl_hr_pairs: list = []
        _flatten_acl_hr(hierarchical_scopes, acl_hr_pairs)
        acl_hr_enc: list[tuple[int, int]] = []
        hr_roles: list[int] = []
        for role, org in acl_hr_pairs:
            rid = it(role) if role is not None else ABSENT
            entry = (rid, it(org))
            if entry not in acl_hr_enc:
                acl_hr_enc.append(entry)
            if role is not None and rid not in hr_roles:
                hr_roles.append(rid)
        if (
            len(ra3) > NRA or len(ra2) > NRA or len(hr_enc) > NHR
            or len(acl_hr_enc) > NHR or len(hr_roles) > NHRR or overflow
        ):
            mark(b, "hr-cap")
            continue
        for j, t3 in enumerate(ra3):
            a["r_ra3"][b, j] = t3
        for j, t2 in enumerate(ra2):
            a["r_ra2"][b, j] = t2
        for j, t2 in enumerate(hr_enc):
            a["r_hr"][b, j] = t2
        for j, t2 in enumerate(acl_hr_enc):
            a["r_acl_hr"][b, j] = t2
        for j, rid in enumerate(hr_roles):
            a["r_hr_roles"][b, j] = rid
        a["r_n_ra"][b] = len(role_assocs)

    # ---- regex matrices [W, E]
    E = max(len(batch_entity_values), 1)
    rgx_set = np.zeros((W, E), bool)
    pfx_neq = np.zeros((W, E), bool)
    for e, value in enumerate(batch_entity_values):
        set_col, neq_col = rgx.lookup(value)
        if set_col:
            rgx_set[:, e] = set_col
            pfx_neq[:, e] = neq_col

    # ---- host-assisted condition pre-pass [C, B]
    C = len(compiled.conditions)
    cond_true = np.zeros((C, B), bool)
    cond_abort = np.zeros((C, B), bool)
    cond_code = np.full((C, B), 200, np.int32)
    cand_cache: dict[tuple, np.ndarray] = {}
    cond_msg: dict[tuple[int, int], str] = {}
    cond_list = [] if skip_conditions else compiled.conditions
    query_cis: set[int] = set()
    if resource_adapter is not None:
        query_cis = {
            ci for ci, cc in enumerate(cond_list)
            if cc.context_query is not None and (
                getattr(cc.context_query, "filters", None)
                or getattr(cc.context_query, "query", None)
            )
        }
    if query_cis:
        # adapter-driven context queries pull resources inside the rule
        # loop and MERGE the result into request.context for the rule's own
        # condition (and everything evaluated after it — reference:
        # accessController.ts:227-254).  The prefetch plan keeps a row on
        # device when that merge provably cannot leak into any later
        # context read (see _prefetch_context_queries); every other
        # candidate row degrades per-row to the oracle as before.
        _prefetch_context_queries(
            compiled, cond_list, sorted(query_cis), a, eligible, mark,
            rgx_set, cand_cache, requests, resource_adapter,
            cond_true, cond_abort, cond_code, cond_msg,
        )
    for ci, cc in enumerate(cond_list):
        if ci in query_cis:
            continue  # handled by the prefetch plan above
        for b, request in enumerate(requests):
            if not eligible[b]:
                continue
            try:
                cond_true[ci, b] = bool(condition_matches(cc.condition, request))
            except Exception as err:  # deny-by-default with the error code
                code = getattr(err, "code", 500)
                cond_abort[ci, b] = True
                cond_code[ci, b] = code if isinstance(code, int) else 500
                # the reference surfaces the error text in
                # operation_status.message (accessController.ts:259-270);
                # cached here so abort rows need no oracle re-run
                cond_msg[(ci, b)] = str(err) or "Unknown Error!"

    # host-precomputed stage-B owner bitplanes: the kernels consume these
    # packed verdicts instead of the raw ra3/ra2/hr/owner-pair arrays
    # (which stay allocated for the ACL stage and the native ABI)
    a.update(pack_owner_bitplanes(a, compiled, skip=skip_owner_bits))
    # relation-closure bitplanes (ReBAC, ops/relation.py): packed against
    # the serving store's flat verdict tables; fail-closed without them
    from .relation import pack_relation_bitplanes

    a.update(pack_relation_bitplanes(
        a, compiled, relation_tables, skip=skip_relation_bits
    ))

    return RequestBatch(
        B=B,
        arrays=a,
        rgx_set=rgx_set,
        pfx_neq=pfx_neq,
        cond_true=cond_true,
        cond_abort=cond_abort,
        cond_code=cond_code,
        eligible=eligible,
        requests=requests,
        ineligible_reasons=ineligible_reasons,
        cond_msg=cond_msg,
    )


def _row_candidates(compiled, a, b, rgx_set, cand_cache):
    """(signature key, candidate target-row vector [T]) for request row
    ``b`` — ops/prefilter.py candidacy, a sound over-approximation of the
    kernel's target match, cached per distinct resource/action signature.
    Candidacy depends only on ``request.target`` (never on context), so it
    is invariant under the reference's context merge."""
    from .prefilter import candidate_rows

    ents = a["r_ent_vals"][b]
    cols = a["r_ent_e"][b]
    valid = ents >= 0
    ent_ids = np.unique(ents[valid])
    ent_cols = np.array(
        [cols[valid][ents[valid] == e][0] for e in ent_ids], np.int64
    )
    ops = a["r_op_vals"][b]
    op_ids = np.unique(ops[ops >= 0])
    acts = a["r_act_vals"][b]
    act_vals = np.unique(acts[acts >= 0])
    key = (tuple(ent_ids.tolist()), tuple(op_ids.tolist()),
           tuple(act_vals.tolist()))
    cand = cand_cache.get(key)
    if cand is None:
        cand = candidate_rows(
            compiled, ent_ids, ent_cols, op_ids, act_vals, rgx_set
        )
        cand_cache[key] = cand
    return key, cand


def _merge_safe(compiled, flat_index, s_r, kp_r, cand, row_acl_ok) -> bool:
    """True when prefetching query rule R's context pull on the host
    provably cannot change any decision for this row signature.

    The reference's pull replaces ``request.context`` with the merged
    ``{"target", "context", "_queryResult"}`` object, so everything
    evaluated AFTER R in walk order loses ``context.subject`` and
    ``context.resources``.  The kernel encodes every stage from the
    ORIGINAL context, so a row is fusable only when no node after R can
    read the context at all:

    - a rule after R reads context through its role-gated subject match,
      its HR-scope check (scoping entity), its condition, or its
      post-match ACL verification (which, with the merged context, early
      all-clears when the row's original ACL state was the no-metadata
      all-clear ``r_acl_short == 1``, and diverges otherwise);
    - a policy evaluated after R's policy reads context when its target is
      role-gated or carries a scoping entity (policy_subject_match);
    - a later set's target match reads context only through a role-gated
      subject.

    Nodes whose targets are not candidates for the row's signature cannot
    match in either world (candidacy is context-free), so they are safe by
    construction."""
    arr = compiled.arrays
    S, KP, KR = compiled.S, compiled.KP, compiled.KR
    rt = arr["rule_target"]
    rht = arr["rule_has_target"]
    has_role = arr["t_has_role"]
    has_scoping = arr["t_has_scoping"]
    skip_acl = arr["t_skip_acl"]
    has_cond = arr["rule_cond"] >= 0
    later = np.arange(S * KP * KR).reshape(S, KP, KR) > flat_index
    reach_t = rht & cand[rt]
    ctx_read = (
        (~rht & has_cond)
        | (reach_t & (
            has_role[rt] | has_scoping[rt] | has_cond
            | ~(skip_acl[rt] | row_acl_ok)
        ))
    )
    if (arr["rule_valid"] & later & ctx_read).any():
        return False
    pol_later = np.arange(S * KP).reshape(S, KP) > (s_r * KP + kp_r)
    pt = arr["pol_target"]
    pol_ctx = (
        arr["pol_valid"] & arr["pol_has_target"] & cand[pt]
        & (has_role[pt] | has_scoping[pt])
    )
    if (pol_ctx & pol_later).any():
        return False
    st = arr["set_target"]
    set_ctx = (
        arr["set_valid"] & arr["set_has_target"] & cand[st] & has_role[st]
    )
    if (set_ctx & (np.arange(S) > s_r)).any():
        return False
    return True


def _prefetch_context_queries(
    compiled, cond_list, query_cis, a, eligible, mark, rgx_set, cand_cache,
    requests, adapter, cond_true, cond_abort, cond_code, cond_msg,
) -> None:
    """Stage (b) of the host eligibility pipeline: for every row that can
    reach exactly ONE adapter-backed context-query rule R and whose later
    walk provably never reads the merged context (_merge_safe), pull R's
    context query concurrently over the pooled transport and evaluate R's
    condition against the SAME merged view the reference builds
    (accessController.ts:227-254, pull_context_resources) — the row then
    rides the kernel.  Rows reaching several query rules, rows whose later
    walk could observe the merge, and rows whose prefetch fails (after the
    adapter's one transient retry, srv/adapters.py) degrade per-row to the
    scalar oracle, never to a changed decision."""
    arr = compiled.arrays
    KP, KR = compiled.KP, compiled.KR
    rule_pos = []
    for ci in query_cis:
        flat = cond_list[ci].rule_flat_index
        s, rem = divmod(flat, KP * KR)
        kp, kr = divmod(rem, KR)
        rule_pos.append((ci, flat, s, kp, kr))
    safety_cache: dict[tuple, bool] = {}
    jobs: list[tuple[int, int]] = []
    for b in np.nonzero(eligible)[0]:
        b = int(b)
        key, cand = _row_candidates(compiled, a, b, rgx_set, cand_cache)
        reach = []
        for ci, flat, s, kp, kr in rule_pos:
            if arr["rule_has_target"][s, kp, kr]:
                if cand[int(arr["rule_target"][s, kp, kr])]:
                    reach.append((ci, flat, s, kp, kr))
            else:
                reach.append((ci, flat, s, kp, kr))  # reachable everywhere
        if not reach:
            continue  # provably never pulls: pre-pass results stay exact
        if len(reach) > 1:
            # a second pull would see the first pull's merged context (and
            # resolve its filters against it); not replayable host-side
            mark(b, "context-query")
            continue
        ci, flat, s, kp, kr = reach[0]
        row_acl_ok = int(a["r_acl_short"][b]) == 1
        if arr["rule_has_target"][s, kp, kr]:
            # R's own ACL verification runs on the MERGED context in the
            # reference (verifyACL after the condition): only rows whose
            # original ACL state is the no-metadata early all-clear (or a
            # skipACL rule) behave identically in both worlds
            rt = int(arr["rule_target"][s, kp, kr])
            if not (bool(arr["t_skip_acl"][rt]) or row_acl_ok):
                mark(b, "context-query")
                continue
        skey = (key, ci, row_acl_ok)
        safe = safety_cache.get(skey)
        if safe is None:
            safe = _merge_safe(compiled, flat, s, kp, cand, row_acl_ok)
            safety_cache[skey] = safe
        if not safe:
            mark(b, "context-query")
            continue
        jobs.append((ci, b))
    if not jobs:
        return
    # concurrent prefetch: filters resolve against the ORIGINAL request
    # (no earlier pull can reach these rows), exactly as the reference's
    # first pull would
    pairs = [(cond_list[ci].context_query, requests[b]) for ci, b in jobs]
    if hasattr(adapter, "query_many"):
        results = adapter.query_many(pairs)
    else:
        results = []
        for cq, request in pairs:
            try:
                results.append(adapter.query(cq, request))
            except Exception as err:  # noqa: BLE001 — per-row fallback
                results.append(err)
    for (ci, b), result in zip(jobs, results):
        if isinstance(result, Exception):
            mark(b, "context-query-error")
            continue
        request = requests[b]
        merged = copy.copy(request)
        # the reference's pull_context_resources merge shape, verbatim
        merged.context = {
            "target": request.target,
            "context": request.context,
            "_queryResult": result,
        }
        cc = cond_list[ci]
        try:
            cond_true[ci, b] = bool(condition_matches(cc.condition, merged))
        except Exception as err:  # deny-by-default with the error code
            code = getattr(err, "code", 500)
            cond_abort[ci, b] = True
            cond_code[ci, b] = code if isinstance(code, int) else 500
            cond_msg[(ci, b)] = str(err) or "Unknown Error!"


def _encode_owners(
    ent_out, inst_out, index, owners, owner_ent_urn, owner_inst_urn, it,
    nown=NOWN,
) -> bool:
    """Flatten owner entries into (owner-entity-value, owner-instance)
    pairs; only well-formed entries participate in matching."""
    slot = 0
    for owner in owners:
        if get_field(owner, "id") != owner_ent_urn:
            continue
        val = it(get_field(owner, "value"))
        for inst_attr in get_field(owner, "attributes") or []:
            if get_field(inst_attr, "id") == owner_inst_urn:
                if slot >= nown:
                    return False
                ent_out[index + (slot,)] = val
                inst_out[index + (slot,)] = it(get_field(inst_attr, "value"))
                slot += 1
    return True
