"""String interning for the policy compiler and request encoder.

Every URN / attribute value that participates in matching is mapped to a
dense int32 id.  Derived ids are computed once per distinct string:

- ``suffix_id``  -- the value after the last ``#`` (regex-mode property
  comparison, reference: src/core/accessController.ts:567-574);
- ``tail_id``    -- the value after the last ``:`` (entity name used for
  property-relevance, reference: :515-516);
- ``prefix_id``  -- the value before the last ``:`` (namespace prefix
  comparison in regex entity matching, reference: :545-548).

Interning is cached, so encoding cost is paid once per *distinct* string,
not once per request.
"""

from __future__ import annotations

ABSENT = -1  # padding / absent sentinel in all tensor encodings


class StringInterner:
    def __init__(self):
        self._ids: dict[str, int] = {}
        self._strings: list[str] = []
        self.suffix_id: list[int] = []
        self.tail_id: list[int] = []
        self.prefix_id: list[int] = []

    def __len__(self) -> int:
        return len(self._strings)

    def intern(self, value: str) -> int:
        if value is None:
            return ABSENT
        existing = self._ids.get(value)
        if existing is not None:
            return existing
        idx = len(self._strings)
        self._ids[value] = idx
        self._strings.append(value)
        # reserve derived slots first (intern() below may recurse)
        self.suffix_id.append(ABSENT)
        self.tail_id.append(ABSENT)
        self.prefix_id.append(ABSENT)
        suffix = value.rsplit("#", 1)[-1]
        tail = value[value.rfind(":") + 1:] if ":" in value else value
        prefix = value[: value.rfind(":")] if ":" in value else ""
        self.suffix_id[idx] = idx if suffix == value else self.intern(suffix)
        self.tail_id[idx] = idx if tail == value else self.intern(tail)
        self.prefix_id[idx] = idx if prefix == value else self.intern(prefix)
        return idx

    def lookup(self, value: str) -> int:
        """Id of an already-interned string, or ABSENT."""
        if value is None:
            return ABSENT
        return self._ids.get(value, ABSENT)

    def string(self, idx: int) -> str:
        return self._strings[idx]
