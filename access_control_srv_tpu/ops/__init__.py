"""TPU evaluator: string interner, policy compiler (tree -> tensors),
request batch encoder and the jitted batched decision kernel."""

from .interner import StringInterner
from .compile import CompiledPolicies, compile_policies
from .encode import RequestBatch, encode_requests
from .kernel import DecisionKernel
from .prefilter import PrefilteredKernel
from .reverse import ReverseQueryKernel, what_is_allowed_batch
from .lattice import (
    CellVerdict,
    LatticeSpec,
    SnapshotWriter,
    diff_snapshots,
    fold_reverse_query,
    load_snapshot,
)

__all__ = [
    "StringInterner",
    "CompiledPolicies",
    "compile_policies",
    "RequestBatch",
    "encode_requests",
    "DecisionKernel",
    "PrefilteredKernel",
    "ReverseQueryKernel",
    "what_is_allowed_batch",
    "CellVerdict",
    "LatticeSpec",
    "SnapshotWriter",
    "diff_snapshots",
    "fold_reverse_query",
    "load_snapshot",
]
