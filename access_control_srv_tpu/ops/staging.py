"""Pooled host staging buffers for the device pipeline.

The depth-N serving pipeline (srv/batcher.py, srv/pipeline.py) keeps up
to N batches in flight: batch i+1's host prep overlaps batch i's device
execution and batch i-1's D2H/decode.  At that rate the per-batch numpy
allocations of the hot path — the packed sig-path row buffer
(ops/prefilter.py ``mega_rows``), the slot/readback maps, the native
encoder's row arrays (native/__init__.py) — become both an allocator tax
and a GC hazard, so they are recycled through this pool instead.

Shapes are stable by construction: every pooled buffer's shape derives
from power-of-two capacity buckets (ops/kernel.pow2_bucket /
half_pow2_bucket and PR 4's capacity-bucketed table dims), so steady-state
traffic cycles through a handful of (shape, dtype) keys and the pool hits
~100% after warmup.

Aliasing discipline — the ONLY correctness rule here: a leased buffer may
be handed to ``jax.device_put`` / ``jnp.asarray``, which on the CPU
backend can alias the numpy memory into the device array ZERO-COPY.  A
buffer must therefore stay leased until every computation that may read
it has completed — in practice, until the batch's ``materialize()`` has
returned (the output fetch orders after every consumer of the inputs on
the device stream).  ``release`` before that point can leak rows between
batches; tests/test_pipeline.py's aliasing test drives exactly that
protocol and the pool refuses double-release outright.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np


class HostBufferPool:
    """Thread-safe free-list of numpy buffers keyed by (shape, dtype).

    ``acquire`` returns a leased buffer (recycled when one is free, else
    freshly allocated); ``release`` returns it to the free list.  Buffers
    are NOT cleared on either side — callers overwrite every byte they
    read (the prefilter packs dense slices; the native arena re-fills with
    the alloc_row_arrays fill values), which the aliasing test enforces.
    """

    def __init__(self, max_per_key: int = 8):
        self.max_per_key = int(max_per_key)
        self._lock = threading.Lock()
        self._free: dict[tuple, list[np.ndarray]] = {}  # guarded-by: _lock
        # id(buffer) -> key, for every buffer currently leased out
        self._leased: dict[int, tuple] = {}  # guarded-by: _lock
        self.hits = 0     # guarded-by: _lock
        self.misses = 0   # guarded-by: _lock
        self.releases = 0  # guarded-by: _lock

    @staticmethod
    def _key(shape, dtype) -> tuple:
        return (tuple(shape), np.dtype(dtype).str)

    def acquire(self, shape, dtype=np.int32) -> np.ndarray:
        # failpoint (srv/faults.py): staging exhaustion / allocator
        # stall — error fails the encode (callers fall back to the pb
        # path), delay models allocator pressure
        from ..srv.faults import REGISTRY as _faults

        _faults.fire("staging.acquire")
        key = self._key(shape, dtype)
        with self._lock:
            free = self._free.get(key)
            if free:
                buf = free.pop()
                self.hits += 1
                self._leased[id(buf)] = key
                return buf
            self.misses += 1
        buf = np.empty(key[0], np.dtype(dtype))
        with self._lock:
            self._leased[id(buf)] = key
        return buf

    def release(self, buf: Optional[np.ndarray]) -> None:
        """Return a leased buffer.  Double-release raises: handing the
        same buffer to two batches is exactly the row-leak the pool must
        make impossible."""
        if buf is None:
            return
        with self._lock:
            key = self._leased.pop(id(buf), None)
            if key is None:
                raise ValueError(
                    "release of a buffer this pool has not leased "
                    "(double release or foreign buffer)"
                )
            self.releases += 1
            free = self._free.setdefault(key, [])
            if len(free) < self.max_per_key:
                free.append(buf)

    def release_all(self, bufs) -> None:
        for buf in bufs:
            self.release(buf)

    def leased_count(self) -> int:
        with self._lock:
            return len(self._leased)

    def stats(self) -> dict:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "releases": self.releases,
                "leased": len(self._leased),
                "free": sum(len(v) for v in self._free.values()),
                "keys": len(self._free),
            }


_default: Optional[HostBufferPool] = None  # guarded-by: _default_lock
_default_lock = threading.Lock()


def default_pool() -> HostBufferPool:
    """Process-wide pool shared by every kernel instance: capacity-stable
    shapes mean kernel swaps (hot updates, PR 4) keep hitting the same
    buffers instead of refilling a cold pool per swap."""
    global _default
    with _default_lock:
        if _default is None:
            _default = HostBufferPool()
        return _default
