"""Batched ``whatIsAllowed`` (reverse query) on device.

The reverse query's cost in a batch setting is the repeated target match
per (request x node): for every policy set, policy and rule the oracle
walks the whole request attribute list (reference:
accessController.ts:326-427 calling targetMatches :661-672).  The device
already computes exactly those match vectors — ``_match_targets`` with
``wia=True`` emits the whatIsAllowed-mode variants for every target row
of the batch in one dispatch.

whatIsAllowed does no HR-scope, ACL or condition work, so the only thing
the device CANNOT reproduce is the obligation side effect: masking
obligations accumulate during the scalar attribute scan, including from
calls whose final verdict is False (reference :592-640).  The split is:

- device: [B, T] wia match vectors + a conservative ``maybe_mask`` bit
  (target has properties AND its entity matched: the precondition for any
  mask append);
- host: replay the oracle's exact control flow per request, substituting
  device booleans for match results, and re-running the scalar matcher
  ONLY on rows whose maybe_mask bit is set (for its obligation side
  effects; its boolean agrees with the device by construction).

Result: bit-identical ReverseQuery trees and obligations (differential:
tests/test_reverse.py), with the scalar matcher invoked only on the small
property-relevant subset instead of every (request x node)."""

from __future__ import annotations

import copy

import numpy as np

from ..models.model import PolicyRQ, PolicySetRQ, ReverseQuery, RuleRQ
from ..models.model import OperationStatus
from .compile import CompiledPolicies
from .encode import RequestBatch, encode_requests
from .kernel import _match_targets, lead_padding, pad_cols, pow2_bucket

# per-signature RESOURCE planes emitted by the components+wia device
# program (kernel._match_targets), cached per signature; the subject fold
# happens host-side per row
_PLANE_KEYS = [
    "sig_wia_ex_p", "sig_wia_ex_d", "sig_wia_rg_p", "sig_wia_rg_d",
    "sig_maybe_ex", "sig_maybe_rg", "sig_act_ok",
]

# below this rule count the scalar reverse-query walk beats the device
# round-trip (measured: seed tree scalar ~6x kernel, ~1000-rule tree kernel
# 3-12x scalar — bench_all.py wia rows); mirrors ops/prefilter.MIN_RULES
REVERSE_MIN_RULES = 512


class ReverseQueryKernel:
    """One jitted dispatch computing the whatIsAllowed match vectors for
    every (request, target row) of a batch.

    ``policy_sets`` is deep-copied at construction: hot tree mutations
    (engine.update_rule & co. mutate combinables dicts in place) must not
    shift nodes under the compiled index arrays mid-serve — the reverse
    query serves version-pinned from this snapshot, exactly like the
    decision kernel serves from its compiled arrays."""

    def __init__(self, compiled: CompiledPolicies, policy_sets,
                 copy_tree: bool = True):
        if not compiled.supported:
            raise ValueError(
                f"policy tree unsupported by kernel: {compiled.unsupported_reason}"
            )
        import jax
        import jax.numpy as jnp

        self.compiled = compiled
        if isinstance(policy_sets, dict):
            sets = [ps for ps in policy_sets.values() if ps is not None]
        else:
            sets = [ps for ps in policy_sets if ps is not None]
        # copy_tree=False: the caller passes a tree that is already a
        # version-pinned snapshot (the evaluator publishes one alongside
        # the compiled arrays) — copying again would be pure waste
        self.sets = copy.deepcopy(sets) if copy_tree else sets
        # RuleRQ carriers are request-independent (id/target/effect/
        # condition/cacheable: pure rule data), so one shared instance per
        # rule serves every request this kernel answers — object
        # construction was the wia-large host-assembly bottleneck.  The
        # cache lives exactly as long as the version-pinned snapshot.
        # ALIASING INVARIANT: one RuleRQ instance appears in MANY
        # concurrent ReverseQuery responses — consumers must treat it as
        # immutable (serialize, never annotate in place), and the id()
        # keys are valid only because self.sets pins the rule objects
        # alive for this kernel's lifetime.
        self._rule_rq_cache: dict[int, RuleRQ] = {}
        self._c = {k: jnp.asarray(v) for k, v in compiled.arrays.items()}
        self._runs: dict[tuple, object] = {}
        self._plane_cache: dict[tuple, np.ndarray] = {}

    def _runner(self, schedule: tuple):
        """Jitted per packed-schedule: representative rows (one per NEW
        resource signature) travel as ONE int32 transfer and the
        per-signature RESOURCE planes return as one stacked readback.
        The subject fold is applied host-side, so this program runs only
        on signature-cache misses — steady-state reverse queries touch
        the device not at all."""
        import jax
        import jax.numpy as jnp

        run = self._runs.get(schedule)
        if run is None:
            c = self._c

            def run(mega, rgx_set, pfx_neq):
                def one(row):
                    offset = 0
                    rr = {"rgx_set": rgx_set, "pfx_neq": pfx_neq}
                    for k, w, tail, is_bool in schedule:
                        v = row[offset:offset + w]
                        offset += w
                        v = v.reshape(tail) if tail else v[0]
                        rr[k] = (v != 0) if is_bool else v
                    m = _match_targets(
                        c, rr, with_hr=False, wia=True, components=True
                    )
                    return jnp.stack([m[k] for k in _PLANE_KEYS])

                return jax.vmap(one)(mega)

            run = jax.jit(run)
            self._runs[schedule] = run
        return run

    def _signature_planes(self, batch: RequestBatch, sig, first_idx):
        """[G, NK, T] resource planes for the batch's distinct signatures,
        via the plane cache; misses are computed in one device dispatch
        over the first batch row of each missing signature (the planes
        depend only on signature fields, so any representative row
        works)."""
        import jax.numpy as jnp

        G = sig.shape[0]
        T = self.compiled.arrays["t_role"].shape[0]
        NK = len(_PLANE_KEYS)
        planes = np.empty((G, NK, T), bool)
        missing = []
        gkeys = []
        for g in range(G):
            gk = (sig[g].tobytes(), self.compiled.version)
            gkeys.append(gk)
            got = self._plane_cache.get(gk)
            if got is None:
                missing.append(g)
            else:
                planes[g] = got
        if missing:
            _, _, e_bucket, _ = lead_padding(batch)
            rows = np.asarray([first_idx[g] for g in missing])
            nm_pad = pow2_bucket(len(rows), floor=8)
            schedule = []
            parts = []
            for k, v in batch.arrays.items():
                a = np.asarray(v)[rows]
                tail = a.shape[1:]
                w = int(np.prod(tail)) if tail else 1
                part = a.reshape(a.shape[0], w).astype(np.int32)
                if nm_pad != part.shape[0]:
                    part = np.concatenate(
                        [part,
                         np.zeros((nm_pad - part.shape[0], w), np.int32)],
                        axis=0,
                    )
                parts.append(part)
                schedule.append((k, w, tuple(tail),
                                 bool(a.dtype == np.bool_)))
            mega = np.ascontiguousarray(np.concatenate(parts, axis=1))
            run = self._runner(tuple(schedule))
            out = np.asarray(run(
                jnp.asarray(mega),
                jnp.asarray(pad_cols(batch.rgx_set, e_bucket)),
                jnp.asarray(pad_cols(batch.pfx_neq, e_bucket)),
            ))  # [nm_pad, NK, T]
            for j, g in enumerate(missing):
                planes[g] = out[j]
                if len(self._plane_cache) >= 4096:
                    self._plane_cache.pop(next(iter(self._plane_cache)))
                # own copy: caching a view of ``planes`` (or ``out``)
                # would pin the whole per-batch buffer for the cache's
                # lifetime
                self._plane_cache[gkeys[g]] = planes[g].copy()
        return planes

    def evaluate(self, batch: RequestBatch) -> dict[str, np.ndarray]:
        """Returns {key: [B, T] bool} for the six wia vectors.

        Split: per-signature RESOURCE planes from the device (cached —
        see kernel._match_targets components+wia), per-row subject fold
        in numpy.  The former [B, T]-per-row device program paid the
        TPU's (8, 128) tile padding on every small-trailing-dim
        intermediate and was ~90% of reverse-query wall time on the
        1000-rule tree (round-5 profile)."""
        a_ = batch.arrays
        ents = np.asarray(a_["r_ent_vals"])
        valid = np.asarray(a_["r_ent_valid"])
        ops = np.asarray(a_["r_op_vals"])
        act_ids = np.asarray(a_["r_act_ids"])
        acts = np.asarray(a_["r_act_vals"])
        hasp = np.asarray(a_["r_has_props"])
        B = ents.shape[0]

        # ordered entity runs (sticky regex state is order-sensitive) +
        # the validity bits (a VALID slot whose value interned to
        # ABSENT=-1 — e.g. a None-valued entity attribute — still drives
        # regex/prefix state and must not collide with an absent slot) +
        # sorted ops + sorted action pairs + the request has-props bit
        # (it flips the wia PERMIT property-fail, reference :592-615)
        ents_m = np.where(valid, ents, -1)
        pair_key = (act_ids.astype(np.int64) << 32) | (
            acts.astype(np.int64) & 0xFFFFFFFF
        )
        order = np.argsort(pair_key, axis=1, kind="stable")
        sig = np.concatenate(
            [ents_m, valid.astype(np.int32), np.sort(ops, 1),
             np.take_along_axis(act_ids, order, 1),
             np.take_along_axis(acts, order, 1),
             hasp.astype(np.int32).reshape(B, 1)],
            axis=1,
        )
        uniq, first_idx, inv = np.unique(
            sig, axis=0, return_index=True, return_inverse=True
        )
        inv = inv.reshape(B)
        planes = self._signature_planes(batch, uniq, first_idx)
        row_planes = planes[inv]  # [B, NK, T]
        pk = {k: i for i, k in enumerate(_PLANE_KEYS)}

        # subject fold in numpy (reference: checkSubjectMatches
        # :793-823); T x batch is bounded by the decision-kernel contract
        # (the masks dict below is [B, T] x 6 either way)
        c = self.compiled.arrays
        t_role = c["t_role"]
        roles = np.asarray(a_["r_roles"])
        role_ok = (
            (t_role[None, :, None] == roles[:, None, :]).any(-1)
            & (t_role >= 0)[None, :]
        )  # [B, T]
        # the pair-subset fold is the widest intermediate
        # ([B, T, KS, KSr]); it only decides USER-targeted rows
        # (subjects without a role attribute), so it runs compacted to
        # that row subset — zero-width for the common role-only tree
        pair_rows = np.nonzero(
            ~c["t_has_role"] & (c["t_n_subjects"] > 0)
        )[0]
        sub_ok = (c["t_n_subjects"] == 0)[None] | (
            c["t_has_role"][None] & role_ok
        )
        if pair_rows.size:
            ts_ids = c["t_sub_ids"][pair_rows]
            ts_vals = c["t_sub_vals"][pair_rows]
            sub_ids = np.asarray(a_["r_sub_ids"])
            sub_vals = np.asarray(a_["r_sub_vals"])
            eq = (
                (ts_ids[None, :, :, None] == sub_ids[:, None, None, :])
                & (ts_vals[None, :, :, None] == sub_vals[:, None, None, :])
                & (sub_ids[:, None, None, :] >= 0)
            )  # [B, Tp, KS, KSr]
            pairs_ok = ((ts_ids[None] < 0) | eq.any(-1)).all(-1)
            sub_ok[:, pair_rows] |= pairs_ok
        base = sub_ok & row_planes[:, pk["sig_act_ok"]]
        return {
            "tm_wia_ex_p": base & row_planes[:, pk["sig_wia_ex_p"]],
            "tm_wia_ex_d": base & row_planes[:, pk["sig_wia_ex_d"]],
            "tm_wia_rg_p": base & row_planes[:, pk["sig_wia_rg_p"]],
            "tm_wia_rg_d": base & row_planes[:, pk["sig_wia_rg_d"]],
            "maybe_mask_ex": row_planes[:, pk["sig_maybe_ex"]],
            "maybe_mask_rg": row_planes[:, pk["sig_maybe_rg"]],
        }


def _rule_match_cubes(compiled: CompiledPolicies, masks: dict):
    """Vectorized per-rule wia verdicts for the whole batch.

    ``rule_match[b, s, kp, kr]``: the oracle's final rule-target verdict
    (no-target rules match; otherwise exact OR regex — the regex call is a
    fallback, so the disjunction equals the sequential result).
    ``rule_maskful[b, s, kp, kr]``: some mode of the rule's target row
    could append obligations for row b — those rules must go through the
    scalar matcher in oracle order, the rest can be collected wholesale."""
    a = compiled.arrays
    rt = a["rule_target"]  # [S, KP, KR]
    deny = (a["rule_effect"] == 2)[None]
    ex = np.where(deny, masks["tm_wia_ex_d"][:, rt],
                  masks["tm_wia_ex_p"][:, rt])
    rg = np.where(deny, masks["tm_wia_rg_d"][:, rt],
                  masks["tm_wia_rg_p"][:, rt])
    has_t = a["rule_has_target"][None]
    rule_match = a["rule_valid"][None] & (~has_t | ex | rg)
    rule_maskful = has_t & (
        masks["maybe_mask_ex"][:, rt] | masks["maybe_mask_rg"][:, rt]
    )
    return rule_match, rule_maskful


def _assemble(
    engine, compiled: CompiledPolicies, sets, request, m,
    match_lists=None, maskful_any=None, rule_rq_cache=None,
) -> ReverseQuery:
    """Replay of AccessController.what_is_allowed (engine.py:373-499,
    reference accessController.ts:326-427) with device match vectors.

    ``sets``: the kernel's version-pinned tree snapshot — MUST be the tree
    the compiled index arrays were built from (live engine.policy_sets can
    mutate under a concurrent hot update).
    ``m``: {key: [T] bool} for this request.  Obligations are produced by
    the scalar matcher re-run on maybe_mask rows — identical side-effect
    order to the oracle because the control flow is identical."""
    a = compiled.arrays
    obligations = []
    engine.prepare_context(request)
    entity_urn = engine.urns.get("entity")

    def tm(row: int, target_obj, effect, regex: bool) -> bool:
        mode = "rg" if regex else "ex"
        if m[f"maybe_mask_{mode}"][row]:
            return engine._target_matches(
                target_obj, request, "whatIsAllowed", obligations,
                effect, regex,
            )
        deny = effect == "DENY"
        return bool(m[f"tm_wia_{mode}_{'d' if deny else 'p'}"][row])

    policy_sets_rq: list[PolicySetRQ] = []
    for s, policy_set in enumerate(sets):
        if policy_set.target is None or tm(
            int(a["set_target"][s]), policy_set.target, None, False
        ):
            pset = PolicySetRQ(
                id=policy_set.id,
                target=policy_set.target,
                combining_algorithm=policy_set.combining_algorithm,
            )

            exact_match = False
            policy_effect = None
            for kp, policy in enumerate(policy_set.combinables.values()):
                if policy is None:
                    continue
                if policy.effect:
                    policy_effect = policy.effect
                if policy.target and tm(
                    int(a["pol_target"][s, kp]), policy.target,
                    policy_effect, False,
                ):
                    exact_match = True
                    break

            req_entity_count = len([
                at for at in (request.target.resources or [])
                if at and at.id == entity_urn
            ])
            if exact_match and req_entity_count > 1:
                exact_match = engine._check_multiple_entities_match(
                    policy_set, request, obligations
                )

            for kp, policy in enumerate(policy_set.combinables.values()):
                if policy is None:
                    continue
                row = int(a["pol_target"][s, kp])
                if (
                    policy.target is None
                    or (exact_match
                        and tm(row, policy.target, policy_effect, False))
                    or (not exact_match
                        and tm(row, policy.target, policy_effect, True))
                ):
                    policy_rq = PolicyRQ(
                        id=policy.id,
                        target=policy.target,
                        effect=policy.effect,
                        evaluation_cacheable=policy.evaluation_cacheable,
                        combining_algorithm=policy.combining_algorithm,
                        has_rules=bool(policy.combinables),
                    )
                    rules_list = list(policy.combinables.values())
                    fast = (
                        match_lists is not None
                        and not maskful_any[s, kp]
                    )
                    if fast:
                        # no rule of this policy can append obligations for
                        # this request: collect matches wholesale from the
                        # pre-grouped (s, kp) -> [kr] lists (identical
                        # verdicts, no side effects to order)
                        matching = match_lists.get((s, kp), ())
                        rule_iter = ((kr, rules_list[kr]) for kr in matching)
                    else:
                        rule_iter = enumerate(rules_list)
                    for kr, rule in rule_iter:
                        if rule is None:
                            continue
                        if fast:
                            matches = True
                        else:
                            rrow = int(a["rule_target"][s, kp, kr])
                            matches = rule.target is None or tm(
                                rrow, rule.target, rule.effect, False
                            )
                            if not matches:
                                matches = tm(rrow, rule.target,
                                             rule.effect, True)
                        if rule.target is None or matches:
                            rq = None
                            if rule_rq_cache is not None:
                                rq = rule_rq_cache.get(id(rule))
                            if rq is None:
                                rq = RuleRQ(
                                    id=rule.id,
                                    target=rule.target,
                                    effect=rule.effect,
                                    condition=rule.condition,
                                    context_query=rule.context_query,
                                    evaluation_cacheable=(
                                        rule.evaluation_cacheable
                                    ),
                                )
                                if rule_rq_cache is not None:
                                    rule_rq_cache[id(rule)] = rq
                            policy_rq.rules.append(rq)
                    if policy_rq.effect or (
                        not policy_rq.effect and policy_rq.rules
                    ):
                        pset.policies.append(policy_rq)

            if pset.policies:
                policy_sets_rq.append(pset)

    return ReverseQuery(
        policy_sets=policy_sets_rq,
        obligations=obligations,
        operation_status=OperationStatus(),
    )


def what_is_allowed_batch(
    engine,
    compiled: CompiledPolicies,
    kernel: ReverseQueryKernel,
    requests: list,
    batch: RequestBatch | None = None,
) -> list[ReverseQuery]:
    """Batched reverse query: device match vectors + host assembly over
    the kernel's version-pinned tree snapshot; ineligible rows fall back
    to the scalar oracle wholesale."""
    if batch is None:
        # the reverse matcher never runs stage B (with_hr=False planes),
        # so the owner-bit packer is skipped alongside conditions
        batch = encode_requests(
            requests, compiled, skip_conditions=True, skip_owner_bits=True
        )
    masks = kernel.evaluate(batch)
    rule_match, rule_maskful = _rule_match_cubes(compiled, masks)
    # one vectorized pass over the whole batch replaces per-request
    # nonzero/any calls in the assembly loop: matching (b, s, kp, kr)
    # tuples grouped per request, and the per-policy "any maskful rule"
    # bit reduced once
    maskful_any = rule_maskful.any(axis=3)  # [B, S, KP]
    mb, ms, mp, mk = np.nonzero(rule_match)
    bounds = np.searchsorted(mb, np.arange(len(requests) + 1))
    out = []
    for b, request in enumerate(requests):
        if not batch.eligible[b]:
            out.append(engine.what_is_allowed(request))
            continue
        m = {k: v[b] for k, v in masks.items()}
        lo, hi = bounds[b], bounds[b + 1]
        match_lists: dict[tuple[int, int], list[int]] = {}
        for j in range(lo, hi):
            match_lists.setdefault(
                (int(ms[j]), int(mp[j])), []
            ).append(int(mk[j]))
        out.append(_assemble(
            engine, compiled, kernel.sets, request, m,
            match_lists, maskful_any[b],
            rule_rq_cache=kernel._rule_rq_cache,
        ))
    return out
