"""Incremental policy-update subsystem: capacity-bucketed compiled tables
and a delta encoder that turns CRUD diffs into row-level patches.

The reference is a PAP as much as a PDP — policies mutate at runtime via
gRPC CRUD and hot-apply to the evaluation tree (reference:
src/resourceManager.ts + accessController.ts:897-937).  The port's naive
translation paid for that with a full ``copy.deepcopy`` of the tree, a
from-scratch ``compile_policies``, a fresh XLA compile (table shapes track
rule count) and a global decision-cache flush on EVERY mutation.  This
module makes sustained policy churn cheap, in three pieces:

1. **Capacity buckets** (:func:`capacities_for` / :func:`pad_compiled`) —
   the rule (S/KP/KR), target-table (T), role-scope-vocab (RV) and entity-
   regex-vocab (W) dims of :class:`CompiledPolicies` are padded to the next
   power of two at >= ``headroom`` x the live size.  Every device shape the
   kernels see derives from these dims (the vocab dims surface through
   ``r_own_bits`` / ``rgx_set``), so an in-capacity mutation keeps every
   shape static and the jitted programs are reused byte-identical
   (ops/kernel.py dynamic-policies mode).  Entity-vocab pad slots hold
   ``(?!)``-prefixed sentinel patterns: valid regexes that can never match
   any entity, with pairwise-distinct tails so the encoder's
   ``tails_ambiguous`` property-relevance guard is unaffected.

2. **Delta encoder** (:func:`apply_events`) — CRUD events (old/new doc
   pairs captured by srv/store.py) are diffed semantically; each affected
   set slot is relowered IN PLACE by the same :func:`ops.compile.
   lower_set_into` loop the from-scratch compiler runs, with target-table
   rows owned by node identity (free-list reuse for deleted rules) and
   condition slots owned by rule identity.  Anything the prover cannot
   certify raises :class:`DeltaIneligible` and the caller falls back to
   the existing full recompile:

   - capacity overflow (policies/rules/target rows/vocab entries),
   - combining-algorithm changes on surviving nodes,
   - condition-set changes (added/removed/edited conditions move the
     [C, B] device shapes),
   - policy-set list or order changes (ops/reverse.py maps tree position
     to set slot positionally),
   - kernel-support or HR-topology flips (``tree_needs_hr`` selects a
     different program variant), prefilter activation-threshold crossings,
   - restore / reset / collection drops (no event stream to diff).

3. **Scoped invalidation footprints** (:func:`footprint_from_events`) —
   the doc-level delta is projected onto the candidate-signature space of
   core/candidate_index.py (exact entity values, regex entity patterns,
   operation values, required action values): a cached decision whose
   request features are disjoint from every touched rule's footprint is
   provably unaffected by the mutation (candidacy is context-free and a
   non-candidate rule's change cannot alter the collected-effect sequence
   of that request), so srv/decision_cache.py keeps it live across the
   scoped epoch bump.  ``evaluation_cacheable`` edits widen the footprint
   to the whole owning policy (the prefix-AND ripple), policy/set-level
   gate changes widen to the node's own target (or to a global flush when
   the gate is target-less) — see docs/HOT_UPDATE.md for the proof
   obligation.

This module is host-only (numpy + model objects; no jax import) so the
decision-cache path stays device-free and the patcher can run on the CRUD
thread.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Callable, Optional

import numpy as np

from ..core.hierarchical_scope import regex_entity_compare
from ..core.loader import policy_from_dict, policy_set_from_dict, rule_from_dict
from ..models.model import ContextQuery, Target, coerce_target
from ..models.urns import Urns
from .compile import (
    CompiledPolicies,
    TARGET_COLUMNS,
    lower_set_into,
    lower_target,
)
from .interner import ABSENT


class DeltaIneligible(Exception):
    """The delta prover cannot certify this mutation as an in-place patch;
    the caller must take the full-recompile path.  ``reason`` is a short
    taxonomy key (docs/HOT_UPDATE.md)."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


# ----------------------------------------------------------- capacity buckets


@dataclass(frozen=True)
class Capacities:
    """Padded table dims; every kernel-visible shape derives from these."""

    S: int   # policy-set slots
    KP: int  # policy slots per set
    KR: int  # rule slots per policy
    T: int   # target-table rows
    RV: int  # (role, scoping) vocab entries (owner-bitplane width driver)
    W: int   # entity regex-vocab rows (rgx_set leading dim)
    # relation-path vocab entries (ReBAC bitplane width driver,
    # ops/relation.py); appended with a default so pre-ReBAC callers and
    # persisted size classes stay valid
    RELV: int = 4

    def as_dict(self) -> dict:
        return {"S": self.S, "KP": self.KP, "KR": self.KR,
                "T": self.T, "RV": self.RV, "W": self.W,
                "RELV": self.RELV}


def _bucket(n: int, headroom: float, floor: int) -> int:
    need = max(floor, int(-(-n * headroom // 1)))
    return 1 << max(0, (need - 1).bit_length())


def capacities_for(
    compiled: CompiledPolicies,
    headroom: float = 1.25,
    prev: Optional[Capacities] = None,
) -> Capacities:
    """Headroom buckets for a freshly compiled (unpadded) tree: the next
    power of two >= ``headroom`` x each live size.  When ``prev`` still
    fits the live sizes and is not more than one bucket oversized, it is
    reused so consecutive full recompiles keep the same compiled shapes
    (and therefore the same XLA programs)."""
    live = Capacities(
        S=compiled.S, KP=compiled.KP, KR=compiled.KR, T=compiled.T,
        RV=int(np.asarray(compiled.arrays["hrv_role"]).shape[0]),
        W=max(len(compiled.entity_vocab), 1),
        RELV=max(len(compiled.rel_vocab), 1),
    )
    fresh = Capacities(
        S=_bucket(live.S, headroom, 2),
        KP=_bucket(live.KP, headroom, 2),
        KR=_bucket(live.KR, headroom, 4),
        T=_bucket(live.T, headroom, 8),
        RV=_bucket(live.RV, headroom, 4),
        W=_bucket(live.W, headroom, 4),
        RELV=_bucket(live.RELV, headroom, 4),
    )
    if prev is not None:
        dims = ("S", "KP", "KR", "T", "RV", "W", "RELV")
        fits = all(getattr(prev, d) >= getattr(live, d) for d in dims)
        tight = all(
            getattr(prev, d) <= 2 * getattr(fresh, d) for d in dims
        )
        if fits and tight:
            return prev
    return fresh


def vocab_pad_value(row: int) -> str:
    """Entity-vocab pad sentinel for row ``row``: ``(?!)`` never matches
    (empty negative lookahead fails at every position), the numeric suffix
    keeps pad tails pairwise distinct so the encoder's ambiguous-tails
    guard (ops/encode.py) sees no duplicates."""
    return f"(?!)__cap{row}"


# pad fills per array family (axis layout in ops/compile.py)
_SET_FILLS = {"set_valid": False, "set_ca": ABSENT,
              "set_has_target": False, "set_target": 0}
_POL_FILLS = {"pol_valid": False, "pol_ca": ABSENT, "pol_effect": 0,
              "pol_cacheable": False, "pol_has_target": False,
              "pol_target": 0, "pol_has_subjects": False, "pol_n_rules": 0,
              "pol_eff_ctx": 0, "pol_has_props": False,
              "pol_ent_vals": ABSENT}
_RULE_FILLS = {"rule_valid": False, "rule_effect": 0,
               "rule_cacheable_raw": False, "rule_cacheable_eff": False,
               "rule_has_target": False, "rule_target": 0,
               "rule_cond": ABSENT}
_T_FILLS = {"t_n_subjects": 0, "t_role": ABSENT, "t_has_role": False,
            "t_scoping": ABSENT, "t_has_scoping": False,
            "t_hr_check": False, "t_skip_acl": False, "t_sub_ids": ABSENT,
            "t_sub_vals": ABSENT, "t_act_ids": ABSENT, "t_act_vals": ABSENT,
            "t_ent_vals": ABSENT, "t_ent_w": ABSENT, "t_ent_tails": ABSENT,
            "t_op_vals": ABSENT, "t_prop_vals": ABSENT, "t_prop_sfx": ABSENT,
            "t_has_props": False, "t_n_res": 0, "t_rs_idx": 0,
            # ABSENT (not 0): pad rows must stay relation-trivial so the
            # tree_needs_rel program selector never flips on padding
            "t_rel_path": ABSENT, "t_rel_idx": ABSENT,
            "t_rel_direct": False}


def _pad_axis(arr: np.ndarray, axis: int, size: int, fill) -> np.ndarray:
    if arr.shape[axis] >= size:
        return arr
    pad_shape = list(arr.shape)
    pad_shape[axis] = size - arr.shape[axis]
    return np.concatenate(
        [arr, np.full(pad_shape, fill, arr.dtype)], axis=axis
    )


def pad_compiled(compiled: CompiledPolicies, caps: Capacities
                 ) -> CompiledPolicies:
    """Pad a freshly compiled tree out to capacity buckets.  Pad slots are
    inert by construction: valid masks are False, pad target rows are
    never referenced by any live node index, pad vocab entries can never
    regex-match, and pad rs-vocab entries carry ABSENT pairs the owner-
    verdict packer masks out.  Returns a NEW CompiledPolicies sharing the
    interner; conditions are re-homed to capacity-based flat indices."""
    a = dict(compiled.arrays)
    for name, fill in _SET_FILLS.items():
        a[name] = _pad_axis(a[name], 0, caps.S, fill)
    for name, fill in _POL_FILLS.items():
        arr = _pad_axis(a[name], 1, caps.KP, fill)
        a[name] = _pad_axis(arr, 0, caps.S, fill)
    for name, fill in _RULE_FILLS.items():
        arr = _pad_axis(a[name], 2, caps.KR, fill)
        arr = _pad_axis(arr, 1, caps.KP, fill)
        a[name] = _pad_axis(arr, 0, caps.S, fill)
    for name, fill in _T_FILLS.items():
        a[name] = _pad_axis(a[name], 0, caps.T, fill)
    a["hrv_role"] = _pad_axis(a["hrv_role"], 0, caps.RV, ABSENT)
    a["hrv_scope"] = _pad_axis(a["hrv_scope"], 0, caps.RV, ABSENT)
    # pad relation-vocab rows are ABSENT and unreferenced by any live
    # t_rel_idx; the store's verdict tables carry empty segments for them
    a["relv_path"] = _pad_axis(a["relv_path"], 0, caps.RELV, ABSENT)

    vocab = list(compiled.entity_vocab)
    while len(vocab) < caps.W:
        vocab.append(vocab_pad_value(len(vocab)))

    conditions = []
    for cond in compiled.conditions:
        s, rem = divmod(cond.rule_flat_index, compiled.KP * compiled.KR)
        kp, kr = divmod(rem, compiled.KR)
        conditions.append(replace(
            cond, rule_flat_index=(s * caps.KP + kp) * caps.KR + kr
        ))

    return replace(
        compiled,
        arrays=a,
        conditions=conditions,
        entity_vocab=vocab,
        entity_vocab_ids=dict(compiled.entity_vocab_ids),
        rel_vocab=list(compiled.rel_vocab),
        rel_vocab_ids=dict(compiled.rel_vocab_ids),
        S=caps.S, KP=caps.KP, KR=caps.KR, T=caps.T,
        target_owners=dict(compiled.target_owners),
    )


def clear_set_slot(a: dict, s: int) -> None:
    """Reset slot ``s`` across every set/policy/rule-level plane to the
    pad fills (relowering writes only the live prefix of each row)."""
    for name, fill in _SET_FILLS.items():
        a[name][s] = fill
    for name, fill in _POL_FILLS.items():
        a[name][s] = fill
    for name, fill in _RULE_FILLS.items():
        a[name][s] = fill


# -------------------------------------------------------------- CRUD events


@dataclass
class CrudEvent:
    """One captured CRUD mutation: the stored doc before and after.  The
    store emits these at mutation time (srv/store.py) so neither the delta
    encoder nor the cache footprint needs a deepcopied old tree."""

    kind: str                 # rule | policy | policy_set
    op: str                   # create | update | upsert | delete | delete_all
    doc_id: str
    old_doc: Optional[dict] = None
    new_doc: Optional[dict] = None


_COMPOSERS = {
    "rule": rule_from_dict,
    "policy": policy_from_dict,
    "policy_set": policy_set_from_dict,
}


def _semantic(kind: str, doc: Optional[dict]):
    """Evaluation-relevant content of a doc: the composed model object with
    cosmetic fields (meta/name/description) blanked, plus the ordered
    child-id list (which the composer itself does not read)."""
    if doc is None:
        return None
    obj = _COMPOSERS[kind](doc)
    obj.meta = None
    obj.name = ""
    obj.description = ""
    if kind == "policy":
        children = tuple(doc.get("rules") or [])
    elif kind == "policy_set":
        children = tuple(doc.get("policies") or [])
    else:
        children = ()
    return obj, children


def event_is_noop(event: CrudEvent) -> bool:
    """True when the mutation left the doc's evaluation-relevant content
    unchanged (e.g. a CRUD payload identical to the stored resource, or a
    metadata-only restamp) — certified empty diffs skip the decision-cache
    flush and the recompile entirely."""
    if event.op == "delete_all":
        return False
    try:
        return _semantic(event.kind, event.old_doc) == _semantic(
            event.kind, event.new_doc
        )
    except Exception:  # malformed doc: let the full path decide
        return False


# ----------------------------------------------------- invalidation footprint


@dataclass(frozen=True)
class RuleScope:
    """Candidate-signature projection of one rule target (the doc-level
    analog of core/candidate_index.py's per-rule features): a request can
    be affected only if its resource features hit the entity/op side AND
    carry every required action value."""

    entities: tuple = ()      # exact values; doubled as regex patterns
    ops: tuple = ()
    acts: tuple = ()          # required action values (all must be present)
    res_any: bool = False     # target matches resource-free / any resource

    def affects(self, features) -> bool:
        if self.acts and not all(v in features.actions for v in self.acts):
            return False
        if self.res_any:
            return True
        for value in self.ops:
            if value in features.ops:
                return True
        for pattern in self.entities:
            if pattern in features.entities:
                return True
            for value in features.entities:
                try:
                    matched, _ = regex_entity_compare(pattern, value)
                except Exception:  # invalid pattern: conservative
                    matched = True
                if matched:
                    return True
        return False


@dataclass
class Footprint:
    """The affected target-signature set of one tree delta.  ``global_``
    forces the pre-delta behavior (every entry flushed); ``scopes`` empty
    with ``global_`` False certifies an empty diff."""

    scopes: list = field(default_factory=list)
    global_: bool = False

    @property
    def empty(self) -> bool:
        return not self.global_ and not self.scopes

    def affects(self, features) -> bool:
        if self.global_:
            return True
        return any(scope.affects(features) for scope in self.scopes)

    def merge(self, other: "Footprint") -> None:
        self.global_ = self.global_ or other.global_
        self.scopes.extend(other.scopes)


def scope_from_target(target, urns: Urns) -> RuleScope:
    """RuleScope of a target (dict or Target or None), mirroring
    candidate_rows / CandidateIndex candidacy: no target or no resources
    -> matches anything; resource-bearing with neither entity nor op ->
    conservatively anything (candidate_index keeps such rules too)."""
    if target is not None and not isinstance(target, Target):
        target = coerce_target(target)
    if target is None:
        return RuleScope(res_any=True)
    entity_urn = urns.get("entity")
    operation_urn = urns.get("operation")
    acts = tuple(
        a.value for a in (target.actions or []) if a.value is not None
    )
    resources = target.resources or []
    if not resources:
        return RuleScope(acts=acts, res_any=True)
    ents = tuple(a.value for a in resources
                 if a.id == entity_urn and a.value is not None)
    ops = tuple(a.value for a in resources
                if a.id == operation_urn and a.value is not None)
    if not ents and not ops:
        return RuleScope(acts=acts, res_any=True)
    return RuleScope(entities=ents, ops=ops, acts=acts)


def _policy_gate_scope(doc: Optional[dict], urns: Urns, out: Footprint
                       ) -> None:
    """A policy/set-level gate change affects every request that can pass
    the node's target; a target-less (or resource-less) gate passes all."""
    target = (doc or {}).get("target")
    scope = scope_from_target(target, urns)
    if scope.res_any and not scope.acts:
        out.global_ = True
    else:
        out.scopes.append(scope)


def footprint_from_events(
    events: list[CrudEvent],
    urns: Urns,
    get_doc: Callable[[str, str], Optional[dict]],
    all_docs: Callable[[str], list],
) -> Footprint:
    """Project a CRUD event list onto the affected target-signature set.

    Conservative by construction (docs/HOT_UPDATE.md states the proof
    obligation): every request whose decision, obligations or
    ``evaluation_cacheable`` flag could differ between the old and new
    tree is covered by the returned footprint.  ``get_doc(kind, id)`` and
    ``all_docs(kind)`` read the store collections (already containing the
    post-mutation state)."""
    out = Footprint()
    policy_docs: Optional[list] = None

    def rule_scope(rule_id: str) -> None:
        doc = get_doc("rule", rule_id)
        if doc is not None:
            out.scopes.append(scope_from_target(doc.get("target"), urns))

    def whole_policy(p_doc: dict) -> None:
        # prefix-AND cacheable ripple / ordering ripple: every rule of the
        # policy is in scope, plus the policy gate itself
        for rid in p_doc.get("rules") or []:
            rule_scope(rid)
        _policy_gate_scope(p_doc, urns, out)

    for event in events:
        if out.global_:
            break
        if event_is_noop(event):
            continue
        if event.op == "delete_all":
            out.global_ = True
            break
        old, new = event.old_doc, event.new_doc
        if event.kind == "rule":
            for doc in (old, new):
                if doc is not None:
                    out.scopes.append(
                        scope_from_target(doc.get("target"), urns)
                    )
            cacheable_changed = bool((old or {}).get(
                "evaluation_cacheable", False
            )) != bool((new or {}).get("evaluation_cacheable", False))
            if cacheable_changed or old is None or new is None:
                # membership/cacheable changes ripple through the owning
                # policies' prefix-AND chain
                if policy_docs is None:
                    policy_docs = all_docs("policy")
                for p_doc in policy_docs:
                    if event.doc_id in (p_doc.get("rules") or []):
                        whole_policy(p_doc)
        elif event.kind == "policy":
            old_rules = list((old or {}).get("rules") or [])
            new_rules = list((new or {}).get("rules") or [])
            old_sem = _semantic("policy", old)
            new_sem = _semantic("policy", new)
            gate_changed = (
                old is None or new is None
                or old_sem is None or new_sem is None
                or old_sem[0] != new_sem[0]
            )
            if (new or {}).get("effect") != (old or {}).get("effect"):
                # carried-policyEffect ripple crosses policy boundaries
                out.global_ = True
                break
            if old_rules != new_rules or gate_changed:
                for rid in dict.fromkeys(old_rules + new_rules):
                    rule_scope(rid)
            if gate_changed:
                for doc in (old, new):
                    if doc is not None:
                        _policy_gate_scope(doc, urns, out)
        else:  # policy_set
            old_pols = list((old or {}).get("policies") or [])
            new_pols = list((new or {}).get("policies") or [])
            old_sem = _semantic("policy_set", old)
            new_sem = _semantic("policy_set", new)
            gate_changed = (
                old is None or new is None
                or old_sem is None or new_sem is None
                or old_sem[0] != new_sem[0]
            )
            if gate_changed or old_pols != new_pols:
                if old is None or new is None or gate_changed:
                    # set create/delete/gate change: last-set-wins ordering
                    # and the set gate both shift — conservative global
                    out.global_ = True
                    break
                for pid in dict.fromkeys(
                    set(old_pols).symmetric_difference(new_pols)
                ):
                    p_doc = get_doc("policy", pid)
                    if p_doc is not None:
                        whole_policy(p_doc)
                if [p for p in old_pols if p in new_pols] != [
                    p for p in new_pols if p in old_pols
                ]:
                    out.global_ = True  # reorder: combining order shifts
                    break
    if out.global_:
        out.scopes = []
    return out


# --------------------------------------------------------------- delta state


@dataclass
class SetState:
    """Per-set ownership ledger: what the current slot content was lowered
    from, keyed by node identity so relowering reuses rows/slots."""

    slot: int
    ca: str
    pol_ca: dict = field(default_factory=dict)    # pol_key -> CA urn
    rows: dict = field(default_factory=dict)      # owner tuple -> target row
    conds: dict = field(default_factory=dict)     # rule owner -> cond index


@dataclass
class DeltaState:
    """Mutable companion of one published bucketed CompiledPolicies: slot
    maps, target-row free list, vocab live sizes and the condition ledger.
    Cloned-and-published together with the patched arrays, never mutated
    in place (srv/evaluator.py swaps both under its publish lock)."""

    caps: Capacities
    sets: dict = field(default_factory=dict)       # set_id -> SetState
    set_order: list = field(default_factory=list)
    t_live: int = 0
    free_rows: list = field(default_factory=list)
    w_live: int = 0
    rv_live: int = 0
    rs_map: dict = field(default_factory=dict)     # (role, scope) id -> row
    cond_content: dict = field(default_factory=dict)  # idx -> (cond, query)
    rule_refs: dict = field(default_factory=dict)  # rule id -> set ids
    pol_refs: dict = field(default_factory=dict)   # policy id -> set ids
    needs_hr: bool = False
    needs_rel: bool = False
    prefilter_active: bool = False
    relv_live: int = 0
    rel_map: dict = field(default_factory=dict)    # interned path id -> row

    def clone(self) -> "DeltaState":
        return DeltaState(
            caps=self.caps,
            sets={
                sid: SetState(
                    slot=st.slot, ca=st.ca, pol_ca=dict(st.pol_ca),
                    rows=dict(st.rows), conds=dict(st.conds),
                )
                for sid, st in self.sets.items()
            },
            set_order=list(self.set_order),
            t_live=self.t_live,
            free_rows=list(self.free_rows),
            w_live=self.w_live,
            rv_live=self.rv_live,
            rs_map=dict(self.rs_map),
            cond_content=dict(self.cond_content),
            rule_refs={k: set(v) for k, v in self.rule_refs.items()},
            pol_refs={k: set(v) for k, v in self.pol_refs.items()},
            needs_hr=self.needs_hr,
            needs_rel=self.needs_rel,
            prefilter_active=self.prefilter_active,
            relv_live=self.relv_live,
            rel_map=dict(self.rel_map),
        )


def _tree_refs(tree) -> tuple[dict, dict]:
    rule_refs: dict = {}
    pol_refs: dict = {}
    for sid, ps in tree.items():
        if ps is None:
            continue
        for pol in ps.combinables.values():
            if pol is None:
                continue
            pol_refs.setdefault(pol.id, set()).add(sid)
            for rule in pol.combinables.values():
                if rule is None:
                    continue
                rule_refs.setdefault(rule.id, set()).add(sid)
    return rule_refs, pol_refs


def _needs_hr(arrays: dict) -> bool:
    # mirrors ops/kernel.tree_needs_hr without importing the jax module
    return bool(
        (np.asarray(arrays["t_has_scoping"])
         & (np.asarray(arrays["t_n_subjects"]) > 0)).any()
    )


def _needs_rel(arrays: dict) -> bool:
    # mirrors ops/kernel.tree_needs_rel without importing the jax module
    t = arrays.get("t_rel_idx")
    return t is not None and bool((np.asarray(t) >= 0).any())


def _prefilter_threshold() -> int:
    # lazy: ops/prefilter imports jax; only the constant is needed here
    from .prefilter import MIN_RULES

    return MIN_RULES


def build_state(
    padded: CompiledPolicies,
    raw: CompiledPolicies,
    tree,
    caps: Capacities,
) -> DeltaState:
    """Ownership ledger for a freshly published bucketed compile.  ``raw``
    is the pre-padding compile (live sizes); ``padded`` the published
    tables whose ``target_owners`` / condition owners seed the maps."""
    state = DeltaState(caps=caps)
    state.t_live = raw.T
    state.w_live = len(raw.entity_vocab)
    state.rv_live = int(np.asarray(raw.arrays["hrv_role"]).shape[0])
    hrv_role = np.asarray(padded.arrays["hrv_role"])[: state.rv_live]
    hrv_scope = np.asarray(padded.arrays["hrv_scope"])[: state.rv_live]
    state.rs_map = {
        (int(r), int(sc)): i
        for i, (r, sc) in enumerate(zip(hrv_role, hrv_scope))
    }
    state.rule_refs, state.pol_refs = _tree_refs(tree)
    state.needs_hr = _needs_hr(padded.arrays)
    state.needs_rel = _needs_rel(padded.arrays)
    state.prefilter_active = raw.n_rules >= _prefilter_threshold()
    state.relv_live = len(raw.rel_vocab)
    state.rel_map = dict(padded.rel_vocab_ids)

    sets = [ps for ps in tree.values() if ps is not None]
    for s, ps in enumerate(sets):
        st = SetState(slot=s, ca=ps.combining_algorithm)
        for pol_key, pol in ps.combinables.items():
            if pol is not None:
                st.pol_ca[pol_key] = pol.combining_algorithm
        state.sets[ps.id] = st
        state.set_order.append(ps.id)
    for owner, row in padded.target_owners.items():
        sid = owner[1]
        if sid in state.sets:
            state.sets[sid].rows[owner] = int(row)
    for idx, cond in enumerate(padded.conditions):
        state.cond_content[idx] = (
            cond.condition, _query_key(cond.context_query)
        )
        if cond.owner is not None and cond.owner[1] in state.sets:
            state.sets[cond.owner[1]].conds[cond.owner] = idx
    return state


def _query_key(context_query) -> tuple:
    if context_query is None:
        return ()
    if isinstance(context_query, ContextQuery):
        return (repr(context_query.filters), context_query.query)
    return (repr(context_query),)


def full_bucketed_compile(
    tree,
    urns: Urns,
    version: int = 0,
    prev_caps: Optional[Capacities] = None,
    headroom: float = 1.25,
):
    """The full-recompile path with capacity bucketing: compile from
    scratch, pad to (possibly reused) capacity buckets, and build the
    ownership state for subsequent patches.  Unsupported trees come back
    unpadded with ``state None`` (no kernel exists to patch)."""
    from .compile import compile_policies

    raw = compile_policies(tree, urns, version=version)
    if not raw.supported:
        return raw, None, None
    caps = capacities_for(raw, headroom=headroom, prev=prev_caps)
    padded = pad_compiled(raw, caps)
    state = build_state(padded, raw, tree, caps)
    return padded, caps, state


def live_capacities(compiled: CompiledPolicies) -> Capacities:
    """Unpadded live sizes of a fresh compile — what size-class selection
    (srv/tenancy.py) measures a tenant tree against."""
    return Capacities(
        S=compiled.S, KP=compiled.KP, KR=compiled.KR, T=compiled.T,
        RV=int(np.asarray(compiled.arrays["hrv_role"]).shape[0]),
        W=max(len(compiled.entity_vocab), 1),
        RELV=max(len(compiled.rel_vocab), 1),
    )


def fixed_caps_compile(tree, urns: Urns, caps: Capacities,
                       version: int = 0):
    """Compile a tree directly into a FIXED capacity class, bypassing
    ``capacities_for``'s tightness preference.  This is the multi-tenant
    packing primitive (srv/tenancy.py): every tenant in one size class
    publishes tables with byte-identical shapes, so the class shares ONE
    set of jitted executables and per-tenant tables enter as jit
    arguments.  Raises DeltaIneligible(``capacity-class-<dim>``) when the
    live tree overflows the class on any dimension — the caller promotes
    the tenant to the next class and recompiles there."""
    from .compile import compile_policies

    raw = compile_policies(tree, urns, version=version)
    if not raw.supported:
        return raw, None, None
    live = live_capacities(raw)
    for dim in ("S", "KP", "KR", "T", "RV", "W", "RELV"):
        if getattr(live, dim) > getattr(caps, dim):
            raise DeltaIneligible(f"capacity-class-{dim}")
    padded = pad_compiled(raw, caps)
    state = build_state(padded, raw, tree, caps)
    return padded, caps, state


# ------------------------------------------------------------- delta patcher


class _DeltaTargetTable:
    """Duck-typed stand-in for compile._TargetTable that writes target rows
    IN PLACE: rows are owned by node identity (reused across relowers),
    freed rows go to the free list, and the entity/rs vocabs grow only
    within their capacity buckets."""

    def __init__(self, arrays: dict, state: DeltaState, set_state: SetState,
                 old_rows: dict, interner, urns: Urns,
                 entity_vocab: list, entity_vocab_ids: dict,
                 rel_vocab: Optional[list] = None,
                 rel_vocab_ids: Optional[dict] = None):
        self.arrays = arrays
        self.state = state
        self.set_state = set_state
        self.old_rows = old_rows        # previous owner -> row map
        self.claimed: set = set()
        self.interner = interner
        self.urns = urns
        self.entity_vocab = entity_vocab
        self.entity_vocab_ids = entity_vocab_ids
        self.rel_vocab = rel_vocab if rel_vocab is not None else []
        self.rel_vocab_ids = rel_vocab_ids if rel_vocab_ids is not None else {}
        self.unsupported: Optional[str] = None
        self.rows_written = 0

    # --- vocab allocation inside the capacity bucket
    def _vocab_row(self, value: str) -> int:
        vid = self.interner.intern(value)
        row = self.entity_vocab_ids.get(vid)
        if row is None:
            if self.state.w_live >= self.state.caps.W:
                raise DeltaIneligible("capacity-entity-vocab")
            row = self.state.w_live
            self.entity_vocab[row] = value
            self.entity_vocab_ids[vid] = row
            self.state.w_live += 1
        return row

    def _rel_row(self, value: str) -> int:
        vid = self.interner.intern(value)
        row = self.rel_vocab_ids.get(vid)
        if row is None:
            if self.state.relv_live >= self.state.caps.RELV:
                raise DeltaIneligible("capacity-rel-vocab")
            row = self.state.relv_live
            if row < len(self.rel_vocab):
                self.rel_vocab[row] = value
            else:
                self.rel_vocab.append(value)
            self.rel_vocab_ids[vid] = row
            self.arrays["relv_path"][row] = vid
            self.state.rel_map[vid] = row
            self.state.relv_live += 1
        return row

    def _rs_row(self, role: int, scope: int) -> int:
        key = (int(role), int(scope))
        idx = self.state.rs_map.get(key)
        if idx is None:
            if self.state.rv_live >= self.state.caps.RV:
                raise DeltaIneligible("capacity-rs-vocab")
            idx = self.state.rv_live
            self.arrays["hrv_role"][idx] = role
            self.arrays["hrv_scope"][idx] = scope
            self.state.rs_map[key] = idx
            self.state.rv_live += 1
        return idx

    def _alloc_row(self, owner: tuple) -> int:
        row = self.old_rows.get(owner)
        if row is None:
            if self.state.free_rows:
                row = self.state.free_rows.pop()
            elif self.state.t_live < self.state.caps.T:
                row = self.state.t_live
                self.state.t_live += 1
            else:
                raise DeltaIneligible("capacity-target-rows")
        return row

    def add(self, target, owner: Optional[tuple] = None) -> int:
        row_dict, unsupported = lower_target(
            target, self.interner, self.urns, self._vocab_row, self._rel_row
        )
        if unsupported:
            self.unsupported = unsupported
        idx = self._alloc_row(owner)
        a = self.arrays
        for name, key, _dtype in TARGET_COLUMNS:
            a[name][idx] = row_dict[key]
        a["t_rs_idx"][idx] = self._rs_row(
            row_dict["role"], row_dict["scoping"]
        )
        self.set_state.rows[owner] = idx
        self.claimed.add(owner)
        self.rows_written += 1
        self._row_info = (row_dict["has_props"], row_dict["ent_vals"])
        self._last_idx = idx
        return idx

    def row_info(self, idx: int) -> tuple[bool, list[int]]:
        assert idx == self._last_idx
        return self._row_info


class _DeltaConditionSink:
    """Identity-checked condition slot reuse: patched trees may neither
    add, remove nor edit conditions (the [C, B] device shapes and the
    prefetch plan's flat indices hang off the list), only re-home the
    surviving rules' flat indices."""

    def __init__(self, state: DeltaState, set_state: SetState,
                 old_conds: dict, conditions: list):
        self.state = state
        self.set_state = set_state
        self.old_conds = old_conds
        self.conditions = conditions
        self.claimed: set = set()

    def add(self, owner: tuple, flat_index: int, condition: str,
            context_query) -> int:
        idx = self.old_conds.get(owner)
        if idx is None:
            raise DeltaIneligible("condition-added")
        if self.state.cond_content.get(idx) != (
            condition, _query_key(context_query)
        ):
            raise DeltaIneligible("condition-changed")
        self.conditions[idx] = replace(
            self.conditions[idx], rule_flat_index=flat_index, owner=owner
        )
        self.set_state.conds[owner] = idx
        self.claimed.add(owner)
        return idx


def apply_events(
    state: DeltaState,
    compiled: CompiledPolicies,
    tree,
    events: list[CrudEvent],
    urns: Urns,
):
    """Turn a CRUD event list into an in-place patch of the bucketed
    tables.

    Returns ``("noop", None, None, stats)`` when every event is
    semantically empty or touches nothing the tree references, or
    ``("patch", new_compiled, new_state, stats)`` with copy-on-write
    arrays (the input ``compiled``/``state`` are never mutated, so a
    version race can drop the result safely).  Raises
    :class:`DeltaIneligible` for everything the prover cannot certify —
    the caller falls back to the full recompile."""
    if not compiled.supported:
        raise DeltaIneligible("unsupported-tree")
    caps = state.caps

    non_noop = [ev for ev in events if not event_is_noop(ev)]
    stats = {"events": len(events), "events_effective": len(non_noop)}
    if not non_noop:
        return "noop", None, None, stats
    if any(ev.op == "delete_all" for ev in non_noop):
        raise DeltaIneligible("collection-drop")

    new_order = [sid for sid, ps in tree.items() if ps is not None]
    if new_order != state.set_order:
        # ops/reverse.py (and the set-slot maps) rely on positional
        # tree <-> slot correspondence; set membership/order changes take
        # the full path (rare next to rule/policy churn)
        raise DeltaIneligible("set-list-changed")

    new_rule_refs, new_pol_refs = _tree_refs(tree)
    affected: set = set()
    for ev in non_noop:
        if ev.kind == "rule":
            affected |= state.rule_refs.get(ev.doc_id, set())
            affected |= new_rule_refs.get(ev.doc_id, set())
        elif ev.kind == "policy":
            affected |= state.pol_refs.get(ev.doc_id, set())
            affected |= new_pol_refs.get(ev.doc_id, set())
        else:
            affected.add(ev.doc_id)
    affected &= set(new_order)
    if not affected:
        # e.g. a rule created before any policy references it
        new_state = state.clone()
        new_state.rule_refs, new_state.pol_refs = new_rule_refs, new_pol_refs
        return "noop", None, new_state, stats

    # ---- copy-on-write working set
    a = {k: np.array(v) for k, v in compiled.arrays.items()}
    vocab = list(compiled.entity_vocab)
    vocab_ids = dict(compiled.entity_vocab_ids)
    rvocab = list(compiled.rel_vocab)
    rvocab_ids = dict(compiled.rel_vocab_ids)
    conditions = list(compiled.conditions)
    owners = dict(compiled.target_owners)
    ns = state.clone()
    ns.rule_refs, ns.pol_refs = new_rule_refs, new_pol_refs

    rows_written = 0
    for sid in sorted(affected, key=new_order.index):
        ps = tree[sid]
        old_set = ns.sets[sid]
        if ps.combining_algorithm != old_set.ca:
            raise DeltaIneligible("combining-algorithm-changed")
        for pol_key, pol in ps.combinables.items():
            if pol is None:
                continue
            prev_ca = old_set.pol_ca.get(pol_key)
            if prev_ca is not None and prev_ca != pol.combining_algorithm:
                raise DeltaIneligible("combining-algorithm-changed")
        if len(ps.combinables) > caps.KP:
            raise DeltaIneligible("capacity-policies")
        for pol in ps.combinables.values():
            if pol is not None and len(pol.combinables) > caps.KR:
                raise DeltaIneligible("capacity-rules")

        s = old_set.slot
        old_rows = dict(old_set.rows)
        old_conds = dict(old_set.conds)
        new_set = SetState(slot=s, ca=ps.combining_algorithm)
        ns.sets[sid] = new_set
        table = _DeltaTargetTable(
            a, ns, new_set, old_rows, compiled.interner, urns,
            vocab, vocab_ids, rvocab, rvocab_ids,
        )
        cond_sink = _DeltaConditionSink(ns, new_set, old_conds, conditions)
        clear_set_slot(a, s)
        reason = lower_set_into(a, s, ps, table, cond_sink, caps.KP, caps.KR)
        if reason or table.unsupported:
            raise DeltaIneligible(
                f"unsupported:{reason or table.unsupported}"
            )
        for pol_key, pol in ps.combinables.items():
            if pol is not None:
                new_set.pol_ca[pol_key] = pol.combining_algorithm
        # free rows of deleted/target-less nodes; deleted conditioned rules
        # would shrink the condition list -> ineligible
        for owner, row in old_rows.items():
            if owner not in table.claimed:
                ns.free_rows.append(row)
                owners.pop(owner, None)
        for owner in old_conds:
            if owner not in cond_sink.claimed:
                raise DeltaIneligible("condition-removed")
        for owner, row in new_set.rows.items():
            owners[owner] = row
        rows_written += table.rows_written

    # ---- post-patch topology guards: the compiled program variant must
    # not change (with_hr selection, prefilter activation threshold)
    if _needs_hr(a) != state.needs_hr:
        raise DeltaIneligible("hr-topology-changed")
    if _needs_rel(a) != state.needs_rel:
        # with_rel selects a different program variant (tree_needs_rel)
        raise DeltaIneligible("rel-topology-changed")
    n_rules = int(a["rule_valid"].sum())
    if (n_rules >= _prefilter_threshold()) != state.prefilter_active:
        raise DeltaIneligible("prefilter-threshold-crossed")

    stats["sets_patched"] = len(affected)
    stats["target_rows_written"] = rows_written
    # the set SLOTS this patch rewrote: the pod-sharded kernel
    # (parallel/pod_shard.py) maps slots to owning shards and re-slices
    # only those, leaving every other shard's host tables untouched
    stats["patched_slots"] = sorted(ns.sets[sid].slot for sid in affected)
    new_compiled = replace(
        compiled,
        arrays=a,
        conditions=conditions,
        entity_vocab=vocab,
        entity_vocab_ids=vocab_ids,
        rel_vocab=rvocab,
        rel_vocab_ids=rvocab_ids,
        target_owners=owners,
    )
    return "patch", new_compiled, ns, stats
