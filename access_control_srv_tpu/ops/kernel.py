"""The batched decision kernel: one vmapped, jitted function computing
isAllowed decisions for a request batch against the compiled policy tensors.

Everything the reference evaluates with nested loops and mutable flags
(reference: src/core/accessController.ts:88-324) is expressed here as masked
boolean algebra over padded tensors:

- target matching over the flat target table ``[T]`` in PERMIT/DENY effect
  variants (the property gates are effect-asymmetric, reference: :578-588,
  644-647), exact and regex modes (regex results come from host-computed
  ``[W, E]`` matrices);
- positional property relevance via cumulative/sticky entity-match state
  per entity run (reference: :501-525 state machine);
- hierarchical-scope checks per target row (direct owner match + flattened
  HR-closure membership, sticky collection scan, reference:
  hierarchicalScope.ts:54-258);
- combining algorithms as masked position reductions along the rule/policy
  axes (first-DENY / first-PERMIT / first / last);
- the exact-match break index and its carried ``policyEffect`` (reference:
  :136-157), the multi-entity recheck (:429-463), condition aborts in flat
  rule order (:240-270) and last-set-wins decision assembly (:293-295).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .compile import CompiledPolicies
from .encode import RequestBatch
from .interner import ABSENT

# plain numpy scalar (not jnp): creating a device array at module scope
# would initialize the jax backend as an import side effect — on hosts whose
# TPU plugin is unreachable, that hangs every importer, including host-only
# code paths that never run the kernel
BIG = np.int32(1 << 30)

# Policy trees whose compiled tensors fit under this size are baked into the
# jitted program as XLA constants (the compiler pre-folds every
# policy-dependent subexpression once); larger trees are passed as
# device-resident arguments, since embedded constants make XLA spend
# unbounded time constant-folding and are re-embedded per batch bucket.
CONSTANT_BAKE_LIMIT_BYTES = 1 << 20


def bake_policy_constants(compiled: CompiledPolicies) -> bool:
    policy_bytes = sum(np.asarray(v).nbytes for v in compiled.arrays.values())
    return policy_bytes <= CONSTANT_BAKE_LIMIT_BYTES


def tree_needs_hr(arrays: dict) -> bool:
    """Static gate for stage B: only target rows carrying BOTH subjects
    and a scoping entity can fail the HR check (hr_trivial covers every
    other row), so trees without such rows skip the owner-check tensors
    entirely (see _match_targets with_hr)."""
    return bool(
        (np.asarray(arrays["t_has_scoping"])
         & (np.asarray(arrays["t_n_subjects"]) > 0)).any()
    )


def tree_needs_rel(arrays: dict) -> bool:
    """Static gate for the relation-plane fold (ReBAC, ops/relation.py):
    only target rows carrying a relation-path requirement can fail it, so
    relation-free trees keep their lowered programs byte-identical (the
    flag is Python-level, like with_hr)."""
    t = arrays.get("t_rel_idx")
    return t is not None and bool((np.asarray(t) >= 0).any())


def pow2_bucket(n: int, floor: int = 8) -> int:
    """Smallest power of two >= n (min `floor`): the shared padding bucket
    used by every kernel entry so varying batch/entity sizes reuse a
    handful of compiled programs instead of one XLA compile per size."""
    return max(floor, 1 << max(n - 1, 1).bit_length())


def half_pow2_bucket(n: int, floor: int = 8) -> int:
    """Smallest value >= n of the form 2^k or 1.5 * 2^k (min ``floor``):
    twice the bucket density of pow2_bucket, capping padding waste at 33%
    instead of 100% while still bounding distinct compile shapes."""
    p = pow2_bucket(n, floor)
    return p - p // 4 if n <= p - p // 4 and p - p // 4 >= floor else p


def pack_rule_key(pos, effect, cacheable):
    """Combine-reduction key: rule position in the high bits, (effect,
    cacheable) payload in the low 3, so position min/max reductions carry
    the selected rule's effect and cacheable bits with them and no
    post-reduction gather is needed (a [S, KP]-at-[S, M] take_along_axis
    here was ~90% of the 100k-rule stress batch on TPU — round-5 profile).
    Shared with the rule-sharded kernel's packed cross-device reductions
    (parallel/rule_shard.py); position ordering is preserved because
    positions are distinct and occupy the high bits."""
    return (pos << 3) | (effect << 1) | cacheable.astype(jnp.int32)


def unpack_rule_key(key):
    """(effect, cacheable) payload of a pack_rule_key winner."""
    return (key >> 1) & 3, key & 1


def pad_cols(a: np.ndarray, width: int) -> np.ndarray:
    """Zero-pad the second axis out to `width` (conditions are [n_cond, B];
    regex matrices are [W, E])."""
    a = np.asarray(a)
    if a.shape[1] == width:
        return a
    fill = np.zeros(a.shape[:1] + (width - a.shape[1],), a.dtype)
    return np.concatenate([a, fill], axis=1)


def lead_padding(batch):
    """Shared batch-axis padding contract for every kernel entry: returns
    (b, bucket, e_bucket, pad_lead) where ``pad_lead`` zero-fills the
    leading axis out to the power-of-two bucket.  Rows are independent
    under vmap, so zero-padded rows cannot affect real rows."""
    b = batch.arrays[next(iter(batch.arrays))].shape[0]
    bucket = pow2_bucket(b)

    def pad_lead(a: np.ndarray) -> np.ndarray:
        a = np.asarray(a)
        if a.shape[0] == bucket:
            return a
        fill = np.zeros((bucket - a.shape[0],) + a.shape[1:], a.dtype)
        return np.concatenate([a, fill], axis=0)

    e_bucket = pow2_bucket(batch.rgx_set.shape[1])
    return b, bucket, e_bucket, pad_lead


def _pairs_subset(rule_ids, rule_vals, req_ids, req_vals):
    """Every valid rule (id, value) pair appears among the request pairs
    (reference: attributesMatch, accessController.ts:681-699)."""
    rule_valid = rule_ids >= 0
    # [K_rule, K_req] equality
    eq = (rule_ids[:, None] == req_ids[None, :]) & (
        rule_vals[:, None] == req_vals[None, :]
    ) & (req_ids[None, :] >= 0)
    return jnp.all(~rule_valid | eq.any(axis=1))


def _member(needle, haystack):
    """needle in haystack (1-D), ignoring ABSENT padding."""
    return jnp.any((haystack == needle) & (haystack >= 0))


def _action_kind(c: dict, r: dict):
    """0 = other, 1 = create, 2 = read/modify/delete, judged on the FIRST
    action attribute only (reference: verifyACL.ts:138-144, 177-181)."""
    k = c["acl_consts"]
    id0, val0 = r["r_act_ids"][0], r["r_act_vals"][0]
    is_action = id0 == k[2]
    create = is_action & (val0 == k[3])
    rmd = is_action & ((val0 == k[4]) | (val0 == k[5]) | (val0 == k[6]))
    return jnp.where(create, 1, jnp.where(rmd, 2, 0))


def _acl_pass(c: dict, r: dict, with_acl: bool):
    """Stage B2: tensorized verifyACL per target row -> [T] bool
    (reference: verifyACL.ts:11-251).

    The request-side encoder pre-reduces the resource walk to
    ``r_acl_short`` (0 pairs / 1 early all-clear / 2 malformed-fail) and
    (scoping entity, instances) pair arrays; the rule-dependent parts —
    skipACL, the rule's scoped roles, the create-path role scan and the
    read/modify/delete membership — run here. The create path's sequential
    break/continue semantics (validated-instance accumulation across roles,
    the carried valid flag across scoping entities, :146-175) are
    reproduced exactly with a lax.scan over the padded (role, instance)
    grid; read/modify/delete (:177-200) is a pure masked reduction.

    ``with_acl=False`` compiles only the no-pair fast path (exact whenever
    the batch carries no ACL pairs, which the kernel entry checks)."""
    k = c["acl_consts"]
    T = c["t_role"].shape[0]
    skip = c["t_skip_acl"]  # [T]
    short = r["r_acl_short"]
    kind = _action_kind(c, r)

    if not with_acl:
        # no-pair fast path: early all-clear passes; otherwise role
        # associations must exist and the first action must be CRUD
        # (create/rmd with an empty entity dict both return True,
        # :147-148, 184-185; any other action falls through to False,
        # :250). short==2 (malformed) correctly yields False here too.
        return skip | (short == 1) | (
            (short == 0) & (r["r_n_ra"] > 0) & (kind > 0)
        )

    ents = r["r_acl_ent"]        # [NACLE]
    insts = r["r_acl_inst"]      # [NACLE, NACLI]
    ev = ents >= 0
    iv = insts >= 0
    NACLE, NACLI = insts.shape
    has_ents = ev.any()

    # rule's scoped roles: subject attr pairs whose id is the role urn
    scoped_mask = (c["t_sub_ids"] == k[0]) & (c["t_sub_vals"] >= 0)  # [T,KS]
    user_e = ev & (ents == k[1])  # [NACLE]

    # subject_scoped existence per entity: any role association (role,
    # scoping-entity) pair with a rule-scoped role (:94-112, 156-157)
    ra2 = r["r_ra2"]
    ra2v = ra2[:, 1] >= 0
    ra2_scoped = (
        (ra2[None, None, :, 0] == c["t_sub_vals"][:, :, None])
        & scoped_mask[:, :, None]
    ).any(axis=1)  # [T, NRA]
    subj_exists = (
        ra2_scoped[:, None, :]
        & (ents[None, :, None] == ra2[None, None, :, 1])
        & ra2v[None, None, :]
    ).any(axis=2)  # [T, NACLE]

    # ---- read/modify/delete: >=1 subject scope instance (or the subject
    # id itself for user-entity ACLs) appears in the ACL (:177-200)
    ra3 = r["r_ra3"]
    ra3v = ra3[:, 1] >= 0
    ra3_scoped = (
        (ra3[None, None, :, 0] == c["t_sub_vals"][:, :, None])
        & scoped_mask[:, :, None]
    ).any(axis=1)  # [T, NRA]
    inst_has = (
        (insts[:, :, None] == ra3[None, None, :, 2]) & iv[:, :, None]
    ).any(axis=1)  # [NACLE, NRA] instance value present in entity's ACL
    rmd_sub = (
        ra3_scoped[:, None, :]
        & (ents[None, :, None] == ra3[None, None, :, 1])
        & inst_has[None, :, :]
        & ra3v[None, None, :]
    ).any(axis=2)  # [T, NACLE]
    subj_in = ((insts == r["r_subject_id"]) & iv).any(axis=1)  # [NACLE]
    rmd_ok = (
        ev[None, :] & ((user_e & subj_in)[None, :] | rmd_sub)
    ).any(axis=1)  # [T]
    rmd_res = ~has_ents | rmd_ok

    # ---- create: every target ACL instance inside the subject's HR org
    # scopes for a shared role (:141-175), exact sequential semantics
    hr_roles = r["r_hr_roles"]  # [NHRR]
    NHRR = hr_roles.shape[0]
    hrr_v = hr_roles >= 0
    role_scoped = (
        (hr_roles[None, None, :] == c["t_sub_vals"][:, :, None])
        & scoped_mask[:, :, None]
    ).any(axis=1) & hrr_v[None, :]  # [T, NHRR]
    ahr = r["r_acl_hr"]  # [NHR, 2] verifyACL flatten (role, org)
    ahrv = ahr[:, 1] >= 0
    # eligible_org_scopes membership per (entity, instance, hr role)
    elig = (
        (insts[:, :, None, None] == ahr[None, None, None, :, 1])
        & (hr_roles[None, None, :, None] == ahr[None, None, None, :, 0])
        & ahrv[None, None, None, :]
    ).any(axis=3) & iv[:, :, None]  # [NACLE, NACLI, NHRR]
    same_val = (
        (insts[:, :, None] == insts[:, None, :]) & iv[:, None, :]
    )  # [NACLE, NACLI(i), NACLI(j)]

    # scan over the flattened (role, instance) grid; carry the validated
    # instance set (persists across roles within an entity), the per-role
    # broken flag (inner-loop break, :169-171) and the last set/fail event
    steps = NHRR * NACLI
    r_of_s = np.arange(steps) // NACLI
    i_of_s = np.arange(steps) % NACLI
    xs = (
        jnp.asarray(np.eye(NACLI, dtype=bool)[i_of_s]),
        # [steps, NACLI] one-hot of the instance position
        elig[:, i_of_s, r_of_s].T,
        # [steps, NACLE] eligibility of (entity, current instance, role)
        jnp.moveaxis(same_val[:, i_of_s, :], 1, 0),
        # [steps, NACLE, NACLI] value-equality row of the current instance
        iv[:, i_of_s].T,                    # [steps, NACLE] instance valid
        jnp.asarray(i_of_s == 0),           # [steps] role-start reset
        jnp.asarray(r_of_s, np.int32),      # [steps] role index
    )

    def step(carry, x):
        validated, broken, last_ev = carry
        onehot, elig_cur, samev_cur, iv_cur, at_start, role_idx = x
        rsc = role_scoped[:, role_idx]  # [T]
        broken = broken & ~at_start
        active = (
            rsc[:, None] & iv_cur[None, :] & ~broken
        )  # [T, NACLE]
        in_validated = (validated & samev_cur[None, :, :]).any(axis=2)
        hit = active & elig_cur[None, :]
        fail = active & ~elig_cur[None, :] & ~in_validated
        validated = validated | (hit[:, :, None] & onehot[None, None, :])
        broken = broken | fail
        last_ev = jnp.where(hit, 1, jnp.where(fail, 2, last_ev))
        return (validated, broken, last_ev), None

    init = (
        jnp.zeros((T, NACLE, NACLI), bool),
        jnp.zeros((T, NACLE), bool),
        jnp.zeros((T, NACLE), jnp.int32),
    )
    (validated, broken, last_ev), _ = jax.lax.scan(step, init, xs)
    ev_any = last_ev > 0           # [T, NACLE]
    ev_true = last_ev == 1

    # compose entities in order with the carried valid flag (:146-175);
    # user-entity ACLs set valid and skip the per-entity check (:150-153)
    v = jnp.zeros((T,), bool)
    alive = jnp.ones((T,), bool)
    for e in range(NACLE):
        is_real = ev[e]
        is_user = user_e[e]
        v_out = jnp.where(ev_any[:, e], ev_true[:, e], v)
        fail_e = ~is_user & (~subj_exists[:, e] | ~v_out)
        v = jnp.where(is_real, jnp.where(is_user, True, v_out), v)
        alive = alive & (~is_real | ~fail_e)
    create_res = ~has_ents | alive

    # create_res/rmd_res already fold the empty-entity-dict -> True case
    # (:147-148, 184-185), so this single pair_ok covers short==0 whether
    # or not the request carries ACL pairs
    pair_ok = (
        (r["r_n_ra"] > 0)
        & jnp.where(kind == 1, create_res, jnp.where(kind == 2, rmd_res, False))
    )
    return skip | (short == 1) | ((short == 0) & pair_ok)


def _owner_bit_reader(bits, v, ebits: int):
    """Unpack accessor over the host-packed owner bitplanes
    (ops/encode.owner_bit_layout): ``bits`` is one request's packed word
    vector [NWORDS], ``v`` an int array of role-scope-vocab indices (any
    shape — target rows in the dense kernel, rule/policy planes in the
    signature kernel).  Returns ``bit(k) -> bool array shaped like v``.
    Arithmetic >> on int32 is safe here: the payload bit is extracted
    with & 1 after the shift."""
    if ebits <= 32:
        epw = 32 // ebits
        codes = jnp.take(bits, v // epw) >> ((v % epw) * ebits)

        def bit(k: int):
            return ((codes >> k) & 1) == 1

        return bit
    wpe = -(-ebits // 32)
    base = v * wpe

    def bit(k: int):
        return ((jnp.take(bits, base + k // 32) >> (k % 32)) & 1) == 1

    return bit


def _hr_pass_from_bits(r: dict, v, collect, op_hit, hr_check, trivial):
    """Stage B from host-precomputed owner bitplanes: combines the packed
    per-(row, vocab) fail verdicts with the signature/target-determined
    collection state and operation hits (reference:
    hierarchicalScope.ts:54-258 — the owner-membership side was folded
    host-side at encode, ops/encode.pack_owner_bitplanes).

    ``v``/``hr_check``/``trivial`` share a leading shape ([T] dense,
    [S, M] / [S, KP] signature planes); ``collect``/``op_hit`` carry one
    trailing run/op-slot axis.  All device work is elementwise + one tiny
    int gather per plane — no matmuls, no [RV, ...] intermediates."""
    runs = r["r_own_runs"]  # [NRU]
    nru = int(runs.shape[0])
    nop = int(op_hit.shape[-1])
    bit = _owner_bit_reader(r["r_own_bits"], v, 2 * (nru + nop))
    bad = jnp.zeros(v.shape, bool)
    n_runs = int(collect.shape[-1])
    for g in range(nru):
        # collect at group g's run: a static select over the run axis, not
        # a gather (post-reduction gathers are the TPU slow path)
        coll_g = jnp.zeros(v.shape, bool)
        for nr in range(n_runs):
            coll_g = coll_g | ((runs[g] == nr) & collect[..., nr])
        bad = bad | (coll_g & jnp.where(hr_check, bit(g), bit(nru + g)))
    for j in range(nop):
        bad = bad | (
            op_hit[..., j]
            & jnp.where(hr_check, bit(2 * nru + j), bit(2 * nru + nop + j))
        )
    ctx_ok = r["r_ctx_present"] & (r["r_n_ra"] > 0)
    return trivial | (ctx_ok & ~bad)


def _rel_pass_from_bits(r: dict, v, collect, direct, trivial):
    """Relation-path gate from host-precomputed closure bitplanes
    (ops/relation.pack_relation_bitplanes) — the ReBAC analog of
    _hr_pass_from_bits over the same packed layout with nop=0 and the
    !direct flag selecting plane B instead of hr_check.

    Unlike the owner gate there is no ctx_ok/role-association term (the
    relation check needs only the subject id and the targeted instances)
    and no operation term (relation requirements apply to resource
    instances only).  ``trivial`` is rows without a relation requirement
    (t_rel_idx < 0); a collected run with any failing instance fails."""
    runs = r["r_rel_runs"]  # [NRU]
    nru = int(runs.shape[0])
    vv = jnp.maximum(v, 0)
    bit = _owner_bit_reader(r["r_rel_bits"], vv, 2 * nru)
    bad = jnp.zeros(vv.shape, bool)
    n_runs = int(collect.shape[-1])
    for g in range(nru):
        coll_g = jnp.zeros(vv.shape, bool)
        for nr in range(n_runs):
            coll_g = coll_g | ((runs[g] == nr) & collect[..., nr])
        bad = bad | (coll_g & jnp.where(direct, bit(nru + g), bit(g)))
    return trivial | ~bad


def _hr_collect_state(c: dict, r: dict, rgx_hit, pfx_neq, ent_valid):
    """Stage B's signature-determined pieces, shared by the dense kernel
    and the components-mode planes builder: the per-(target row, entity
    run) sticky collection state (exact OR regex sets, prefix mismatch
    resets) and the per-(target row, op slot) operation hit (reference:
    hierarchicalScope.ts:61-147)."""
    em_ex_k = (
        (c["t_ent_vals"][:, :, None] == r["r_ent_vals"][None, None, :])
        & (c["t_ent_vals"][:, :, None] >= 0)
        & ent_valid[None, None, :]
    )  # [T, K_ENT, NR]
    set_k = em_ex_k | rgx_hit  # regex set wins over reset
    reset_k = pfx_neq & ~set_k

    def _sticky_k(carry, inputs):
        set_bit, reset_bit = inputs
        state = jnp.where(set_bit, True, jnp.where(reset_bit, False, carry))
        return state, state

    _, coll_t = jax.lax.scan(
        _sticky_k,
        jnp.zeros(set_k.shape[:2], bool),
        (jnp.moveaxis(set_k, 2, 0), jnp.moveaxis(reset_k, 2, 0)),
    )
    collect = jnp.moveaxis(coll_t, 0, 2).any(axis=1)  # [T, NR]
    op_hit = (
        (c["t_op_vals"][:, :, None] == r["r_op_vals"][None, None, :])
        & (c["t_op_vals"][:, :, None] >= 0)
        & (r["r_op_vals"][None, None, :] >= 0)
    ).any(axis=1)  # [T, NOP]
    return collect, op_hit


def _subject_ok(c: dict, r: dict):
    """Subject matching per target row -> [T] bool (reference:
    checkSubjectMatches, accessController.ts:793-823).  Shared by the
    full matcher and the signature-bit kernel (whose stage-A resource/
    action planes are precomputed per signature but whose subject side is
    inherently per-request)."""
    sub_pairs_ok = jax.vmap(
        lambda ids, vals: _pairs_subset(ids, vals, r["r_sub_ids"], r["r_sub_vals"])
    )(c["t_sub_ids"], c["t_sub_vals"])
    role_ok = jax.vmap(lambda role: _member(role, r["r_roles"]))(c["t_role"])
    return (c["t_n_subjects"] == 0) | jnp.where(
        c["t_has_role"], role_ok, sub_pairs_ok
    )


def _match_targets(c: dict, r: dict, with_hr: bool = True,
                   wia: bool = False, components: bool = False,
                   with_rel: bool = False):
    """Stages A (target matching) + B (HR scopes) for one request: returns
    per-target-row match vectors the rule/policy stages gather from.

    Factored out so the rule-sharded kernel (parallel/rule_shard.py) can run
    it against a per-device compacted target subtable.

    ``with_hr=False`` skips stage B entirely: exact whenever no target row
    carries both subjects and a scoping entity (then ``hr_trivial`` is True
    for every row and hr_pass degenerates to all-ones); callers assert that
    tree property statically so XLA never materializes the owner-check
    tensors.

    ``components=True`` returns the resource/action stage-A planes
    (res_ex_p/res_ex_d/res_rg_p/res_rg_d/act_ok) WITHOUT the subject fold
    — the signature-bit path precomputes exactly these per resource
    signature (they depend only on the request's entity/operation/action
    attributes, not its subject/context) and re-folds _subject_ok on
    device per row.  The caller passes a property-free pseudo-request, so
    the PERMIT property-fail reduces to has_props & entity-hit and the
    DENY skip is vacuous (reference: :578-588, 644-647 with no request
    properties).

    ``wia=True`` additionally emits the whatIsAllowed-mode match vectors
    (reference: accessController.ts:592-640 — PERMIT fails only when the
    target has properties, the request has none and the entity matched;
    DENY never property-fails; the isAllowed deny-skip is not applied) and
    conservative ``maybe_mask_*`` bits (the row COULD append masking
    obligations: target properties + an entity hit), which the host-side
    reverse-query assembler (ops/reverse.py) uses to decide when the
    scalar matcher must re-run for its side effects."""
    T = c["t_role"].shape[0]

    # ---------------------------------------------------------------- A: targets
    # subject matching (reference: checkSubjectMatches :793-823)
    sub_ok = _subject_ok(c, r)

    act_ok = jax.vmap(
        lambda ids, vals: _pairs_subset(ids, vals, r["r_act_ids"], r["r_act_vals"])
    )(c["t_act_ids"], c["t_act_vals"])

    # entity matches per (target, run): exact and regex
    ent_valid = r["r_ent_valid"]  # [NR]
    em_ex = (
        (c["t_ent_vals"][:, :, None] == r["r_ent_vals"][None, None, :])
        & (c["t_ent_vals"][:, :, None] >= 0)
        & ent_valid[None, None, :]
    ).any(axis=1)  # [T, NR]
    w_idx = jnp.clip(c["t_ent_w"], 0, None)  # [T, K_ENT]
    e_idx = jnp.clip(r["r_ent_e"], 0, None)  # [NR]
    rgx_hit = r["rgx_set"][w_idx[:, :, None], e_idx[None, None, :]]  # [T,K,NR]
    rgx_hit = rgx_hit & (c["t_ent_w"][:, :, None] >= 0) & ent_valid[None, None, :]
    em_rg = rgx_hit.any(axis=1)  # [T, NR]
    pfx_neq = r["pfx_neq"][w_idx[:, :, None], e_idx[None, None, :]]
    pfx_neq = pfx_neq & (c["t_ent_w"][:, :, None] >= 0) & ent_valid[None, None, :]

    ent_any_ex = em_ex.any(axis=1)  # [T]
    ent_any_rg = em_rg.any(axis=1)

    # operation match (exact mode only; the regex branch has no operation
    # comparison, reference: :526-574)
    opm = (
        (c["t_op_vals"][:, :, None] == r["r_op_vals"][None, None, :])
        & (c["t_op_vals"][:, :, None] >= 0)
        & (r["r_op_vals"][None, None, :] >= 0)
    ).any(axis=(1, 2))  # [T]

    # positional entity-match state per run:
    # exact mode: cumulative OR (never resets, reference: :501-505)
    state_ex = jnp.cumsum(em_ex.astype(jnp.int32), axis=1) > 0  # [T, NR]
    # regex mode: sticky with prefix-mismatch reset (reference: :526-566)
    def _sticky(carry, inputs):
        set_bit, reset_bit = inputs
        state = jnp.where(set_bit, True, jnp.where(reset_bit, False, carry))
        return state, state

    # per run j: set if regex matched, else reset if prefix mismatched
    reset_rg = pfx_neq.any(axis=1) & ~em_rg  # [T, NR]
    _, state_rg_t = jax.lax.scan(
        _sticky,
        jnp.zeros((T,), bool),
        (em_rg.T, reset_rg.T),
    )
    state_rg = state_rg_t.T  # [T, NR]

    # property gates
    prop_valid = r["r_prop_vals"] >= 0  # [NP]
    prop_run = jnp.clip(r["r_prop_run"], 0, None)  # [NP]
    prop_has_run = r["r_prop_run"] >= 0
    # relevance (exact): entity matched at-or-before the prop's run AND the
    # target entity tail equals the prop's prefix tail (verified by the
    # encoder to coincide with the reference substring check)
    state_at_prop_ex = jnp.take(state_ex, prop_run, axis=1) & prop_has_run[None, :]
    tail_eq = (
        (c["t_ent_tails"][:, :, None] == r["r_prop_tail"][None, None, :])
        & (c["t_ent_tails"][:, :, None] >= 0)
    ).any(axis=1)  # [T, NP]
    relevant_ex = prop_valid[None, :] & state_at_prop_ex & tail_eq
    in_rule = (
        (c["t_prop_vals"][:, :, None] == r["r_prop_vals"][None, None, :])
        & (c["t_prop_vals"][:, :, None] >= 0)
    ).any(axis=1)  # [T, NP]
    sfx_in_rule = (
        (c["t_prop_sfx"][:, :, None] == r["r_prop_sfx"][None, None, :])
        & (c["t_prop_sfx"][:, :, None] >= 0)
    ).any(axis=1)  # [T, NP]
    state_at_prop_rg = jnp.take(state_rg, prop_run, axis=1) & prop_has_run[None, :]
    relevant_rg = prop_valid[None, :] & state_at_prop_rg

    has_props = c["t_has_props"]
    r_has_props = r["r_has_props"]
    # regex-mode entity state: "true at any point" feeds the per-attribute
    # PERMIT fail check; the *final* state feeds the end-of-loop entity gate
    # (a later prefix mismatch can reset it, reference: :545-566, 650-653);
    # exact-mode state is monotone so any == final
    state_any_rg = state_rg.any(axis=1)
    NRr = state_rg.shape[1]
    state_final_rg = state_rg[:, NRr - 1]
    permit_fail_ex = has_props & (
        (~r_has_props & ent_any_ex) | (relevant_ex & ~in_rule).any(axis=1)
    )
    deny_skip_ex = has_props & r_has_props & ~(relevant_ex & in_rule).any(axis=1)
    permit_fail_rg = has_props & (
        (~r_has_props & state_any_rg) | (relevant_rg & ~sfx_in_rule).any(axis=1)
    )
    deny_skip_rg = has_props & r_has_props & ~(relevant_rg & sfx_in_rule).any(axis=1)

    no_res = c["t_n_res"] == 0
    res_ex_p = no_res | ((ent_any_ex | opm) & ~permit_fail_ex)
    res_ex_d = no_res | ((ent_any_ex | opm) & ~deny_skip_ex)
    res_rg_p = no_res | (state_final_rg & ~permit_fail_rg)
    res_rg_d = no_res | (state_final_rg & ~deny_skip_rg)

    if components:
        out = {
            "sig_res_ex_p": res_ex_p,
            "sig_res_ex_d": res_ex_d,
            "sig_res_rg_p": res_rg_p,
            "sig_res_rg_d": res_rg_d,
            "sig_act_ok": act_ok,
        }
        if wia:
            # whatIsAllowed-mode RESOURCE planes at signature granularity
            # (reference: accessController.ts:592-640): everything but the
            # subject fold is (entity, operation, action, has-props)-
            # determined, so the reverse-query kernel caches these per
            # signature and folds subjects host-side (ops/reverse.py)
            wia_fail_ex = has_props & ~r_has_props & ent_any_ex
            wia_fail_rg = has_props & ~r_has_props & state_any_rg
            out["sig_wia_ex_p"] = no_res | (
                (ent_any_ex | opm) & ~wia_fail_ex
            )
            out["sig_wia_ex_d"] = no_res | ent_any_ex | opm
            out["sig_wia_rg_p"] = no_res | (
                state_final_rg & ~wia_fail_rg
            )
            out["sig_wia_rg_d"] = no_res | state_final_rg
            out["sig_maybe_ex"] = has_props & ent_any_ex
            out["sig_maybe_rg"] = has_props & state_any_rg
        if with_hr or with_rel:
            # stage B's signature-determined parts — the owner side
            # stays per-request (shared helper with the dense stage B);
            # the relation fold reuses the same collection state
            collect, op_hit = _hr_collect_state(
                c, r, rgx_hit, pfx_neq, ent_valid
            )
            out["sig_collect"] = collect
            out["sig_op_hit"] = op_hit
        return out

    base = sub_ok & act_ok
    tm_ex_p = base & res_ex_p
    tm_ex_d = base & res_ex_d
    tm_rg_p = base & res_rg_p
    tm_rg_d = base & res_rg_d

    out = {
        "tm_ex_p": tm_ex_p,
        "tm_ex_d": tm_ex_d,
        "tm_rg_p": tm_rg_p,
        "tm_rg_d": tm_rg_d,
    }
    if wia:
        # whatIsAllowed PERMIT property-fail: target props, request has no
        # props, entity matched somewhere (ref :592-615 return branch)
        wia_fail_ex = has_props & ~r_has_props & ent_any_ex
        wia_fail_rg = has_props & ~r_has_props & state_any_rg
        out["tm_wia_ex_p"] = base & (
            no_res | ((ent_any_ex | opm) & ~wia_fail_ex)
        )
        out["tm_wia_ex_d"] = base & (no_res | ent_any_ex | opm)
        out["tm_wia_rg_p"] = base & (
            no_res | (state_final_rg & ~wia_fail_rg)
        )
        out["tm_wia_rg_d"] = base & (no_res | state_final_rg)
        out["maybe_mask_ex"] = has_props & ent_any_ex
        out["maybe_mask_rg"] = has_props & state_any_rg

    # ------------------------------------------------------------- B: HR scopes
    if not with_hr and not with_rel:
        out["hr_pass"] = jnp.ones((T,), bool)
        return out
    # collection per (target, entity slot, run) with sticky state like the
    # reference HR loop (exact OR regex sets, prefix mismatch resets,
    # reference: hierarchicalScope.ts:61-124) — shared with the signature
    # planes builder.  The owner-membership side arrives as host-packed
    # bitplanes indexed by the (role, scoping) vocab (compile.py hrv_*,
    # encode.pack_owner_bitplanes), gathered per target row via t_rs_idx.
    collect, op_hit = _hr_collect_state(c, r, rgx_hit, pfx_neq, ent_valid)
    if with_hr:
        hr_trivial = (c["t_n_subjects"] == 0) | ~c["t_has_scoping"]
        hr = _hr_pass_from_bits(
            r, c["t_rs_idx"], collect, op_hit, c["t_hr_check"], hr_trivial
        )
    else:
        hr = jnp.ones((T,), bool)
    if with_rel:
        # relation-path fold (ReBAC): same collection state, packed
        # closure planes gathered per target row via t_rel_idx; ANDed
        # into hr_pass so both gate sites (hr_rule in _rule_predicates
        # and the pol_subject gate) pick it up — mirroring the oracle's
        # paired check_hierarchical_scope/check_target_relations calls
        hr = hr & _rel_pass_from_bits(
            r, c["t_rel_idx"], collect, c["t_rel_direct"],
            c["t_rel_idx"] < 0,
        )
    out["hr_pass"] = hr
    return out


def _rule_predicates(c: dict, r: dict, m: dict, with_acl: bool = True):
    """Stage C: per-rule reachability, ACL gate and condition wiring;
    shared by the single-device and rule-sharded kernels (the latter passes
    a KR-chunked ``c`` with a compacted target subtable)."""
    tm_ex_p, tm_ex_d = m["tm_ex_p"], m["tm_ex_d"]
    tm_rg_p, tm_rg_d = m["tm_rg_p"], m["tm_rg_d"]
    hr_pass = m["hr_pass"]

    def gather_t(table, idx):
        return jnp.take(table, idx, axis=0)

    rt = c["rule_target"]  # [S, KP, KR]
    rule_deny = c["rule_effect"] == 2
    tm_rule_ex = jnp.where(rule_deny, gather_t(tm_ex_d, rt), gather_t(tm_ex_p, rt))
    tm_rule_rg = jnp.where(rule_deny, gather_t(tm_rg_d, rt), gather_t(tm_rg_p, rt))
    tm_rule = ~c["rule_has_target"] | tm_rule_ex | tm_rule_rg
    hr_rule = ~c["rule_has_target"] | gather_t(hr_pass, rt)
    reached = c["rule_valid"] & tm_rule & hr_rule

    # verifyACL per target row (stage B2): full tensorized semantics when
    # the batch carries ACL pairs, the cheap no-pair formula otherwise
    acl_rule = ~c["rule_has_target"] | gather_t(_acl_pass(c, r, with_acl), rt)

    has_cond, cond_t, cond_a, cond_c = _rule_conditions(c, r)
    return reached, acl_rule, has_cond, cond_t, cond_a, cond_c


def _rule_conditions(c: dict, r: dict):
    """Per-rule condition wiring: host-evaluated predicate bits joined to
    the rule mask (reference: conditionMatches eval, utils.ts:47-56)."""
    has_cond = c["rule_cond"] >= 0
    cond_idx = jnp.clip(c["rule_cond"], 0, None)
    if r["cond_true"].shape[0] > 0:
        cond_t = jnp.take(r["cond_true"], cond_idx)
        cond_a = jnp.take(r["cond_abort"], cond_idx)
        cond_c = jnp.take(r["cond_code"], cond_idx)
    else:
        cond_t = jnp.ones_like(cond_idx, dtype=bool)
        cond_a = jnp.zeros_like(cond_idx, dtype=bool)
        cond_c = jnp.full_like(cond_idx, 200)
    return has_cond, cond_t, cond_a, cond_c


def _multi_entity_ok(c: dict, r_ent_vals, r_ent_valid):
    """Multi-entity recheck -> [S] (reference: accessController.ts
    :429-463): every requested entity must exactly match some policy's
    resources; PERMIT policies with properties never match a bare entity
    attribute.  Shared by the full kernel (request entities) and the
    signature planes builder (the signature IS the entity list)."""
    pol_ent_hit = (
        (c["pol_ent_vals"][:, :, :, None] == r_ent_vals[None, None, None, :])
        & (c["pol_ent_vals"][:, :, :, None] >= 0)
        & r_ent_valid[None, None, None, :]
    ).any(axis=2)  # [S, KP, NR]
    pol_multi_ok = pol_ent_hit & ~(
        (c["pol_effect"] == 1) & c["pol_has_props"]
    )[:, :, None] & c["pol_valid"][:, :, None]
    return jnp.all(~r_ent_valid[None, :] | pol_multi_ok.any(axis=1), axis=1)


def _policy_gates_core(c: dict, pp_ex_p, pp_ex_d, pp_rg_p, pp_rg_d,
                       multi_gate):
    """First/second policy loop on pre-gathered policy-row match planes
    ([S, KP], full target match incl. subject fold): carried policyEffect
    selection, exact-match break, and the policy gate (reference:
    accessController.ts:130-195).  Shared by the full kernel (planes
    gathered from [T] match vectors) and the signature kernel (planes
    precomputed per signature, subject side folded by the caller)."""
    ctx_deny = c["pol_eff_ctx"] == 2
    pol_tm_first = jnp.where(ctx_deny, pp_ex_d, pp_ex_p)
    pol_tm_first = pol_tm_first & c["pol_valid"] & c["pol_has_target"]  # [S, KP]
    KP = pol_tm_first.shape[1]
    kp_pos = jnp.arange(KP)
    first_kp = jnp.min(
        jnp.where(pol_tm_first, kp_pos[None, :], BIG), axis=1
    )  # [S]
    exact0 = pol_tm_first.any(axis=1)
    last_valid_kp = jnp.max(
        jnp.where(c["pol_valid"], kp_pos[None, :], -1), axis=1
    )
    eff_src_kp = jnp.where(exact0, jnp.clip(first_kp, 0, KP - 1),
                           jnp.clip(last_valid_kp, 0, KP - 1))
    eval_eff = jnp.take_along_axis(
        c["pol_eff_ctx"], eff_src_kp[:, None], axis=1
    )[:, 0]  # [S] carried policyEffect after the break (reference: :130-157)

    exact = exact0 & multi_gate

    # second loop: policy gate with the frozen carried effect
    eval_deny = (eval_eff == 2)[:, None]
    pol_tm_ex = jnp.where(eval_deny, pp_ex_d, pp_ex_p)
    pol_tm_rg = jnp.where(eval_deny, pp_rg_d, pp_rg_p)
    pol_gate = ~c["pol_has_target"] | jnp.where(exact[:, None], pol_tm_ex, pol_tm_rg)
    return pol_gate & c["pol_valid"]


def _policy_gates(c: dict, r: dict, m: dict):
    """Stage D: set-level exact match, carried policyEffect, multi-entity
    recheck and the policy/set gates (reference: accessController.ts
    :130-195, 429-463); shared by both kernels."""
    tm_ex_p, tm_ex_d = m["tm_ex_p"], m["tm_ex_d"]
    tm_rg_p, tm_rg_d = m["tm_rg_p"], m["tm_rg_d"]
    hr_pass = m["hr_pass"]

    def gather_t(table, idx):
        return jnp.take(table, idx, axis=0)

    pt = c["pol_target"]
    multi_ok = _multi_entity_ok(c, r["r_ent_vals"], r["r_ent_valid"])
    multi_gate = jnp.where(r["r_n_entity_attrs"] > 1, multi_ok, True)
    pol_gate = _policy_gates_core(
        c,
        gather_t(tm_ex_p, pt), gather_t(tm_ex_d, pt),
        gather_t(tm_rg_p, pt), gather_t(tm_rg_d, pt),
        multi_gate,
    )

    # set gate: exact mode only, PERMIT variant (reference: :131-134)
    set_gate = ~c["set_has_target"] | gather_t(tm_ex_p, c["set_target"])
    set_gate = set_gate & c["set_valid"]  # [S]

    pol_subject = ~c["pol_has_subjects"] | gather_t(hr_pass, pt)  # [S, KP]
    return pol_gate, set_gate, pol_subject


def _combine_and_decide_flat(c: dict, reached, acl_rule, has_cond, cond_t,
                             cond_a, cond_c, pol_gate, set_gate,
                             pol_subject=None, explain: bool = False):
    """Flat-rule-axis variant of _combine_and_decide for the signature
    kernel: inputs arrive as [S, KP*KR] planes and the per-policy KR
    reductions run as reduce_windows, so batched callers avoid
    [B, S, KP, KR] intermediates whose tiny trailing dim pads to the
    TPU's 128-lane tile (8x memory at KR=16).  Flat positions preserve
    the original (set, policy, rule) ordering, so first/last semantics
    and the abort's flat-order selection are unchanged.

    ``explain=True`` appends a 4th int32 output encoding the deciding
    node: ``(flat_pos << 2) | kind`` with kind 0 = no contribution,
    1 = rule at flat pos (s*KP + kp)*KR + kr, 2 = no-rules policy at
    pos s*KP + kp, 3 = condition abort at the rule's flat pos.  When the
    caller compacted the rule axis (ops/prefilter.compact_rules) it
    supplies ``c["rule_orig_flat"]`` mapping compacted slots back to
    original flat positions; the flag is Python-level, so the False
    trace is exactly the pre-explain computation."""
    S, KP, KR = c["rule_effect"].shape
    M = KP * KR
    re_f = c["rule_effect"].reshape(S, M)
    cach_eff_f = c["rule_cacheable_eff"].reshape(S, M)
    cach_raw_f = c["rule_cacheable_raw"].reshape(S, M)

    scope = set_gate[:, None] & pol_gate          # [S, KP]
    scope_f = jnp.repeat(scope, KR, axis=1)       # [S, M]
    abort_rule = reached & has_cond & cond_a & scope_f
    matches = reached & (~has_cond | cond_t) & ~(has_cond & cond_a) & acl_rule
    coll = matches & scope_f
    if pol_subject is not None:  # policy-subject HR gate (reference :188-195)
        coll = coll & jnp.repeat(pol_subject, KR, axis=1)

    m_pos = jnp.broadcast_to(
        jnp.arange(M, dtype=jnp.int32)[None, :], (S, M)
    )
    code_f = pack_rule_key(m_pos, re_f, cach_eff_f)

    def win_min(x):
        return jax.lax.reduce_window(
            x, jnp.int32(BIG), jax.lax.min, (1, KR), (1, KR), "VALID"
        )

    def win_max(x):
        return jax.lax.reduce_window(
            x, jnp.int32(-1), jax.lax.max, (1, KR), (1, KR), "VALID"
        )

    first_deny = win_min(jnp.where(coll & (re_f == 2), code_f, BIG))
    first_permit = win_min(jnp.where(coll & (re_f == 1), code_f, BIG))
    first_coll = win_min(jnp.where(coll, code_f, BIG))
    last_coll = win_max(jnp.where(coll, code_f, -1))
    any_coll = win_max(coll.astype(jnp.int32)) > 0

    sel_do = jnp.where(first_deny < BIG, first_deny, last_coll)
    sel_po = jnp.where(first_permit < BIG, first_permit, last_coll)
    sel = jnp.select(
        [c["pol_ca"] == 0, c["pol_ca"] == 1, c["pol_ca"] == 2],
        [sel_do, sel_po, first_coll],
        default=jnp.zeros_like(sel_do),
    )
    rule_eff_sel, rule_cach_sel = unpack_rule_key(sel)

    no_rules_contrib = (
        c["pol_valid"]
        & set_gate[:, None]
        & pol_gate
        & (c["pol_n_rules"] == 0)
        & (c["pol_effect"] > 0)
    )
    contrib_present = no_rules_contrib | any_coll
    contrib_eff = jnp.where(no_rules_contrib, c["pol_effect"], rule_eff_sel)
    contrib_cach = jnp.where(
        no_rules_contrib, c["pol_cacheable"], rule_cach_sel
    )
    if explain:
        decision, cacheable, win_s, have, s_sel_c = _combine_sets(
            c, contrib_present, contrib_eff, contrib_cach, explain=True
        )
    else:
        decision, cacheable = _combine_sets(
            c, contrib_present, contrib_eff, contrib_cach
        )
    status = jnp.int32(200)

    # condition aborts preempt everything, first in flat rule order
    # (s*M + m == s*(KP*KR) + kp*KR + kr: identical to the 3-D variant)
    flat_order = jnp.arange(S * M, dtype=jnp.int32).reshape(S, M)
    abort_pos = jnp.min(jnp.where(abort_rule, flat_order, BIG))
    has_abort = abort_pos < BIG
    abort_flat = jnp.clip(abort_pos, 0, S * M - 1)
    abort_code = jnp.take(cond_c.reshape(-1), abort_flat)
    abort_cach = jnp.take(cach_raw_f.reshape(-1), abort_flat).astype(
        jnp.int32
    )
    decision = jnp.where(has_abort, 2, decision)
    cacheable = jnp.where(has_abort, abort_cach, cacheable)
    status = jnp.where(has_abort, abort_code, status)
    if not explain:
        return decision.astype(jnp.int32), cacheable, status.astype(jnp.int32)

    # ------------------------------------------------- explain recovery
    # the winner's packed key already carries its flat position in the
    # high bits (pack_rule_key); re-derive (set, policy, rule) from the
    # same selections the decision used, so provenance is the decision's
    # by construction
    win_kp = jnp.take(s_sel_c, win_s)
    win_flat = win_s * KP + win_kp
    win_m = jnp.take(sel.reshape(-1), win_flat) >> 3
    no_rules_win = jnp.take(no_rules_contrib.reshape(-1), win_flat)
    orig = c.get("rule_orig_flat")
    if orig is None:
        rule_pos = win_s * M + win_m
        abort_orig = abort_flat
    else:
        orig_f = orig.reshape(-1)
        rule_pos = jnp.take(orig_f, jnp.clip(win_s * M + win_m, 0, S * M - 1))
        abort_orig = jnp.take(orig_f, abort_flat)
    expl = jnp.where(
        have,
        jnp.where(no_rules_win, (win_flat << 2) | 2, (rule_pos << 2) | 1),
        0,
    )
    expl = jnp.where(has_abort, (abort_orig << 2) | 3, expl)
    return (decision.astype(jnp.int32), cacheable, status.astype(jnp.int32),
            expl.astype(jnp.int32))


def _combine_sets(c: dict, contrib_present, contrib_eff, contrib_cach,
                  explain: bool = False):
    """Stages F-G (pre-abort): policy-effect combination per set and the
    last-set-wins decision; shared by both kernels.  ``explain=True``
    additionally returns the winning set slot, whether any set
    contributed, and the per-set selected policy slot — the coordinates
    explain recovery re-derives provenance from."""
    if explain:
        set_eff, set_cach, set_any, s_sel_c = _per_set_effects(
            c, contrib_present, contrib_eff, contrib_cach, explain=True
        )
    else:
        set_eff, set_cach, set_any = _per_set_effects(
            c, contrib_present, contrib_eff, contrib_cach
        )

    # last-set-wins (reference: :293-295); effect present but neither
    # PERMIT nor DENY folds to INDETERMINATE with the winning cacheable
    # (reference: :312-318)
    S = set_eff.shape[0]
    s_pos = jnp.arange(S)
    winner = jnp.max(jnp.where(set_any, s_pos, -1))
    have = winner >= 0
    winner_c = jnp.clip(winner, 0, S - 1)
    decision = jnp.where(have, jnp.take(set_eff, winner_c), 0)
    cacheable = jnp.where(
        have, jnp.take(set_cach, winner_c).astype(jnp.int32), -1
    )
    if explain:
        return decision, cacheable, winner_c, have, s_sel_c
    return decision, cacheable


def _per_set_effects(c: dict, contrib_present, contrib_eff, contrib_cach,
                     explain: bool = False):
    """Stage F alone: combine each set's policy contributions under its
    combining algorithm, returning per-set ``(set_eff, set_cach, set_any)``
    WITHOUT the last-set-wins tail.  Split out so the pod-sharded kernel
    (parallel/pod_shard.py) can run it shard-locally — whole sets live on
    one shard — and merge the per-set results across shards with a packed
    positional pmax instead of the local winner scan."""
    KP = contrib_present.shape[1]
    kp_pos2 = jnp.arange(KP)[None, :]
    p_first_deny = jnp.min(
        jnp.where(contrib_present & (contrib_eff == 2), kp_pos2, BIG), axis=1
    )
    p_first_permit = jnp.min(
        jnp.where(contrib_present & (contrib_eff == 1), kp_pos2, BIG), axis=1
    )
    p_first = jnp.min(jnp.where(contrib_present, kp_pos2, BIG), axis=1)
    p_last = jnp.max(jnp.where(contrib_present, kp_pos2, -1), axis=1)
    set_any = contrib_present.any(axis=1)

    s_sel_do = jnp.where(p_first_deny < BIG, p_first_deny, p_last)
    s_sel_po = jnp.where(p_first_permit < BIG, p_first_permit, p_last)
    s_sel = jnp.select(
        [c["set_ca"] == 0, c["set_ca"] == 1, c["set_ca"] == 2],
        [s_sel_do, s_sel_po, p_first],
        default=jnp.zeros_like(s_sel_do),
    )
    s_sel_c = jnp.clip(s_sel, 0, KP - 1)
    set_eff = jnp.take_along_axis(contrib_eff, s_sel_c[:, None], axis=1)[:, 0]
    set_cach = jnp.take_along_axis(contrib_cach, s_sel_c[:, None], axis=1)[:, 0]
    if explain:
        return set_eff, set_cach, set_any, s_sel_c
    return set_eff, set_cach, set_any


def _evaluate_one(c: dict, r: dict, with_acl: bool = True,
                  with_hr: bool = True, explain: bool = False,
                  with_rel: bool = False):
    """Decision for a single encoded request; vmapped over the batch.

    ``c``: compiled policy arrays (replicated across devices).
    ``r``: per-request encoded arrays.
    ``with_acl``: compile the full verifyACL stage (exact when ACL pairs
    are present; batches without pairs may use the cheaper False variant).
    ``with_hr``: compile stage B (exact when some target row carries both
    subjects and a scoping entity; see _match_targets).
    Returns (decision, cacheable, status_code) int32 scalars where
    decision: 0=INDETERMINATE 1=PERMIT 2=DENY; cacheable: -1 none 0/1 bool;
    ``explain=True`` appends the packed provenance code (see
    _combine_and_decide).
    """
    m = _match_targets(c, r, with_hr, with_rel=with_rel)
    return _evaluate_from_matches(c, r, m, with_acl, explain=explain)


def _evaluate_from_matches(c: dict, r: dict, m: dict, with_acl: bool = True,
                           explain: bool = False):
    """Stages C-G given the stage-A/B match vectors ``m``: rule
    reachability, policy/set gates, combining, aborts.  Shared by the full
    kernel (m from _match_targets) and the signature-bit kernel (m rebuilt
    from precomputed per-signature planes + the per-row subject fold)."""
    reached, acl_rule, has_cond, cond_t, cond_a, cond_c = _rule_predicates(
        c, r, m, with_acl
    )
    pol_gate, set_gate, pol_subject = _policy_gates(c, r, m)
    return _combine_and_decide(
        c, reached, acl_rule, has_cond, cond_t, cond_a, cond_c,
        pol_gate, set_gate, pol_subject, explain=explain,
    )


def _policy_contributions(c: dict, reached, acl_rule, has_cond, cond_t,
                          cond_a, pol_gate, set_gate, pol_subject,
                          explain: bool = False):
    """Stage E alone: per-policy winning-rule contributions plus the
    abort-rule mask.  Split out of _combine_and_decide so the pod-sharded
    kernel (parallel/pod_shard.py) can run stages A-F shard-locally —
    whole sets live on one shard — before its cross-shard collectives.
    ``explain=True`` additionally returns the per-policy selected rule
    slot and the no-rules-contribution mask for provenance recovery."""
    scope = set_gate[:, None, None] & pol_gate[:, :, None]
    abort_rule = reached & has_cond & cond_a & scope
    matches = reached & (~has_cond | cond_t) & ~(has_cond & cond_a) & acl_rule
    coll = matches & pol_subject[:, :, None] & scope  # [S, KP, KR]

    KR = coll.shape[2]
    kr_pos = jnp.arange(KR)[None, None, :]
    first_deny = jnp.min(
        jnp.where(coll & (c["rule_effect"] == 2), kr_pos, BIG), axis=2
    )
    first_permit = jnp.min(
        jnp.where(coll & (c["rule_effect"] == 1), kr_pos, BIG), axis=2
    )
    first_coll = jnp.min(jnp.where(coll, kr_pos, BIG), axis=2)
    last_coll = jnp.max(jnp.where(coll, kr_pos, -1), axis=2)
    any_coll = coll.any(axis=2)

    sel_do = jnp.where(first_deny < BIG, first_deny, last_coll)
    sel_po = jnp.where(first_permit < BIG, first_permit, last_coll)
    sel = jnp.select(
        [c["pol_ca"] == 0, c["pol_ca"] == 1, c["pol_ca"] == 2],
        [sel_do, sel_po, first_coll],
        default=jnp.zeros_like(sel_do),
    )
    sel_c = jnp.clip(sel, 0, KR - 1)
    rule_eff_sel = jnp.take_along_axis(c["rule_effect"], sel_c[:, :, None], axis=2)[
        :, :, 0
    ]
    rule_cach_sel = jnp.take_along_axis(
        c["rule_cacheable_eff"], sel_c[:, :, None], axis=2
    )[:, :, 0]

    no_rules_contrib = (
        c["pol_valid"]
        & set_gate[:, None]
        & pol_gate
        & (c["pol_n_rules"] == 0)
        & (c["pol_effect"] > 0)
    )
    contrib_present = no_rules_contrib | any_coll
    contrib_eff = jnp.where(no_rules_contrib, c["pol_effect"], rule_eff_sel)
    contrib_cach = jnp.where(no_rules_contrib, c["pol_cacheable"], rule_cach_sel)
    if explain:
        return (contrib_present, contrib_eff, contrib_cach, abort_rule,
                sel_c, no_rules_contrib)
    return contrib_present, contrib_eff, contrib_cach, abort_rule


def _combine_and_decide(c: dict, reached, acl_rule, has_cond, cond_t,
                        cond_a, cond_c, pol_gate, set_gate, pol_subject,
                        explain: bool = False):
    """Stages E-G: rule-effect combination per policy, policy-effect
    combination per set, last-set-wins decision and condition aborts —
    shared tail of every kernel variant.  ``explain=True`` appends a 4th
    int32 output ``(flat_pos << 2) | kind`` (see _combine_and_decide_flat)
    recovered from the same positional selections the decision used."""
    # -------------------------------------------------- E: combine rule effects
    if explain:
        (contrib_present, contrib_eff, contrib_cach, abort_rule,
         sel_c, no_rules_contrib) = _policy_contributions(
            c, reached, acl_rule, has_cond, cond_t, cond_a,
            pol_gate, set_gate, pol_subject, explain=True,
        )
    else:
        contrib_present, contrib_eff, contrib_cach, abort_rule = (
            _policy_contributions(
                c, reached, acl_rule, has_cond, cond_t, cond_a,
                pol_gate, set_gate, pol_subject,
            )
        )

    # --------------------------------------- F-G: combine + last-set-wins
    if explain:
        decision, cacheable, win_s, have, s_sel_c = _combine_sets(
            c, contrib_present, contrib_eff, contrib_cach, explain=True
        )
    else:
        decision, cacheable = _combine_sets(
            c, contrib_present, contrib_eff, contrib_cach
        )
    status = jnp.int32(200)

    # condition aborts preempt everything, first in flat rule order
    S, KP, KR = abort_rule.shape
    flat_order = (
        jnp.arange(S)[:, None, None] * (KP * KR)
        + jnp.arange(KP)[None, :, None] * KR
        + jnp.arange(KR)[None, None, :]
    )
    abort_pos = jnp.min(jnp.where(abort_rule, flat_order, BIG))
    has_abort = abort_pos < BIG
    # gather the aborting rule's condition code and raw cacheable
    abort_flat = jnp.clip(abort_pos, 0, abort_rule.size - 1)
    cond_c_flat = cond_c.reshape(-1)
    cach_raw_flat = c["rule_cacheable_raw"].reshape(-1)
    abort_code = jnp.take(cond_c_flat, abort_flat)
    abort_cach = jnp.take(cach_raw_flat, abort_flat).astype(jnp.int32)

    decision = jnp.where(has_abort, 2, decision)
    cacheable = jnp.where(has_abort, abort_cach, cacheable)
    status = jnp.where(has_abort, abort_code, status)

    if not explain:
        return decision.astype(jnp.int32), cacheable, status.astype(jnp.int32)

    # ------------------------------------------------- explain recovery
    win_kp = jnp.take(s_sel_c, win_s)
    win_flat = win_s * KP + win_kp
    win_kr = jnp.take(sel_c.reshape(-1), win_flat)
    no_rules_win = jnp.take(no_rules_contrib.reshape(-1), win_flat)
    orig = c.get("rule_orig_flat")
    if orig is None:
        rule_pos = win_flat * KR + win_kr
        abort_orig = abort_flat
    else:
        orig_f = orig.reshape(-1)
        rule_pos = jnp.take(orig_f, win_flat * KR + win_kr)
        abort_orig = jnp.take(orig_f, abort_flat)
    expl = jnp.where(
        have,
        jnp.where(no_rules_win, (win_flat << 2) | 2, (rule_pos << 2) | 1),
        0,
    )
    expl = jnp.where(has_abort, (abort_orig << 2) | 3, expl)
    return (decision.astype(jnp.int32), cacheable, status.astype(jnp.int32),
            expl.astype(jnp.int32))


class DecisionKernel:
    """Compiled-policy decision kernel with a jitted vmapped evaluate.

    ``dynamic_policies=True`` (the hot-update mode, ops/delta.py) forces
    the policy tables to enter jit as ARGUMENTS — never baked as XLA
    constants — and registers the jitted callables in ``shared_jits`` so a
    swapped-in kernel over patched tables with identical shapes reuses the
    existing executables: an in-capacity policy mutation then costs zero
    new XLA compilations."""

    def __init__(self, compiled: CompiledPolicies,
                 dynamic_policies: bool = False,
                 shared_jits: Optional[dict] = None,
                 explain: bool = False):
        if not compiled.supported:
            raise ValueError(
                f"policy tree unsupported by kernel: {compiled.unsupported_reason}"
            )
        self.compiled = compiled
        self.dynamic_policies = dynamic_policies
        # explain mode (docs/EXPLAIN.md): a 4th packed-provenance output
        # per row.  The flag is part of the shared-jit key, so explain-off
        # kernels keep their pre-explain executables byte-identical.
        self.explain = bool(explain)
        # (KP, KR) strides of the packed explain positions — the host
        # decoder (srv/explain.py) maps flat positions back to tree slots
        self.explain_strides = (compiled.KP, compiled.KR)
        self._shared = shared_jits if shared_jits is not None else {}
        # hrv_role/hrv_scope stay host-side (encode's owner-bit packer
        # consumes them; the device programs read only packed bitplanes).
        # t_rel_path/relv_path likewise: the relation packer and the store
        # consume them, the kernel reads only t_rel_idx + packed planes.
        self._c = {
            k: jnp.asarray(v) for k, v in compiled.arrays.items()
            if k not in ("hrv_role", "hrv_scope", "t_rel_path", "relv_path")
        }
        self._bake_constants = (
            not dynamic_policies and bake_policy_constants(compiled)
        )
        with_hr = tree_needs_hr(compiled.arrays)
        with_rel = tree_needs_rel(compiled.arrays)

        def make_run(with_acl: bool):
            key = ("dense", with_acl, with_hr, with_rel)
            if explain:
                key = key + ("explain",)
            if dynamic_policies and key in self._shared:
                jitted = self._shared[key]
                return lambda *args: jitted(self._c, *args)

            def run(c, batch_arrays, rgx_set, pfx_neq,
                    cond_true, cond_abort, cond_code):
                # vmap over the leading batch axis of request arrays; regex
                # matrices and compiled arrays are broadcast
                in_axes = ({k: 0 for k in batch_arrays}, None, None, 0, 0, 0)

                def one(ra, rs, pn, ct, ca, cc):
                    rr = {**ra, "rgx_set": rs, "pfx_neq": pn,
                          "cond_true": ct, "cond_abort": ca, "cond_code": cc}
                    return _evaluate_one(c, rr, with_acl, with_hr,
                                         explain=explain, with_rel=with_rel)

                return jax.vmap(one, in_axes=in_axes)(
                    batch_arrays, rgx_set, pfx_neq,
                    cond_true.T, cond_abort.T, cond_code.T,
                )

            if self._bake_constants:
                return jax.jit(partial(run, self._c))
            jitted = jax.jit(run)
            if dynamic_policies:
                self._shared[key] = jitted
            return lambda *args: jitted(self._c, *args)

        # two compiled variants: batches without ACL pairs (the common
        # serving mix) skip the create-path scan entirely; the entry
        # dispatches on the batch's actual content
        self._run_noacl = make_run(False)
        self._run_acl = make_run(True)
        self._run = self._run_noacl

    def evaluate(self, batch: RequestBatch):
        """Returns (decision, cacheable, status) numpy arrays [B]."""
        return self.evaluate_async(batch)()

    def evaluate_async(self, batch: RequestBatch):
        """Host prep + device dispatch WITHOUT blocking on the result;
        returns a zero-arg callable that materializes the (decision,
        cacheable, status) tuple — the dense-kernel leg of the depth-N
        serving pipeline (srv/batcher.py overlaps the next batch's prep
        with this batch's device execution).

        The batch axis is padded to a power-of-two bucket before entering
        jit: without bucketing every distinct batch size is a fresh XLA
        compile, which would stall a micro-batched serving path on nearly
        every call (the distinct-entity axis of the regex matrices is
        bucketed for the same reason)."""
        # failpoints (srv/faults.py): host-side only — fired before the
        # jitted call / inside the materialize thunk, so the lowered
        # device program is byte-identical with faults configured
        # (tpu_compat_audit.py failpoints-zero-device-ops)
        from ..srv.faults import REGISTRY as _faults

        _faults.fire("device.dispatch")
        b, bucket, e_bucket, pad_lead = lead_padding(batch)

        # dispatch on ACL content: only batches actually carrying ACL
        # pairs pay for the tensorized verifyACL create-scan (the no-pair
        # variant is exact for everything else, incl. short==1/2 rows)
        run = (
            self._run_acl
            if bool((np.asarray(batch.arrays["r_acl_ent"]) >= 0).any())
            else self._run_noacl
        )
        out = run(
            {k: jnp.asarray(pad_lead(v)) for k, v in batch.arrays.items()},
            jnp.asarray(pad_cols(batch.rgx_set, e_bucket)),
            jnp.asarray(pad_cols(batch.pfx_neq, e_bucket)),
            jnp.asarray(pad_cols(batch.cond_true, bucket)),
            jnp.asarray(pad_cols(batch.cond_abort, bucket)),
            jnp.asarray(pad_cols(batch.cond_code, bucket)),
        )
        def materialize():
            # hang here models a wedged D2H fetch — the watchdog
            # (srv/watchdog.py) bounds it on the serving pipeline
            _faults.fire("device.materialize")
            return tuple(np.asarray(x)[:b] for x in out)

        return materialize
