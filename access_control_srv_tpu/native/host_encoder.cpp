// Native host-side request encoder: serialized wire batches -> dense
// int32/bool kernel rows, bit-identical to the Python encoder
// (access_control_srv_tpu/ops/encode.py).
//
// This is the framework's native runtime component: the TPU kernel
// evaluates ~10M decisions/s, but the serving path was bounded by the
// per-request Python encode (~8us/req).  This library parses the
// protobuf wire bytes (acstpu.Request, proto/access_control.proto) and
// the JSON context payloads directly and fills the numpy row buffers in
// one pass.  The reference has no native code anywhere (SURVEY.md §2);
// this component exists for the new framework's own serving throughput.
//
// Semantics transcribed from ops/encode.py (which in turn cites
// reference/src/core/accessController.ts); every eligibility early-exit
// and partial-fill point is replicated in the same order so the
// differential test can require array equality, not just decision
// equality.
//
// Build: g++ -O2 -shared -fPIC -std=c++17 host_encoder.cpp -o libacs_host.so

#include <algorithm>
#include <array>
#include <cstdint>
#include <cstring>
#include <deque>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

namespace {

constexpr int32_t ABSENT = -1;
// padding caps: runtime parameters of acs_enc_batch (13 int32s in the
// order of ops/encode._CAPS_FLOOR); null means the floor defaults below.
// The serving path encodes at the floor first and re-encodes over-cap
// rows (flagged via the overcap output) at the ceiling shapes, so deep-HR
// traffic stays on the native fast path instead of falling to the oracle.
struct Caps {
  int NR = 4, NI = 4, NP = 8, NSUB = 8, NACT = 4, NOP = 2;
  int NOWN = 4, NRA = 8, NHR = 32, NROLE = 4;
  int NACLE = 4, NACLI = 8, NHRR = 8;
};

// ------------------------------------------------------------- interner

struct Interner {
  // deque: element addresses are stable across growth, so the
  // string_view map keys below stay valid
  std::deque<std::string> strings;
  std::vector<int32_t> suffix_id, tail_id, prefix_id;
  std::unordered_map<std::string_view, int32_t> ids;

  int32_t intern(std::string_view v) {
    auto hit = ids.find(v);
    if (hit != ids.end()) return hit->second;
    int32_t idx = (int32_t)strings.size();
    strings.emplace_back(v);
    // reserve derived slots first (intern below may recurse and grow)
    suffix_id.push_back(ABSENT);
    tail_id.push_back(ABSENT);
    prefix_id.push_back(ABSENT);
    ids.emplace(std::string_view(strings.back()), idx);
    const std::string& s = strings[idx];
    // suffix: after last '#'; tail: after last ':'; prefix: before last ':'
    size_t hash_pos = s.rfind('#');
    std::string suffix = hash_pos == std::string::npos ? s : s.substr(hash_pos + 1);
    size_t colon_pos = s.rfind(':');
    std::string tail = colon_pos == std::string::npos ? s : s.substr(colon_pos + 1);
    std::string prefix = colon_pos == std::string::npos ? std::string() : s.substr(0, colon_pos);
    suffix_id[idx] = suffix == s ? idx : intern(suffix);
    tail_id[idx] = tail == s ? idx : intern(tail);
    prefix_id[idx] = prefix == s ? idx : intern(prefix);
    return idx;
  }
};

// --------------------------------------------------------- JSON parsing
// Minimal JSON DOM sufficient for the context payloads (objects, arrays,
// strings, numbers, true/false/null).  Parse failures yield Null.

struct JValue;
using JArray = std::vector<JValue>;
using JObject = std::vector<std::pair<std::string, JValue>>;

struct JValue {
  enum Kind { Null, Bool, Num, Str, Arr, Obj } kind = Null;
  bool b = false;
  double num = 0;
  std::string str;
  std::vector<JValue> arr;
  std::vector<std::pair<std::string, JValue>> obj;

  const JValue* get(std::string_view key) const {
    // LAST match wins, matching python dict semantics for duplicate JSON
    // keys (json.loads keeps the final occurrence)
    if (kind != Obj) return nullptr;
    const JValue* found = nullptr;
    for (auto& kv : obj)
      if (kv.first == key) found = &kv.second;
    return found;
  }
  bool truthy() const {
    switch (kind) {
      case Null: return false;
      case Bool: return b;
      case Num: return num != 0;
      case Str: return !str.empty();
      case Arr: return !arr.empty();
      case Obj: return !obj.empty();
    }
    return false;
  }
};

struct JsonParser {
  const char* p;
  const char* end;
  bool ok = true;
  int depth = 0;
  // gRPC payloads are attacker-controlled: without a cap, one nested
  // object/array per stack frame overflows the C stack well under the
  // message size limit. Past the cap the row goes ineligible and is served
  // by the (recursion-safe) Python fallback. The cap also bounds JValue
  // destructor recursion, since the DOM can never get deeper than this.
  static constexpr int kMaxDepth = 64;

  explicit JsonParser(std::string_view s) : p(s.data()), end(s.data() + s.size()) {}

  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p;
  }
  bool lit(const char* s, size_t n) {
    if ((size_t)(end - p) < n || memcmp(p, s, n) != 0) return false;
    p += n;
    return true;
  }
  JValue parse() {
    skip_ws();
    JValue v = parse_value();
    skip_ws();
    if (p != end) ok = false;  // trailing garbage: json.loads raises
    return v;
  }
  JValue parse_value() {
    skip_ws();
    JValue v;
    if (p >= end) { ok = false; return v; }
    char c = *p;
    if (c == '{') {
      if (++depth > kMaxDepth) { ok = false; return v; }
      ++p;
      v.kind = JValue::Obj;
      skip_ws();
      if (p < end && *p == '}') { ++p; --depth; return v; }
      while (ok) {
        skip_ws();
        if (p >= end || *p != '"') { ok = false; break; }
        std::string key = parse_string_raw();
        skip_ws();
        if (p >= end || *p != ':') { ok = false; break; }
        ++p;
        v.obj.emplace_back(std::move(key), parse_value());
        skip_ws();
        if (p < end && *p == ',') { ++p; continue; }
        if (p < end && *p == '}') { ++p; break; }
        ok = false;
      }
      --depth;
    } else if (c == '[') {
      if (++depth > kMaxDepth) { ok = false; return v; }
      ++p;
      v.kind = JValue::Arr;
      skip_ws();
      if (p < end && *p == ']') { ++p; --depth; return v; }
      while (ok) {
        v.arr.push_back(parse_value());
        skip_ws();
        if (p < end && *p == ',') { ++p; continue; }
        if (p < end && *p == ']') { ++p; break; }
        ok = false;
      }
      --depth;
    } else if (c == '"') {
      v.kind = JValue::Str;
      v.str = parse_string_raw();
    } else if (c == 't') {
      if (lit("true", 4)) { v.kind = JValue::Bool; v.b = true; } else ok = false;
    } else if (c == 'f') {
      if (lit("false", 5)) { v.kind = JValue::Bool; v.b = false; } else ok = false;
    } else if (c == 'n') {
      if (!lit("null", 4)) ok = false;
    } else {
      // number, RFC 8259 grammar: -?(0|[1-9][0-9]*)(\.[0-9]+)?([eE][+-]?[0-9]+)?
      const char* start = p;
      if (p < end && *p == '-') ++p;
      if (p < end && *p == '0') {
        ++p;
      } else if (p < end && *p >= '1' && *p <= '9') {
        while (p < end && *p >= '0' && *p <= '9') ++p;
      } else {
        ok = false;
        return v;
      }
      if (p < end && *p == '.') {
        ++p;
        if (p >= end || *p < '0' || *p > '9') { ok = false; return v; }
        while (p < end && *p >= '0' && *p <= '9') ++p;
      }
      if (p < end && (*p == 'e' || *p == 'E')) {
        ++p;
        if (p < end && (*p == '+' || *p == '-')) ++p;
        if (p >= end || *p < '0' || *p > '9') { ok = false; return v; }
        while (p < end && *p >= '0' && *p <= '9') ++p;
      }
      v.kind = JValue::Num;
      v.num = strtod(std::string(start, p - start).c_str(), nullptr);
    }
    return v;
  }
  std::string parse_string_raw() {
    // assumes *p == '"'. Strict: any input json.loads would reject
    // (unterminated string, unknown or truncated escape, non-hex \uXXXX,
    // raw control character) sets ok=false so the row falls back to the
    // Python path instead of serving a decision computed from a misparse.
    ++p;
    std::string out;
    while (p < end && *p != '"') {
      if (*p == '\\') {
        if (p + 1 >= end) { ok = false; return out; }  // truncated escape
        ++p;
        switch (*p) {
          case '"': out.push_back('"'); break;
          case '\\': out.push_back('\\'); break;
          case '/': out.push_back('/'); break;
          case 'n': out.push_back('\n'); break;
          case 't': out.push_back('\t'); break;
          case 'r': out.push_back('\r'); break;
          case 'b': out.push_back('\b'); break;
          case 'f': out.push_back('\f'); break;
          case 'u': {
            // \uXXXX -> UTF-8 (no surrogate-pair handling; URNs are ASCII)
            if (end - p < 5) { ok = false; return out; }
            unsigned code = 0;
            for (int i = 1; i <= 4; ++i) {
              char h = p[i];
              code <<= 4;
              if (h >= '0' && h <= '9') code |= h - '0';
              else if (h >= 'a' && h <= 'f') code |= h - 'a' + 10;
              else if (h >= 'A' && h <= 'F') code |= h - 'A' + 10;
              else { ok = false; return out; }  // non-hex digit
            }
            p += 4;
            if (code >= 0xD800 && code <= 0xDFFF) {
              // surrogate range: json.loads decodes pairs (and even lone
              // surrogates) with semantics this 3-byte encoder does not
              // reproduce — fall back rather than serve from a misparse
              ok = false;
              return out;
            }
            if (code < 0x80) out.push_back((char)code);
            else if (code < 0x800) {
              out.push_back((char)(0xC0 | (code >> 6)));
              out.push_back((char)(0x80 | (code & 0x3F)));
            } else {
              out.push_back((char)(0xE0 | (code >> 12)));
              out.push_back((char)(0x80 | ((code >> 6) & 0x3F)));
              out.push_back((char)(0x80 | (code & 0x3F)));
            }
            break;
          }
          default: ok = false; return out;  // unknown escape
        }
        ++p;
      } else if ((unsigned char)*p < 0x20) {
        ok = false;  // raw control character: json.loads rejects
        return out;
      } else {
        out.push_back(*p);
        ++p;
      }
    }
    if (p >= end) { ok = false; return out; }  // unterminated string
    ++p;  // closing quote
    return out;
  }
};

// ----------------------------------------------------- protobuf parsing
// Hand-rolled reader for the fixed schema in proto/access_control.proto.

struct PbReader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  PbReader(const uint8_t* data, size_t n) : p(data), end(data + n) {}

  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (p < end && shift < 64) {
      uint8_t b = *p++;
      v |= (uint64_t)(b & 0x7F) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
    }
    ok = false;
    return 0;
  }
  std::string_view len_delim() {
    uint64_t n = varint();
    if (!ok || (uint64_t)(end - p) < n) { ok = false; return {}; }
    std::string_view out((const char*)p, n);
    p += n;
    return out;
  }
  // returns field number, sets wire type; 0 on end
  uint32_t field(uint32_t* wire_type) {
    if (p >= end) return 0;
    uint64_t key = varint();
    if (!ok) return 0;
    *wire_type = key & 7;
    return (uint32_t)(key >> 3);
  }
  void skip(uint32_t wire_type) {
    switch (wire_type) {
      case 0: varint(); break;
      case 1: p += 8; break;
      case 2: len_delim(); break;
      case 5: p += 4; break;
      default: ok = false;
    }
    if (p > end) ok = false;
  }
};

struct Attr {
  std::string_view id;
  std::string_view value;
  std::vector<Attr> attributes;
};

Attr parse_attribute(std::string_view bytes, bool* ok) {
  Attr a;
  PbReader r((const uint8_t*)bytes.data(), bytes.size());
  uint32_t wt;
  while (uint32_t f = r.field(&wt)) {
    if (f == 1 && wt == 2) a.id = r.len_delim();
    else if (f == 2 && wt == 2) a.value = r.len_delim();
    else if (f == 3 && wt == 2)
      a.attributes.push_back(parse_attribute(r.len_delim(), ok));
    else r.skip(wt);
    if (!r.ok) break;
  }
  if (!r.ok) *ok = false;
  return a;
}

struct WireRequest {
  bool parse_ok = true;  // false -> the row must NOT be fabricated into a
                         // 200 decision; it stays on the fallback path
  bool has_target = false;
  bool has_context = false;
  std::vector<Attr> subjects, resources, actions;
  std::string_view subject_json;   // ContextValue.value of context.subject
  bool has_subject = false;
  std::vector<std::string_view> resource_jsons;
};

std::string_view parse_context_value(std::string_view bytes, bool* ok) {
  PbReader r((const uint8_t*)bytes.data(), bytes.size());
  uint32_t wt;
  std::string_view value;
  while (uint32_t f = r.field(&wt)) {
    if (f == 2 && wt == 2) value = r.len_delim();
    else r.skip(wt);
    if (!r.ok) break;
  }
  if (!r.ok) *ok = false;
  return value;
}

WireRequest parse_request(std::string_view bytes) {
  WireRequest req;
  PbReader r((const uint8_t*)bytes.data(), bytes.size());
  uint32_t wt;
  while (uint32_t f = r.field(&wt)) {
    if (f == 1 && wt == 2) {  // Target
      req.has_target = true;
      std::string_view tb = r.len_delim();
      PbReader tr((const uint8_t*)tb.data(), tb.size());
      uint32_t twt;
      while (uint32_t tf = tr.field(&twt)) {
        if (tf == 1 && twt == 2)
          req.subjects.push_back(parse_attribute(tr.len_delim(), &req.parse_ok));
        else if (tf == 2 && twt == 2)
          req.resources.push_back(parse_attribute(tr.len_delim(), &req.parse_ok));
        else if (tf == 3 && twt == 2)
          req.actions.push_back(parse_attribute(tr.len_delim(), &req.parse_ok));
        else tr.skip(twt);
        if (!tr.ok) break;
      }
      if (!tr.ok) req.parse_ok = false;
    } else if (f == 2 && wt == 2) {  // Context
      req.has_context = true;
      std::string_view cb = r.len_delim();
      PbReader cr((const uint8_t*)cb.data(), cb.size());
      uint32_t cwt;
      while (uint32_t cf = cr.field(&cwt)) {
        if (cf == 1 && cwt == 2) {
          req.has_subject = true;
          req.subject_json = parse_context_value(cr.len_delim(), &req.parse_ok);
        } else if (cf == 2 && cwt == 2) {
          req.resource_jsons.push_back(
              parse_context_value(cr.len_delim(), &req.parse_ok));
        } else cr.skip(cwt);
        if (!cr.ok) break;
      }
      if (!cr.ok) req.parse_ok = false;
    } else r.skip(wt);
    if (!r.ok) break;
  }
  if (!r.ok) req.parse_ok = false;
  return req;
}

// ------------------------------------------------------- encoder state

struct Encoder {
  Interner interner;
  // urn ids (into interner): see acs_enc_create for the order
  int32_t urn_entity, urn_property, urn_operation, urn_resource_id;
  int32_t urn_role, urn_scoping, urn_scoping_inst, urn_owner_ent, urn_owner_inst;
  // urn_action_id / crud mirror _URN_ORDER slots 9-13; currently unread
  // (the kernel derives action kind from acl_consts on device)
  int32_t urn_action_id;
  int32_t crud[4];
  int32_t urn_acl_ind, urn_acl_inst;
  bool tails_ambiguous = false;
  std::vector<std::string> vocab_tails;  // tail strings of entity vocab
  // relevance cache keyed by "<tail idx>\x1f<prop value>"
  std::unordered_map<std::string, bool> relevance_ok;
};

struct OutArrays {
  int32_t* r_sub_ids;        // [B, NSUB]
  int32_t* r_sub_vals;       // [B, NSUB]
  int32_t* r_roles;          // [B, NROLE]
  int32_t* r_act_ids;        // [B, NACT]
  int32_t* r_act_vals;       // [B, NACT]
  int32_t* r_ent_vals;       // [B, NR]
  int32_t* r_ent_e;          // [B, NR]
  uint8_t* r_ent_valid;      // [B, NR]
  int32_t* r_inst_run;       // [B, NI]
  int32_t* r_inst_id;        // [B, NI] interned instance-id strings
  uint8_t* r_inst_valid;     // [B, NI]
  uint8_t* r_inst_present;   // [B, NI]
  uint8_t* r_inst_has_owners;// [B, NI]
  int32_t* r_inst_owner_ent; // [B, NI, NOWN]
  int32_t* r_inst_owner_inst;// [B, NI, NOWN]
  int32_t* r_prop_vals;      // [B, NP]
  int32_t* r_prop_sfx;       // [B, NP]
  int32_t* r_prop_run;       // [B, NP]
  int32_t* r_prop_tail;      // [B, NP]
  int32_t* r_op_vals;        // [B, NOP]
  uint8_t* r_op_present;     // [B, NOP]
  uint8_t* r_op_has_owners;  // [B, NOP]
  int32_t* r_op_owner_ent;   // [B, NOP, NOWN]
  int32_t* r_op_owner_inst;  // [B, NOP, NOWN]
  int32_t* r_ra3;            // [B, NRA, 3]
  int32_t* r_ra2;            // [B, NRA, 2]
  int32_t* r_n_ra;           // [B]
  int32_t* r_hr;             // [B, NHR, 2]
  uint8_t* r_ctx_present;    // [B]
  int32_t* r_n_entity_attrs; // [B]
  uint8_t* r_has_props;      // [B]
  uint8_t* r_has_target;     // [B]
  int32_t* r_acl_short;      // [B] 0 pairs / 1 early all-clear / 2 malformed
  int32_t* r_acl_ent;        // [B, NACLE]
  int32_t* r_acl_inst;       // [B, NACLE, NACLI]
  int32_t* r_acl_hr;         // [B, NHR, 2] verifyACL flatten (role, org)
  int32_t* r_hr_roles;       // [B, NHRR] distinct verifyACL-flatten roles
  int32_t* r_subject_id;     // [B]
  uint8_t* eligible;         // [B]
  int32_t* batch_entities;   // [B * NR] distinct entity interner ids out
  uint8_t* overcap;          // [B] ineligible ONLY because a cap overflowed
};

// entity tail: URN segment after the last ':' -- the reference's
// entity_name in the property-relevance check (accessController.ts:515-516).
// Mirrors ops/encode.py:urn_tail and StringInterner.tail_id so r_prop_tail
// compares against the compiled table's t_ent_tails.
std::string entity_tail(const std::string& value) {
  size_t colon = value.rfind(':');
  return colon == std::string::npos ? value : value.substr(colon + 1);
}

const JValue* jget(const JValue* v, std::string_view key) {
  return v == nullptr ? nullptr : v->get(key);
}

std::string_view jstr(const JValue* v) {
  static const std::string empty;
  if (v == nullptr || v->kind != JValue::Str) return std::string_view();
  return v->str;
}

int32_t intern_jstr(Encoder& enc, const JValue* v) {
  if (v == nullptr || v->kind != JValue::Str) return ABSENT;  // intern(None)
  return enc.interner.intern(v->str);
}

// owners -> (entity, instance) pairs; false on NOWN overflow
// (mirrors encode.py:_encode_owners)
bool encode_owners(Encoder& enc, const JValue* owners, int32_t* ent_out,
                   int32_t* inst_out, int NOWN) {
  if (owners == nullptr || owners->kind != JValue::Arr) return true;
  int slot = 0;
  for (const JValue& owner : owners->arr) {
    const JValue* oid = owner.get("id");
    if (jstr(oid) != enc.interner.strings[enc.urn_owner_ent]) continue;
    int32_t val = intern_jstr(enc, owner.get("value"));
    const JValue* attrs = owner.get("attributes");
    if (attrs == nullptr || attrs->kind != JValue::Arr) continue;
    for (const JValue& inst_attr : attrs->arr) {
      if (jstr(inst_attr.get("id")) != enc.interner.strings[enc.urn_owner_inst])
        continue;
      if (slot >= NOWN) return false;
      ent_out[slot] = val;
      inst_out[slot] = intern_jstr(enc, inst_attr.get("value"));
      ++slot;
    }
  }
  return true;
}

// verifyACL's role->org flatten: true pre-order with per-node role
// override (mirrors encode.py:_flatten_acl_hr; reference:
// verifyACL.ts:119-129). Recursion depth is bounded by the JSON parser's
// depth cap. Dedups (role, org) pairs and records distinct non-null role
// keys in first-occurrence order (the create-path scan is order-sensitive).
void flatten_acl_hr(Encoder& enc, const JValue* nodes, bool has_role,
                    std::string_view role_sv,
                    std::vector<std::array<int32_t, 2>>& pairs,
                    std::vector<int32_t>& role_order) {
  if (nodes == nullptr || nodes->kind != JValue::Arr) return;
  for (const JValue& node : nodes->arr) {
    const JValue* role = node.get("role");
    bool node_has_role = has_role;
    std::string_view node_role = role_sv;
    if (role != nullptr && role->kind == JValue::Str) {
      node_has_role = true;
      node_role = role->str;
    }
    std::string_view node_id = jstr(node.get("id"));
    if (!node_id.empty()) {
      // intern ONLY when a pair is appended, role before org — the exact
      // interning order of the Python encoder, so lazily-assigned ids for
      // novel strings stay identical across both encoders
      int32_t rid = node_has_role ? enc.interner.intern(node_role) : ABSENT;
      std::array<int32_t, 2> entry = {rid, enc.interner.intern(node_id)};
      bool seen = false;
      for (auto& existing : pairs) seen |= existing == entry;
      if (!seen) pairs.push_back(entry);
      if (rid != ABSENT) {
        bool have = false;
        for (int32_t r : role_order) have |= r == rid;
        if (!have) role_order.push_back(rid);
      }
    }
    flatten_acl_hr(enc, node.get("children"), node_has_role, node_role,
                   pairs, role_order);
  }
}

// find_ctx_resource: wrapped instance id first, then direct id
// (mirrors core/common.py:find_ctx_resource)
const JValue* find_ctx_resource(const std::vector<JValue>& resources,
                                std::string_view instance_id) {
  for (const JValue& res : resources) {
    const JValue* inst = res.get("instance");
    if (inst != nullptr && jstr(inst->get("id")) == instance_id) return inst;
  }
  for (const JValue& res : resources) {
    if (jstr(res.get("id")) == instance_id) return &res;
  }
  return nullptr;
}

}  // namespace

extern "C" {

// strings: concatenated UTF-8; offs[n+1] boundaries.  urn_ids order:
// [entity, property, operation, resourceID, role, roleScopingEntity,
//  roleScopingInstance, ownerEntity, ownerInstance, actionID,
//  create, read, modify, delete, aclIndicatoryEntity, aclInstance]
// (indices into the preloaded strings)
// vocab_tail_ids: tail interner ids of the entity vocab (W entries).
void* acs_enc_create(const char* strings, const int64_t* offs, int32_t n,
                     const int32_t* urn_ids, int32_t tails_ambiguous,
                     const int32_t* vocab_tail_ids, int32_t W) {
  Encoder* enc = new Encoder();
  for (int32_t i = 0; i < n; ++i) {
    std::string_view s(strings + offs[i], (size_t)(offs[i + 1] - offs[i]));
    int32_t idx = enc->interner.intern(s);
    if (idx != i) {  // preload must reproduce compile-time ids exactly
      delete enc;
      return nullptr;
    }
  }
  enc->urn_entity = urn_ids[0];
  enc->urn_property = urn_ids[1];
  enc->urn_operation = urn_ids[2];
  enc->urn_resource_id = urn_ids[3];
  enc->urn_role = urn_ids[4];
  enc->urn_scoping = urn_ids[5];
  enc->urn_scoping_inst = urn_ids[6];
  enc->urn_owner_ent = urn_ids[7];
  enc->urn_owner_inst = urn_ids[8];
  enc->urn_action_id = urn_ids[9];
  for (int i = 0; i < 4; ++i) enc->crud[i] = urn_ids[10 + i];
  enc->urn_acl_ind = urn_ids[14];
  enc->urn_acl_inst = urn_ids[15];
  enc->tails_ambiguous = tails_ambiguous != 0;
  for (int32_t w = 0; w < W; ++w)
    enc->vocab_tails.push_back(enc->interner.strings[vocab_tail_ids[w]]);
  return enc;
}

void acs_enc_destroy(void* h) { delete (Encoder*)h; }

int32_t acs_enc_n_strings(void* h) {
  return (int32_t)((Encoder*)h)->interner.strings.size();
}

// copy string idx into out (cap bytes); returns its length
int32_t acs_enc_string(void* h, int32_t idx, char* out, int32_t cap) {
  const std::string& s = ((Encoder*)h)->interner.strings[idx];
  int32_t n = (int32_t)s.size();
  if (out != nullptr && cap >= n) memcpy(out, s.data(), n);
  return n;
}

// Encode B serialized acstpu.Request messages (concatenated; offs[B+1]).
// ptrs: the OutArrays fields in declaration order.
// Returns the number of distinct batch entity values (written to
// batch_entities as interner ids), or -1 on a malformed wire input.
int32_t acs_enc_batch(void* h, const uint8_t* buf, const int64_t* offs,
                      int32_t B, void** ptrs, const int32_t* caps) {
  Encoder& enc = *(Encoder*)h;
  Caps C;
  if (caps != nullptr) {
    C.NR = caps[0]; C.NI = caps[1]; C.NP = caps[2]; C.NSUB = caps[3];
    C.NACT = caps[4]; C.NOP = caps[5]; C.NOWN = caps[6]; C.NRA = caps[7];
    C.NHR = caps[8]; C.NROLE = caps[9]; C.NACLE = caps[10];
    C.NACLI = caps[11]; C.NHRR = caps[12];
  }
  const int NR = C.NR, NI = C.NI, NP = C.NP, NSUB = C.NSUB, NACT = C.NACT,
            NOP = C.NOP, NOWN = C.NOWN, NRA = C.NRA, NHR = C.NHR,
            NROLE = C.NROLE, NACLE = C.NACLE, NACLI = C.NACLI,
            NHRR = C.NHRR;
  OutArrays o;
  int pi = 0;
  o.r_sub_ids = (int32_t*)ptrs[pi++];
  o.r_sub_vals = (int32_t*)ptrs[pi++];
  o.r_roles = (int32_t*)ptrs[pi++];
  o.r_act_ids = (int32_t*)ptrs[pi++];
  o.r_act_vals = (int32_t*)ptrs[pi++];
  o.r_ent_vals = (int32_t*)ptrs[pi++];
  o.r_ent_e = (int32_t*)ptrs[pi++];
  o.r_ent_valid = (uint8_t*)ptrs[pi++];
  o.r_inst_run = (int32_t*)ptrs[pi++];
  o.r_inst_id = (int32_t*)ptrs[pi++];
  o.r_inst_valid = (uint8_t*)ptrs[pi++];
  o.r_inst_present = (uint8_t*)ptrs[pi++];
  o.r_inst_has_owners = (uint8_t*)ptrs[pi++];
  o.r_inst_owner_ent = (int32_t*)ptrs[pi++];
  o.r_inst_owner_inst = (int32_t*)ptrs[pi++];
  o.r_prop_vals = (int32_t*)ptrs[pi++];
  o.r_prop_sfx = (int32_t*)ptrs[pi++];
  o.r_prop_run = (int32_t*)ptrs[pi++];
  o.r_prop_tail = (int32_t*)ptrs[pi++];
  o.r_op_vals = (int32_t*)ptrs[pi++];
  o.r_op_present = (uint8_t*)ptrs[pi++];
  o.r_op_has_owners = (uint8_t*)ptrs[pi++];
  o.r_op_owner_ent = (int32_t*)ptrs[pi++];
  o.r_op_owner_inst = (int32_t*)ptrs[pi++];
  o.r_ra3 = (int32_t*)ptrs[pi++];
  o.r_ra2 = (int32_t*)ptrs[pi++];
  o.r_n_ra = (int32_t*)ptrs[pi++];
  o.r_hr = (int32_t*)ptrs[pi++];
  o.r_ctx_present = (uint8_t*)ptrs[pi++];
  o.r_n_entity_attrs = (int32_t*)ptrs[pi++];
  o.r_has_props = (uint8_t*)ptrs[pi++];
  o.r_has_target = (uint8_t*)ptrs[pi++];
  o.r_acl_short = (int32_t*)ptrs[pi++];
  o.r_acl_ent = (int32_t*)ptrs[pi++];
  o.r_acl_inst = (int32_t*)ptrs[pi++];
  o.r_acl_hr = (int32_t*)ptrs[pi++];
  o.r_hr_roles = (int32_t*)ptrs[pi++];
  o.r_subject_id = (int32_t*)ptrs[pi++];
  o.eligible = (uint8_t*)ptrs[pi++];
  o.batch_entities = (int32_t*)ptrs[pi++];
  o.overcap = (uint8_t*)ptrs[pi++];

  std::unordered_map<int32_t, int32_t> batch_entity_idx;
  int32_t n_batch_entities = 0;

  for (int32_t b = 0; b < B; ++b) {
    std::string_view bytes((const char*)(buf + offs[b]),
                           (size_t)(offs[b + 1] - offs[b]));
    WireRequest req = parse_request(bytes);
    if (!req.parse_ok) {
      // malformed wire bytes: never fabricate a decision -- the row falls
      // back to the protobuf path, which surfaces the parse error
      o.eligible[b] = 0;
      continue;
    }

    if (!req.has_target) {  // no-target requests are a host-side 400 DENY
      o.eligible[b] = 0;
      continue;
    }
    o.r_has_target[b] = 1;

    JValue subject;  // Null when absent
    if (req.has_subject && !req.subject_json.empty()) {
      JsonParser jp(req.subject_json);
      subject = jp.parse();
      if (!jp.ok) {
        o.eligible[b] = 0;  // invalid subject JSON -> fallback path
        continue;
      }
    }
    if (subject.get("token") != nullptr && subject.get("token")->truthy()) {
      o.eligible[b] = 0;  // token subjects take the host protocol path
      continue;
    }
    if (subject.kind == JValue::Null) {
      // quirk parity with the Python encoder: a subject-less context can
      // make the reference's unguarded context.subject dereference throw
      // inside verifyACL (verifyACL.ts:112), which the kernel formula
      // cannot represent -- all such rows take the oracle path
      o.eligible[b] = 0;
      continue;
    }

    // ---- subject / roles / actions
    if ((int)req.subjects.size() > NSUB || (int)req.actions.size() > NACT) {
      o.eligible[b] = 0;
      o.overcap[b] = 1;
      continue;
    }
    for (size_t j = 0; j < req.subjects.size(); ++j) {
      o.r_sub_ids[b * NSUB + j] = enc.interner.intern(req.subjects[j].id);
      o.r_sub_vals[b * NSUB + j] = enc.interner.intern(req.subjects[j].value);
    }
    for (size_t j = 0; j < req.actions.size(); ++j) {
      o.r_act_ids[b * NACT + j] = enc.interner.intern(req.actions[j].id);
      o.r_act_vals[b * NACT + j] = enc.interner.intern(req.actions[j].value);
    }

    // distinct roles by STRING, interned only at fill time (after the cap
    // check) -- interning order must match the Python encoder exactly so
    // lazily-assigned ids stay identical across both encoders
    const JValue* role_assocs = subject.get("role_associations");
    std::vector<std::string_view> roles;  // distinct, insertion order
    size_t n_role_assocs = 0;
    if (role_assocs != nullptr && role_assocs->kind == JValue::Arr) {
      n_role_assocs = role_assocs->arr.size();
      for (const JValue& ra : role_assocs->arr) {
        const JValue* role = ra.get("role");
        if (role == nullptr || role->kind != JValue::Str) continue;
        std::string_view rv = role->str;
        bool seen = false;
        for (std::string_view existing : roles) seen |= existing == rv;
        if (!seen) roles.push_back(rv);
      }
    }
    if ((int)roles.size() > NROLE) {
      o.eligible[b] = 0;
      o.overcap[b] = 1;
      continue;
    }
    for (size_t j = 0; j < roles.size(); ++j)
      o.r_roles[b * NROLE + j] = enc.interner.intern(roles[j]);

    // ---- resources: (entity, id*, prop*) runs / operations
    struct Run { std::string_view value; std::vector<std::string_view> instances; };
    std::vector<Run> runs;
    std::vector<std::pair<std::string_view, int>> props;  // (value, run idx)
    std::vector<std::string_view> ops;
    bool ok = true;
    const std::string& s_entity = enc.interner.strings[enc.urn_entity];
    const std::string& s_property = enc.interner.strings[enc.urn_property];
    const std::string& s_operation = enc.interner.strings[enc.urn_operation];
    const std::string& s_resource_id = enc.interner.strings[enc.urn_resource_id];
    for (const Attr& attr : req.resources) {
      if (attr.id == s_entity) {
        runs.push_back({attr.value, {}});
      } else if (attr.id == s_resource_id) {
        if (runs.empty()) continue;  // ids before any entity are ignored
        runs.back().instances.push_back(attr.value);
      } else if (attr.id == s_property) {
        props.emplace_back(attr.value, (int)runs.size() - 1);
      } else if (attr.id == s_operation) {
        ops.push_back(attr.value);
      } else {
        ok = false;  // unknown resource attribute id
        break;
      }
    }
    size_t total_instances = 0;
    for (const Run& run : runs) total_instances += run.instances.size();
    if (!ok) {
      o.eligible[b] = 0;
      continue;
    }
    if ((int)runs.size() > NR || (int)props.size() > NP ||
        (int)ops.size() > NOP || (int)total_instances > NI) {
      o.eligible[b] = 0;
      o.overcap[b] = 1;
      continue;
    }
    if (enc.tails_ambiguous && !props.empty()) {
      o.eligible[b] = 0;
      continue;
    }
    // substring relevance == tail equality for (vocab entity, prop) pairs;
    // cache keyed by the prop STRING (interning here would assign ids
    // earlier than the Python encoder does and break id parity)
    bool relevance_broken = false;
    for (auto& pv : props) {
      std::string value(pv.first);
      bool any_bad = false;
      for (size_t ti = 0; ti < enc.vocab_tails.size(); ++ti) {
        std::string key = std::to_string(ti) + "\x1f" + value;
        auto hit = enc.relevance_ok.find(key);
        bool good;
        if (hit != enc.relevance_ok.end()) {
          good = hit->second;
        } else {
          const std::string& vt = enc.vocab_tails[ti];
          size_t hash_pos = value.find('#');
          std::string prefix =
              hash_pos == std::string::npos ? value : value.substr(0, hash_pos);
          std::string prop_tail = entity_tail(prefix);
          good = (value.find(vt) != std::string::npos) == (vt == prop_tail);
          enc.relevance_ok.emplace(key, good);
        }
        any_bad |= !good;
      }
      if (any_bad) { relevance_broken = true; break; }
    }
    if (relevance_broken) {
      o.eligible[b] = 0;
      continue;
    }

    // ---- context resources (JSON each)
    std::vector<JValue> ctx_resources;
    ctx_resources.reserve(req.resource_jsons.size());
    for (std::string_view rj : req.resource_jsons) {
      if (rj.empty()) {
        ctx_resources.emplace_back();  // Null
      } else {
        JsonParser jp(rj);
        ctx_resources.push_back(jp.parse());
        if (!jp.ok) {
          o.eligible[b] = 0;  // invalid resource JSON -> fallback path
          break;
        }
      }
    }
    if (!o.eligible[b]) continue;

    // ---- ACL pair collection (mirrors encode.py; reference:
    // verifyACL.ts:49-88): walk targeted resource attributes in order; the
    // first one without ACL metadata is the early all-clear, a malformed
    // ACL fails, otherwise (entity -> instances) accumulate
    int32_t acl_short = 0;
    std::vector<int32_t> acl_ents;
    std::vector<std::vector<int32_t>> acl_insts;
    const std::string& s_acl_ind = enc.interner.strings[enc.urn_acl_ind];
    const std::string& s_acl_inst = enc.interner.strings[enc.urn_acl_inst];
    for (const Attr& attr : req.resources) {
      if (attr.id != s_resource_id && attr.id != s_operation) continue;
      const JValue* ctx_res = find_ctx_resource(ctx_resources, attr.value);
      const JValue* acl_list = nullptr;
      if (ctx_res != nullptr) {
        const JValue* acls = jget(ctx_res->get("meta"), "acls");
        if (acls != nullptr && acls->kind == JValue::Arr && !acls->arr.empty())
          acl_list = acls;
      }
      if (acl_list == nullptr) {
        acl_short = 1;  // no ACL metadata: verification passes
        break;
      }
      bool malformed = false;
      for (const JValue& acl : acl_list->arr) {
        if (jstr(acl.get("id")) == s_acl_ind) {
          int32_t ent_id = intern_jstr(enc, acl.get("value"));
          int pos = -1;
          for (size_t e = 0; e < acl_ents.size(); ++e)
            if (acl_ents[e] == ent_id) { pos = (int)e; break; }
          if (pos < 0) {
            pos = (int)acl_ents.size();
            acl_ents.push_back(ent_id);
            acl_insts.emplace_back();
          }
          const JValue* acl_attrs = acl.get("attributes");
          if (acl_attrs == nullptr || acl_attrs->kind != JValue::Arr ||
              acl_attrs->arr.empty()) {
            malformed = true;  // missing ACL instances
            break;
          }
          for (const JValue& attribute : acl_attrs->arr) {
            if (jstr(attribute.get("id")) == s_acl_inst) {
              acl_insts[pos].push_back(
                  intern_jstr(enc, attribute.get("value")));
            } else {
              malformed = true;  // missing ACL instance value
              break;
            }
          }
          if (malformed) break;
        } else {
          malformed = true;  // missing ACL indicatory entity
          break;
        }
      }
      if (malformed) {
        acl_short = 2;
        break;
      }
    }
    if (acl_short == 0) {
      bool over = (int)acl_ents.size() > NACLE;
      for (auto& insts : acl_insts) over |= (int)insts.size() > NACLI;
      // a missing/non-string ACL entity or instance value interns to
      // ABSENT; the kernel's validity masks would silently drop it and
      // pass where the reference fails closed (verifyACL.ts keys its map
      // on undefined) -- fall back to the oracle instead
      bool absent = false;
      for (int32_t e : acl_ents) absent |= e < 0;
      for (auto& insts : acl_insts)
        for (int32_t i : insts) absent |= i < 0;
      if (over || absent) {
        o.eligible[b] = 0;  // ACL shape beyond caps/ABSENT values: fallback
        if (over && !absent) o.overcap[b] = 1;
        continue;
      }
      for (size_t e = 0; e < acl_ents.size(); ++e) {
        o.r_acl_ent[b * NACLE + e] = acl_ents[e];
        for (size_t i = 0; i < acl_insts[e].size(); ++i)
          o.r_acl_inst[(b * NACLE + e) * NACLI + i] = acl_insts[e][i];
      }
    }
    o.r_acl_short[b] = acl_short;
    o.r_subject_id[b] = intern_jstr(enc, subject.get("id"));

    o.r_ctx_present[b] = req.has_context ? 1 : 0;
    o.r_n_entity_attrs[b] = (int32_t)runs.size();
    o.r_has_props[b] = props.empty() ? 0 : 1;

    int inst_slot = 0;
    bool overflow = false;
    for (size_t j = 0; j < runs.size(); ++j) {
      int32_t ent_id = enc.interner.intern(runs[j].value);
      o.r_ent_vals[b * NR + j] = ent_id;
      auto hit = batch_entity_idx.find(ent_id);
      int32_t e;
      if (hit != batch_entity_idx.end()) {
        e = hit->second;
      } else {
        e = n_batch_entities;
        batch_entity_idx.emplace(ent_id, e);
        o.batch_entities[n_batch_entities++] = ent_id;
      }
      o.r_ent_e[b * NR + j] = e;
      o.r_ent_valid[b * NR + j] = 1;
      for (std::string_view inst : runs[j].instances) {
        const JValue* ctx_res = find_ctx_resource(ctx_resources, inst);
        o.r_inst_run[b * NI + inst_slot] = (int32_t)j;
        // interned HERE, matching the Python encoder's fill order (the
        // relation-bit packer keys closure lookups on this id)
        o.r_inst_id[b * NI + inst_slot] = enc.interner.intern(inst);
        o.r_inst_valid[b * NI + inst_slot] = 1;
        if (ctx_res != nullptr) {
          o.r_inst_present[b * NI + inst_slot] = 1;
          const JValue* owners = jget(ctx_res->get("meta"), "owners");
          bool have = owners != nullptr && owners->kind == JValue::Arr &&
                      !owners->arr.empty();
          o.r_inst_has_owners[b * NI + inst_slot] = have ? 1 : 0;
          if (!encode_owners(enc, owners,
                             o.r_inst_owner_ent + (b * NI + inst_slot) * NOWN,
                             o.r_inst_owner_inst + (b * NI + inst_slot) * NOWN,
                             NOWN))
            overflow = true;
        }
        ++inst_slot;
      }
    }
    for (size_t j = 0; j < props.size(); ++j) {
      int32_t vid = enc.interner.intern(props[j].first);
      o.r_prop_vals[b * NP + j] = vid;
      o.r_prop_sfx[b * NP + j] = enc.interner.suffix_id[vid];
      o.r_prop_run[b * NP + j] = props[j].second;
      const std::string& value = enc.interner.strings[vid];
      size_t hash_pos = value.find('#');
      std::string prefix =
          hash_pos == std::string::npos ? value : value.substr(0, hash_pos);
      o.r_prop_tail[b * NP + j] = enc.interner.intern(entity_tail(prefix));
    }
    for (size_t j = 0; j < ops.size(); ++j) {
      o.r_op_vals[b * NOP + j] = enc.interner.intern(ops[j]);
      const JValue* ctx_res = nullptr;
      for (const JValue& res : ctx_resources) {
        if (jstr(res.get("id")) == ops[j]) { ctx_res = &res; break; }
      }
      if (ctx_res != nullptr) {
        o.r_op_present[b * NOP + j] = 1;
        const JValue* owners = jget(ctx_res->get("meta"), "owners");
        bool have = owners != nullptr && owners->kind == JValue::Arr &&
                    !owners->arr.empty();
        o.r_op_has_owners[b * NOP + j] = have ? 1 : 0;
        if (!encode_owners(enc, owners,
                           o.r_op_owner_ent + (b * NOP + j) * NOWN,
                           o.r_op_owner_inst + (b * NOP + j) * NOWN,
                           NOWN))
          overflow = true;
      }
    }

    // ---- role-association triples / pairs + HR closure
    std::vector<std::array<int32_t, 3>> ra3;
    std::vector<std::array<int32_t, 2>> ra2;
    const std::string& s_scoping = enc.interner.strings[enc.urn_scoping];
    const std::string& s_scoping_inst = enc.interner.strings[enc.urn_scoping_inst];
    if (role_assocs != nullptr && role_assocs->kind == JValue::Arr) {
      for (const JValue& ra : role_assocs->arr) {
        int32_t role_id = intern_jstr(enc, ra.get("role"));
        const JValue* attrs = ra.get("attributes");
        if (attrs == nullptr || attrs->kind != JValue::Arr) continue;
        for (const JValue& ra_attr : attrs->arr) {
          if (jstr(ra_attr.get("id")) != s_scoping) continue;
          int32_t ent_id = intern_jstr(enc, ra_attr.get("value"));
          std::array<int32_t, 2> pair = {role_id, ent_id};
          bool seen = false;
          for (auto& existing : ra2) seen |= existing == pair;
          if (!seen) ra2.push_back(pair);
          const JValue* insts = ra_attr.get("attributes");
          if (insts == nullptr || insts->kind != JValue::Arr) continue;
          for (const JValue& inst : insts->arr) {
            if (jstr(inst.get("id")) == s_scoping_inst)
              ra3.push_back({role_id, ent_id, intern_jstr(enc, inst.get("value"))});
          }
        }
      }
    }
    const JValue* hierarchical_scopes = subject.get("hierarchical_scopes");
    bool hs_missing = hierarchical_scopes == nullptr ||
                      hierarchical_scopes->kind == JValue::Null;
    if (hs_missing && n_role_assocs > 0) {
      o.eligible[b] = 0;  // the oracle raises InvalidRequestContext here
      continue;
    }
    // flatten: per top-level subtree, (top role, node id) pairs in
    // stack-DFS order (mirrors encode.py:_flatten_hr)
    std::vector<std::array<int32_t, 2>> hr_enc;
    if (!hs_missing && hierarchical_scopes->kind == JValue::Arr) {
      for (const JValue& top : hierarchical_scopes->arr) {
        int32_t role_id = intern_jstr(enc, top.get("role"));
        std::vector<const JValue*> stack = {&top};
        while (!stack.empty()) {
          const JValue* node = stack.back();
          stack.pop_back();
          std::string_view node_id = jstr(node->get("id"));
          if (!node_id.empty()) {
            std::array<int32_t, 2> entry = {role_id,
                                            enc.interner.intern(node_id)};
            bool seen = false;
            for (auto& existing : hr_enc) seen |= existing == entry;
            if (!seen) hr_enc.push_back(entry);
          }
          const JValue* children = node->get("children");
          if (children != nullptr && children->kind == JValue::Arr)
            for (const JValue& child : children->arr) stack.push_back(&child);
        }
      }
    }
    // verifyACL's own flatten (per-node role override) + its distinct
    // role keys in first-occurrence order (mirrors encode.py)
    std::vector<std::array<int32_t, 2>> acl_hr_enc;
    std::vector<int32_t> hr_roles;
    if (!hs_missing)
      flatten_acl_hr(enc, hierarchical_scopes, false, std::string_view(),
                     acl_hr_enc, hr_roles);
    if ((int)ra3.size() > NRA || (int)ra2.size() > NRA ||
        (int)hr_enc.size() > NHR || (int)acl_hr_enc.size() > NHR ||
        (int)hr_roles.size() > NHRR || overflow) {
      o.eligible[b] = 0;
      o.overcap[b] = 1;
      continue;
    }
    for (size_t j = 0; j < ra3.size(); ++j)
      for (int k = 0; k < 3; ++k) o.r_ra3[(b * NRA + j) * 3 + k] = ra3[j][k];
    for (size_t j = 0; j < ra2.size(); ++j)
      for (int k = 0; k < 2; ++k) o.r_ra2[(b * NRA + j) * 2 + k] = ra2[j][k];
    for (size_t j = 0; j < hr_enc.size(); ++j)
      for (int k = 0; k < 2; ++k) o.r_hr[(b * NHR + j) * 2 + k] = hr_enc[j][k];
    for (size_t j = 0; j < acl_hr_enc.size(); ++j)
      for (int k = 0; k < 2; ++k)
        o.r_acl_hr[(b * NHR + j) * 2 + k] = acl_hr_enc[j][k];
    for (size_t j = 0; j < hr_roles.size(); ++j)
      o.r_hr_roles[b * NHRR + j] = hr_roles[j];
    o.r_n_ra[b] = (int32_t)n_role_assocs;
  }
  return n_batch_entities;
}

// --------------------------------------------------- owner-bit packing
// Native transcription of ops/encode.pack_owner_bitplanes (which PR 2
// deferred to the Python packer): per (row, role-scope-vocab entry) the
// stage-B owner verdicts pack as 2*(nru+NOP) fail bits, laid out exactly
// per ops/encode.owner_bit_layout.  Bit-identity with the Python packer
// is enforced by tests/test_native_encoder.py's fuzz comparison; with
// this, the native encode stage runs zero per-row (and zero per-batch)
// Python.

// max over rows of the count of DISTINCT valid instance-bearing runs
// (the Python packer's `counts.max()`; the caller pow2-buckets it to nru)
int32_t acs_own_max_runs(const int32_t* inst_run, const uint8_t* inst_valid,
                         int32_t B, int32_t NI) {
  int32_t max_runs = 0;
  for (int32_t b = 0; b < B; ++b) {
    int32_t distinct = 0;
    // NI is tiny (<= 32): quadratic dedup beats any allocation
    for (int32_t i = 0; i < NI; ++i) {
      if (!inst_valid[b * NI + i]) continue;
      int32_t run = inst_run[b * NI + i];
      if (run < 0) continue;
      bool seen = false;
      for (int32_t j = 0; j < i; ++j)
        seen |= inst_valid[b * NI + j] && inst_run[b * NI + j] == run;
      if (!seen) ++distinct;
    }
    if (distinct > max_runs) max_runs = distinct;
  }
  return max_runs;
}

// own_runs_out: [B, nru] (filled ABSENT-padded, sorted ascending);
// bits_out: [B, nwords] uint32 (fully overwritten) where nwords follows
// owner_bit_layout(RV, nru, NOP).  Raw arrays are the acs_enc_batch
// outputs (or any buffers the Python packer would accept).
void acs_pack_owner_bits(
    const int32_t* inst_run, const uint8_t* inst_valid,
    const uint8_t* inst_present, const uint8_t* inst_has_owners,
    const int32_t* inst_owner_ent, const int32_t* inst_owner_inst,
    const int32_t* op_vals, const uint8_t* op_present,
    const uint8_t* op_has_owners,
    const int32_t* op_owner_ent, const int32_t* op_owner_inst,
    const int32_t* ra3, const int32_t* ra2, const int32_t* hr,
    int32_t B, int32_t NI, int32_t NOWN, int32_t NOP, int32_t NRA,
    int32_t NHR, const int32_t* hrv_role, const int32_t* hrv_scope,
    int32_t RV, int32_t nru, int32_t* own_runs_out, uint32_t* bits_out) {
  const int ebits = 2 * (nru + NOP);
  int epw = 0, wpe = 1, nwords;
  if (ebits <= 32) {
    epw = 32 / ebits;
    nwords = (RV + epw - 1) / epw;
  } else {
    epw = 0;
    wpe = (ebits + 31) / 32;
    nwords = RV * wpe;
  }
  std::vector<int32_t> runs;            // distinct valid runs, ascending
  std::vector<uint8_t> bits(ebits);     // per-entry fail bits, k-indexed
  for (int32_t b = 0; b < B; ++b) {
    const int32_t* b_inst_run = inst_run + b * NI;
    const uint8_t* b_inst_valid = inst_valid + b * NI;
    const int32_t* b_ra3 = ra3 + b * NRA * 3;
    const int32_t* b_ra2 = ra2 + b * NRA * 2;
    const int32_t* b_hr = hr + b * NHR * 2;
    uint32_t* b_words = bits_out + (int64_t)b * nwords;
    for (int w = 0; w < nwords; ++w) b_words[w] = 0;
    int32_t* b_runs = own_runs_out + (int64_t)b * nru;
    for (int g = 0; g < nru; ++g) b_runs[g] = ABSENT;

    runs.clear();
    for (int32_t i = 0; i < NI; ++i) {
      if (!b_inst_valid[i]) continue;
      int32_t run = b_inst_run[i];
      if (run < 0) continue;
      auto it = runs.begin();
      while (it != runs.end() && *it < run) ++it;
      if (it == runs.end() || *it != run) runs.insert(it, run);
    }
    for (size_t g = 0; g < runs.size() && (int)g < nru; ++g)
      b_runs[g] = runs[g];

    for (int32_t e = 0; e < RV; ++e) {
      const int32_t role_e = hrv_role[e];
      const int32_t scope_e = hrv_scope[e];
      // ra2_ok: the (role, scoping entity) pair exists among the valid
      // role-association pairs (mirrors _owner_verdicts' ra2 branch)
      bool ra2_ok = false;
      for (int32_t j = 0; j < NRA; ++j)
        ra2_ok |= b_ra2[j * 2 + 1] >= 0 && b_ra2[j * 2] == role_e &&
                  b_ra2[j * 2 + 1] == scope_e;

      // dir/hier verdict for ONE owner (entity, instance) pair
      auto pair_ok = [&](int32_t qe, int32_t qi, bool* dir, bool* hier) {
        *dir = false;
        *hier = false;
        if (qe != scope_e || qe < 0) return;  // ent_m gate
        for (int32_t j = 0; j < NRA && !*dir; ++j)
          *dir = b_ra3[j * 3 + 1] >= 0 && b_ra3[j * 3] == role_e &&
                 b_ra3[j * 3 + 1] == scope_e && b_ra3[j * 3 + 2] == qi;
        if (ra2_ok)
          for (int32_t j = 0; j < NHR && !*hier; ++j)
            *hier = b_hr[j * 2 + 1] >= 0 && b_hr[j * 2] == role_e &&
                    b_hr[j * 2 + 1] == qi;
      };

      for (int k = 0; k < ebits; ++k) bits[k] = 0;
      for (int32_t i = 0; i < NI; ++i) {
        // valid_i in the Python packer is r_inst_valid & (inst_run >= 0)
        if (!b_inst_valid[i] || b_inst_run[i] < 0) continue;
        bool miss = !(inst_present[b * NI + i] && inst_has_owners[b * NI + i]);
        bool any_dir = false, any_hier = false;
        for (int32_t o = 0; o < NOWN; ++o) {
          bool dir, hier;
          pair_ok(inst_owner_ent[(b * NI + i) * NOWN + o],
                  inst_owner_inst[(b * NI + i) * NOWN + o], &dir, &hier);
          any_dir |= dir;
          any_hier |= hier;
        }
        bool bad_a = miss || !(any_dir || any_hier);
        bool bad_b = miss || !any_dir;
        if (!bad_a && !bad_b) continue;
        // fold into the run group this instance belongs to
        int32_t run = b_inst_run[i];
        for (int g = 0; g < nru; ++g) {
          if (b_runs[g] != run) continue;
          bits[g] |= bad_a ? 1 : 0;
          bits[nru + g] |= bad_b ? 1 : 0;
        }
      }
      for (int32_t j = 0; j < NOP; ++j) {
        if (op_vals[b * NOP + j] < 0) continue;  // op_valid gate
        bool miss = !(op_present[b * NOP + j] && op_has_owners[b * NOP + j]);
        bool any_dir = false, any_hier = false;
        for (int32_t o = 0; o < NOWN; ++o) {
          bool dir, hier;
          pair_ok(op_owner_ent[(b * NOP + j) * NOWN + o],
                  op_owner_inst[(b * NOP + j) * NOWN + o], &dir, &hier);
          any_dir |= dir;
          any_hier |= hier;
        }
        bits[2 * nru + j] |= (miss || !(any_dir || any_hier)) ? 1 : 0;
        bits[2 * nru + NOP + j] |= (miss || !any_dir) ? 1 : 0;
      }

      // pack entry e's bits per owner_bit_layout
      if (epw) {
        uint32_t* word = b_words + e / epw;
        int base = (e % epw) * ebits;
        for (int k = 0; k < ebits; ++k)
          if (bits[k]) *word |= 1u << (base + k);
      } else {
        for (int k = 0; k < ebits; ++k)
          if (bits[k]) b_words[e * wpe + k / 32] |= 1u << (k % 32);
      }
    }
  }
}

// intern (or look up) one string in THIS encoder's id space.  The
// serving store translates its relation verdict tables into native ids
// with this (srv/relations.tables_for(space="native")) — strings interned
// after the preload snapshot diverge between the Python and C++ spaces,
// so each id space gets its own table build.  Caller holds the
// per-encoder call lock (the interner is not thread-safe).
int32_t acs_enc_intern(void* h, const char* bytes, int32_t len) {
  return ((Encoder*)h)->interner.intern(
      std::string_view(bytes, (size_t)len));
}

// ------------------------------------------------- relation-bit packing
// Native transcription of ops/relation.pack_relation_bitplanes: per
// (row, relation-vocab entry) the reachable-subject verdicts of the
// targeted instances fold into packed A/B fail bits laid out by
// ops/encode.owner_bit_layout(RELV, nru, 0) (ebits = 2*nru; bit g =
// full-closure plane fails for run g, bit nru+g = literal-tuples plane
// fails).  Membership comes from the store's flat verdict tables
// (translated into this encoder's id space): segment [obj_offs[v*2+p],
// obj_offs[v*2+p+1]) of sorted (ent<<32)|inst object keys, plus one
// globally sorted (object_row<<32)|subject pairs array — two binary
// searches per (instance, vocab, plane).  Bit-identity with the Python
// packer is enforced by tests/test_native_encoder.py's fuzz comparison.
void acs_pack_relation_bits(
    const int32_t* inst_run, const uint8_t* inst_valid,
    const int32_t* ent_vals, const int32_t* inst_id,
    const int32_t* subject_id,
    int32_t B, int32_t NR, int32_t NI,
    const int64_t* obj_offs, const int64_t* obj_keys,
    const int64_t* pairs, int64_t n_pairs,
    int32_t RELV, int32_t nru,
    int32_t* rel_runs_out, uint32_t* bits_out) {
  const int ebits = 2 * nru;
  int epw = 0, wpe = 1, nwords;
  if (ebits <= 32) {
    epw = 32 / ebits;
    nwords = (RELV + epw - 1) / epw;
  } else {
    wpe = (ebits + 31) / 32;
    nwords = RELV * wpe;
  }
  // verdict for one (vocab, plane) segment: object-key search, then the
  // (GLOBAL object row, subject) pair search — mirrors _plane_pass
  auto plane_pass = [&](int32_t idx, int64_t key, int64_t subj) -> bool {
    int64_t lo = obj_offs[idx], hi = obj_offs[idx + 1];
    if (hi <= lo || n_pairs == 0) return false;
    const int64_t* it = std::lower_bound(obj_keys + lo, obj_keys + hi, key);
    if (it == obj_keys + hi || *it != key) return false;
    int64_t pk = ((int64_t)(it - obj_keys) << 32) | subj;
    const int64_t* pit = std::lower_bound(pairs, pairs + n_pairs, pk);
    return pit != pairs + n_pairs && *pit == pk;
  };
  std::vector<int32_t> runs;         // distinct valid runs, ascending
  std::vector<uint8_t> bits(ebits);  // per-entry fail bits, k-indexed
  for (int32_t b = 0; b < B; ++b) {
    const int32_t* b_inst_run = inst_run + (int64_t)b * NI;
    const uint8_t* b_inst_valid = inst_valid + (int64_t)b * NI;
    uint32_t* b_words = bits_out + (int64_t)b * nwords;
    for (int w = 0; w < nwords; ++w) b_words[w] = 0;
    int32_t* b_runs = rel_runs_out + (int64_t)b * nru;
    for (int g = 0; g < nru; ++g) b_runs[g] = ABSENT;

    runs.clear();
    for (int32_t i = 0; i < NI; ++i) {
      if (!b_inst_valid[i]) continue;
      int32_t run = b_inst_run[i];
      if (run < 0) continue;
      auto it = runs.begin();
      while (it != runs.end() && *it < run) ++it;
      if (it == runs.end() || *it != run) runs.insert(it, run);
    }
    for (size_t g = 0; g < runs.size() && (int)g < nru; ++g)
      b_runs[g] = runs[g];

    const bool subj_ok = subject_id[b] >= 0;
    const int64_t subj_pk = subj_ok ? (int64_t)subject_id[b] : 0;
    for (int32_t v = 0; v < RELV; ++v) {
      for (int k = 0; k < ebits; ++k) bits[k] = 0;
      for (int32_t i = 0; i < NI; ++i) {
        // valid_i in the Python packer: r_inst_valid & (inst_run >= 0)
        if (!b_inst_valid[i] || b_inst_run[i] < 0) continue;
        int32_t run = b_inst_run[i];
        int32_t ent = ent_vals[(int64_t)b * NR + run];
        int32_t inst = inst_id[(int64_t)b * NI + i];
        bool key_ok = ent >= 0 && inst >= 0 && subj_ok;
        int64_t key = ((int64_t)(ent < 0 ? 0 : ent) << 32)
                      | (int64_t)(inst < 0 ? 0 : inst);
        bool ok_f = key_ok && plane_pass(v * 2, key, subj_pk);
        bool ok_d = key_ok && plane_pass(v * 2 + 1, key, subj_pk);
        if (ok_f && ok_d) continue;
        for (int g = 0; g < nru; ++g) {
          if (b_runs[g] != run) continue;
          if (!ok_f) bits[g] = 1;
          if (!ok_d) bits[nru + g] = 1;
        }
      }
      // pack entry v's bits per owner_bit_layout(RELV, nru, 0)
      if (epw) {
        uint32_t* word = b_words + v / epw;
        int base = (v % epw) * ebits;
        for (int k = 0; k < ebits; ++k)
          if (bits[k]) *word |= 1u << (base + k);
      } else {
        for (int k = 0; k < ebits; ++k)
          if (bits[k]) b_words[v * wpe + k / 32] |= 1u << (k % 32);
      }
    }
  }
}

}  // extern "C"
